"""Semiring-generic contraction core — one device engine for
optimization, marginals, and counting (``docs/semirings.md``).

DPOP's join+project+argmin, Max-Sum's factor marginalization, and
SyncBB's bound evaluation are all instances of ONE functional
aggregate query: a semiring contraction over an elimination order
(FAQ, arXiv:1504.04044; "Juggling Functions Inside a Database",
arXiv:1703.03147).  This module factors that query out of the
per-algorithm kernels:

- a :class:`Semiring` registry — ``min/+`` (exact optimization:
  today's DPOP UTIL join), ``max/+`` (MAP, i.e. ``max/×`` in
  log-space), ``+/×`` via stable logsumexp (weighted counting — the
  partition function ``log Z``), and ``+/×`` with per-message
  normalization (marginal inference).  Everything operates in the
  LOG DOMAIN, where ``⊗`` is ``+`` — so every kernel is the same
  broadcast-add join with only the ``⊕`` projection swapped;
- :func:`contraction_kernel` — the jitted device kernel for one
  ``(joined shape, aligned part shapes)`` bucket, cached per
  SEMIRING so swapping ``⊕`` on the same shape bucket compiles at
  most one new executable (the level-pack keys themselves are
  shape-only and shared — ``tools/recompile_guard.py:
  run_semiring_guard`` pins this);
- pluggable elimination orders (:func:`build_plan`):
  ``"pseudo_tree"`` — the DFS order today's DPOP uses — and
  ``"min_fill"`` — the classic greedy width heuristic, often much
  narrower on loopy graphs;
- :func:`run_infer_many` — the merged multi-instance contraction
  sweep behind ``api.infer``/``api.infer_many``: waves by node
  height, device-eligible contractions bucketed across instances by
  level-pack key (``ops/padding.py:util_level_key``) and dispatched
  as ONE vmapped kernel per bucket, exactly the machinery the
  level-synchronous DPOP sweep built (``docs/performance.md``), with
  every device dispatch routed through the ambient supervisor
  (``engine/supervisor.py``).

Precision contract, per ``⊕``:

- **Idempotent ⊕ (min, max)** — the f32 exactness CERTIFICATE
  generalizes: the device returns only the arg-reduce plus each
  cell's decision margin; a margin ≥ 2·(#parts+1)·eps32·Σmax|part|
  proves the f32 arg equals the true arg, near-ties are repaired on
  host, and the projected values are re-evaluated on host in exact
  f64 at the certified arg — results are EXACT at any depth (the
  DPOP scheme, ``algorithms/dpop.py``).
- **logsumexp ⊕** — there is no arg to certify: the VALUE is the
  answer, so the engine does error-BOUND ACCOUNTING instead.  Each
  contraction carries an accumulated log-domain error bound
  (children's bounds + the local f32 join/reduction rounding); a
  contraction whose bound would exceed ``tol`` runs on host f64
  (counted as ``semiring.logsumexp_repairs``), and the result
  reports the final bound as ``error_bound``.  With the default
  ``tol=1e-6`` small problems run entirely in host f64; loosening
  ``tol`` buys device throughput at a known, reported cost.

This module is numpy-only at import (jax loads inside the kernel
builder, like ``algorithms/dpop.py``) so the API/CLI surfaces stay
jax-free (``tests/test_import_time.py``); ``pydcop_tpu.ops``
re-exports it lazily (PEP 562).
"""

from __future__ import annotations

import dataclasses
import difflib
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.ops.padding import (
    INT8_NEG_INF,
    INT8_POS_INF,
    NO_PADDING,
    PadPolicy,
    as_pad_policy,
    as_table_dtype,
    int8_quant_bound,
    pad_util_parts,
    quantize_table_int8,
    stack_bucket,
    table_dtype_bytes,
    table_dtype_eps,
    util_level_key,
)
from pydcop_tpu.ops.sparse import (
    SparseTable,
    as_table_format,
    pack_table,
    sparse_contraction_kernel,
    sparse_node_prep,
)

_EPS32 = float(np.finfo(np.float32).eps)
_EPS64 = float(np.finfo(np.float64).eps)


def _np_table_dtype(table_dtype: str):
    """numpy STORAGE dtype for a canonical float table dtype (int8
    packs go through :func:`~pydcop_tpu.ops.padding.
    quantize_table_int8` instead).  bf16 resolves through ml_dtypes —
    jax's own numpy bridge, always present with it — lazily, so the
    module import surface stays numpy-only."""
    if table_dtype == "bf16":
        import ml_dtypes

        return ml_dtypes.bfloat16
    return np.float32


# -- the semiring registry ---------------------------------------------


def _np_logsumexp(a: np.ndarray, axis=None, keepdims: bool = False):
    """Stable host-f64 logsumexp: max-shifted, and an all-``-inf``
    slice reduces to ``-inf`` (no ``nan`` from ``-inf - -inf``)."""
    a = np.asarray(a, dtype=np.float64)
    m = np.max(a, axis=axis, keepdims=True)
    m = np.where(np.isfinite(m), m, 0.0)
    with np.errstate(divide="ignore"):  # log(0) = -inf is the
        # correct, expected reduce of an all--inf slice
        out = np.log(
            np.sum(np.exp(a - m), axis=axis, keepdims=True)
        ) + m
    if not keepdims:
        out = np.squeeze(
            out, axis=tuple(range(a.ndim)) if axis is None else axis
        )
    return out


def _norm_cell_axes(axis, n_dims: int) -> Tuple[int, ...]:
    """Normalize a reduce ``axis`` spec against the DIM axes of a
    cell-carrying array (the trailing cell axis is never reduced —
    negative indices count from the last dim axis).  Out-of-range
    axes raise, exactly like numpy on a scalar-cell array — a
    caller's axis-bookkeeping bug must crash, not silently reduce
    the wrong dimension."""
    if axis is None:
        return tuple(range(n_dims))
    if isinstance(axis, int):
        axis = (axis,)
    for a in axis:
        if not -n_dims <= a < n_dims:
            raise np.exceptions.AxisError(a, n_dims)
    return tuple(sorted(a % n_dims for a in axis))


def _kbest_sorted(a: np.ndarray, k: int) -> np.ndarray:
    """The k smallest of the candidate axis, sorted ascending (the
    top-K ⊕ primitive — stable, +inf-padded when candidates run out)."""
    out = np.sort(a, axis=-1, kind="stable")[..., :k]
    if out.shape[-1] < k:
        pad = np.full(
            out.shape[:-1] + (k - out.shape[-1],), np.inf
        )
        out = np.concatenate([out, pad], axis=-1)
    return out


def _exp_pair_reduce(a: np.ndarray, axes: Tuple[int, ...]):
    """⊕-reduce of expectation pairs ``(log w, r)`` over dim ``axes``:
    ``log w`` reduces by stable logsumexp, ``r`` by the matching
    convex (softmax-weighted) combine — the first-order expectation
    semiring in its normalized ``(log W, Σwr/W)`` representation."""
    lw = np.asarray(a[..., 0], dtype=np.float64)
    r = np.asarray(a[..., 1], dtype=np.float64)
    m = np.max(lw, axis=axes, keepdims=True)
    safe_m = np.where(np.isfinite(m), m, 0.0)
    w = np.exp(lw - safe_m)
    s = np.sum(w, axis=axes, keepdims=True)
    with np.errstate(divide="ignore"):
        lw_out = np.where(np.isfinite(m), safe_m + np.log(s), m)
    # a zero-weight cell contributes nothing whatever its r plane
    # holds — hard-constraint pairs are (-inf, +inf) and the naive
    # 0·inf product would poison the whole combine with NaN
    with np.errstate(invalid="ignore"):
        wr = np.where(w > 0, w * r, 0.0)
    r_out = np.where(
        s > 0, np.sum(wr, axis=axes, keepdims=True)
        / np.where(s > 0, s, 1.0), 0.0,
    )
    lw_out = np.squeeze(lw_out, axis=axes)
    r_out = np.squeeze(r_out, axis=axes)
    return np.stack([lw_out, r_out], axis=-1)


@dataclasses.dataclass(frozen=True)
class Semiring:
    """One ``(⊕, ⊗)`` pair in LOG-DOMAIN representation (``⊗ = +``).

    ``idempotent`` ⊕ (min/max) supports an arg-reduce and the f32
    exactness certificate; non-idempotent ⊕ (logsumexp) uses
    error-bound accounting instead.  ``normalize`` marks the
    marginal-inference variant whose messages are shift-normalized
    (the shifts are tracked, so absolute aggregates like ``log Z``
    are still recovered exactly).

    ``kind``/``cell_width`` extend the algebra from scalar cells to
    STRUCTURED cells — a trailing static value axis of width
    ``cell_width`` on every table/message cell, so XLA shapes stay
    static and the level-pack lattice is untouched
    (``docs/semirings.md``, "Structured cells"):

    - ``"scalar"`` — the classic one-float cell (``cell_width=1``);
    - ``"kbest"`` — the k best partial COSTS per cell, sorted
      ascending and +inf-padded; ⊕ merges two sorted k-vectors, ⊗
      cross-sums and truncates (exact: a sum's rank-k prefix only
      needs each argument's rank-k prefix);
    - ``"expectation"`` — the first-order expectation pair
      ``(log w, r)`` in normalized form (``r = Σ w·cost / w``): ⊗
      adds both planes, ⊕ logsumexps the weights and convex-combines
      ``r`` — the root pair is ``(log Z, E[cost])``.
    """

    name: str
    idempotent: bool
    maximize: bool = False  # direction of an idempotent ⊕
    normalize: bool = False
    doc: str = ""
    kind: str = "scalar"  # "scalar" | "kbest" | "expectation"
    cell_width: int = 1  # trailing static value axis per cell

    # -- algebra (log domain) ------------------------------------------

    @property
    def plus_identity(self) -> float:
        """Identity of ``⊕`` — also the annihilator of ``⊗``.  For
        structured cells this is the scalar every component of the
        identity cell holds (kbest: all +inf) or the scalar that
        annihilates the weight plane (expectation: -inf log-weight),
        which is exactly what the ghost-guard mask adds."""
        if self.kind == "kbest":
            return float(np.inf)
        if self.idempotent and not self.maximize:
            return float(np.inf)
        return float(-np.inf)

    @property
    def times_identity(self) -> float:
        """Identity of ``⊗`` (log-domain ``+``)."""
        return 0.0

    @property
    def error_bounded(self) -> bool:
        """Whether this ⊕ runs under error-BOUND accounting (the
        ``tol`` device gate) rather than an exactness certificate.
        kbest is non-idempotent but still CERTIFIED: each component is
        a selection with an arg, so the per-component margin
        certificate + host-f64 re-evaluation keeps it exact."""
        return not self.idempotent and self.kind != "kbest"

    def identity_cell(self) -> np.ndarray:
        """The ⊕-identity as one cell (length ``cell_width``)."""
        if self.kind == "expectation":
            return np.array([-np.inf, 0.0])
        return np.full(self.cell_width, self.plus_identity)

    def times_identity_cell(self) -> np.ndarray:
        """The ⊗-identity as one cell (length ``cell_width``)."""
        cell = np.zeros(self.cell_width)
        if self.kind == "kbest" and self.cell_width > 1:
            cell[1:] = np.inf
        return cell

    def add(self, a, b):
        """Elementwise ``⊕`` (host f64) — the axiom-test primitive.
        Structured kinds take cell-carrying arrays (trailing axis
        ``cell_width``)."""
        if self.kind == "kbest":
            return _kbest_sorted(
                np.concatenate(
                    [
                        np.asarray(a, dtype=np.float64),
                        np.asarray(b, dtype=np.float64),
                    ],
                    axis=-1,
                ),
                self.cell_width,
            )
        if self.kind == "expectation":
            return _exp_pair_reduce(
                np.stack(
                    [
                        np.asarray(a, dtype=np.float64),
                        np.asarray(b, dtype=np.float64),
                    ]
                ),
                (0,),
            )
        if self.idempotent:
            return (np.maximum if self.maximize else np.minimum)(a, b)
        return _np_logsumexp(np.stack([a, b]), axis=0)

    def combine(self, a, b):
        """Elementwise ``⊗`` (host f64): ``+`` in the log domain;
        cross-sum-truncate for kbest cells, per-plane ``+`` for
        expectation pairs."""
        if self.kind == "kbest":
            a = np.asarray(a, dtype=np.float64)
            b = np.asarray(b, dtype=np.float64)
            sums = a[..., :, None] + b[..., None, :]
            return _kbest_sorted(
                sums.reshape(sums.shape[:-2] + (-1,)), self.cell_width
            )
        if self.kind == "expectation":
            a = np.asarray(a, dtype=np.float64)
            b = np.asarray(b, dtype=np.float64)
            return a + b  # both planes add: log w multiplies, r sums
        return np.asarray(a, dtype=np.float64) + np.asarray(
            b, dtype=np.float64
        )

    def reduce(self, a, axis=None, keepdims: bool = False):
        """``⊕``-projection over ``axis`` (host f64).  For structured
        kinds ``axis`` names DIM axes of a cell-carrying array (the
        trailing cell axis is carried, never reduced); ``keepdims``
        applies to the dim axes."""
        if self.kind == "kbest":
            a = np.asarray(a, dtype=np.float64)
            axes = _norm_cell_axes(axis, a.ndim - 1)
            if not axes:
                return a
            dst = tuple(
                range(a.ndim - 1 - len(axes), a.ndim - 1)
            )
            moved = np.moveaxis(a, axes, dst)
            flat = moved.reshape(moved.shape[: dst[0]] + (-1,))
            out = _kbest_sorted(flat, self.cell_width)
            if keepdims:
                for ax in axes:
                    out = np.expand_dims(out, axis=ax)
            return out
        if self.kind == "expectation":
            a = np.asarray(a, dtype=np.float64)
            axes = _norm_cell_axes(axis, a.ndim - 1)
            if not axes:
                return a
            out = _exp_pair_reduce(a, axes)
            if keepdims:
                for ax in axes:
                    out = np.expand_dims(out, axis=ax)
            return out
        if self.idempotent:
            fn = np.max if self.maximize else np.min
            return fn(a, axis=axis, keepdims=keepdims)
        return _np_logsumexp(a, axis=axis, keepdims=keepdims)

    def arg_reduce(self, a, axis: int = -1):
        """Argmin/argmax over ``axis`` — idempotent ⊕ only (kbest
        keeps per-component backpointers through its own kernels)."""
        if not self.idempotent:
            raise ValueError(
                f"semiring {self.name!r}: ⊕ is not idempotent — there "
                "is no arg to reduce to"
            )
        return (np.argmax if self.maximize else np.argmin)(a, axis=axis)

    def shift_of(self, a: np.ndarray) -> float:
        """Message-normalization offset: the value subtracted from an
        outgoing message (min for ``min/+`` — DPOP's normalization —
        max otherwise, which is also the logsumexp stability shift).
        Structured cells shift on their leading component (kbest: the
        per-cell best; expectation: the log-weight plane), ignoring
        non-finite entries (+inf slot padding / -inf zero weights)."""
        if a.size == 0:
            return 0.0
        if self.kind in ("kbest", "expectation"):
            lead = np.asarray(a[..., 0], dtype=np.float64)
            lead = lead[np.isfinite(lead)]
            if lead.size == 0:
                return 0.0
            return float(
                lead.min() if self.kind == "kbest" else lead.max()
            )
        if self.idempotent and not self.maximize:
            return float(a.min())
        return float(a.max())

    def apply_shift(self, a: np.ndarray, shift: float) -> np.ndarray:
        """⊗-divide a message by the scalar ``shift``: scalar and
        kbest cells subtract it from every component; the expectation
        pair subtracts it from the log-weight plane only (``r`` is
        already weight-normalized)."""
        if self.kind == "expectation":
            out = np.array(a, dtype=np.float64)
            out[..., 0] -= shift
            return out
        return a - shift

    # -- traced (jnp) variants for use inside compiled steps -----------

    def jnp_reduce(self, a, axis, keepdims: bool = False):
        """``⊕``-projection inside a jax trace (``bp_factor_messages``
        and the contraction kernels)."""
        import jax.numpy as jnp

        if self.idempotent:
            fn = jnp.max if self.maximize else jnp.min
            return fn(a, axis=axis, keepdims=keepdims)
        m = jnp.max(a, axis=axis, keepdims=True)
        m = jnp.where(jnp.isfinite(m), m, 0.0)
        out = (
            jnp.log(jnp.sum(jnp.exp(a - m), axis=axis, keepdims=True))
            + m
        )
        return out if keepdims else jnp.squeeze(out, axis=axis)


SEMIRINGS: Dict[str, Semiring] = {}


def register_semiring(sr: Semiring) -> Semiring:
    """Add a semiring to the registry (``get_semiring`` name lookup)."""
    SEMIRINGS[sr.name] = sr
    return sr


def _did_you_mean(name: str, candidates: Sequence[str]) -> str:
    """One nearest-name hint (difflib) for unknown-name errors — "I
    typed log_sumexp" should not require reading the whole registry
    dump to spot the typo."""
    close = difflib.get_close_matches(
        str(name), list(candidates), n=1, cutoff=0.55
    )
    return f" — did you mean {close[0]!r}?" if close else ""


def get_semiring(name: str) -> Semiring:
    if isinstance(name, Semiring):
        return name
    got = SEMIRINGS.get(name)
    if got is not None:
        return got
    if isinstance(name, str) and name.startswith("kbest:"):
        try:
            k = int(name.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"malformed kbest semiring {name!r} — the width is "
                "an integer, e.g. 'kbest:5'"
            )
        return kbest_semiring(k)
    raise ValueError(
        f"unknown semiring {name!r} (registered: "
        f"{sorted(SEMIRINGS)}, plus parametric 'kbest:<k>')"
        + _did_you_mean(name, sorted(SEMIRINGS) + ["kbest:5"])
    )


MIN_SUM = register_semiring(
    Semiring(
        "min_sum", idempotent=True, maximize=False,
        doc="exact optimization over costs — DPOP's UTIL join",
    )
)
MAX_SUM = register_semiring(
    Semiring(
        "max_sum", idempotent=True, maximize=True,
        doc="MAP over log-weights (max/x in log space)",
    )
)
LOG_SUM_EXP = register_semiring(
    Semiring(
        "log_sum_exp", idempotent=False,
        doc="weighted counting: partition function log Z (+/x via "
        "stable logsumexp)",
    )
)
MARGINALS = register_semiring(
    Semiring(
        "marginals", idempotent=False, normalize=True,
        doc="+/x with message normalization — marginal inference",
    )
)
EXPECTATION = register_semiring(
    Semiring(
        "expectation", idempotent=False, kind="expectation",
        cell_width=2,
        doc="first-order expectation pairs (log w, E[cost]) — E[cost] "
        "under the Gibbs distribution and optional stochastic "
        "externals",
    )
)

#: widest registered top-K cell (the candidate sort is O(k^2 log k)
#: per cross-sum — past this, K-best enumeration wants a search
#: algorithm, not a semiring)
KBEST_MAX = 64


def kbest_semiring(k: int) -> Semiring:
    """The top-K semiring for width ``k`` (registered on first use —
    ``get_semiring("kbest:5")`` and ``query="kbest:5"`` resolve
    here).  Fixed ``k`` keeps every cell shape static for XLA."""
    k = int(k)
    if not 2 <= k <= KBEST_MAX:
        raise ValueError(
            f"kbest wants 2 <= k <= {KBEST_MAX}, got {k} (k=1 is "
            "query='map')"
        )
    name = f"kbest:{k}"
    got = SEMIRINGS.get(name)
    if got is None:
        got = register_semiring(
            Semiring(
                name, idempotent=False, kind="kbest", cell_width=k,
                doc="top-K cost tuples: ⊕ merge-sorts k-vectors, ⊗ "
                "cross-sums and truncates — the K best assignments",
            )
        )
    return got


# query name (api.infer) -> the semiring its sweep runs on
QUERY_SEMIRINGS = {
    "map": "max_sum",
    "log_z": "log_sum_exp",
    "marginals": "marginals",
    "expectation": "expectation",
}

#: every query ``api.infer`` understands (``kbest:<k>`` is
#: parametric; ``marginal_map`` rides max/+ with a two-block order)
KNOWN_QUERIES = (
    "map", "log_z", "marginals", "marginal_map", "expectation",
    "kbest:<k>",
)


def parse_query(query: str) -> Tuple[str, Semiring]:
    """Resolve an ``api.infer`` query string to ``(kind, semiring)``,
    where ``kind`` is the query family (``"kbest"`` for any
    ``kbest:<k>``).  ``marginal_map`` returns the max/+ semiring —
    its sum block rides ``log_sum_exp`` per node via the plan's
    two-block elimination order (:func:`build_plan` ``max_vars``).
    Unknown queries fail with the nearest known name suggested."""
    if query in QUERY_SEMIRINGS:
        return query, get_semiring(QUERY_SEMIRINGS[query])
    if query == "marginal_map":
        return "marginal_map", get_semiring("max_sum")
    if isinstance(query, str) and (
        query == "kbest" or query.startswith("kbest:")
    ):
        if query == "kbest":
            k = 5  # the documented default width
        else:
            try:
                k = int(query.split(":", 1)[1])
            except ValueError:
                raise ValueError(
                    f"malformed query {query!r} — the kbest width is "
                    "an integer, e.g. 'kbest:5'"
                )
        return "kbest", kbest_semiring(k)
    known = [q for q in KNOWN_QUERIES if "<" not in q] + ["kbest:5"]
    raise ValueError(
        f"unknown query {query!r} (expected one of "
        f"{sorted(KNOWN_QUERIES)})" + _did_you_mean(query, known)
    )


# -- branch-and-bound pruning (the two-pass ⊕-bounded kernels) ----------
#
# arXiv:1906.06863 accelerates BP-based DCOP algorithms generically by
# branch-and-bound INSIDE the marginalization: most rows of a
# high-arity join can be skipped because a cheap per-row ⊕-bound
# already proves they cannot matter.  Here that becomes a TWO-PASS
# device kernel behind :func:`contraction_kernel` (``bnb=True``):
#
# - **pass 1** computes a per-row (= per kept-configuration) bound —
#   in-kernel it is the joined row's own ⊕-extremum (free for the
#   value-carrying kinds whose outputs already bound the row;
#   CSE-merged with pass 2's join for the arg-only idempotent
#   kernels, so it costs one extra reduce, not a second join); the
#   DPOP sweep's host-side pass 1 uses per-part own-axis extrema
#   instead (O(Σ part sizes), no join materialized) — compared
#   against a per-row ``budget`` scalar derived from the running
#   incumbent (a greedy full assignment evaluated exactly on host,
#   :class:`_BnbContext`);
# - **pass 2** runs the dense join+project with the pruned rows
#   masked to the ⊕-identity (``jnp.where`` — static shapes, so the
#   level-pack lattice, the per-semiring kernel LRU, and the vmapped
#   stack/membound-lane machinery are untouched).
#
# Exactness, per ⊕ (docs/semirings.md, "Branch-and-bound pruning"):
# idempotent ⊕ (min/max) prunes a row only when its bound plus the
# rest-of-problem bound provably exceeds the incumbent — no optimal
# assignment passes through a pruned row, so results stay
# BIT-IDENTICAL to the unpruned kernel (f32 slack folded into the
# budget keeps the comparison conservative); kbest prunes against the
# k-th incumbent (k distinct greedy variants), so the whole k-list
# survives; logsumexp/marginals/expectation prune rows whose mass
# contribution is provably negligible and ACCOUNT the discarded mass
# (the kernel returns its logsumexp) into the existing ``error_bound``
# ledger under the same ``tol`` gate.

BNB_MODES = ("auto", "on", "off")

#: ``bnb='auto'`` threshold: a dispatch whose per-row joined table
#: (level-pack padded cells × cell width) is below this keeps the
#: single-pass kernel — for small factors the bound pass, the masked
#: ``where`` and the keep-mask transfer cost more than they prune;
#: only genuinely compute-bound dispatches (~0.5 MiB of f32 per row
#: and up) can repay the two-pass overhead on a CPU host, and on
#: TPU the threshold errs the same safe way
#: (``semiring.bnb_skipped_small`` counts the skips).
BNB_AUTO_MIN_CELLS = 1 << 17

#: pruned-row fraction at or above which pass 2 abandons the device
#: for a COMPACT host contraction of the survivors (exact f64, no
#: certificate needed): with most of the join dead, gathering the
#: surviving rows beats a dense f32 dispatch plus the dense host
#: re-evaluation glue.  Below it the masked device kernel runs and
#: the glue still compacts on the keep mask.
BNB_HOST_FRAC = 0.5


def as_bnb(value, default: str = "auto") -> str:
    """Normalize a ``bnb`` knob value to ``'auto'|'on'|'off'``."""
    if value is None:
        return default
    if value is True:
        return "on"
    if value is False:
        return "off"
    v = str(value).lower()
    if v not in BNB_MODES:
        raise ValueError(
            f"bnb must be one of {BNB_MODES}, got {value!r}"
        )
    return v


def greedy_assignment(
    order_rev: Sequence[str],
    domains: Mapping[str, Sequence],
    owned: Mapping[str, Sequence[Tuple[Sequence[str], np.ndarray]]],
    maximize: bool,
):
    """One cheap full assignment for the incumbent: walk ``order_rev``
    (reversed elimination order, or the pseudo-tree pre-order) and
    score each candidate value of ``v`` against EVERY part whose
    scope contains ``v`` — assigned variables fixed, unassigned ones
    ⊕-marginalized out (a one-step lookahead, so a hard-capped part
    owned further down the order steers the walk away from values
    that would doom it to ``+inf``); keep the ⊕-best (first index on
    ties — deterministic).  Returns ``(value-index assignment, exact
    f64 total over ALL parts)``.  Tables are in KERNEL domain."""
    by_var: Dict[str, list] = {}
    flat: List[Tuple[list, np.ndarray]] = []
    for parts in owned.values():
        for scope, table in parts:
            flat.append((list(scope), table))
            for u in scope:
                by_var.setdefault(u, []).append((scope, table))
    red = np.max if maximize else np.min
    worst = -np.inf if maximize else np.inf
    assigned: Dict[str, int] = {}
    for v in order_rev:
        d = len(domains[v])
        score = np.zeros(d, dtype=np.float64)
        for scope, table in by_var.get(v, ()):
            t = np.asarray(table, dtype=np.float64)
            idx = tuple(
                assigned[u] if u in assigned and u != v
                else slice(None)
                for u in scope
            )
            sub = t[idx]
            rem = [u for u in scope if u == v or u not in assigned]
            vax = rem.index(v)
            axes = tuple(a for a in range(sub.ndim) if a != vax)
            with np.errstate(invalid="ignore"):
                vec = red(sub, axis=axes) if axes else sub
            score = score + vec.reshape(d)
        # a NaN score (±inf parts cancelling) is "unknown" — rank it
        # worst so the walk prefers provably-finite values
        score = np.where(np.isnan(score), worst, score)
        assigned[v] = int(
            np.argmax(score) if maximize else np.argmin(score)
        )
    # coordinate-descent polish: re-pick each variable's ⊕-best value
    # with every other variable FIXED (exact part evaluations, no
    # marginalizing) — two sweeps close most of the greedy-vs-optimum
    # gap, and the incumbent's tightness is the pruning budget's
    # tightness
    for _ in range(2):
        changed = False
        for v in order_rev:
            d = len(domains[v])
            if d < 2:
                continue
            score = np.zeros(d, dtype=np.float64)
            for scope, table in by_var.get(v, ()):
                idx = tuple(
                    slice(None) if u == v else assigned[u]
                    for u in scope
                )
                score = score + np.asarray(
                    table, dtype=np.float64
                )[idx].reshape(d)
            score = np.where(np.isnan(score), worst, score)
            pick = int(
                np.argmax(score) if maximize else np.argmin(score)
            )
            if pick != assigned[v]:
                assigned[v] = pick
                changed = True
        if not changed:
            break
    total = 0.0
    for scope, table in flat:
        total += float(
            np.asarray(table, dtype=np.float64)[
                tuple(assigned[u] for u in scope)
            ]
        )
    return assigned, total


def _eval_assignment(owned, assigned) -> float:
    total = 0.0
    for parts in owned.values():
        for scope, table in parts:
            total += float(
                np.asarray(table, dtype=np.float64)[
                    tuple(assigned[u] for u in scope)
                ]
            )
    return total


class _BnbContext:
    """Per-instance branch-and-bound state for one sweep.

    Built from the instance's KERNEL-domain parts (energies for
    ``min_sum``/kbest, log-weights ``-β·E`` (+ log-prob parts)
    otherwise), keyed by owner node:

    - ``inc`` — the incumbent: exact f64 total of a greedy full
      assignment (an upper bound on the optimum for min, a lower
      bound for max / on ``log Z`` for the mass semirings);
      ``inc_k`` (kbest) is the k-th smallest total over k DISTINCT
      greedy variants — a valid upper bound on the k-th best cost —
      or None when the instance has fewer than k assignments;
    - ``rest[v]`` — Σ of per-part extrema over every part OUTSIDE
      ``v``'s subtree (total minus the subtree prefix sums);
    - ``rest_logdom[v]`` — Σ log|domain| over variables outside the
      subtree (the completion-count term of the mass bound);
    - ``cumshift[v]`` — shifts applied inside ``v``'s subtree so far
      (filled by the sweep as messages normalize), bridging stored
      (shifted) message values back to true subtree aggregates.

    ``budget(v, n_children_shift, n_parts, parts_max, d_own,
    n_rows)`` returns the f32-safe per-row threshold pass 1 compares
    against (conservative under f32 rounding: questionable rows are
    KEPT), or the no-prune sentinel when any input is non-finite."""

    __slots__ = (
        "sr", "tol_node", "inc", "inc_k", "rest", "rest_logdom",
        "cumshift", "table_dtype",
    )

    def __init__(
        self,
        sr: Semiring,
        order_rev: Sequence[str],
        domains: Mapping[str, Sequence],
        owned: Mapping[str, list],
        children: Mapping[str, Sequence[str]],
        tol: float = 1e-6,
        table_dtype: str = "f32",
    ):
        self.sr = sr
        # pass-1 row bounds are computed at the STORAGE dtype — the
        # budget slack widens to that dtype's roundoff (plus the int8
        # quantization term) so pruning stays conservative below f32
        self.table_dtype = as_table_dtype(table_dtype)
        self.cumshift: Dict[str, float] = {}
        n_nodes = max(len(order_rev), 1)
        self.tol_node = (
            tol / (2.0 * n_nodes) if sr.error_bounded or
            sr.kind == "expectation" else 0.0
        )
        maximize = sr.maximize or not sr.idempotent
        if sr.kind == "kbest":
            maximize = False
        assigned, inc = greedy_assignment(
            order_rev, domains, owned, maximize
        )
        self.inc = inc
        self.inc_k: Optional[float] = None
        if sr.kind == "kbest":
            self.inc_k = self._kth_incumbent(
                assigned, domains, owned, order_rev, sr.cell_width
            )
        # per-node extremum of the OWNED parts, then subtree prefix
        # sums bottom-up (order_rev reversed = children before
        # parents); rest = total - subtree
        ext: Dict[str, float] = {}
        logdom: Dict[str, float] = {}
        red = np.max if maximize else np.min
        for v in order_rev:
            e = 0.0
            for _, table in owned.get(v, ()):
                e += float(red(np.asarray(table, dtype=np.float64)))
            ext[v] = e
            logdom[v] = float(np.log(max(len(domains[v]), 1)))
        sub_ext: Dict[str, float] = {}
        sub_logdom: Dict[str, float] = {}
        for v in reversed(order_rev):  # children first
            sub_ext[v] = ext[v] + sum(
                sub_ext[c] for c in children.get(v, ())
            )
            sub_logdom[v] = logdom[v] + sum(
                sub_logdom[c] for c in children.get(v, ())
            )
        total_ext = sum(ext.values())
        total_logdom = sum(logdom.values())
        self.rest = {v: total_ext - sub_ext[v] for v in order_rev}
        self.rest_logdom = {
            v: total_logdom - sub_logdom[v] for v in order_rev
        }

    @staticmethod
    def _kth_incumbent(assigned, domains, owned, order_rev, k):
        """k DISTINCT assignments around the greedy one (vary the
        widest-domain variables combinatorially); the k-th smallest
        exact total upper-bounds the k-th best cost.  None when the
        assignment space itself has fewer than k points."""
        space = 1.0
        for v in order_rev:
            space *= max(len(domains[v]), 1)
            if space >= k:
                break
        if space < k:
            return None
        variants = [dict(assigned)]
        by_width = sorted(
            order_rev,
            key=lambda v: (-len(domains[v]), v),
        )
        for v in by_width:
            if len(variants) >= k:
                break
            d = len(domains[v])
            if d < 2:
                continue
            variants = [
                {**a, v: i} for a in variants for i in range(d)
            ]
        totals = sorted(
            _eval_assignment(owned, a) for a in variants[: 4 * k]
        )
        return totals[k - 1] if len(totals) >= k else None

    def seed_incumbent(self, owned, assigned) -> bool:
        """Adopt a caller-provided full assignment (``{var: value
        index}`` — e.g. the previous solution of a memoized serving
        session, re-evaluated under the CURRENT tables) as the
        incumbent when its exact total beats the greedy one.  Any
        full assignment's total is a valid bound, so this only ever
        tightens the budgets.  kbest keeps its own ``inc_k``
        (k-th-best bounds don't follow from one assignment)."""
        try:
            tot = _eval_assignment(owned, assigned)
        except (KeyError, IndexError):
            return False
        if not np.isfinite(tot):
            return False
        sr = self.sr
        maximize = sr.maximize or not sr.idempotent
        if sr.kind == "kbest":
            maximize = False
        if maximize:
            better = tot > self.inc
        else:
            better = tot < self.inc
        if better:
            self.inc = tot
        return better

    def no_prune(self) -> float:
        """Budget sentinel without a usable incumbent: keeps every
        FINITE row — rows whose bound is already the ⊕-annihilator
        (``+inf`` joint infeasibility under min/kbest, ``-inf`` zero
        mass) still prune, exactly (their value IS the ⊕-identity;
        masking them only skips the dead work)."""
        big = float(np.finfo(np.float32).max) / 2
        if self.sr.idempotent and not self.sr.maximize:
            return big
        if self.sr.kind == "kbest":
            return big
        return -big

    def shift_under(self, children: Sequence[str]) -> float:
        return sum(self.cumshift.get(c, 0.0) for c in children)

    def record_shift(
        self, name: str, shift: float, children: Sequence[str]
    ) -> None:
        self.cumshift[name] = shift + self.shift_under(children)

    def budget(
        self,
        name: str,
        shift_children: float,
        n_parts: int,
        parts_max: float,
        d_own: int,
        n_rows: int,
    ) -> float:
        """The per-row pass-1 threshold for node ``name`` (module
        comment above; f32 slack keeps pruning conservative)."""
        sr = self.sr
        inc = self.inc_k if sr.kind == "kbest" else self.inc
        if inc is None:
            return self.no_prune()
        rest = self.rest.get(name, 0.0)
        eps_dt = table_dtype_eps(self.table_dtype)
        slack = (
            2.0
            * (n_parts + 2)
            * eps_dt
            * (
                max(parts_max, 1.0)
                + abs(inc)
                + abs(rest)
                + abs(shift_children)
            )
        )
        if self.table_dtype == "int8":
            slack += 2.0 * int8_quant_bound(parts_max)
        if sr.idempotent or sr.kind == "kbest":
            if sr.maximize:
                b = inc - rest - shift_children - slack
            else:
                b = inc - rest - shift_children + slack
            return b if np.isfinite(b) else self.no_prune()
        # mass semirings: keep rows whose mass upper bound could
        # contribute more than tol_node relative to the incumbent's
        # exact mass (itself a lower bound on Z); log-domain terms —
        # the own-axis count, the completion count, and the row count
        # — make the per-dispatch worst case <= tol_node even before
        # the kernel measures the true discard
        b = (
            self.inc
            - shift_children
            - (rest + self.rest_logdom.get(name, 0.0))
            - float(np.log(max(d_own, 1)))
            - float(np.log(max(n_rows, 1)))
            + float(np.log(max(self.tol_node, 1e-300)))
            - slack
        )
        return b if np.isfinite(b) else self.no_prune()

    def account(
        self,
        name: str,
        disc: float,
        shift_children: float,
        d_own: int,
    ) -> float:
        """Error-ledger term for a mass dispatch's measured discard
        ``disc`` (kernel pass-1 logsumexp over pruned row bounds):
        relative discarded mass vs the incumbent's exact mass, with a
        2x inflation covering the f32 bound arithmetic."""
        if not np.isfinite(disc):  # nothing pruned
            return 0.0
        rest = self.rest.get(name, 0.0) + self.rest_logdom.get(
            name, 0.0
        )
        ln = (
            disc
            + float(np.log(max(d_own, 1)))
            + shift_children
            + rest
            - self.inc
        )
        return 2.0 * float(np.exp(min(ln, 50.0)))


def max_padded_join_cells(plan: "ContractionPlan", pad) -> int:
    """Dims-only upper bound on the plan's largest PADDED join (the
    quantity ``bnb='auto'`` gates on): the O(nodes·width) separator
    simulation `plan.width()` runs, sized on the pad lattice.  Lets
    callers skip the (greedy incumbent + per-part extrema) context
    build entirely on instances where no dispatch can ever clear
    ``BNB_AUTO_MIN_CELLS`` — small solves must not pay for pruning
    that cannot happen."""
    from pydcop_tpu.ops.padding import bucket_util_shape

    dsize = {
        v: bucket_util_shape((len(plan.domains[v]),), pad)[0]
        for v in plan.domains
    }
    seps: Dict[str, List[str]] = {}
    mx = 1
    for v in plan.order:
        seps[v] = plan.sep_of(v, seps)
        size = dsize[v]
        for d in seps[v]:
            size *= dsize[d]
        mx = max(mx, size)
    return mx


def plan_bnb_context(
    plan: "ContractionPlan", sr: Semiring, beta: float, tol: float,
    table_dtype: str = "f32",
) -> Optional[_BnbContext]:
    """Build the BnB context for one plan, or None when the sweep
    shape does not support pruning (mixed-⊕ marginal-MAP plans: a
    max node's subtree contains sums, so neither bound family
    applies cleanly)."""
    if plan.node_semiring is not None:
        return None
    sign_mass = not (
        sr.kind == "kbest" or (sr.idempotent and not sr.maximize)
    )
    owned: Dict[str, list] = {}
    for v in plan.order:
        parts = []
        for scope, table in plan.buckets[v]:
            t = np.asarray(table, dtype=np.float64)
            parts.append(
                (list(scope), (-beta) * t if sign_mass else t)
            )
        for scope, table in plan.wbuckets[v]:
            # log-prob parts are already kernel-domain log-weights
            parts.append((list(scope), np.asarray(table, np.float64)))
        if parts:
            owned[v] = parts
    return _BnbContext(
        sr, list(reversed(plan.order)), plan.domains, owned,
        plan.children, tol=tol, table_dtype=table_dtype,
    )


# -- device kernels -----------------------------------------------------
#
# One jitted join+projection per (semiring, joined shape, aligned part
# shapes) bucket.  The level-pack KEY is shape-only and shared across
# semirings (ops/padding.py:util_level_key), so swapping the semiring
# on the same problem bucket reuses the bucketing and compiles at most
# one new executable per semiring — zero on repeat
# (tools/recompile_guard.py:run_semiring_guard).  LRU-bounded for the
# same reason the DPOP join-kernel cache was: long-lived processes
# must not retain one executable per distinct shape forever.

_KERNELS: Dict[Tuple, Any] = {}
_KERNELS_MAX = 256


def contraction_kernel(
    sr: Semiring,
    shape: Tuple[int, ...],
    part_shapes: Tuple[Tuple[int, ...], ...],
    batched: bool = False,
    bnb: bool = False,
    table_dtype: str = "f32",
):
    """Jit-compiled semiring contraction for one bucket: broadcast-add
    join of the aligned parts, then the ``⊕``-projection over the own
    (last) axis.  ``batched=True`` vmaps it over a leading stack axis.

    Idempotent ⊕ returns ``(arg, margins)`` — the exactness-
    certificate outputs; the projected values are NOT shipped back
    (the caller re-evaluates them exactly on host at the certified
    arg, so the transfer would be dead).  For ``min_sum`` this is
    bit-for-bit the historical DPOP join kernel
    (``algorithms/dpop.py:_join_kernel`` now delegates here).
    Non-idempotent ⊕ returns ``(values,)`` — a max-shifted f32
    logsumexp whose rounding is covered by the caller's error-bound
    accounting.

    ``bnb=True`` builds the TWO-PASS branch-and-bound variant
    (module comment above): the kernel takes a leading per-row
    ``budget`` scalar (vmapped with the parts when ``batched``),
    pass 1 derives a per-row ⊕-bound from per-part own-axis extrema
    (each part reduced once per dispatch), and pass 2's outputs are
    masked to the ⊕-identity on pruned rows — margins become
    ``+inf`` (pruned rows never enter certification or repair), and
    the returned outputs gain a trailing ``keep`` mask plus, for the
    mass semirings, the logsumexp of the pruned row bounds (the
    discarded-mass measurement the caller accounts into the
    ``error_bound`` ledger).  Same static shapes, one extra
    executable per ``(semiring, bucket)`` at most.

    ``table_dtype`` is the STORAGE precision of the parts
    (``docs/performance.md``, "Mixed-precision table packs"): bf16
    parts join straight into the f32 accumulator (jax's promotion —
    the join and reduce stay wide); int8 parts arrive as codes with
    per-part ``scales``/``offsets`` f32 vectors PREPENDED to the
    argument list (after the bnb ``budget`` when both are on) and
    dequantize in-trace, the reserved top/bottom codes restoring
    ``±inf`` exactly.  The dtype joins the cache key, so a bucket
    pays at most one extra executable per dtype it actually runs at
    (``tools/recompile_guard.py:run_precision_guard``).
    """
    sr = get_semiring(sr)
    table_dtype = as_table_dtype(table_dtype)
    key = (
        sr.name, tuple(shape), tuple(part_shapes), batched, bnb,
        table_dtype,
    )
    fn = _KERNELS.get(key)
    if fn is not None:
        return fn
    if len(_KERNELS) >= _KERNELS_MAX:
        _KERNELS.pop(next(iter(_KERNELS)))
    import jax
    import jax.numpy as jnp

    nd_own = len(shape)

    def _row_bound(tabs, lo: bool):
        """Pass 1 bound for the scalar idempotent kinds: the joined
        row's own-axis extremum — the EXACT (up to f32 rounding,
        covered by the budget's slack) row projection, so the prune
        test is as tight as the incumbent and rest bounds allow.
        XLA's common-subexpression elimination merges this join with
        pass 2's, so the bound costs one extra reduce, not a second
        join; the ghost-guard mask part rides the join, keeping
        level-pack ghost cells out of the bound (a per-part minima
        bound would read a padded part's ghost zeros as real)."""
        red = jnp.min if lo else jnp.max
        j = jnp.zeros(shape, dtype=jnp.float32)
        for t in tabs:
            j = j + t
        return red(j, axis=-1)

    def _discard(rowb, keep):
        """logsumexp of the pruned rows' mass bounds (``-inf`` when
        nothing was pruned) — the measured discard the host accounts."""
        pr = jnp.where(keep, -jnp.inf, rowb)
        m = jnp.max(pr)
        safe = jnp.where(jnp.isfinite(m), m, 0.0)
        s = jnp.sum(jnp.where(jnp.isfinite(pr), jnp.exp(pr - safe), 0.0))
        return jnp.where(
            (s > 0) & jnp.isfinite(m), safe + jnp.log(s), -jnp.inf
        )

    if sr.kind == "kbest":
        # structured cells: parts of ndim len(shape) are scalar
        # (pre-summed energies + the ghost mask), parts of ndim+1
        # carry the trailing K axis (child messages).  The kernel
        # returns, per separator cell: the K best candidate values,
        # the margin to the NEXT candidate per slot (the
        # per-component certificate input), the selected own-value
        # index, and one selected child-slot index per vector part —
        # the backpointers the host value phase walks.
        kk = sr.cell_width
        nd = len(shape)
        d = shape[-1]

        def contract(*tabs):
            scal = [t for t in tabs if t.ndim == nd]
            vecs = [t for t in tabs if t.ndim == nd + 1]
            j = jnp.zeros(shape, dtype=jnp.float32)
            for t in scal:
                j = j + t
            if vecs:
                cell = j[..., None] + vecs[0]
                provs = [
                    jnp.broadcast_to(
                        jnp.arange(kk, dtype=jnp.int32), cell.shape
                    )
                ]
                for t in vecs[1:]:
                    # cross-sum-truncate: exact for top-K because
                    # sums are monotone in each argument — a dropped
                    # rank->K candidate already has K smaller sums
                    sums = cell[..., :, None] + t[..., None, :]
                    flat = sums.reshape(
                        sums.shape[:-2] + (kk * kk,)
                    )
                    idx = jnp.argsort(flat, axis=-1)[..., :kk]
                    cell = jnp.take_along_axis(flat, idx, axis=-1)
                    a_i = (idx // kk).astype(jnp.int32)
                    provs = [
                        jnp.take_along_axis(p, a_i, axis=-1)
                        for p in provs
                    ] + [(idx % kk).astype(jnp.int32)]
            else:
                lift = jnp.full((kk,), jnp.inf, dtype=jnp.float32)
                lift = lift.at[0].set(0.0)
                cell = j[..., None] + lift
                provs = []
            flat = cell.reshape(cell.shape[:-2] + (d * kk,))
            # one +inf pad column so the (K+1)-th candidate — the
            # margin reference — always exists, even at d*kk == kk
            flat = jnp.concatenate(
                [
                    flat,
                    jnp.full(
                        flat.shape[:-1] + (1,), jnp.inf, flat.dtype
                    ),
                ],
                axis=-1,
            )
            idx = jnp.argsort(flat, axis=-1)[..., : kk + 1]
            vals_all = jnp.take_along_axis(flat, idx, axis=-1)
            vals = vals_all[..., :kk]
            margins = jnp.where(
                jnp.isfinite(vals), vals_all[..., 1:] - vals, jnp.inf
            )
            sel = jnp.minimum(idx[..., :kk], d * kk - 1)
            own = (sel // kk).astype(jnp.int32)
            slot = sel % kk
            outs = [vals, margins, own]
            for p in provs:
                pf = p.reshape(p.shape[:-2] + (d * kk,))
                outs.append(
                    jnp.take_along_axis(pf, slot + own * kk, axis=-1)
                )
            return tuple(outs)

    elif sr.kind == "expectation":
        nd = len(shape)

        def contract(*tabs):
            lw = jnp.zeros(shape, dtype=jnp.float32)
            r = jnp.zeros(shape, dtype=jnp.float32)
            for t in tabs:
                if t.ndim == nd + 1:
                    lw = lw + t[..., 0]
                    r = r + t[..., 1]
                else:
                    lw = lw + t  # scalar parts weight only (the mask)
            m = jnp.max(lw, axis=-1)
            safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
            w = jnp.exp(lw - safe_m[..., None])
            s = jnp.sum(w, axis=-1)
            lw_out = jnp.where(
                jnp.isfinite(m), safe_m + jnp.log(s), m
            )
            # zero-weight cells contribute nothing — mask before the
            # product or a hard-constraint (-inf, +inf) pair's 0·inf
            # poisons the row with NaN
            wr = jnp.where(w > 0, w * r, 0.0)
            r_out = jnp.where(
                s > 0,
                jnp.sum(wr, axis=-1) / jnp.where(s > 0, s, 1.0),
                0.0,
            )
            return (jnp.stack([lw_out, r_out], axis=-1),)

    elif sr.idempotent:
        if sr.maximize:

            def contract(*tabs):
                j = jnp.zeros(shape, dtype=jnp.float32)
                for t in tabs:
                    j = j + t  # aligned: broadcast over missing axes
                u = jnp.max(j, axis=-1)
                arg = jnp.argmax(j, axis=-1)
                if shape[-1] == 1:
                    margins = jnp.full(shape[:-1], jnp.inf)
                else:
                    one_hot = (
                        jnp.arange(shape[-1]) == arg[..., None]
                    )
                    second = jnp.max(
                        jnp.where(one_hot, -jnp.inf, j), axis=-1
                    )
                    margins = u - second
                return arg, margins

        else:

            def contract(*tabs):
                j = jnp.zeros(shape, dtype=jnp.float32)
                for t in tabs:
                    j = j + t  # aligned: broadcast over missing axes
                u = jnp.min(j, axis=-1)
                amin = jnp.argmin(j, axis=-1)
                if shape[-1] == 1:
                    margins = jnp.full(shape[:-1], jnp.inf)
                else:
                    # second best via masking the arg cell (exact; no
                    # sort)
                    one_hot = (
                        jnp.arange(shape[-1]) == amin[..., None]
                    )
                    second = jnp.min(
                        jnp.where(one_hot, jnp.inf, j), axis=-1
                    )
                    margins = second - u
                # values are NOT returned: the caller re-evaluates
                # them exactly on host at the certified arg
                return amin, margins

    else:

        def contract(*tabs):
            j = jnp.zeros(shape, dtype=jnp.float32)
            for t in tabs:
                j = j + t
            m = jnp.max(j, axis=-1)
            safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
            s = jnp.sum(jnp.exp(j - safe_m[..., None]), axis=-1)
            # an all--inf row (impossible configuration, or a padded
            # ghost guard row) stays -inf instead of going nan
            vals = jnp.where(
                jnp.isfinite(m), safe_m + jnp.log(s), m
            )
            return (vals,)

    if bnb:
        base = contract
        lo = sr.kind == "kbest" or (
            sr.idempotent and not sr.maximize
        )

        def contract(budget, *tabs):  # noqa: F811 — bnb wrap
            # pass-1 bound per row: for the output-carrying kinds it
            # is FREE — the base kernel's own row values bound their
            # mass/best exactly; the idempotent arg-only kernels
            # re-derive the row extremum from the join (CSE-merged).
            # Negated comparisons so a NaN bound (mixed ±inf
            # hard-constraint parts cancelling in the sum) is always
            # KEPT — pruning must stay conservative
            outs = base(*tabs)
            if sr.kind == "kbest":
                vals, margins, own, *slots = outs
                rowb = vals[..., 0]  # the row's best candidate
                keep = jnp.logical_not(rowb > budget)
                k3 = keep[..., None]
                return (
                    jnp.where(k3, vals, jnp.inf),
                    jnp.where(k3, margins, jnp.inf),
                    own, *slots, keep,
                )
            if sr.kind == "expectation":
                (pair,) = outs
                rowb = pair[..., 0]  # the row's exact log-mass
                keep = jnp.logical_not(rowb < budget)
                lw = jnp.where(keep, pair[..., 0], -jnp.inf)
                rr = jnp.where(keep, pair[..., 1], 0.0)
                return (
                    jnp.stack([lw, rr], axis=-1),
                    keep,
                    _discard(rowb, keep),
                )
            if sr.idempotent:
                arg, margins = outs
                rowb = _row_bound(tabs, lo)
                keep = (
                    jnp.logical_not(rowb > budget)
                    if lo
                    else jnp.logical_not(rowb < budget)
                )
                return arg, jnp.where(keep, margins, jnp.inf), keep
            (vals,) = outs
            rowb = vals  # the row's exact logsumexp mass
            keep = jnp.logical_not(rowb < budget)
            return (
                jnp.where(keep, vals, -jnp.inf),
                keep,
                _discard(rowb, keep),
            )

    if table_dtype == "int8":
        # OUTERMOST dequant wrap — the (possibly bnb-wrapped) float
        # kernel below never sees codes, so the bound pass and every
        # ⊕ body stay dtype-oblivious.  Reserved codes restore ±inf
        # exactly: hard caps, ghost guards and noprune sentinels
        # survive packing bit-for-bit (ops/padding.py).
        inner = contract

        def contract(*args):  # noqa: F811 — int8 wrap
            if bnb:
                budget, scales, offsets, *qtabs = args
            else:
                scales, offsets, *qtabs = args
            tabs = []
            for i, q in enumerate(qtabs):
                f = (
                    q.astype(jnp.float32) * scales[i] + offsets[i]
                )
                f = jnp.where(q == INT8_POS_INF, jnp.inf, f)
                f = jnp.where(q == INT8_NEG_INF, -jnp.inf, f)
                tabs.append(f)
            return inner(budget, *tabs) if bnb else inner(*tabs)

    from pydcop_tpu.telemetry.jit import profiled_jit

    fn = profiled_jit(
        jax.vmap(contract) if batched else contract,
        label=f"semiring-{sr.name}"
        + ("-bnb" if bnb else "")
        + ("" if table_dtype == "f32" else f"-{table_dtype}"),
    )
    _KERNELS[key] = fn
    return fn


def bp_factor_messages(
    sr: Semiring,
    tab,
    q_pos: Sequence,
    mdt,
    bnb: bool = False,
) -> list:
    """Factor→variable belief-propagation messages for one arity
    bucket, as a semiring contraction inside a jax trace.

    The standard sum-then-subtract marginalization:
    ``S = table ⊗ ⊗_p q_p`` (broadcast-add over the bucket's axes),
    ``M_p = ⊕`` over all axes but ``p``, ``r_p = M_p − q_p``,
    shift-normalized per edge.  With ``sr=min_sum`` this is bit-for-
    bit Max-Sum's factor phase (``algorithms/maxsum.py`` step 2 now
    delegates here); other semirings turn the same wiring into
    sum-product (marginal BP) or max-product message passing.

    ``tab`` is the bucket's ``[d, ..., d, m]`` table stack (f32),
    ``q_pos`` the ``k`` per-position ``[d, m]`` incoming messages
    (message dtype ``mdt`` — bf16 upcasts on the add), and the
    returned list holds the ``k`` outgoing ``[d, m]`` messages in
    ``mdt``.

    ``bnb=True`` (idempotent ⊕ only; ignored otherwise) runs the
    two-pass ⊕-bounded marginalization of arXiv:1906.06863 per
    output position: pass 1 derives, per configuration, a bound from
    the per-position q extrema and, per output cell, an incumbent —
    the table evaluated AT the q-extrema configuration (one exact
    candidate, so a valid incumbent for every output cell) — and
    pass 2 masks configurations whose bound provably cannot beat the
    incumbent to the ⊕-identity before each reduce.  An f32 slack on
    the comparison keeps pruning conservative, and pruned entries
    are STRICTLY worse than each output's optimum, so the returned
    messages are bit-identical to the unpruned kernel.
    """
    import jax.numpy as jnp

    sr = get_semiring(sr)
    k = len(q_pos)
    d = q_pos[0].shape[0]
    m = q_pos[0].shape[1]
    s = tab  # [d, ..., d, m] — f32; mdt q upcasts on the add
    for p in range(k):
        shape = (1,) * p + (d,) + (1,) * (k - 1 - p) + (m,)
        s = s + q_pos[p].astype(tab.dtype).reshape(shape)
    use_bnb = bool(bnb) and sr.idempotent
    if use_bnb:
        guard = jnp.asarray(
            -jnp.inf if sr.maximize else jnp.inf, dtype=s.dtype
        )
        red = jnp.max if sr.maximize else jnp.min
        arg = jnp.argmax if sr.maximize else jnp.argmin
        qf = [q.astype(tab.dtype) for q in q_pos]
        qv = [red(q, axis=0) for q in qf]  # [m] per position
        qa = [arg(q, axis=0) for q in qf]
        fin = lambda a: jnp.where(jnp.isfinite(a), jnp.abs(a), 0.0)
        scale = jnp.max(fin(tab), initial=0.0)
        for q in qf:
            scale = scale + jnp.max(fin(q), initial=0.0)
        # covers three independently-rounded f32 sums (the joint s,
        # the bound lb, the incumbent ub), each within
        # (k+1)·eps32·scale of its exact value — pruned entries are
        # then STRICTLY worse than every output's f32 optimum
        slack = 4.0 * (k + 2) * _EPS32 * jnp.maximum(scale, 1.0)
    outs = []
    for p in range(k):
        axes = tuple(a for a in range(k) if a != p)
        sp = s
        if use_bnb:
            # incumbent per output cell (p, v): the table at the
            # q-extrema configuration of the other axes — gathered
            # once per position, O(d·m) against the O(d^k·m) join
            t = tab
            for a in range(k):
                if a == p:
                    continue
                idx = qa[a].reshape((1,) * k + (-1,))
                t = jnp.take_along_axis(t, idx, axis=a)
            ub = t  # [1,..,d@p,..,1, m_tab]
            lb = tab
            for a in range(k):
                if a == p:
                    continue
                ub = ub + qv[a].reshape((1,) * k + (-1,))
                lb = lb + qv[a].reshape((1,) * k + (-1,))
            qp = qf[p].reshape(
                (1,) * p + (d,) + (1,) * (k - 1 - p) + (m,)
            )
            lb = lb + qp
            ub = ub + qp  # incumbent includes this output's own q
            # negated comparison: NaN bounds (±inf cancellation in
            # hard-constraint tables) always KEEP — conservative
            worse = (
                (lb < ub - slack)
                if sr.maximize
                else (lb > ub + slack)
            )
            sp = jnp.where(jnp.logical_not(worse), s, guard)
        mp = sr.jnp_reduce(sp, axes)  # [d, m]
        rp = mp - q_pos[p].astype(tab.dtype)
        # shift-normalize per edge (bounded over cycles): min for
        # min/+ — the historical Max-Sum normalization — max for the
        # maximizing/summing semirings
        if sr.idempotent and not sr.maximize:
            rp = rp - jnp.min(rp, axis=0, keepdims=True)
        else:
            rp = rp - jnp.max(rp, axis=0, keepdims=True)
        outs.append(rp.astype(mdt))
    return outs


# -- elimination orders and contraction plans ---------------------------


ELIMINATION_ORDERS = ("pseudo_tree", "min_fill")


def min_fill_order(
    domains: Dict[str, Sequence],
    scopes: Sequence[Sequence[str]],
    deadline: Optional[float] = None,
    last_block: Optional[set] = None,
) -> List[str]:
    """Greedy min-fill elimination order over the primal graph: at
    each step eliminate the variable whose removal adds the fewest
    fill edges among its remaining neighbors (ties: smallest
    neighborhood, then name — deterministic).  The classic width
    heuristic; on loopy graphs it is often far narrower than the DFS
    pseudo-tree order.

    Fill counts are cached and invalidated INCREMENTALLY — a count
    changes only for the eliminated variable's neighbors and for the
    common neighbors of each added fill edge — so the selection loop
    is O(n) per step instead of recomputing every count
    (recompute-everything measured ~20s at just 800 vars; this stays
    sub-second at that size).  Dense graphs can still be slow —
    ``deadline`` (a ``perf_counter`` timestamp) raises
    ``TimeoutError`` between steps so an ``infer(timeout=...)``
    cannot hang inside plan construction.

    ``last_block`` constrains the order into TWO BLOCKS: variables in
    it are only eligible once every other variable is eliminated —
    the marginal-MAP constraint (sum variables first, max variables
    last), applied inside the greedy selection so the heuristic still
    minimizes fill within each block."""
    adj: Dict[str, set] = {v: set() for v in domains}
    for scope in scopes:
        sc = [v for v in scope if v in adj]
        for a in sc:
            for b in sc:
                if a != b:
                    adj[a].add(b)
    remaining = {v: set(ns) for v, ns in adj.items()}
    order: List[str] = []
    cache: Dict[str, int] = {}

    def fill_count(v: str) -> int:
        ns = list(remaining[v])
        cnt = 0
        for i in range(len(ns)):
            ri = remaining[ns[i]]
            for j in range(i + 1, len(ns)):
                if ns[j] not in ri:
                    cnt += 1
        return cnt

    while remaining:
        if deadline is not None and time.perf_counter() > deadline:
            raise TimeoutError(
                f"min_fill elimination order timed out with "
                f"{len(remaining)} of {len(adj)} variables left"
            )
        if last_block:
            pool = [x for x in remaining if x not in last_block]
            if not pool:  # only the last block is left
                pool = list(remaining)
        else:
            pool = remaining
        best_key = None
        best = None
        for x in pool:
            c = cache.get(x)
            if c is None:
                c = cache[x] = fill_count(x)
            key = (c, len(remaining[x]), x)
            if best_key is None or key < best_key:
                best_key, best = key, x
        v = best
        order.append(v)
        ns = list(remaining[v])
        # invalidation set: v's neighbors (their neighborhoods change)
        # plus, per added fill edge (a, b), every common neighbor of
        # a and b (the pair stops counting as missing for them)
        dirty = set(ns)
        for i in range(len(ns)):
            for j in range(i + 1, len(ns)):
                a, b = ns[i], ns[j]
                if b not in remaining[a]:
                    remaining[a].add(b)
                    remaining[b].add(a)
                    dirty |= remaining[a] & remaining[b]
        for n in ns:
            remaining[n].discard(v)
        del remaining[v]
        cache.pop(v, None)
        for x in dirty:
            cache.pop(x, None)
    return order


class ContractionPlan:
    """One instance's bucket tree: the elimination order, per-variable
    buckets of owned ENERGY tables (f64, minimization convention —
    semiring transforms apply at sweep time so one plan serves every
    query), and the parent/children structure a dims-only simulation
    of the elimination derives.  ``const_energy`` accumulates
    fully-external (scope-free after slicing) parts — invisible to
    arg queries, a constant factor of ``Z``.

    ``wbuckets`` holds LOG-WEIGHT parts (already in kernel domain —
    no ``beta`` scaling, no cost contribution): the stochastic
    external distributions of an expectation query.  ``node_semiring``
    (marginal MAP) overrides the ⊕ per node — ``"log_sum_exp"`` for
    the summed block, ``"max_sum"`` for the ``max_vars`` block the
    two-block elimination order puts last."""

    __slots__ = (
        "domains", "order", "pos", "buckets", "parent", "children",
        "roots", "height", "const_energy", "order_name", "wbuckets",
        "node_semiring", "max_vars",
    )

    def __init__(
        self, domains, order, buckets, const_energy, order_name,
        wbuckets=None, node_semiring=None, max_vars=None,
    ):
        self.domains = domains
        self.order = order
        self.pos = {v: i for i, v in enumerate(order)}
        self.buckets = buckets
        self.wbuckets = (
            {v: [] for v in order} if wbuckets is None else wbuckets
        )
        self.node_semiring = node_semiring
        self.max_vars = max_vars
        self.const_energy = const_energy
        self.order_name = order_name
        # dims-only elimination simulation: the message scope of v is
        # the union of its bucket dims and its children's message
        # dims, minus v; its parent is the earliest-ELIMINATED
        # variable of that scope (the bucket the message lands in)
        self.parent: Dict[str, Optional[str]] = {}
        self.children: Dict[str, List[str]] = {v: [] for v in order}
        self.roots: List[str] = []
        msg_dims: Dict[str, set] = {}
        for v in order:
            dims: set = set()
            for scope, _ in buckets[v]:
                dims.update(scope)
            for scope, _ in self.wbuckets[v]:
                dims.update(scope)
            for c in self.children[v]:
                dims.update(msg_dims[c])
            dims.discard(v)
            msg_dims[v] = dims
            if dims:
                p = min(dims, key=self.pos.__getitem__)
                self.parent[v] = p
                self.children[p].append(v)
            else:
                self.parent[v] = None
                self.roots.append(v)
        # wave index = node HEIGHT (children resolve strictly earlier
        # waves; every leaf lands in wave 0 — the ragged-tree batching
        # property the level-sync DPOP sweep established)
        self.height: Dict[str, int] = {}
        for v in order:  # children precede parents in elim order
            self.height[v] = 1 + max(
                (self.height[c] for c in self.children[v]), default=-1
            )

    def sep_of(self, name: str, child_seps: Dict[str, List[str]]):
        """Separator of ``name``: dims of its own parts plus its
        children's separators, minus itself — sorted root-most first
        (descending elimination position), the axis convention every
        stored message uses."""
        dims: set = set()
        for scope, _ in self.buckets[name]:
            dims.update(scope)
        for scope, _ in self.wbuckets[name]:
            dims.update(scope)
        for c in self.children[name]:
            dims.update(child_seps[c])
        dims.discard(name)
        return sorted(dims, key=lambda v: -self.pos[v])

    def width(self) -> int:
        """Induced width: the largest separator the sweep will build
        (dims-only; cheap enough to report up front)."""
        seps: Dict[str, List[str]] = {}
        w = 0
        for v in self.order:
            seps[v] = self.sep_of(v, seps)
            w = max(w, len(seps[v]))
        return w


def build_plan(
    dcop,
    order: str = "pseudo_tree",
    deadline: Optional[float] = None,
    max_vars: Optional[Sequence[str]] = None,
    external_dists: Optional[Mapping[str, Mapping[Any, float]]] = None,
    provenance: Optional[dict] = None,
) -> ContractionPlan:
    """Build the contraction plan for one DCOP under an elimination
    order heuristic.  ``deadline`` (a ``perf_counter`` timestamp)
    bounds the ``min_fill`` search — it raises ``TimeoutError``, which
    :func:`run_infer_many` turns into ``status="timeout"`` results.

    Tables are extracted ONCE as f64 energies (sign-folded for
    ``objective: max`` problems, external variables sliced out,
    variable value-costs folded in as unary parts — the same
    preparation DPOP's ``_prepare_instance`` performs); each part is
    owned by its earliest-eliminated scope variable, which under the
    ``pseudo_tree`` order reproduces DPOP's deepest-variable
    ownership exactly.

    ``max_vars`` (marginal MAP) constrains BOTH heuristics to a
    two-block order — every summed variable eliminated before every
    maximized one, so the max stays outside the sum — and annotates
    the plan with a per-node ⊕ (``node_semiring``).  ``external_dists``
    (expectation) maps external-variable names to ``{value: prob}``
    distributions: those externals are NOT sliced to their pinned
    value but join the plan as summed variables carrying a unary
    log-probability part (``wbuckets``).

    ``provenance`` (optional out-param) records, per EXTERNAL-scoped
    constraint name, where its sliced table landed: ``(owner, index)``
    into ``plan.buckets[owner]``, or ``("const",)`` when the slice
    folded into ``const_energy`` — the hook
    :class:`~pydcop_tpu.engine.memo.InferSession` uses to re-tabulate
    only the constraints a ``set_values`` delta touched."""
    if order not in ELIMINATION_ORDERS:
        raise ValueError(
            f"unknown elimination order {order!r} (expected one of "
            f"{ELIMINATION_ORDERS})"
        )
    sign = -1.0 if dcop.objective == "max" else 1.0
    dists = dict(external_dists) if external_dists else {}
    unknown_ext = set(dists) - set(dcop.external_variables)
    if unknown_ext:
        raise ValueError(
            f"external_dists names {sorted(unknown_ext)} — not "
            "external variables of this dcop (externals: "
            f"{sorted(dcop.external_variables)})"
        )
    ext_values = {
        n: ev.value
        for n, ev in dcop.external_variables.items()
        if n not in dists
    }
    domains: Dict[str, list] = {
        v.name: list(v.domain.values) for v in dcop.variables.values()
    }
    wparts: List[Tuple[List[str], np.ndarray]] = []
    for n, dist in dists.items():
        ev = dcop.external_variables[n]
        dom = list(ev.domain.values)
        # a JSON-shipped dist (the CLI / wire path) carries string
        # keys — match domain values with a str() fallback
        dom_keys = set(dom) | {str(x) for x in dom}
        bad = sorted(str(x) for x in set(dist) - dom_keys)
        if bad:
            raise ValueError(
                f"external_dists[{n!r}] names values {bad} outside "
                f"the external's domain {dom}"
            )
        probs = np.array(
            [
                float(dist.get(x, dist.get(str(x), 0.0)))
                for x in dom
            ],
            dtype=np.float64,
        )
        if (probs < 0).any() or probs.sum() <= 0:
            raise ValueError(
                f"external_dists[{n!r}] must be non-negative with "
                "positive total mass"
            )
        probs = probs / probs.sum()
        with np.errstate(divide="ignore"):  # p=0 -> log -inf: the
            # value simply carries zero weight
            wparts.append(([n], np.log(probs)))
        domains[n] = dom

    if max_vars is not None:
        mv = set(max_vars)
        unknown_mv = mv - set(domains)
        if unknown_mv:
            raise ValueError(
                f"map_vars names {sorted(unknown_mv)} — not "
                "variables of this dcop"
            )
        if not mv:
            raise ValueError(
                "map_vars is empty — with nothing maximized the "
                "query is 'log_z'"
            )
    else:
        mv = None

    parts: List[Tuple[List[str], np.ndarray, Optional[str]]] = []
    const_energy = 0.0
    for v in dcop.variables.values():
        if v.has_cost:
            costs = np.array(
                [sign * v.cost_for_val(x) for x in v.domain.values],
                dtype=np.float64,
            )
            parts.append(([v.name], costs, None))
    for c in dcop.constraints.values():
        cname = c.name
        scope_ext = [n for n in c.scope_names if n in ext_values]
        if scope_ext:
            c = c.slice({n: ext_values[n] for n in scope_ext})
        scope = list(c.scope_names)
        m = c.as_matrix()
        table = sign * np.asarray(m.matrix, dtype=np.float64)
        if not scope:
            const_energy += float(table)
            if provenance is not None and scope_ext:
                provenance[cname] = ("const",)
            continue
        parts.append((scope, table, cname if scope_ext else None))

    if order == "min_fill":
        elim = min_fill_order(
            domains,
            [s for s, _, _ in parts] + [s for s, _ in wparts],
            deadline=deadline,
            last_block=mv,
        )
    else:
        from pydcop_tpu.graphs import pseudotree as _pt

        graph = _pt.build_computation_graph(dcop)
        names = [
            n
            for root in graph.roots
            for n in graph.depth_first_order(root)
        ]
        # reverse DFS pre-order: children strictly before parents —
        # the elimination order whose bucket tree IS the pseudo-tree.
        # Distribution-carrying externals are summed leaves: eliminate
        # them first (they hang off whatever constraints scope them)
        elim = sorted(dists) + list(reversed(names))
        if mv is not None:
            # two-block constraint, DFS order preserved within each
            # block: sum variables first, max variables last
            elim = [v for v in elim if v not in mv] + [
                v for v in elim if v in mv
            ]

    node_semiring = None
    if mv is not None:
        node_semiring = {
            v: ("max_sum" if v in mv else "log_sum_exp") for v in elim
        }

    pos = {v: i for i, v in enumerate(elim)}
    buckets: Dict[str, List[Tuple[List[str], np.ndarray]]] = {
        v: [] for v in elim
    }
    wbuckets: Dict[str, List[Tuple[List[str], np.ndarray]]] = {
        v: [] for v in elim
    }
    for scope, table, cname in parts:
        owner = min(scope, key=pos.__getitem__)
        if provenance is not None and cname is not None:
            provenance[cname] = (owner, len(buckets[owner]))
        buckets[owner].append((scope, table))
    for scope, table in wparts:
        owner = min(scope, key=pos.__getitem__)
        wbuckets[owner].append((scope, table))
    return ContractionPlan(
        domains, elim, buckets, const_energy, order,
        wbuckets=wbuckets, node_semiring=node_semiring,
        max_vars=(sorted(mv) if mv is not None else None),
    )


# -- the merged contraction sweep ---------------------------------------


def _align(table, dims, target):
    """Jax-free broadcast alignment (the DPOP join primitive —
    ``algorithms/_tables.align_table``, imported lazily to keep ops/
    free of an algorithms/ import at module load).  A part with one
    more axis than named ``dims`` is a STRUCTURED-cell part: the
    named axes align as usual and the trailing cell axis rides
    along."""
    from pydcop_tpu.algorithms._tables import align_table

    table = np.asarray(table)
    if table.ndim == len(dims) + 1:
        order = [d for d in target if d in dims]
        t = np.transpose(
            table,
            [list(dims).index(d) for d in order] + [len(dims)],
        )
        shape = [
            t.shape[order.index(d)] if d in dims else 1
            for d in target
        ]
        return t.reshape(shape + [table.shape[-1]])
    return align_table(table, dims, target)


def _finite_amax(a) -> float:
    """max |finite entries| — the message-magnitude scale structured
    cells use (+inf slot padding / -inf zero weights are structural,
    not magnitudes the rounding analysis should see)."""
    if isinstance(a, SparseTable):
        # packed fast path: absent cells are the exact ⊕-identity,
        # never a magnitude the rounding analysis should see
        return a.finite_amax()
    a = np.asarray(a)
    if a.size == 0:
        return 0.0
    m = np.abs(a[np.isfinite(a)])
    return float(m.max()) if m.size else 0.0


def _pack_parts(parts, table_dtype, met=None):
    """Pack one dispatch row's aligned+padded float parts at the
    storage dtype: f32 passes through, bf16 casts (one extra
    rounding, covered by the widened certificates), int8 quantizes
    each part and PREPENDS the per-part scale/offset f32 vectors the
    kernel's dequant wrap consumes (``semiring.int8_requant`` counts
    the part packs)."""
    if table_dtype == "f32":
        return parts
    if table_dtype == "bf16":
        dt = _np_table_dtype("bf16")
        return [np.asarray(p, dtype=dt) for p in parts]
    scales = np.zeros(len(parts), dtype=np.float32)
    offsets = np.zeros(len(parts), dtype=np.float32)
    qs = []
    for i, p in enumerate(parts):
        q, s, o = quantize_table_int8(p)
        qs.append(q)
        scales[i] = s
        offsets[i] = o
    if met is not None and met.enabled:
        met.inc("semiring.int8_requant", len(parts))
    return [scales, offsets] + qs


class _Sweep:
    """Per-call state of one merged upward sweep (K instances)."""

    __slots__ = (
        "msgs", "args", "root_total", "total_shift", "cells",
        "device_nodes", "host_nodes", "dispatches", "err", "seps",
        "root_cells",
    )

    def __init__(self, K: int):
        # msgs[k][name] = (sep, message f64, max|message|)
        self.msgs: List[Dict[str, tuple]] = [{} for _ in range(K)]
        self.args: List[Dict[str, tuple]] = [{} for _ in range(K)]
        self.seps: List[Dict[str, List[str]]] = [{} for _ in range(K)]
        self.root_total = [0.0] * K
        # structured-cell kinds keep per-root CELLS (the kbest value
        # phase re-merges them with provenance; expectation pairs
        # ⊗-combine at result assembly); scalar sweeps fold into
        # root_total as before
        self.root_cells: List[Dict[str, np.ndarray]] = [
            {} for _ in range(K)
        ]
        self.total_shift = [0.0] * K
        self.cells = [0] * K
        self.device_nodes = [0] * K
        self.host_nodes = [0] * K
        self.dispatches = [0] * K
        self.err = [
            {} for _ in range(K)
        ]  # name -> accumulated log-domain error bound


def contract_sweep(
    plans: Sequence[ContractionPlan],
    sr: Semiring,
    *,
    beta: float = 1.0,
    device_min_cells: Optional[int] = 1 << 14,
    pad: PadPolicy = NO_PADDING,
    level_sync: bool = True,
    tol: float = 1e-6,
    max_table_size: int = 1 << 26,
    want_args: bool = False,
    t0: Optional[float] = None,
    timeout: Optional[float] = None,
    on_oom: str = "host",
    bnb: str = "off",
    memos: Optional[Sequence[Any]] = None,
    table_dtype: str = "f32",
    table_format: str = "dense",
) -> Optional[_Sweep]:
    """Merged bottom-up contraction sweep over K instances.

    Wave ``w`` holds every instance's height-``w`` nodes;
    device-eligible contractions bucket by level-pack key ACROSS
    instances (``ops/padding.py:util_level_key``) and run as ONE
    vmapped :func:`contraction_kernel` dispatch per bucket under the
    ambient supervisor — the level-synchronous DPOP machinery with
    the ``⊕`` swapped.  Tables enter the sweep in KERNEL domain:
    energies for ``min_sum``, log-weights ``-beta·E`` otherwise.

    Per ``⊕``: idempotent contractions are certified + host-repaired
    (exact, ``want_args`` retains the arg tables for a MAP value
    phase); logsumexp contractions carry accumulated error bounds
    and fall back to host f64 when a device pass would push the
    bound past ``tol`` (``semiring.logsumexp_repairs``).  Returns
    the sweep state, or None on timeout.  Counters:
    ``semiring.contractions`` per node, ``semiring.dispatches`` per
    device dispatch.

    ``on_oom`` picks the bottom rung of the device-OOM ladder: a
    level stack that OOMs always degrades to per-node dispatches;
    a PER-NODE OOM then either redoes that node on host f64
    (``"host"``, the default) or raises the ``DeviceOOMError``
    (``"raise"`` — the budgeted sweeps of ``ops/membound.py``, which
    answer it by RE-PLANNING at a tighter ``max_util_bytes`` before
    abandoning the device).

    ``bnb`` enables the two-pass branch-and-bound pruned kernels
    (module comment above ``BNB_MODES``): ``"on"`` prunes every
    device dispatch, ``"auto"`` only those whose per-row padded
    table clears ``BNB_AUTO_MIN_CELLS`` (small factors keep the
    single-pass kernel, ``semiring.bnb_skipped_small``), ``"off"``
    is the historical sweep.  Counters ``semiring.bnb_passes`` /
    ``semiring.bnb_pruned_cells`` and a per-dispatch-group
    ``semiring.bnb`` trace event make the pruning observable.

    ``memos`` (one ``engine.memo.SweepMemoView`` or None per
    instance) enables subtree-fingerprint message reuse: a node
    whose fingerprint is unchanged reinstalls its stored message —
    separator, shifted values, magnitude, cumulative error, args —
    and is skipped entirely; re-contracted nodes re-store.  Memoized
    instances that build a PRUNING context run unmemoized instead
    (a budget-pruned message depends on the global incumbent, not
    just the subtree) — sessions wanting memoized deltas run with
    ``bnb='off'`` or below the auto threshold.

    ``table_dtype`` packs every device part at the requested storage
    precision (``docs/performance.md``, "Mixed-precision table
    packs") with the accumulator kept f32.  Correctness rides the
    SAME machinery re-scaled per precision: idempotent/kbest
    certificates widen to the storage roundoff (plus the int8
    quantization bound) and repair exactly as at f32 — per-cell
    host-f64 gathers at the certified arg, so results stay
    bit-identical to the f32 sweep; mass ⊕ nodes whose widened local
    bound would blow ``tol`` DEMOTE to an f32 dispatch first
    (``semiring.precision_repairs``) and only then fall back to host
    f64 — the bf16 → f32 → f64 repair ladder.  The dtype joins the
    level-pack bucket key (demoted nodes land in f32 buckets, never
    mixing kernels) and ``semiring.int8_requant`` counts int8 part
    packs.

    ``table_format="sparse"`` COO-packs qualifying tables
    (``ops/sparse.py``): scalar-⊕ own parts and outgoing messages
    whose non-identity fraction clears the density threshold pack as
    sorted feasible-tuple indices + values (``semiring.
    sparse_packs``), and a node holding packed parts contracts
    through the gather/segment-reduce kernels over the candidate
    list — the intersection of the packed supports
    (``semiring.sparse_nodes``; an intersection too dense to pay
    falls back to the dense kernels, ``semiring.sparse_fallbacks``).
    The format joins the bucket key, so sparse nodes batch into
    their own pow-2 candidate buckets and never mix executables with
    dense ones.  Exactness is unchanged: absent tuples are the
    ⊕-identity, so idempotent results stay bit-identical (same
    certificates, same host-f64 re-evaluation — now a packed-lookup
    gather), mass queries fold any truncated-mass term
    (:attr:`~pydcop_tpu.ops.sparse.SparseTable.trunc`) into the
    error ledger, and bnb budgets prune the candidate list's segment
    reduce directly.
    """
    from pydcop_tpu.engine.supervisor import (
        DeviceOOMError,
        get_supervisor,
    )
    from pydcop_tpu.telemetry import get_metrics, get_tracer

    met = get_metrics()
    tracer = get_tracer()
    sup = get_supervisor()
    t0 = time.perf_counter() if t0 is None else t0
    K = len(plans)
    sw = _Sweep(K)
    _key_memo: Dict[tuple, tuple] = {}

    bnb = as_bnb(bnb, "off")
    call_dt = as_table_dtype(table_dtype)
    # packing pays only where the device kernels run — an all-host
    # sweep joins in exact f64 and would just densify the packs back
    fmt_sparse = (
        as_table_format(table_format) == "sparse"
        and device_min_cells is not None
    )
    ctxs: List[Optional[_BnbContext]] = [None] * K
    if bnb != "off" and device_min_cells is not None:
        for k, p in enumerate(plans):
            if (
                bnb == "auto"
                and max_padded_join_cells(p, pad) * sr.cell_width
                < BNB_AUTO_MIN_CELLS
            ):
                # no dispatch of this instance can ever clear the
                # auto threshold — skip the (greedy incumbent +
                # extrema) context build entirely, recorded once as
                # a call-level skip
                if met.enabled:
                    met.inc("semiring.bnb_skipped_small")
                continue
            ctxs[k] = plan_bnb_context(
                p, sr, beta, tol, table_dtype=call_dt
            )
    bnb_call = any(c is not None for c in ctxs)
    if memos is not None:
        # docstring contract: pruning and memoization are mutually
        # exclusive per instance — pruned messages aren't pure
        # functions of the subtree
        memos = [
            None if ctxs[k] is not None else m
            for k, m in enumerate(memos)
        ]

    def table_in(tbl: np.ndarray) -> np.ndarray:
        if sr.kind == "kbest" or (
            sr.idempotent and not sr.maximize
        ):
            return tbl  # cost-ordered kinds (min/+, top-K): raw
            # energies (beta rescales argmins by nothing and the
            # magnitudes stay familiar)
        return (-beta) * tbl

    def finish(sr_n, k, name, plan, sep, u, arg):
        if met.enabled:
            met.inc("semiring.contractions")
            if sr_n.kind == "kbest":
                met.inc("semiring.kbest_merges")
        if want_args and arg is not None:
            sw.args[k][name] = (sep, arg)
        rootval = None
        if plan.parent[name] is None:
            if sr_n.cell_width > 1:
                # structured kinds keep the root CELL (kbest re-merges
                # roots with provenance; expectation pairs ⊗-combine
                # at result assembly)
                cell = np.asarray(u, dtype=np.float64)
                sw.root_cells[k][name] = cell
                rootval = ("cell", cell)
            else:
                # root: the reduce is a scalar — fold it into the
                # instance aggregate (plus every shift already applied)
                rootval = ("total", float(u))
                sw.root_total[k] += float(u)
            if ctxs[k] is not None:
                ctxs[k].record_shift(name, 0.0, plan.children[name])
        else:
            shift = sr_n.shift_of(u)
            if not np.isfinite(shift):
                shift = 0.0  # an all--inf message normalizes to itself
            u = sr_n.apply_shift(u, shift)
            sw.total_shift[k] += shift
            if ctxs[k] is not None:
                ctxs[k].record_shift(
                    name, shift, plan.children[name]
                )
            # finite-masked magnitude: pruned rows carry the
            # ⊕-identity and hard constraints carry ±inf — both are
            # exact values, not rounding scales
            mag = _finite_amax(u)
            if (
                fmt_sparse
                and sr_n.cell_width == 1
                and isinstance(u, np.ndarray)
            ):
                # a mostly-identity message (hard caps, bnb pruning)
                # re-packs before it feeds the parent — absent cells
                # stay the exact ⊕-identity, so nothing changes but
                # the bytes (``.size`` keeps the dense cell count for
                # the util metrics)
                ps = pack_table(u, sr_n.plus_identity)
                if ps is not None:
                    u = ps
                    if met.enabled:
                        met.inc("semiring.sparse_packs")
            sw.msgs[k][name] = (sep, u, mag)
            sw.cells[k] += u.size
        memo = memos[k] if memos is not None else None
        if memo is not None:
            # every non-idempotent path sets sw.err[name] BEFORE
            # finish, so the stored error is the node's CUMULATIVE
            # subtree bound — a memo hit re-accounts exactly what the
            # cold solve accounted, and only dirty-path nodes add new
            # error on a warm delta
            if rootval is not None:
                memo.store(
                    name,
                    (
                        sep, None, 0.0, 0.0,
                        sw.args[k].get(name),
                        sw.err[k].get(name, 0.0), True, rootval,
                    ),
                )
            else:
                mu = (
                    u.copy()
                    if isinstance(u, np.ndarray)
                    and u.base is not None
                    else u  # owned arrays and immutable packs as-is
                )
                memo.store(
                    name,
                    (
                        sep, mu, mag, shift,
                        sw.args[k].get(name),
                        sw.err[k].get(name, 0.0), False, None,
                    ),
                )

    def host_contract(
        sr_n, k, name, plan, sep, target, shape, parts, err_in
    ):
        if sr_n.kind == "kbest":
            u, own, provs = _kbest_host(
                parts, target, shape, sr_n.cell_width
            )
            sw.host_nodes[k] += 1
            arg = (own, dict(zip(plan.children[name], provs)))
            finish(sr_n, k, name, plan, sep, u, arg)
            return
        if sr_n.kind == "expectation":
            u = _expect_host(parts, target, shape)
            sw.host_nodes[k] += 1
            scale = max(
                sum(_finite_amax(t) for _, t in parts), 1.0
            )
            sw.err[k][name] = err_in + _EPS64 * (
                (len(parts) + 1) * scale + shape[-1] + 2
            )
            finish(sr_n, k, name, plan, sep, u, None)
            return
        j = np.zeros(shape, dtype=np.float64)
        for dims, table in parts:
            j = j + _align(table, dims, target)
        arg = (
            sr_n.arg_reduce(j, axis=-1)
            if want_args and sr_n.idempotent
            else None
        )
        u = sr_n.reduce(j, axis=-1)
        sw.host_nodes[k] += 1
        if not sr_n.idempotent:
            # f64 rounding of the same computation: negligible, but
            # accounted so the reported bound is never an understatement
            scale = max(
                sum(
                    float(np.max(np.abs(t), initial=0.0))
                    for _, t in parts
                ),
                1.0,
            )
            sw.err[k][name] = err_in + _EPS64 * (
                (len(parts) + 1) * scale + shape[-1] + 2
            )
        elif err_in:
            # a mixed sweep's max node: the sum block's accumulated
            # bounds flow through to the root report unchanged
            sw.err[k][name] = err_in
        finish(sr_n, k, name, plan, sep, u, arg)

    waves: List[List[Tuple[int, str]]] = []
    for k, plan in enumerate(plans):
        for n in plan.order:
            w = plan.height[n]
            while len(waves) <= w:
                waves.append([])
            waves[w].append((k, n))

    mixed = any(p.node_semiring for p in plans)
    t_sweep = time.perf_counter()
    for wave in waves:
        buckets: Dict[tuple, list] = {}
        order: List[tuple] = []
        wave_srs: set = set()
        for k, name in wave:
            if timeout is not None and time.perf_counter() - t0 > timeout:
                return None
            plan = plans[k]
            domains = plan.domains
            # per-node ⊕: a mixed (marginal-MAP) plan sums its first
            # block and maximizes its last; everything else runs the
            # sweep's one semiring
            sr_n = (
                get_semiring(plan.node_semiring[name])
                if plan.node_semiring is not None
                else sr
            )
            if mixed:
                wave_srs.add(sr_n.name)
            cw = sr_n.cell_width
            memo = memos[k] if memos is not None else None
            if memo is not None:
                payload = memo.lookup(name)
                if payload is not None:
                    (msep, mu, mmag, mshift, margp, merr, mroot,
                     mrootval) = payload
                    # an arg-consuming query can't hit an entry
                    # stored without args (a prior solve with a
                    # different query); everything else reinstalls
                    if not (want_args and margp is None):
                        sw.seps[k][name] = msep
                        if margp is not None and want_args:
                            sw.args[k][name] = margp
                        if merr:
                            sw.err[k][name] = merr
                        if mroot:
                            if mrootval[0] == "cell":
                                sw.root_cells[k][name] = mrootval[1]
                            else:
                                sw.root_total[k] += mrootval[1]
                        else:
                            sw.msgs[k][name] = (msep, mu, mmag)
                            sw.total_shift[k] += mshift
                        memo.mark_hit()
                        continue
            sep = plan.sep_of(name, sw.seps[k])
            sw.seps[k][name] = sep
            target = sep + [name]
            shape = [len(domains[d]) for d in target]
            size = 1
            for s in shape:
                size *= s
            if size * cw > max_table_size:
                raise ValueError(
                    f"contraction table for {name!r} needs "
                    f"{size * cw} cells (separator {sep}, cell width "
                    f"{cw}); exceeds "
                    f"max_table_size={max_table_size}.  The induced "
                    f"width under order={plan.order_name!r} is too "
                    "large — try order='min_fill', or an approximate "
                    "(message-passing) algorithm."
                )
            # own parts PRE-SUMMED into one exact f64 part (the DPOP
            # trick: bitwise the same join, collapses leaf kernel
            # signatures, tightens the f32 bound), then children
            own_parts = plan.buckets[name]
            own_w = plan.wbuckets[name]
            parts: List[Tuple[List[str], np.ndarray]] = []
            parts_max = 0.0
            err_in = 0.0
            if own_w and sr_n.kind != "expectation":
                # only the expectation pair carries a weight plane;
                # a selecting ⊕ cannot weight assignments and the
                # scalar sums would need the pair's r-plane anyway
                raise ValueError(
                    "external distributions weight assignments — "
                    f"the {sr_n.name!r} ⊕ cannot carry them (use "
                    "query='expectation')"
                )
            if sr_n.kind == "expectation":
                if own_parts or own_w:
                    odims: List[str] = []
                    for dims, _ in own_parts:
                        odims.extend(
                            d for d in dims if d not in odims
                        )
                    for dims, _ in own_w:
                        odims.extend(
                            d for d in dims if d not in odims
                        )
                    oshape = [len(domains[d]) for d in odims]
                    e = np.zeros(oshape, dtype=np.float64)
                    for dims, table in own_parts:
                        e = e + _align(table, dims, odims)
                    lw = (-beta) * e
                    for dims, table in own_w:
                        lw = lw + _align(table, dims, odims)
                    o = np.stack([lw, e], axis=-1)
                    parts.append((odims, o))
                    parts_max += _finite_amax(o)
            elif own_parts:
                odims = []
                for dims, _ in own_parts:
                    odims.extend(d for d in dims if d not in odims)
                if len(own_parts) > 1:
                    o = np.zeros(
                        [len(domains[d]) for d in odims],
                        dtype=np.float64,
                    )
                    for dims, table in own_parts:
                        o = o + _align(
                            table_in(table), dims, odims
                        )
                else:
                    o = np.asarray(
                        table_in(own_parts[0][1]), dtype=np.float64
                    )
                    odims = list(own_parts[0][0])
                if (
                    fmt_sparse
                    and sr_n.cell_width == 1
                    and size * cw >= device_min_cells
                ):
                    # COO-pack a qualifying own part (hard caps make
                    # most cells the ⊕-identity): the node can then
                    # contract over the candidate list instead of
                    # the dense box
                    ps = pack_table(o, sr_n.plus_identity)
                    if ps is not None:
                        o = ps
                        if met.enabled:
                            met.inc("semiring.sparse_packs")
                parts.append((odims, o))
                # finite-masked: ±inf hard-constraint entries are
                # EXACT in f32 (no rounding to bound), and an inf
                # scale would force every hard-capped instance off
                # the device
                parts_max += _finite_amax(o)
            for c in plan.children[name]:
                cdims, ctable, cmax = sw.msgs[k][c]
                parts.append((cdims, ctable))
                parts_max += cmax
                err_in += sw.err[k].get(c, 0.0)
            if not parts:
                # an isolated, cost-free variable: its contraction is
                # the reduce of a ⊗-identity table over its own domain
                if sr_n.kind == "expectation":
                    parts.append(([name], np.zeros((shape[-1], 2))))
                else:
                    parts.append(([name], np.zeros(shape[-1])))

            dmc = device_min_cells
            use_device = dmc is not None and size * cw >= dmc
            node_dt = call_dt
            local = 0.0
            if use_device and sr_n.error_bounded:
                # error-budget gate: a device pass whose accumulated
                # bound would exceed tol first DEMOTES to f32 storage
                # (the precision-repair rung of the ladder), then
                # runs on host f64 — the logsumexp analogue of the
                # exactness certificate (there is no arg to repair;
                # the value IS the answer)
                scale = max(parts_max, 1.0)

                def _local_err(dt):
                    q = (
                        int8_quant_bound(parts_max)
                        if dt == "int8"
                        else 0.0
                    )
                    return table_dtype_eps(dt) * (
                        (len(parts) + 1) * scale + shape[-1] + 2
                    ) + q

                local = _local_err(node_dt)
                if err_in + local > tol and node_dt != "f32":
                    node_dt = "f32"
                    local = _local_err(node_dt)
                    if met.enabled:
                        met.inc("semiring.precision_repairs")
                if err_in + local > tol:
                    use_device = False
                    if met.enabled:
                        met.inc("semiring.logsumexp_repairs")
            if not use_device:
                host_contract(
                    sr_n, k, name, plan, sep, target, shape, parts,
                    err_in,
                )
                continue
            # per-row BnB budget (host f64, f32 slack folded in).
            # Mass semirings additionally gate on the ledger: when
            # this node's worst-case pruned mass (tol_node by
            # construction) would push the accumulated bound past
            # tol, the dispatch stays device but UNPRUNED — the same
            # tol discipline that forces host-f64 above.
            ctx = ctxs[k]
            shiftc = 0.0
            budget = None
            if ctx is not None:
                shiftc = ctx.shift_under(plan.children[name])
                # `local` is the node's (post-demotion) storage-dtype
                # rounding bound computed by the gate above
                if not sr_n.error_bounded or (
                    err_in + local + ctx.tol_node <= tol
                ):
                    n_rows = size // max(shape[-1], 1)
                    budget = ctx.budget(
                        name, shiftc, len(parts), parts_max,
                        shape[-1], n_rows,
                    )

            if fmt_sparse and sr_n.kind == "scalar":
                sprep = sparse_node_prep(
                    parts, target, shape, sr_n.plus_identity
                )
                if sprep is not None:
                    # candidate-list join: bucket by the pow-2
                    # candidate geometry — the sparse sibling of the
                    # level-pack key, so the format never mixes
                    # executables with the dense buckets
                    if met.enabled:
                        met.inc("semiring.sparse_nodes")
                    sp_bnb = (
                        bnb_call
                        and budget is not None
                        and (
                            bnb == "on"
                            or size * cw >= BNB_AUTO_MIN_CELLS
                        )
                    )
                    key = (
                        "sparse", sr_n.name, node_dt, sprep.key,
                        sp_bnb,
                    )
                    if key not in buckets:
                        buckets[key] = []
                        order.append(key)
                    buckets[key].append(
                        (
                            (k, name, sep, target, shape, parts,
                             parts_max, err_in + sprep.trunc,
                             budget, shiftc, node_dt),
                            sprep,
                        )
                    )
                    continue
                if met.enabled and any(
                    isinstance(t, SparseTable) for _, t in parts
                ):
                    # packed parts present but the intersection
                    # would not pay: the dense path densifies them
                    # back (exact either way)
                    met.inc("semiring.sparse_fallbacks")
            aligned = [
                _align(t, dims, target) for dims, t in parts
            ]
            raw = (
                sr_n.name, node_dt, tuple(shape),
                tuple(a.shape for a in aligned),
            )
            key = _key_memo.get(raw)
            if key is None:
                # the level-pack key is shape-only and shared; the ⊕
                # AND the storage dtype join the BUCKET key so a
                # mixed wave dispatches one block per (semiring,
                # dtype) without ever mixing kernels — a tol-demoted
                # node lands in the f32 bucket, not its call-dtype one
                key = _key_memo[raw] = (
                    sr_n.name, node_dt,
                    util_level_key(raw[2], raw[3], pad),
                )
            if key not in buckets:
                buckets[key] = []
                order.append(key)
            buckets[key].append(
                (
                    (k, name, sep, target, shape, parts,
                     parts_max, err_in, budget, shiftc, node_dt),
                    aligned,
                )
            )

        if mixed and len(wave_srs) > 1 and met.enabled:
            # one per wave that contracted nodes from more than one
            # ⊕ block of a mixed-elimination (marginal-MAP) sweep
            met.inc("semiring.mixed_blocks")

        for key in order:
            entries = buckets[key]
            if timeout is not None and time.perf_counter() - t0 > timeout:
                return None
            if key[0] == "sparse":
                sr_b = get_semiring(key[1])
                ok = _dispatch_sparse(
                    sw, sr_b, entries, pad, tol, want_args, finish,
                    sup, met, plans, use_bnb=key[4], ctxs=ctxs,
                    tracer=tracer, memos=memos,
                    table_dtype=key[2], on_oom=on_oom,
                )
                if not ok:
                    # device OOM on the candidate dispatch: redo the
                    # bucket's nodes on host f64 (exact — _align
                    # densifies the packs back)
                    if met.enabled:
                        met.inc("engine.oom_splits")
                    for item, _sp in entries:
                        host_contract(
                            sr_b, item[0], item[1], plans[item[0]],
                            item[2], item[3], item[4], item[5],
                            item[7],
                        )
                continue
            sr_b = get_semiring(key[0])
            # ghost guard over padded own-axis cells is the ⊕-identity:
            # +inf keeps a MIN arg-reduce (and every kbest component)
            # inside the real domain; -inf is absorbing for max AND
            # contributes exp(-inf)=0 weight to logsumexp/expectation
            guard = sr_b.plus_identity
            bucket_dt = key[1]
            pshape, part_shapes = key[2]
            n_rows = len(entries)
            shape0 = entries[0][0][4]
            uniform = all(it[4] == shape0 for it, _ in entries)
            # two-pass bnb kernels: "on" prunes every device bucket,
            # "auto" only buckets whose per-row padded table clears
            # the threshold (the decision is a pure function of the
            # bucket key, so every entry of a bucket agrees)
            use_bnb = False
            if bnb_call and any(
                it[8] is not None for it, _ in entries
            ):
                per_row = int(np.prod(pshape)) * sr_b.cell_width
                use_bnb = (
                    bnb == "on" or per_row >= BNB_AUTO_MIN_CELLS
                )
                if not use_bnb and met.enabled:
                    met.inc("semiring.bnb_skipped_small")
            # finite sentinel (±f32max/2): rows bounded at the
            # ⊕-annihilator (joint infeasibility / zero mass) prune
            # even without an incumbent — their value IS the identity
            big = float(np.finfo(np.float32).max) / 2
            noprune = (
                big
                if sr_b.kind == "kbest"
                or (sr_b.idempotent and not sr_b.maximize)
                else -big
            )
            # memoized instances take the stacked path even for a
            # single row: a warm delta's lone dirty node then lands
            # on the stack-height-1 kernel the memo pre-warmed after
            # the cold solve — zero XLA compiles on the delta path
            memo_rows = memos is not None and any(
                memos[item[0]] is not None for item, _ in entries
            )
            if level_sync and uniform and (n_rows > 1 or memo_rows):
                ok = _dispatch_stacked(
                    sw, sr_b, entries, pshape, part_shapes, shape0,
                    pad, guard, tol, want_args, finish, sup, met,
                    plans, use_bnb, noprune, ctxs, tracer,
                    memos=memos, table_dtype=bucket_dt,
                )
                if ok:
                    continue
                # OOM on the stacked dispatch: degrade to the
                # per-node path below (a single join that still OOMs
                # degrades further to the exact host contraction)
                if met.enabled:
                    met.inc("engine.oom_splits")
            fn = contraction_kernel(
                sr_b, pshape, part_shapes, bnb=use_bnb,
                table_dtype=bucket_dt,
            )
            for item, aligned in entries:
                (k, name, sep, target, shape, parts,
                 parts_max, err_in, budget, shiftc, node_dt) = item
                if (
                    timeout is not None
                    and time.perf_counter() - t0 > timeout
                ):
                    return None
                # the ONE padding-contract implementation
                # (ops/padding.py): the mask is part of the kernel
                # signature exactly when the policy is enabled
                # (util_level_key), and the guard is this semiring's
                # ⊕-identity
                padded = pad_util_parts(
                    aligned, shape, pshape, guard=guard,
                    with_mask=pad.enabled,
                )
                padded = _pack_parts(
                    list(padded), bucket_dt, met
                )
                if use_bnb:
                    b32 = np.float32(
                        budget if budget is not None else noprune
                    )
                    padded = [b32] + list(padded)
                try:
                    outs = sup.dispatch(
                        lambda p=padded: tuple(
                            np.asarray(x) for x in fn(*p)
                        ),
                        scope="semiring.node", width=1,
                        table_bytes=table_dtype_bytes(bucket_dt)
                        * int(np.prod(pshape)) * sr_b.cell_width,
                    )
                except DeviceOOMError:
                    if on_oom == "raise":
                        raise
                    host_contract(
                        sr_b, k, name, plans[k], sep, target, shape,
                        parts, err_in,
                    )
                    continue
                if met.enabled:
                    met.inc("semiring.dispatches")
                    if use_bnb:
                        met.inc("semiring.bnb_passes")
                sw.dispatches[k] += 1
                region = tuple(slice(0, s) for s in shape[:-1])
                pruned = _finish_device_row(
                    sw, sr_b, plans[k], item, outs, region, tol,
                    want_args, finish, bnb=use_bnb, ctx=ctxs[k],
                )
                if use_bnb:
                    if pruned and met.enabled:
                        met.inc("semiring.bnb_pruned_cells", pruned)
                    if tracer.enabled:
                        tracer.event(
                            "semiring-bnb", cat="supervisor",
                            semiring=sr_b.name, rows=1,
                            pruned_cells=int(pruned),
                            table_cells=int(np.prod(shape))
                            * sr_b.cell_width,
                        )
    if tracer.enabled:
        tracer.add_span(
            "semiring.contract", "phase", t_sweep,
            time.perf_counter() - t_sweep, semiring=sr.name,
            instances=K, cells=sum(sw.cells),
        )
    return sw


def _dispatch_stacked(
    sw, sr, entries, pshape, part_shapes, shape0, pad, guard, tol,
    want_args, finish, sup, met, plans, use_bnb=False,
    noprune=float("inf"), ctxs=(), tracer=None, memos=None,
    table_dtype="f32",
) -> bool:
    """One vmapped dispatch for a uniform level-pack bucket.  Returns
    False on device OOM (caller degrades to per-node dispatches).
    ``use_bnb`` prepends the per-row budget vector (pad rows get the
    ``noprune`` sentinel, so ghost rows never contribute to the
    pruning counters or the discard measurement).  ``table_dtype``
    packs the stacked part buffers at the bucket's storage dtype —
    int8 quantizes per (row, part), so every row carries its own
    scale/offset pair and the quant bound stays the per-instance
    ``parts_max / 252``."""
    from pydcop_tpu.engine.supervisor import DeviceOOMError

    n_rows = len(entries)
    stack_h = stack_bucket(n_rows) if pad.enabled else n_rows
    n_parts = len(part_shapes)
    has_mask = n_parts == len(entries[0][1]) + 1
    bufs = [
        np.zeros((stack_h,) + tuple(ps), dtype=np.float64)
        for ps in part_shapes
    ]
    for r, (item, aligned) in enumerate(entries):
        for i, a in enumerate(aligned):
            bufs[i][r][tuple(slice(0, s) for s in a.shape)] = a
        if has_mask:
            bufs[-1][r][..., shape0[-1]:] = guard
    fn = contraction_kernel(
        sr, pshape, part_shapes, batched=True, bnb=use_bnb,
        table_dtype=table_dtype,
    )
    if table_dtype == "int8":
        # per-(row, part) quantization: ghost rows stay all-zero
        # codes under the identity (scale 1, offset 0) dequant
        scales = np.ones((stack_h, n_parts), dtype=np.float32)
        offsets = np.zeros((stack_h, n_parts), dtype=np.float32)
        qbufs = [
            np.zeros(b.shape, dtype=np.int8) for b in bufs
        ]
        for r in range(n_rows):
            for i, b in enumerate(bufs):
                q, s, o = quantize_table_int8(b[r])
                qbufs[i][r] = q
                scales[r, i] = s
                offsets[r, i] = o
        if met.enabled:
            met.inc("semiring.int8_requant", n_rows * n_parts)
        casts = [scales, offsets] + qbufs
    else:
        casts = [
            b.astype(_np_table_dtype(table_dtype)) for b in bufs
        ]
    if use_bnb:
        budgets = np.full(stack_h, noprune, dtype=np.float32)
        for r, (item, _) in enumerate(entries):
            b = item[8]
            budgets[r] = b if b is not None else noprune
        casts = [budgets] + casts
    try:
        outs = sup.dispatch(
            lambda: tuple(np.asarray(x) for x in fn(*casts)),
            scope="semiring.level", width=stack_h,
            table_bytes=table_dtype_bytes(table_dtype)
            * int(np.prod(pshape)) * sr.cell_width,
        )
    except DeviceOOMError:
        return False
    if met.enabled:
        met.inc("semiring.dispatches")
        if use_bnb:
            met.inc("semiring.bnb_passes")
    for k in sorted({item[0] for item, _ in entries}):
        sw.dispatches[k] += 1
    if memos is not None:
        # record the kernel spec so the session's post-solve prewarm
        # compiles the 1-row variant (zero compiles on warm deltas)
        for item, _ in entries:
            m = memos[item[0]]
            if m is not None:
                m.note_kernel(
                    sr.name, pshape, part_shapes, use_bnb,
                    table_dtype,
                )
    region_rows = tuple(slice(0, s) for s in shape0[:-1])
    pruned_total = 0
    for r, (item, aligned) in enumerate(entries):
        row_outs = tuple(o[r] for o in outs)
        pruned_total += _finish_device_row(
            sw, sr, plans[item[0]], item, row_outs, region_rows,
            tol, want_args, finish, bnb=use_bnb,
            ctx=(ctxs[item[0]] if use_bnb else None),
        )
    if use_bnb:
        if pruned_total and met.enabled:
            met.inc("semiring.bnb_pruned_cells", pruned_total)
        if tracer is not None and tracer.enabled:
            tracer.event(
                "semiring-bnb", cat="supervisor", semiring=sr.name,
                rows=n_rows, pruned_cells=int(pruned_total),
                table_cells=int(np.prod(shape0)) * sr.cell_width
                * n_rows,
            )
    return True


def _dispatch_sparse(
    sw, sr, entries, pad, tol, want_args, finish, sup, met, plans,
    use_bnb=False, ctxs=(), tracer=None, memos=None,
    table_dtype="f32", on_oom="host",
) -> bool:
    """One vmapped candidate-list dispatch for a sparse bucket
    (``ops/sparse.py``): every entry shares the pow-2 candidate
    geometry, so the rows stack under one
    :func:`~pydcop_tpu.ops.sparse.sparse_contraction_kernel` exactly
    like the dense level packs.  Ghost candidates land in the ghost
    segment and padded rows carry the ``noprune`` sentinel, so
    neither perturbs results or counters.  Returns False on device
    OOM (the caller redoes the bucket on host f64) unless
    ``on_oom="raise"`` — the budgeted sweeps re-plan instead."""
    from pydcop_tpu.engine.supervisor import DeviceOOMError

    sp0 = entries[0][1]
    n_cand_b, n_seg_b, part_lens_b = sp0.key
    n_rows = len(entries)
    stack_h = stack_bucket(n_rows) if pad.enabled else n_rows
    P = len(part_lens_b)
    sep_b = np.full(
        (stack_h, n_cand_b), n_seg_b, dtype=np.int32
    )
    own_b = np.zeros((stack_h, n_cand_b), dtype=np.int32)
    val_bufs = [
        np.zeros((stack_h, L), dtype=np.float64)
        for L in part_lens_b
    ]
    gid_bufs = [
        np.zeros((stack_h, n_cand_b), dtype=np.int32)
        for _ in part_lens_b
    ]
    for r, (_item, sp) in enumerate(entries):
        nc = sp.n_cand
        sep_b[r, :nc] = sp.sep_ids
        own_b[r, :nc] = sp.own_ids
        for i in range(P):
            val_bufs[i][r, : sp.part_flats[i].size] = (
                sp.part_flats[i]
            )
            gid_bufs[i][r, :nc] = sp.gidx[i]
    fn = sparse_contraction_kernel(
        sr, n_cand_b, n_seg_b, part_lens_b, bnb=use_bnb,
        table_dtype=table_dtype,
    )
    if table_dtype == "int8":
        # per-(row, part) quantization of the PACKED value vectors —
        # the sparse composition with int8: indices stay i32, values
        # carry their own scale/offset pair per row
        scales = np.ones((stack_h, P), dtype=np.float32)
        offsets = np.zeros((stack_h, P), dtype=np.float32)
        qbufs = [
            np.zeros(b.shape, dtype=np.int8) for b in val_bufs
        ]
        for r in range(n_rows):
            for i, b in enumerate(val_bufs):
                q, s, o = quantize_table_int8(b[r])
                qbufs[i][r] = q
                scales[r, i] = s
                offsets[r, i] = o
        if met.enabled:
            met.inc("semiring.int8_requant", n_rows * P)
        args = [scales, offsets, sep_b, own_b] + qbufs + gid_bufs
    else:
        tabs = [
            b.astype(_np_table_dtype(table_dtype))
            for b in val_bufs
        ]
        args = [sep_b, own_b] + tabs + gid_bufs
    if use_bnb:
        big = float(np.finfo(np.float32).max) / 2
        noprune = (
            big if sr.idempotent and not sr.maximize else -big
        )
        budgets = np.full(stack_h, noprune, dtype=np.float32)
        for r, (item, _sp) in enumerate(entries):
            b = item[8]
            budgets[r] = b if b is not None else noprune
        args = [budgets] + args
    try:
        outs = sup.dispatch(
            lambda: tuple(np.asarray(x) for x in fn(*args)),
            scope="semiring.level", width=stack_h,
            # real packed bytes: the candidate index buffers plus
            # the value packs at the storage dtype — NOT the dense
            # box (that is the whole point)
            table_bytes=n_cand_b * (8 + 4 * P)
            + table_dtype_bytes(table_dtype) * sum(part_lens_b),
        )
    except DeviceOOMError:
        if on_oom == "raise":
            raise
        return False
    if met.enabled:
        met.inc("semiring.dispatches")
        if use_bnb:
            met.inc("semiring.bnb_passes")
    for k in sorted({item[0] for item, _ in entries}):
        sw.dispatches[k] += 1
    if memos is not None:
        for item, _sp in entries:
            m = memos[item[0]]
            if m is not None:
                m.note_kernel(
                    sr.name, (n_cand_b, n_seg_b), part_lens_b,
                    use_bnb, table_dtype, table_format="sparse",
                )
    pruned_total = 0
    dense_cells = 0
    for r, (item, _sp) in enumerate(entries):
        shape = item[4]
        sshape = tuple(shape[:-1])
        n_seg = 1
        for s in sshape:
            n_seg *= s
        dense_cells += n_seg * shape[-1]
        row_outs = []
        for o in outs:
            a = np.asarray(o[r])
            if a.ndim == 0:
                row_outs.append(a)  # the mass-bnb discard scalar
            else:
                row_outs.append(a[:n_seg].reshape(sshape))
        region = tuple(slice(0, s) for s in sshape)
        pruned_total += _finish_device_row(
            sw, sr, plans[item[0]], item, tuple(row_outs), region,
            tol, want_args, finish, bnb=use_bnb,
            ctx=(ctxs[item[0]] if use_bnb else None),
        )
    if use_bnb:
        if pruned_total and met.enabled:
            met.inc("semiring.bnb_pruned_cells", pruned_total)
        if tracer is not None and tracer.enabled:
            tracer.event(
                "semiring-bnb", cat="supervisor", semiring=sr.name,
                rows=n_rows, pruned_cells=int(pruned_total),
                table_cells=int(dense_cells),
            )
    return True


def _finish_device_row(
    sw, sr, plan, item, outs, region, tol, want_args, finish,
    bnb=False, ctx=None,
):
    """Certify / account one device contraction and finish the node.

    Idempotent ⊕: certify the f32 arg against the decision-margin
    bound, repair near-ties on host, re-evaluate the projected
    values in exact f64 at the certified arg (tie-heavy tables are
    redone wholesale on host — same contract as DPOP).  logsumexp ⊕:
    accept the f32 values and extend the accumulated error bound
    (the tol gate already ran before dispatch).

    ``bnb``: the kernel's trailing outputs are the keep mask (and
    the measured discard for mass semirings).  Pruned rows carry the
    ⊕-identity and ``+inf`` margins, so they skip certification and
    repair entirely; when most of a row's cells are pruned the exact
    f64 re-evaluation gathers ONLY the survivors (the host-glue half
    of the two-pass win).  Returns the pruned JOIN-cell count (0
    without pruning) for the counters."""
    from pydcop_tpu.telemetry import get_metrics

    met = get_metrics()
    (k, name, sep, target, shape, parts, parts_max, err_in,
     _budget, shiftc, node_dt) = item
    # certificates and ledgers re-scale to the STORAGE dtype the
    # dispatch ran at: its unit roundoff replaces eps32, and int8
    # adds the (pre-computable) quantization bound — repairs below
    # land on exact host f64 either way, so results stay bit-parity
    # with the f32 path
    eps_dt = table_dtype_eps(node_dt)
    quant = (
        int8_quant_bound(parts_max) if node_dt == "int8" else 0.0
    )
    keep_r = None
    disc = None
    pruned_cells = 0
    if bnb:
        if sr.idempotent or sr.kind == "kbest":
            *outs, keep = outs
        else:
            *outs, keep, disc = outs
        keep_r = np.asarray(keep[region], dtype=bool)
        pruned_cells = int(keep_r.size - keep_r.sum()) * shape[
            -1
        ] * sr.cell_width
    if sr.kind == "kbest":
        vals, margins, own_idx, *slots = outs
        margins = np.asarray(margins[region], dtype=np.float64)
        local_err = eps_dt * (len(parts) + 1) * parts_max + quant
        # per-COMPONENT certificate: every selected slot must beat
        # the next candidate by the storage-dtype rounding bound, or
        # the slot sequence (and so the backpointers) is uncertain —
        # the whole node is then redone on host f64, still exact
        if np.any(margins < 2.0 * (local_err + err_in)):
            if met.enabled:
                met.inc("semiring.cert_fallbacks")
                if node_dt != "f32":
                    met.inc("semiring.precision_repairs")
            host_kw = _kbest_host(
                parts, target, shape, sr.cell_width
            )
            u, own, provs = host_kw
            sw.host_nodes[k] += 1
            finish(
                sr, k, name, plan, sep, u,
                (own, dict(zip(plan.children[name], provs))),
            )
            return pruned_cells
        own = np.asarray(own_idx[region], dtype=np.intp)
        slot_arrs = [
            np.asarray(s[region], dtype=np.intp) for s in slots
        ]
        u = _kbest_reeval(parts, target, shape, own, slot_arrs)
        # slots past the candidate count (or genuinely infeasible)
        # are +inf in the kernel's values; their backpointers are
        # clamped padding — the re-evaluation must not resurrect them
        # (a bnb-pruned row's slots are all +inf, so this same mask
        # keeps pruned rows at the ⊕-identity)
        u = np.where(
            np.isfinite(np.asarray(vals[region])), u, np.inf
        )
        sw.device_nodes[k] += 1
        finish(
            sr, k, name, plan, sep, u,
            (own, dict(zip(plan.children[name], slot_arrs))),
        )
    elif sr.kind == "expectation":
        (vals,) = outs
        u = np.asarray(vals[region], dtype=np.float64)
        scale = max(parts_max, 1.0)
        extra = (
            ctx.account(name, float(disc), shiftc, shape[-1])
            if ctx is not None and disc is not None
            else 0.0
        )
        sw.err[k][name] = err_in + eps_dt * (
            (len(parts) + 1) * scale + shape[-1] + 2
        ) + quant + extra
        sw.device_nodes[k] += 1
        finish(sr, k, name, plan, sep, u, None)
    elif sr.idempotent:
        arg, margins = outs
        arg = np.array(arg[region])  # writable (repair)
        margins = np.asarray(margins[region], dtype=np.float64)
        local_err = eps_dt * (len(parts) + 1) * parts_max + quant
        bad = np.argwhere(margins < 2.0 * (local_err + err_in))
        if node_dt != "f32" and len(bad) and met.enabled:
            met.inc("semiring.precision_repairs")
        if len(bad) * 10 > margins.size:
            # tie-heavy: per-cell repair would dominate — redo the
            # whole contraction on host f64 (still exact)
            if met.enabled:
                met.inc("semiring.cert_fallbacks")
            j = np.zeros(shape, dtype=np.float64)
            for dims, table in parts:
                j = j + _align(table, dims, target)
            u = sr.reduce(j, axis=-1)
            arg = sr.arg_reduce(j, axis=-1) if want_args else None
            sw.host_nodes[k] += 1
            if err_in:
                sw.err[k][name] = err_in
            finish(sr, k, name, plan, sep, u, arg)
            return pruned_cells
        own = target[-1]
        for cell in map(tuple, bad):
            row = np.zeros(shape[-1], dtype=np.float64)
            for dims, table in parts:
                row += _cell_row(table, dims, target, cell)
            arg[cell] = int(sr.arg_reduce(row, axis=-1))
        # exact f64 values AT the certified arg: children contribute
        # zero error to their parents, whatever the tree depth
        identity = sr.plus_identity
        if (
            keep_r is not None
            and len(shape) > 1
            and 4 * int(keep_r.sum()) < 3 * keep_r.size
        ):
            # >=25% pruned: the same compact-gather break-even the
            # dpop glue uses (algorithms/dpop.py _exact_u_at)
            # most rows pruned: gather the exact values at the
            # SURVIVORS only — O(survivors·parts) host work instead
            # of O(cells·parts), the host-glue half of the bnb win
            coords = np.nonzero(keep_r)
            a_sel = arg[coords]
            acc = np.zeros(len(coords[0]), dtype=np.float64)
            for dims, table in parts:
                idx = []
                for d in dims:
                    if d == own:
                        idx.append(a_sel)
                    else:
                        idx.append(coords[target.index(d)])
                acc += _part_gather(table, tuple(idx))
            u = np.full(tuple(shape[:-1]), identity)
            u[coords] = acc
        else:
            grids = (
                np.indices(tuple(shape[:-1]), dtype=np.intp)
                if len(shape) > 1
                else None
            )
            u = np.zeros(tuple(shape[:-1]), dtype=np.float64)
            for dims, table in parts:
                idx = []
                for d in dims:
                    if d == own:
                        idx.append(arg)
                    else:
                        idx.append(grids[target.index(d)])
                u += _part_gather(table, tuple(idx))
            if keep_r is not None:
                u = np.where(keep_r, u, identity)
        sw.device_nodes[k] += 1
        if err_in:
            sw.err[k][name] = err_in
        finish(sr, k, name, plan, sep, u, arg)
    else:
        (vals,) = outs
        u = np.asarray(vals[region], dtype=np.float64)
        scale = max(parts_max, 1.0)
        extra = (
            ctx.account(name, float(disc), shiftc, shape[-1])
            if ctx is not None and disc is not None
            else 0.0
        )
        sw.err[k][name] = err_in + eps_dt * (
            (len(parts) + 1) * scale + shape[-1] + 2
        ) + quant + extra
        sw.device_nodes[k] += 1
        finish(sr, k, name, plan, sep, u, None)
    return pruned_cells


def _part_gather(table, idx):
    """Exact f64 advanced-indexing gather of one part — the sparse
    fast path looks packed values up by flat index (misses return
    the ⊕-identity fill) instead of densifying the box."""
    if isinstance(table, SparseTable):
        return table.gather(idx)
    return np.asarray(table, dtype=np.float64)[idx]


def _cell_row(table, dims, target, cell):
    """Exact f64 row of one part at a fixed separator cell (broadcast
    over the own axis when the part does not carry it)."""
    own = target[-1]
    if isinstance(table, SparseTable):
        if own not in dims:
            fix = tuple(cell[target.index(d)] for d in dims)
            return np.full(1, float(table.gather(fix)))
        ax = list(dims).index(own)
        return table.gather(
            tuple(
                np.arange(table.shape[ax])
                if d == own
                else cell[target.index(d)]
                for d in dims
            )
        )
    idx = []
    for d in dims:
        if d == own:
            idx.append(slice(None))
        else:
            idx.append(cell[target.index(d)])
    row = np.asarray(table, dtype=np.float64)[tuple(idx)]
    if own not in dims:
        return np.full(1, float(row))
    return row


# -- structured-cell host contractions ----------------------------------


def _kbest_host(parts, target, shape, kk):
    """Exact host-f64 top-K contraction of one node with provenance:
    scalar parts broadcast-add into the base ``j``, child k-cells
    cross-sum-truncate one at a time (exact — sums are monotone in
    each argument, so a dropped rank->K candidate already had K
    smaller sums), then the own-axis projection merge-sorts the
    ``d·k`` candidates.  Returns ``(values [sep..,k], own-value index
    [sep..,k], per-child slot arrays)`` — the backpointers the value
    phase walks.  Selection among exact ties is by candidate index
    (stable argsort): deterministic, and shared with the device
    kernel's ordering."""
    nd = len(shape)
    j = np.zeros(shape, dtype=np.float64)
    vecs = []
    for dims, t in parts:
        t = np.asarray(t, dtype=np.float64)
        if t.ndim == len(dims) + 1:
            vecs.append(_align(t, dims, target))
        else:
            j = j + _align(t, dims, target)
    if vecs:
        with np.errstate(invalid="ignore"):
            cell = j[..., None] + np.broadcast_to(
                vecs[0], shape + [kk]
            )
        provs = [
            np.broadcast_to(
                np.arange(kk, dtype=np.intp), cell.shape
            )
        ]
        for t in vecs[1:]:
            with np.errstate(invalid="ignore"):
                sums = cell[..., :, None] + np.broadcast_to(
                    t, shape + [kk]
                )[..., None, :]
            flat = sums.reshape(sums.shape[:-2] + (kk * kk,))
            idx = np.argsort(flat, axis=-1, kind="stable")[..., :kk]
            cell = np.take_along_axis(flat, idx, axis=-1)
            provs = [
                np.take_along_axis(p, idx // kk, axis=-1)
                for p in provs
            ]
            provs.append(idx % kk)
    else:
        lift = np.full(kk, np.inf)
        lift[0] = 0.0
        cell = j[..., None] + lift
        provs = []
    d = shape[-1]
    flat = cell.reshape(cell.shape[:-2] + (d * kk,))
    idx = np.argsort(flat, axis=-1, kind="stable")[..., :kk]
    vals = np.take_along_axis(flat, idx, axis=-1)
    own = idx // kk
    provs = [
        np.take_along_axis(
            np.ascontiguousarray(p).reshape(
                p.shape[:-2] + (d * kk,)
            ),
            idx,
            axis=-1,
        )
        for p in provs
    ]
    if vals.shape[-1] < kk:
        pad = kk - vals.shape[-1]
        vals = np.concatenate(
            [vals, np.full(vals.shape[:-1] + (pad,), np.inf)], -1
        )
        own = np.concatenate(
            [own, np.zeros(own.shape[:-1] + (pad,), np.intp)], -1
        )
        provs = [
            np.concatenate(
                [p, np.zeros(p.shape[:-1] + (pad,), np.intp)], -1
            )
            for p in provs
        ]
    return vals, own, provs


def _kbest_reeval(parts, target, shape, own, slot_arrs):
    """Exact f64 top-K values AT certified device backpointers: the
    same part-order accumulation as :func:`_kbest_host`, gathered at
    the selected (own value, child slot) per separator cell and slot
    — children contribute zero error to their parents, whatever the
    tree depth (the kbest twin of DPOP's value re-evaluation)."""
    kk = own.shape[-1]
    own_var = target[-1]
    grids = np.indices(tuple(shape[:-1]) + (kk,), dtype=np.intp)
    u = np.zeros(tuple(shape[:-1]) + (kk,), dtype=np.float64)
    vec_i = 0
    for dims, t in parts:
        t64 = np.asarray(t, dtype=np.float64)
        if t64.ndim == len(dims) + 1:
            a = np.broadcast_to(
                _align(t64, dims, target), tuple(shape) + (kk,)
            )
            idx = [
                grids[target.index(d)] for d in target[:-1]
            ]
            u = u + a[tuple(idx) + (own, slot_arrs[vec_i])]
            vec_i += 1
        else:
            a = np.broadcast_to(
                _align(t64, dims, target), tuple(shape)
            )
            idx = [
                grids[target.index(d)] for d in target[:-1]
            ]
            u = u + a[tuple(idx) + (own,)]
    return u


def _expect_host(parts, target, shape):
    """Host-f64 expectation contraction of one node: pair parts add
    per plane (scalar parts weight-only), then the own-axis ⊕ —
    logsumexp on the weights, softmax-weighted combine on ``r``."""
    lw = np.zeros(shape, dtype=np.float64)
    r = np.zeros(shape, dtype=np.float64)
    for dims, t in parts:
        t = np.asarray(t, dtype=np.float64)
        if t.ndim == len(dims) + 1:
            a = _align(t, dims, target)
            lw = lw + a[..., 0]
            r = r + a[..., 1]
        else:
            lw = lw + _align(t, dims, target)
    return _exp_pair_reduce(
        np.stack([lw, r], axis=-1), (len(shape) - 1,)
    )


# -- queries ------------------------------------------------------------


def _value_phase(
    plan: ContractionPlan, args, only: Optional[set] = None
) -> Dict[str, Any]:
    """Top-down MAP value wave: condition each node's retained arg
    table on the accumulated ancestor assignment (parents precede
    children in reversed elimination order).  ``only`` restricts the
    walk to the maximized block of a marginal-MAP plan — those nodes
    come LAST in elimination order (so first here), and their
    separators contain only maximized variables, so the walk never
    needs a summed node's (nonexistent) arg table."""
    assignment: Dict[str, Any] = {}
    idx: Dict[str, int] = {}
    for name in reversed(plan.order):
        if only is not None and name not in only:
            continue
        sep, arg = args[name]
        best = int(arg[tuple(idx[d] for d in sep)])
        idx[name] = best
        assignment[name] = plan.domains[name][best]
    return assignment


def _kbest_solutions(plan: ContractionPlan, root_cells, args, kk):
    """The K best full assignments of one instance (or one lane), in
    cost order: cross-sum the per-root K-best cells tracking the
    per-root slot each final slot came from (roots are independent,
    so the instance optimum list is the truncated cross-sum of root
    lists), then walk each slot's backpointers top-down.  Returns
    ``[(energy value — shifts excluded, {var: value-index})]``;
    deterministic under exact ties via the (value, slot-tuple)
    sort key."""
    combos: List[Tuple[float, tuple]] = [(0.0, ())]
    for rt in plan.roots:
        cell = root_cells[rt]
        nxt = []
        for base, slots in combos:
            for s in range(cell.shape[-1]):
                v = float(cell[s])
                if np.isfinite(v):
                    nxt.append((base + v, slots + (s,)))
        nxt.sort(key=lambda t: (t[0], t[1]))
        combos = nxt[:kk]
    out = []
    for val, slots in combos:
        idx: Dict[str, int] = {}
        stack = list(zip(plan.roots, slots))
        while stack:
            v, s = stack.pop()
            sep, (own, cslots) = args[v]
            cell_i = tuple(idx[d] for d in sep)
            idx[v] = int(own[cell_i + (s,)])
            for c in plan.children[v]:
                stack.append(
                    (c, int(cslots[c][cell_i + (s,)]))
                )
        out.append((val, idx))
    return out


def _downward_marginals(
    plan: ContractionPlan,
    sw: _Sweep,
    k: int,
    sr: Semiring,
    beta: float,
    t0: float,
    timeout: Optional[float],
) -> Optional[Dict[str, np.ndarray]]:
    """Host-f64 downward pass: outside-messages root→leaves, then each
    variable's normalized marginal.  Prefix/suffix child combines (no
    log-domain subtraction — ``-inf`` entries from hard constraints
    stay well-defined)."""
    down: Dict[str, Tuple[List[str], np.ndarray]] = {}
    marginals: Dict[str, np.ndarray] = {}

    def tin(tbl):
        return (-beta) * tbl

    for name in reversed(plan.order):  # parents before children
        if timeout is not None and time.perf_counter() - t0 > timeout:
            return None
        sep = sw.seps[k][name]
        target = sep + [name]
        shape = [len(plan.domains[d]) for d in target]
        base = np.zeros(shape, dtype=np.float64)
        for dims, table in plan.buckets[name]:
            base = base + _align(tin(table), dims, target)
        if name in down:
            ddims, dtable = down[name]
            base = base + _align(dtable, ddims, target)
        cs = plan.children[name]
        aligned_c = [
            _align(sw.msgs[k][c][1], sw.msgs[k][c][0], target)
            for c in cs
        ]
        # prefix[i] = ⊗ of children < i, suffix[i] = ⊗ of children >= i
        prefix = [np.zeros(shape, dtype=np.float64)]
        for a in aligned_c:
            prefix.append(prefix[-1] + a)
        suffix = [np.zeros(shape, dtype=np.float64)]
        for a in reversed(aligned_c):
            suffix.append(suffix[-1] + a)
        suffix.reverse()
        joint = base + prefix[-1]
        b = sr.reduce(joint, axis=tuple(range(len(sep)))) if sep else joint
        m = float(np.max(b)) if np.isfinite(np.max(b)) else 0.0
        p = np.exp(b - m)
        total = float(p.sum())
        marginals[name] = (
            p / total if total > 0 else np.full_like(p, 1.0 / p.size)
        )
        for i, c in enumerate(cs):
            excl = base + prefix[i] + suffix[i + 1]
            sep_c = sw.msgs[k][c][0]
            keep = set(sep_c)
            axes = tuple(
                ax for ax, d in enumerate(target) if d not in keep
            )
            d_c = sr.reduce(excl, axis=axes) if axes else excl
            shift = float(np.max(d_c))
            if np.isfinite(shift):
                d_c = d_c - shift
            down[c] = ([d for d in target if d in keep], d_c)
    return marginals


def run_infer_many(
    dcops: Sequence[Any],
    query: str,
    *,
    order: str = "pseudo_tree",
    beta: float = 1.0,
    tol: float = 1e-6,
    device: str = "auto",
    device_min_cells: int = 1 << 14,
    pad_policy: Any = None,
    max_table_size: int = 1 << 26,
    timeout: Optional[float] = None,
    max_util_bytes: Optional[int] = None,
    map_vars: Optional[Sequence[str]] = None,
    external_dists: Optional[
        Mapping[str, Mapping[Any, float]]
    ] = None,
    bnb: str = "auto",
    table_dtype: str = "f32",
    table_format: str = "dense",
    _plans: Optional[Sequence["ContractionPlan"]] = None,
    _memos: Optional[Sequence[Any]] = None,
) -> List[Dict[str, Any]]:
    """Run one inference query over K instances with their contraction
    sweeps MERGED (the ``solve_many`` batching contract: same-bucket
    contractions from different instances share one vmapped dispatch
    and one compiled kernel; per-instance results are identical to
    sequential calls).  The engine behind ``api.infer`` /
    ``api.infer_many`` — callers own the telemetry session and
    supervisor installation.

    ``max_util_bytes`` runs the sweep MEMORY-BOUNDED
    (``ops/membound.py``): domains are consistency-pruned, every
    contraction table is kept under the budget by conditioning a cut
    set of variables, and the cut assignments ride the level-pack
    stack as extra vmapped lanes — exact results (per the query's ⊕
    contract) on instances whose naive tables dwarf device memory,
    at the cost of one sweep pass per cut lane.  The result carries
    a ``membound`` block (cut width/lanes, peak table bytes,
    replans).  An unplannable budget raises
    :class:`~pydcop_tpu.ops.membound.MemboundError`, which reports
    peak-table-bytes-vs-budget and the cut width reached — the
    actionable sizing, not a retry hint.

    Queries: ``"map"`` (max/+ — the exact MAP assignment, certified
    like DPOP), ``"log_z"`` (+/x — ``log Σ_x exp(-beta·E(x))``),
    ``"marginals"`` (+/x normalized — per-variable distributions
    ``p(x_v)``, plus ``log_z`` which the upward pass yields for
    free), ``"kbest:<k>"`` (top-K cells — the k best assignments in
    cost order, certified per component + host-f64 re-evaluated, so
    exact like ``map``), ``"marginal_map"`` (mixed elimination:
    ``map_vars`` maximized LAST over the logsumexp of the rest —
    both order heuristics honor the two-block constraint), and
    ``"expectation"`` (expectation pairs — ``E[cost]`` under the
    Gibbs distribution and, via ``external_dists = {external:
    {value: prob}}``, under stochastic externals: a modeled
    expectation, not a chaos-injected sample).

    ``_plans`` / ``_memos`` are the private session hooks
    (``engine/memo.py:InferSession``): pre-built plans skip
    ``build_plan`` (the session mutates its plan's buckets in place
    on deltas) and per-instance memo views flow into
    :func:`contract_sweep` for subtree-fingerprint message reuse.
    """
    t0 = time.perf_counter()
    qkind, sr = parse_query(query)
    bnb = as_bnb(bnb, "auto")
    table_dtype = as_table_dtype(table_dtype)
    table_format = as_table_format(table_format)
    if device not in ("auto", "never", "always"):
        raise ValueError(
            f"device must be 'auto'|'never'|'always', got {device!r}"
        )
    if beta <= 0:
        raise ValueError(f"beta must be > 0, got {beta}")
    if qkind == "marginal_map":
        if not map_vars:
            raise ValueError(
                "marginal_map needs map_vars=[...] — the variables "
                "maximized over (every other variable is summed out; "
                "with none maximized the query is 'log_z')"
            )
    elif map_vars:
        raise ValueError(
            f"map_vars applies to query='marginal_map' only, not "
            f"{query!r}"
        )
    if external_dists and qkind != "expectation":
        raise ValueError(
            "external_dists weight assignments by external-variable "
            f"probabilities — query {query!r} has no expectation to "
            "weight (use query='expectation')"
        )
    pad = as_pad_policy(pad_policy)
    dmc: Optional[int]
    if device == "never":
        dmc = None
    elif device == "always":
        dmc = 0
    else:
        dmc = int(device_min_cells)

    from pydcop_tpu.telemetry import get_tracer

    tracer = get_tracer()
    K = len(dcops)
    deadline = None if timeout is None else t0 + timeout
    if _plans is not None:
        plans = list(_plans)
    else:
        try:
            plans = [
                build_plan(
                    d, order=order, deadline=deadline,
                    max_vars=(
                        map_vars if qkind == "marginal_map" else None
                    ),
                    external_dists=(
                        external_dists
                        if qkind == "expectation"
                        else None
                    ),
                )
                for d in dcops
            ]
        except TimeoutError:
            # plan construction (the min_fill search) ate the budget
            # — same contract as a sweep timeout
            return [_timeout_result(query, t0) for _ in range(K)]
    want_args = qkind in ("map", "marginal_map", "kbest")

    if max_util_bytes is not None:
        if qkind == "marginal_map":
            raise ValueError(
                "marginal_map cannot run memory-bounded: "
                "conditioning a summed variable would hoist the max "
                "outside its sum (lanes ⊕-combine per lane, and "
                "max_{M} Σ_{S} ≠ Σ_{S cut} max_{M}) — raise the "
                "budget or narrow the order instead"
            )
        return _run_bounded_infer(
            dcops, plans, qkind, sr,
            max_util_bytes=int(max_util_bytes), beta=beta, dmc=dmc,
            pad=pad, tol=tol, max_table_size=max_table_size,
            want_args=want_args, t0=t0, timeout=timeout, K=K,
            query=query, bnb=bnb, table_dtype=table_dtype,
            table_format=table_format,
        )

    sw = contract_sweep(
        plans, sr, beta=beta, device_min_cells=dmc, pad=pad,
        tol=tol, max_table_size=max_table_size, want_args=want_args,
        t0=t0, timeout=timeout, bnb=bnb, memos=_memos,
        table_dtype=table_dtype, table_format=table_format,
    )
    if sw is None:
        return [_timeout_result(query, t0) for _ in range(K)]

    results: List[Dict[str, Any]] = []
    for k, (dcop, plan) in enumerate(zip(dcops, plans)):
        agg = (
            sw.root_total[k]
            + sw.total_shift[k]
            - beta * plan.const_energy
        )
        # the instance bound is the sum over ROOT accumulations only:
        # each node's entry already chains its whole subtree via
        # err_in, so summing every node would count a leaf's local
        # error once per ancestor
        err = sum(sw.err[k].get(r, 0.0) for r in plan.roots)
        out: Dict[str, Any] = {
            "query": query,
            "semiring": sr.name,
            "order": plan.order_name,
            "status": "finished",
            "cells": sw.cells[k],
            "dispatches": sw.dispatches[k],
            "device_nodes": sw.device_nodes[k],
            "host_nodes": sw.host_nodes[k],
            # the sweep already derived every separator — don't re-run
            # the dims-only pass plan.width() would
            "width": max(
                (len(s) for s in sw.seps[k].values()), default=0
            ),
            "error_bound": err,
            "instances_batched": K,
        }
        if qkind == "map":
            assignment = _value_phase(plan, sw.args[k])
            cost = dcop.solution_cost(assignment)
            out["assignment"] = assignment
            out["cost"] = cost
            out["log_weight"] = agg
        elif qkind == "marginal_map":
            assignment = _value_phase(
                plan, sw.args[k], only=set(plan.max_vars)
            )
            out["assignment"] = assignment
            out["map_vars"] = list(plan.max_vars)
            out["value"] = agg  # max_{x_M} log Σ_{x_S} e^{-βE}
        elif qkind == "kbest":
            out.update(
                _kbest_result(plan, sw, k, sr.cell_width, dcop)
            )
        elif qkind == "expectation":
            cells = [
                sw.root_cells[k][rt] for rt in plan.roots
            ]
            lw = sum(float(c[0]) for c in cells)
            rr = sum(float(c[1]) for c in cells)
            out["log_z"] = (
                lw + sw.total_shift[k] - beta * plan.const_energy
            )
            out["e_cost"] = rr + plan.const_energy
        elif qkind == "log_z":
            out["log_z"] = agg
        else:  # marginals
            t_down = time.perf_counter()
            margs = _downward_marginals(
                plan, sw, k, sr, beta, t0, timeout
            )
            if margs is None:
                results.append(_timeout_result(query, t0))
                continue
            if tracer.enabled:
                tracer.add_span(
                    "semiring.downward", "phase", t_down,
                    time.perf_counter() - t_down, semiring=sr.name,
                )
            out["marginals"] = {
                v: [float(x) for x in p] for v, p in margs.items()
            }
            out["log_z"] = agg
        out["time"] = (time.perf_counter() - t0) / K
        results.append(out)
    return results


def _kbest_result(plan, sw, k, kk, dcop) -> Dict[str, Any]:
    """The kbest result block for one instance of an unbounded sweep:
    walk the backpointers, fold shifts back into the energy values,
    and report each solution with its true (dcop-convention) cost —
    K DISTINCT assignments, best first."""
    sols = _kbest_solutions(
        plan, sw.root_cells[k], sw.args[k], kk
    )
    solutions = []
    for val, idx in sols:
        assignment = {
            v: plan.domains[v][i] for v, i in idx.items()
        }
        solutions.append(
            {
                "assignment": assignment,
                "cost": dcop.solution_cost(assignment),
                "energy": val + sw.total_shift[k]
                + plan.const_energy,
            }
        )
    out: Dict[str, Any] = {
        "k": kk,
        "solutions": solutions,
        "costs": [s["cost"] for s in solutions],
    }
    if solutions:
        out["assignment"] = solutions[0]["assignment"]
        out["cost"] = solutions[0]["cost"]
    return out


def _timeout_result(query: str, t0: float) -> Dict[str, Any]:
    return {
        "query": query,
        "status": "timeout",
        "time": time.perf_counter() - t0,
    }


def _run_bounded_infer(
    dcops, plans, qkind, sr, *, max_util_bytes, beta, dmc, pad,
    tol, max_table_size, want_args, t0, timeout, K,
    query: Optional[str] = None, bnb: str = "off",
    table_dtype: str = "f32", table_format: str = "dense",
) -> List[Dict[str, Any]]:
    """Memory-bounded assembly behind :func:`run_infer_many`
    (``max_util_bytes`` set): the budgeted lane sweep
    (``ops/membound.py``) plus the per-⊕ cross-lane combines —
    idempotent ⊕ picks the best lane (exact), logsumexp ⊕-combines
    the lane values under the worst-lane error bound, marginals mix
    lane marginals by lane weight and scatter over the original
    (pre-pruning) domains, kbest merge-sorts the lanes' solution
    lists (lanes partition the assignment space, so the truncated
    merge is the exact instance list), and expectation ⊕-combines
    the lanes' (log w, r) pairs."""
    from pydcop_tpu.ops import membound as _mb
    from pydcop_tpu.telemetry import get_tracer

    query = qkind if query is None else query
    tracer = get_tracer()
    bs = _mb.run_bounded(
        plans, sr, max_util_bytes=max_util_bytes, beta=beta,
        device_min_cells=dmc, pad=pad, tol=tol,
        max_table_size=max_table_size, want_args=want_args,
        t0=t0, timeout=timeout, bnb=bnb, table_dtype=table_dtype,
        table_format=table_format,
    )
    if bs is None:
        return [_timeout_result(query, t0) for _ in range(K)]
    results: List[Dict[str, Any]] = []
    for k, (dcop, plan) in enumerate(zip(dcops, bs.plans)):
        const = beta * plan.const_energy
        out: Dict[str, Any] = {
            "query": query,
            "semiring": sr.name,
            "order": plan.order_name,
            "status": "finished",
            **bs.stats(k),
            "width": bs.width(k),
            "instances_batched": K,
            "membound": bs.meta(k),
        }
        if qkind == "map":
            winner = bs.best_lane(k, maximize=True)
            assignment = _value_phase(
                bs.lanes[winner], bs.sw.args[winner]
            )
            out["assignment"] = assignment
            out["cost"] = dcop.solution_cost(assignment)
            out["log_weight"] = (
                bs.lane_values(k)[winner - bs.ranges[k][0]] - const
            )
            out["error_bound"] = 0.0  # certified per lane, exact
        elif qkind == "kbest":
            kk = sr.cell_width
            lo, hi = bs.ranges[k]
            all_sols: List[Tuple[float, Dict[str, Any]]] = []
            for l in range(lo, hi):
                lane = bs.lanes[l]
                for val, idx in _kbest_solutions(
                    lane, bs.sw.root_cells[l], bs.sw.args[l], kk
                ):
                    a = {
                        v: lane.domains[v][i]
                        for v, i in idx.items()
                    }
                    all_sols.append(
                        (
                            val + bs.sw.total_shift[l]
                            + plan.const_energy,
                            a,
                        )
                    )
            all_sols.sort(
                key=lambda t: (t[0], sorted(t[1].items()).__repr__())
            )
            solutions = [
                {
                    "assignment": a,
                    "cost": dcop.solution_cost(a),
                    "energy": val,
                }
                for val, a in all_sols[:kk]
            ]
            out["k"] = kk
            out["solutions"] = solutions
            out["costs"] = [s["cost"] for s in solutions]
            if solutions:
                out["assignment"] = solutions[0]["assignment"]
                out["cost"] = solutions[0]["cost"]
            out["error_bound"] = 0.0  # certified per lane, exact
        elif qkind == "expectation":
            lo, hi = bs.ranges[k]
            lws, rs = [], []
            for l in range(lo, hi):
                cells = [
                    bs.sw.root_cells[l][rt]
                    for rt in bs.lanes[l].roots
                ]
                lws.append(
                    sum(float(c[0]) for c in cells)
                    + bs.sw.total_shift[l]
                )
                rs.append(sum(float(c[1]) for c in cells))
            pair = _exp_pair_reduce(
                np.stack(
                    [np.asarray(lws), np.asarray(rs)], axis=-1
                ),
                (0,),
            )
            out["log_z"] = float(pair[0]) - const
            out["e_cost"] = float(pair[1]) + plan.const_energy
            errs = bs.lane_errs(k)
            out["error_bound"] = (
                max(errs, default=0.0)
                + _EPS64 * (len(errs) + 2)
            )
        elif qkind == "log_z":
            v, err = bs.logsumexp_lanes(k)
            out["log_z"] = v - const
            out["error_bound"] = err
        else:  # marginals
            t_down = time.perf_counter()
            margs = _mb.combine_marginals(
                bs, k, sr, beta, t0, timeout
            )
            if margs is None:
                results.append(_timeout_result(query, t0))
                continue
            if tracer.enabled:
                tracer.add_span(
                    "semiring.downward", "phase", t_down,
                    time.perf_counter() - t_down, semiring=sr.name,
                )
            out["marginals"] = {
                v: [float(x) for x in p] for v, p in margs.items()
            }
            z, err = bs.logsumexp_lanes(k)
            out["log_z"] = z - const
            out["error_bound"] = err
        out["time"] = (time.perf_counter() - t0) / K
        results.append(out)
    return results
