from pydcop_tpu.ops.compile import (
    BIG,
    ArityBucket,
    CompiledProblem,
    StackedProblem,
    canonical_execution_problem,
    compile_dcop,
    compile_from_arrays,
    decode_assignment,
    enable_persistent_compilation_cache,
    encode_assignment,
    problem_group_key,
    stack_problems,
)
from pydcop_tpu.ops.costs import (
    local_cost_sweep,
    neighbor_gather,
    segment_sum_edges,
    total_cost,
)
from pydcop_tpu.ops.padding import PadPolicy, as_pad_policy

__all__ = [
    "BIG",
    "ArityBucket",
    "CompiledProblem",
    "StackedProblem",
    "PadPolicy",
    "as_pad_policy",
    "canonical_execution_problem",
    "compile_dcop",
    "compile_from_arrays",
    "decode_assignment",
    "enable_persistent_compilation_cache",
    "encode_assignment",
    "local_cost_sweep",
    "neighbor_gather",
    "problem_group_key",
    "segment_sum_edges",
    "stack_problems",
    "total_cost",
]
