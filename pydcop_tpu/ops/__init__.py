"""``pydcop_tpu.ops`` — the TPU compute path.

Re-exports are LAZY (PEP 562): ``pydcop_tpu.ops.compile`` and
``pydcop_tpu.ops.costs`` import jax at module level, and pulling them
eagerly here put ~1.2s of jax import on every CLI/API cold start —
the BENCH_r05 ``init`` stage burned its 90s budget "stuck in imports"
on exactly this chain.  Importing :mod:`pydcop_tpu.ops` (or the
jax-free :mod:`pydcop_tpu.ops.padding` submodule) now costs nothing;
jax loads the first time a compile/cost symbol is actually touched.
``tests/test_import_time.py`` pins this budget.

``BIG`` and ``util_level_key`` are re-exported from
:mod:`pydcop_tpu.ops.padding` directly (their canonical home) so
reading them never forces the jax-heavy compiler module — DPOP's
host path keys its level buckets without touching jax.
"""

from pydcop_tpu.ops.padding import (
    BIG,
    PadPolicy,
    as_pad_policy,
    util_level_key,
)

_COMPILE_EXPORTS = {
    "ArityBucket",
    "CompiledProblem",
    "StackedProblem",
    "canonical_execution_problem",
    "compile_dcop",
    "compile_from_arrays",
    "decode_assignment",
    "enable_persistent_compilation_cache",
    "encode_assignment",
    "problem_group_key",
    "stack_problems",
}
_COSTS_EXPORTS = {
    "local_cost_sweep",
    "neighbor_gather",
    "segment_sum_edges",
    "total_cost",
}
# the semiring contraction core (ops/semiring.py) is numpy-only at
# import, but numpy itself must stay off the `import pydcop_tpu` cold
# path — so its surface rides the same PEP 562 laziness as compile/
# costs (jax loads even later, inside its kernel builder)
_SEMIRING_EXPORTS = {
    "ELIMINATION_ORDERS",
    "KNOWN_QUERIES",
    "QUERY_SEMIRINGS",
    "SEMIRINGS",
    "Semiring",
    "bp_factor_messages",
    "build_plan",
    "contraction_kernel",
    "get_semiring",
    "kbest_semiring",
    "min_fill_order",
    "parse_query",
    "register_semiring",
    "run_infer_many",
}

__all__ = [
    "BIG",
    "PadPolicy",
    "as_pad_policy",
    "util_level_key",
    *sorted(_COMPILE_EXPORTS),
    *sorted(_COSTS_EXPORTS),
    *sorted(_SEMIRING_EXPORTS),
]


def __getattr__(name):
    if name in _COMPILE_EXPORTS:
        import pydcop_tpu.ops.compile as _compile

        return getattr(_compile, name)
    if name in _COSTS_EXPORTS:
        import pydcop_tpu.ops.costs as _costs

        return getattr(_costs, name)
    if name in _SEMIRING_EXPORTS:
        import pydcop_tpu.ops.semiring as _semiring

        return getattr(_semiring, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


def __dir__():
    return sorted(__all__)
