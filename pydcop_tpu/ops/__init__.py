from pydcop_tpu.ops.compile import (
    BIG,
    ArityBucket,
    CompiledProblem,
    compile_dcop,
    compile_from_arrays,
    decode_assignment,
    encode_assignment,
)
from pydcop_tpu.ops.costs import (
    local_cost_sweep,
    neighbor_gather,
    segment_sum_edges,
    total_cost,
)

__all__ = [
    "BIG",
    "ArityBucket",
    "CompiledProblem",
    "compile_dcop",
    "compile_from_arrays",
    "decode_assignment",
    "encode_assignment",
    "local_cost_sweep",
    "neighbor_gather",
    "segment_sum_edges",
    "total_cost",
]
