"""The problem compiler: DCOP model → static device arrays.

This module is the TPU build's replacement for the reference's
``NAryMatrixRelation``-as-hot-path design (reference:
``pydcop/dcop/relations.py`` + per-algorithm numpy loops): the *whole
problem* is tabulated once, at setup time, into a pytree of index arrays
and dense cost tables with fully static shapes.  Every algorithm then
runs as pure jitted functions over this pytree — no Python per message,
no object dispatch, no dynamic shapes.

Representation
--------------

All domains are padded to ``d_max``; invalid values carry a ``BIG``
unary cost so no argmin ever selects them.

Constraints are tabulated over the *padded* domain grid and stored twice:

1. **Flat form** (drives local search + cost evaluation): all tables
   concatenated into one ``tables_flat: f32[total_cells]``, each
   constraint addressed by ``offset + Σ_j value_j · stride_j`` with
   strides in d_max radix.  One directed **edge** per (constraint,
   scope position); for each edge we precompute its own-position stride
   and its co-variables' indices/strides, so the per-variable cost sweep

       base_e  = offset_e + Σ_j values[covar_e,j] · costride_e,j
       sweep_e = tables_flat[base_e + arange(d_max) · stride_e]     # [d]
       local_cost = segment_sum(sweep_e by edge_var) + unary        # [n, d]

   is two gathers + one segment-sum — a single fused XLA kernel that
   evaluates *every* variable's full candidate-value cost row
   simultaneously, for any mix of constraint arities.

2. **Arity-bucketed dense form** (drives Max-Sum marginalization):
   ``tables: f32[m, d_max, ..., d_max]`` per arity, where the factor
   min-marginal is computed by broadcast-add of the incoming messages
   followed by min-reductions (see ``algorithms/maxsum.py``).

Unary constraints and variable value costs are folded into
``unary: f32[n_vars, d_max]``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# jax is now definitely loaded: attach the telemetry backend-compile
# listener before any compile can run.  session() itself skips the
# registration while jax is absent so host-only runs never import it.
from pydcop_tpu.telemetry.jit import ensure_backend_compile_listener

ensure_backend_compile_listener()

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import RelationProtocol
from pydcop_tpu.ops.padding import (
    BIG,  # noqa: F401 (canonical home: ops.padding; re-exported here)
    NO_PADDING,
    PadPolicy,
    as_pad_policy,
    ghost_scopes,
    ghost_unary,
)

# Guard: dense tabulation over padded domains is d_max**arity cells.
MAX_ARITY = 6


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ArityBucket:
    """Dense tables for all constraints of one arity.

    tables: f32[m, d_max^k] reshaped to [m, d_max, ..., d_max]
    tables_t: the same tables transposed to [d_max, ..., d_max, m] —
        the Max-Sum layout: m rides the 128-lane axis, so the d×…×d
        minor dims don't get padded to a full (8, 128) tile each.
        Kept alongside ``tables`` (local search indexes constraint-
        major) — a deliberate memory/simplicity trade: both are
        m·d^k floats, small next to the per-edge message state, and a
        uniform static pytree avoids per-algorithm recompiles
    scopes: i32[m, k] — variable index per scope position
    edge_slot: i32[m, k] — global edge index of (constraint, position)
    """

    tables: jax.Array
    tables_t: jax.Array
    scopes: jax.Array
    edge_slot: jax.Array

    @property
    def n_cons(self) -> int:
        """Constraints in the bucket (tables may hold 1 shared entry
        instead of n_cons — consumers must size loops off THIS)."""
        return self.scopes.shape[0]

    @property
    def shared_table(self) -> bool:
        return self.tables.shape[0] == 1 and self.scopes.shape[0] > 1


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CompiledProblem:
    """A DCOP compiled to device arrays.  See module docstring.

    Static (hashable, hashed per jit-cache lookup) metadata lives in
    ``meta`` fields marked static; array leaves are jit-traceable.
    """

    # -- per variable ---------------------------------------------------
    domain_sizes: jax.Array  # i32[n_vars]
    unary: jax.Array  # f32[n_vars, d_max]; BIG on padded values
    init_idx: jax.Array  # i32[n_vars]
    # -- flat constraint form ------------------------------------------
    tables_flat: jax.Array  # f32[total_cells]
    con_offset: jax.Array  # i32[n_con]
    con_scopes: jax.Array  # i32[n_con, k_max] (0-padded)
    con_strides: jax.Array  # i32[n_con, k_max] (0-padded)
    # -- directed edges (constraint, position) -------------------------
    edge_var: jax.Array  # i32[n_edges]
    edge_con: jax.Array  # i32[n_edges]
    edge_offset: jax.Array  # i32[n_edges]
    edge_stride: jax.Array  # i32[n_edges]
    edge_covars: jax.Array  # i32[n_edges, k_max-1] (0-padded)
    edge_costrides: jax.Array  # i32[n_edges, k_max-1] (0-padded)
    # -- primal-graph neighbor structure -------------------------------
    neighbors: jax.Array  # i32[n_vars, max_deg] (0-padded)
    neighbor_mask: jax.Array  # bool[n_vars, max_deg]
    # -- per-variable incoming-edge lists ------------------------------
    # padded with sentinel n_edges (callers append a zero row before
    # gathering); single-shard only — sharded runs segment-sum instead
    var_edges: jax.Array  # i32[n_vars, max_var_deg]
    # -- arity buckets for message-passing ------------------------------
    buckets: Dict[int, ArityBucket]
    # -- static metadata ------------------------------------------------
    var_names: Tuple[str, ...] = dataclasses.field(
        metadata={"static": True}
    )
    domain_labels: Tuple[Tuple[Any, ...], ...] = dataclasses.field(
        metadata={"static": True}
    )
    con_names: Tuple[str, ...] = dataclasses.field(
        metadata={"static": True}
    )
    maximize: bool = dataclasses.field(metadata={"static": True})
    # shard-major layout: constraint/edge/bucket arrays are contiguous
    # per shard with equal sizes, so axis 0 shards evenly over a mesh
    n_shards: int = dataclasses.field(metadata={"static": True})
    # directed edges belonging to real (non-ghost-padding) constraints —
    # the auditable message count (BASELINE.md accounting rule)
    n_real_edges: int = dataclasses.field(metadata={"static": True})
    # per var_edges slot p: how many variables have a REAL edge there.
    # Variables are compiled in degree-descending order, so column p's
    # real entries are the prefix [0, var_slot_counts[p]) — Max-Sum's
    # belief gather reads only that prefix instead of n_vars rows per
    # slot (the gather is element-bound on TPU, BASELINE.md round 3)
    var_slot_counts: Tuple[int, ...] = dataclasses.field(
        metadata={"static": True}, default=()
    )
    # trailing ghost variables added by a pad policy (shape bucketing,
    # ops/padding.py): excluded from assignments in/out, pinned to a
    # 1-value domain at zero cost
    n_pad_vars: int = dataclasses.field(
        metadata={"static": True}, default=0
    )

    # -- derived sizes (host-side helpers, not traced) ------------------

    @property
    def n_vars(self) -> int:
        return self.unary.shape[0]

    @property
    def n_real_vars(self) -> int:
        """Variables that exist in the source problem (ghost padding
        excluded) — the prefix of every per-variable array."""
        return self.n_vars - self.n_pad_vars

    @property
    def d_max(self) -> int:
        return self.unary.shape[1]

    @property
    def n_cons(self) -> int:
        return self.con_offset.shape[0]

    @property
    def n_edges(self) -> int:
        return self.edge_var.shape[0]

    @property
    def max_degree(self) -> int:
        return self.neighbors.shape[1]

    def var_index(self, name: str) -> int:
        return self.var_names.index(name)


def _resolve_table_dtype(table_dtype, dtype):
    """Map the shared ``table_dtype`` vocabulary (``ops/padding.py:
    as_table_dtype`` — one spelling of ``bf16``/``bfloat16``, typo
    suggestions) onto the packed jnp dtype of a
    :class:`CompiledProblem`.  ``None`` keeps the explicit ``dtype``
    arg (backward compatible).  ``int8`` is rejected here: quantized
    packs carry per-table scale/offset dequant params that only the
    contraction stack threads (``ops/semiring.py:contract_sweep``,
    ``api.infer``, DPOP) — the iterative message-passing engines
    take f32 or bf16."""
    if table_dtype is None:
        return dtype
    from pydcop_tpu.ops.padding import as_table_dtype

    dt = as_table_dtype(table_dtype)
    if dt == "int8":
        raise ValueError(
            "table_dtype='int8' is only supported by the "
            "contraction stack (api.infer / api.solve with "
            "algo='dpop'): int8 packs carry scale/offset dequant "
            "params the iterative engines do not thread — use "
            "'f32' or 'bf16' here"
        )
    return jnp.bfloat16 if dt == "bf16" else jnp.float32


def _reject_sparse_format(table_format) -> None:
    """The iterative message-passing engines run on dense packed
    boxes; ``table_format='sparse'`` (COO packs + gather joins,
    ``ops/sparse.py``) lives in the contraction stack only.  One
    explicit rejection beats K engines silently densifying."""
    if table_format is None:
        return
    from pydcop_tpu.ops.sparse import as_table_format

    if as_table_format(table_format) == "sparse":
        raise ValueError(
            "table_format='sparse' is only supported by the "
            "contraction stack (api.infer / api.solve with "
            "algo='dpop'): COO packs are joined by gather/"
            "segment-reduce kernels the iterative engines do not "
            "thread — use 'dense' here"
        )


def compile_dcop(
    dcop: DCOP, dtype=jnp.float32, n_shards: int = 1,
    pad_policy="none", table_dtype=None, table_format=None,
) -> CompiledProblem:
    """Tabulate and pack a DCOP into a :class:`CompiledProblem` (see
    :func:`_compile_dcop`); records a ``compile-problem`` span when a
    telemetry session is active (``docs/observability.md``).

    ``pad_policy`` (``"none"`` | ``"pow2"`` | ``"pow2:<floor>"`` | a
    :class:`~pydcop_tpu.ops.padding.PadPolicy`) buckets every array
    dimension so similarly-sized problems share compiled executables —
    see ``ops/padding.py`` and ``docs/performance.md``.

    ``table_dtype`` (``"f32"`` | ``"bf16"``) is the string-vocabulary
    alias of ``dtype`` shared with the contraction stack's knob
    (``docs/performance.md``, mixed-precision table packs); when given
    it overrides ``dtype``.  ``table_format`` is accepted for knob
    symmetry but only ``"dense"`` is valid here — ``"sparse"`` raises
    (COO packs live in the contraction stack, ``ops/sparse.py``).
    """
    import time as _time

    from pydcop_tpu.telemetry import get_tracer

    _reject_sparse_format(table_format)
    dtype = _resolve_table_dtype(table_dtype, dtype)
    tr = get_tracer()
    if not tr.enabled:
        return _compile_dcop(dcop, dtype, n_shards, pad_policy)
    t0 = _time.perf_counter()
    problem = _compile_dcop(dcop, dtype, n_shards, pad_policy)
    tr.add_span(
        "compile-problem", "compile", t0, _time.perf_counter() - t0,
        n_vars=int(problem.n_vars), n_edges=int(problem.n_edges),
        n_shards=n_shards,
    )
    return problem


def _compile_dcop(
    dcop: DCOP, dtype=jnp.float32, n_shards: int = 1, pad_policy="none"
) -> CompiledProblem:
    """Tabulate and pack a DCOP into a :class:`CompiledProblem`.

    ``max`` objectives are compiled by negating all costs (solvers always
    minimize); decode/report paths re-negate (see ``total_cost``'s
    ``sign`` handling in callers).

    With ``n_shards > 1`` the constraint list is laid out shard-major:
    constraints are balanced round-robin per arity across shards and
    each shard's per-arity bucket is padded to equal size with zero
    "ghost" constraints (scope = variable 0, all-zero table — they
    contribute nothing to costs or messages).  Axis 0 of every
    constraint/edge/bucket array then splits evenly over a mesh axis,
    which is what ``engine.run_batched(mesh=...)`` shards.
    """
    variables: List[Variable] = list(dcop.variables.values())
    if not variables:
        raise ValueError("Cannot compile a DCOP with no variables")
    # Compile variables in DEGREE-DESCENDING order (stable): each
    # variable's incoming-edge count is its appearance count over
    # multi-variable constraint scopes.  The per-variable edge table
    # then has the prefix property var_slot_counts documents, halving
    # the belief-gather volume on low-degree-tailed graphs.  Order is
    # internal: assignments in/out are keyed by name.
    _ext = set(dcop.external_variables)
    _deg: Dict[str, int] = {v.name: 0 for v in variables}
    for c in dcop.constraints.values():
        scope_live = [n for n in c.scope_names if n not in _ext]
        if len(scope_live) >= 2:
            for n in scope_live:
                if n in _deg:
                    _deg[n] += 1
    variables.sort(key=lambda v: -_deg.get(v.name, 0))
    var_names = tuple(v.name for v in variables)
    var_idx = {n: i for i, n in enumerate(var_names)}
    n_vars = len(variables)
    d_max = max(len(v.domain) for v in variables)
    sign = -1.0 if dcop.objective == "max" else 1.0

    ext_values: Dict[str, Any] = {
        name: ev.value for name, ev in dcop.external_variables.items()
    }

    domain_sizes = np.array(
        [len(v.domain) for v in variables], dtype=np.int32
    )
    domain_labels = tuple(tuple(v.domain.values) for v in variables)

    # unary: variable value costs + BIG padding
    unary = np.zeros((n_vars, d_max), dtype=np.float32)
    for i, v in enumerate(variables):
        dlen = len(v.domain)
        if v.has_cost:
            for k in range(dlen):
                unary[i, k] = sign * v.cost_for_val(v.domain[k])
        unary[i, dlen:] = BIG

    # initial values: declared initial_value, else 0
    init_idx = np.zeros(n_vars, dtype=np.int32)
    for i, v in enumerate(variables):
        if v.initial_value is not None:
            init_idx[i] = v.domain.index(v.initial_value)

    # -- tabulate constraints ------------------------------------------
    # External variables are fixed at their current value (sliced out);
    # unary results fold into `unary`.
    multi_cons: List[Tuple[str, List[int], np.ndarray]] = []
    for c in dcop.constraints.values():
        scope_ext = [n for n in c.scope_names if n in ext_values]
        if scope_ext:
            c = c.slice({n: ext_values[n] for n in scope_ext})
        scope = [n for n in c.scope_names]
        if len(scope) == 0:
            continue  # fully external constraint: constant, ignore
        if len(scope) > MAX_ARITY:
            raise ValueError(
                f"Constraint {c.name} has arity {len(scope)} > "
                f"MAX_ARITY={MAX_ARITY}; dense tabulation would need "
                f"{d_max}^{len(scope)} cells"
            )
        table = _tabulate_padded(c, d_max) * sign
        if len(scope) == 1:
            i = var_idx[scope[0]]
            dlen = int(domain_sizes[i])
            unary[i, :dlen] += table[:dlen]
        else:
            multi_cons.append(
                (c.name, [var_idx[n] for n in scope], table)
            )

    n_real_edges = sum(len(scope) for _, scope, _ in multi_cons)

    # shape bucketing (ops/padding.py): ghost variables first — ghost
    # constraints below scope THEM, keeping real variables' adjacency
    # untouched.  Ghosts pin to value 0 (1-value domain, BIG on the
    # rest) at zero cost.
    pol = as_pad_policy(pad_policy)
    n_pad_vars = 0
    ghost_vars: List[int] = []
    if pol.enabled:
        n_pad_vars = pol.bucket(n_vars) - n_vars
        if n_pad_vars:
            ghost_vars = list(range(n_vars, n_vars + n_pad_vars))
            domain_sizes = np.concatenate(
                [domain_sizes, np.ones(n_pad_vars, dtype=np.int32)]
            )
            unary = np.concatenate([unary, ghost_unary(n_pad_vars, d_max)])
            init_idx = np.concatenate(
                [init_idx, np.zeros(n_pad_vars, dtype=np.int32)]
            )
            var_names = var_names + tuple(
                f"__pad_v{i}" for i in range(n_pad_vars)
            )
            domain_labels = domain_labels + ((0,),) * n_pad_vars
            n_vars += n_pad_vars

    if n_shards > 1:
        multi_cons = _shard_major_layout(
            multi_cons, n_shards, d_max, policy=pol, ghost_vars=ghost_vars
        )
    else:
        # arity-major (stable) order: every arity bucket's constraints —
        # and therefore its edges (emitted constraint-major below) —
        # occupy one contiguous range of the edge array.  Max-Sum's
        # factor phase exploits this to read its q inputs as static
        # slices and write r as stacked blocks (no scatter/gather).
        # The shard-major branch already guarantees it per shard.
        multi_cons = sorted(multi_cons, key=lambda it: len(it[1]))
        if pol.enabled:
            multi_cons = _pad_arity_groups(
                multi_cons, pol, d_max, ghost_vars
            )

    con_names = tuple(name for name, _, _ in multi_cons)
    n_cons = len(multi_cons)

    # Contiguous same-arity RUNS per shard segment (constraints are
    # arity-sorted within each segment, so one run per arity per
    # segment).  All per-constraint/per-edge packing works in numpy
    # blocks over runs (see ``_pack_runs``) — per-edge Python loops
    # dominated compile time beyond ~50k variables.
    seg_count = max(n_shards, 1)
    per_seg = n_cons // seg_count if n_cons else 0
    run_bounds: List[Tuple[int, int, int]] = []  # (ci_start, ci_end, k)
    for s in range(seg_count):
        c0, c1 = s * per_seg, (s + 1) * per_seg
        i = c0
        while i < c1:
            k = len(multi_cons[i][1])
            j = i
            while j < c1 and len(multi_cons[j][1]) == k:
                j += 1
            run_bounds.append((i, j, k))
            i = j

    # per-run scope matrices + table stacks (the one remaining
    # per-constraint pass); trailing ghost constraints (pad/shard
    # padding, always appended at group tails) are counted per run so
    # packing can keep their edges out of the per-variable edge lists
    runs: List[Tuple[int, np.ndarray, np.ndarray, int]] = []
    for i, j, k in run_bounds:
        sc = np.asarray(
            [multi_cons[ci][1] for ci in range(i, j)], dtype=np.int32
        ).reshape(j - i, k)
        tb = (
            np.stack([multi_cons[ci][2] for ci in range(i, j)])
            if j > i
            else np.zeros((0,) + (d_max,) * k, dtype=np.float32)
        )
        tail = 0
        while tail < j - i and _is_ghost_name(multi_cons[j - 1 - tail][0]):
            tail += 1
        runs.append((k, sc, tb, tail))

    packed = _pack_runs(
        runs, n_vars, d_max, dtype,
        policy=pol, drop_ghost_edges=pol.enabled,
    )

    return CompiledProblem(
        domain_sizes=jnp.asarray(domain_sizes),
        unary=jnp.asarray(unary, dtype=dtype),
        init_idx=jnp.asarray(init_idx),
        var_names=var_names,
        domain_labels=domain_labels,
        con_names=con_names,
        maximize=dcop.objective == "max",
        n_shards=n_shards,
        n_real_edges=n_real_edges,
        n_pad_vars=n_pad_vars,
        **packed,
    )


def _is_ghost_name(name: str) -> bool:
    """Ghost constraints: shard-divisibility padding (``__ghost_``) and
    pad-policy bucketing (``__pad_c``)."""
    return name.startswith("__ghost_") or name.startswith("__pad_c")


def _pad_arity_groups(
    multi_cons: List[Tuple[str, List[int], np.ndarray]],
    policy: PadPolicy,
    d_max: int,
    ghost_vars: Sequence[int],
) -> List[Tuple[str, List[int], np.ndarray]]:
    """Pad each arity group of an arity-sorted constraint list up to
    the policy's bucket with zero-table ghost constraints scoped on
    ghost variables (cycled; variable 0 when the problem's variable
    count already sat on a bucket boundary — harmless either way, the
    tables are all-zero and the edges never enter ``var_edges``)."""
    out: List[Tuple[str, List[int], np.ndarray]] = []
    i = 0
    gi = 0
    while i < len(multi_cons):
        k = len(multi_cons[i][1])
        j = i
        while j < len(multi_cons) and len(multi_cons[j][1]) == k:
            j += 1
        group = multi_cons[i:j]
        m = len(group)
        need = policy.bucket(m) - m
        scopes = ghost_scopes(ghost_vars, need, k, start=gi)
        gi += need
        for t in range(need):
            group.append(
                (
                    f"__pad_c{k}_{t}",
                    list(scopes[t]),
                    np.zeros((d_max,) * k, dtype=np.float32),
                )
            )
        out.extend(group)
        i = j
    return out


def _pack_runs(
    runs: Sequence[Tuple[int, np.ndarray, np.ndarray, int]],
    n_vars: int,
    d_max: int,
    dtype,
    policy: PadPolicy = NO_PADDING,
    drop_ghost_edges: bool = False,
) -> Dict[str, Any]:
    """Vectorized packing of constraint runs into the flat + edge +
    bucket arrays of :class:`CompiledProblem`.

    ``runs`` is the constraint list in its final (segment-major,
    arity-sorted-within-segment) order, as contiguous same-arity runs:
    ``(k, scopes i32[m, k], tables f32[m, d_max^k], ghost_tail)`` —
    one run per (shard segment, arity); ``ghost_tail`` counts the
    zero-table ghost constraints padded onto the run's end.  A run
    whose tables have leading dim 1 while its scopes have m > 1 is a
    **shared-table run**: all m constraints use the one table.  Its
    flat form stores the table ONCE (every constraint's offset points
    at it) and its arity bucket keeps the [1, ...] shape (broadcast by
    consumers) — at 1M variables this removes ~d²·m floats of memory
    and per-round HBM traffic from the Max-Sum factor phase.  Returns
    the keyword dict of every constraint-derived CompiledProblem field.

    With ``drop_ghost_edges`` (pad-policy compiles), ghost constraints'
    edges are kept out of the per-variable ``var_edges`` lists so pad
    counts never widen ``max_var_deg`` — their zero tables already make
    them inert everywhere else.  ``policy`` additionally quantizes the
    adjacency widths, ``var_slot_counts`` prefixes, and the flat-table
    length, so problems that differ only within a bucket produce
    byte-compatible array SHAPES (see ``ops/padding.py``).
    """
    # tolerate legacy 3-tuple runs (no ghost tail) from direct callers
    runs = [r if len(r) == 4 else (*r, 0) for r in runs]
    k_max = max((k for k, _, _, _ in runs), default=2)
    k_max = max(k_max, 2)
    n_cons = sum(sc.shape[0] for _, sc, _, _ in runs)

    def _is_shared(sc: np.ndarray, tb: np.ndarray) -> bool:
        return tb.shape[0] == 1 and sc.shape[0] > 1

    # offsets are int32 (this IS the ~1M-variable entry point): beyond
    # 2^31 flat table cells the offset assignments below would silently
    # wrap — corrupt offsets, wrong costs, no error.  Refuse up front.
    total_cells = sum(
        (1 if _is_shared(sc, tb) else sc.shape[0]) * d_max**k
        for k, sc, tb, _ in runs
    )
    if total_cells > np.iinfo(np.int32).max:
        raise ValueError(
            f"problem too large for int32 table offsets: the flat "
            f"table needs {total_cells} cells "
            f"(> {np.iinfo(np.int32).max}); reduce domain size, "
            "arity, or constraint count — or split the problem"
        )

    # flat form (constraint-major): offsets/scopes/strides per run
    offsets = np.zeros(n_cons, dtype=np.int32)
    con_scopes = np.zeros((n_cons, k_max), dtype=np.int32)
    con_strides = np.zeros((n_cons, k_max), dtype=np.int32)
    total = 0
    ci = 0
    run_con_base = []
    for k, sc, tb, _ in runs:
        m = sc.shape[0]
        size = d_max**k
        run_con_base.append(ci)
        if _is_shared(sc, tb):
            offsets[ci : ci + m] = total  # every constraint → one copy
            total += size
        else:
            offsets[ci : ci + m] = (
                total + np.arange(m, dtype=np.int64) * size
            )
            total += m * size
        strides = np.array(
            [d_max ** (k - 1 - q) for q in range(k)], dtype=np.int32
        )
        con_scopes[ci : ci + m, :k] = sc
        con_strides[ci : ci + m, :k] = strides
        ci += m
    tables_flat = (
        np.concatenate([tb.reshape(-1) for _, _, tb, _ in runs])
        if runs
        else np.zeros(1, dtype=np.float32)
    )
    if policy.enabled:
        # quantize the flat pool's length (block multiples, not pow2 —
        # the pool can be huge); no offset ever points at the padding
        tgt_cells = policy.bucket_cells(tables_flat.size)
        if tgt_cells > tables_flat.size:
            tables_flat = np.concatenate(
                [
                    tables_flat,
                    np.zeros(
                        tgt_cells - tables_flat.size, dtype=np.float32
                    ),
                ]
            )

    # Edge ids are POSITION-MAJOR within each (shard segment, arity)
    # run: all position-0 edges of the run's constraints, then all
    # position-1, …  Max-Sum then reads each bucket position's q as one
    # contiguous slice and writes r as concatenated blocks — zero
    # scatters/gathers on the factor side (n_shards=1: whole list is
    # one segment; shard-major: each shard's sublist is arity-sorted).
    n_edges = sum(sc.shape[0] * k for k, sc, _, _ in runs)
    edge_var = np.zeros(max(n_edges, 1), dtype=np.int32)
    edge_con = np.zeros(max(n_edges, 1), dtype=np.int32)
    edge_offset = np.zeros(max(n_edges, 1), dtype=np.int32)
    edge_stride = np.zeros(max(n_edges, 1), dtype=np.int32)
    edge_covars = np.zeros((max(n_edges, 1), k_max - 1), dtype=np.int32)
    edge_costrides = np.zeros((max(n_edges, 1), k_max - 1), dtype=np.int32)
    edge_ghost = np.zeros(max(n_edges, 1), dtype=bool)
    run_edge_base = []
    edge_base = 0
    for ri, (k, sc, _, gtail) in enumerate(runs):
        m = sc.shape[0]
        i = run_con_base[ri]
        strides = np.array(
            [d_max ** (k - 1 - q) for q in range(k)], dtype=np.int32
        )
        run_edge_base.append(edge_base)
        for p in range(k):
            sl = slice(edge_base + p * m, edge_base + (p + 1) * m)
            edge_var[sl] = sc[:, p]
            edge_con[sl] = np.arange(i, i + m, dtype=np.int32)
            edge_offset[sl] = offsets[i : i + m]
            edge_stride[sl] = strides[p]
            other = [q for q in range(k) if q != p]
            edge_covars[sl, : k - 1] = sc[:, other]
            edge_costrides[sl, : k - 1] = strides[other]
            if gtail:
                edge_ghost[
                    edge_base + p * m + (m - gtail) : edge_base + (p + 1) * m
                ] = True
        edge_base += m * k

    # per-variable incoming edge lists (sentinel-padded with n_edges):
    # stable sort by owner variable = the ascending edge ids the old
    # append loop produced.  Pad-policy compiles keep GHOST edges out
    # of these lists (their contribution is zero everywhere), so the
    # list width stays the real max degree and never varies with the
    # amount of padding.
    sel = np.arange(n_edges, dtype=np.int64)
    if drop_ghost_edges and n_edges:
        sel = sel[~edge_ghost[:n_edges]]
    if sel.size:
        ev = edge_var[sel]
        counts = np.bincount(ev, minlength=n_vars)
        max_var_deg = max(int(counts.max(initial=0)), 1)
        var_edges = np.full((n_vars, max_var_deg), n_edges, dtype=np.int32)
        order = np.argsort(ev, kind="stable")
        ev_sorted = ev[order]
        starts = np.zeros(n_vars, dtype=np.int64)
        starts[1:] = np.cumsum(counts)[:-1]
        rank = np.arange(sel.size, dtype=np.int64) - starts[ev_sorted]
        var_edges[ev_sorted, rank] = sel[order].astype(np.int32)
    else:
        max_var_deg = 1
        var_edges = np.full((n_vars, 1), n_edges, dtype=np.int32)
    # prefix invariant check: the degree sort above must reproduce the
    # ACTUAL per-variable edge counts (non-increasing over rows) or the
    # prefix gather would silently drop real edges — fall back to full
    # gathers loudly if a future constraint path breaks the invariant
    _row_deg = (var_edges != n_edges).sum(axis=1)
    if np.all(_row_deg[:-1] >= _row_deg[1:]):
        var_slot_counts = tuple(
            int(x) for x in (var_edges != n_edges).sum(axis=0)
        )
    else:  # pragma: no cover — guarded invariant
        import logging

        logging.getLogger(__name__).warning(
            "variable degree sort does not match edge counts; belief "
            "prefix-gather optimization disabled for this problem"
        )
        var_slot_counts = ()
    if policy.enabled:
        # quantize the adjacency width and the per-slot prefix counts:
        # both are jit-static (the counts bound the belief prefix
        # gathers), so problems in the same bucket must agree on them
        # exactly.  Over-approximated counts are safe — the extra rows
        # are sentinels gathering the callers' zero pad row.
        w = policy.bucket_dim(max_var_deg)
        if w > var_edges.shape[1]:
            var_edges = np.concatenate(
                [
                    var_edges,
                    np.full(
                        (n_vars, w - var_edges.shape[1]),
                        n_edges,
                        dtype=np.int32,
                    ),
                ],
                axis=1,
            )
        if var_slot_counts:
            var_slot_counts = var_slot_counts + (0,) * (
                w - len(var_slot_counts)
            )
            var_slot_counts = tuple(
                0 if c == 0 else min(policy.bucket(c), n_vars)
                for c in var_slot_counts
            )

    # primal neighbors (padded): directed in-scope pairs, value-deduped
    # (ghost constraints self-reference a variable → dropped by the
    # a != b value test, as before)
    pair_parts = []
    for k, sc, _, _ in runs:
        for a in range(k):
            for b in range(k):
                if a != b:
                    pair_parts.append(
                        np.stack([sc[:, a], sc[:, b]], axis=1)
                    )
    if pair_parts:
        pairs = np.concatenate(pair_parts)
        pairs = pairs[pairs[:, 0] != pairs[:, 1]]
        pairs = np.unique(pairs, axis=0)  # sorted (var, neighbor)
    else:
        pairs = np.zeros((0, 2), dtype=np.int32)
    ncounts = np.bincount(pairs[:, 0], minlength=n_vars)
    max_deg = max(int(ncounts.max(initial=0)), 1)
    if policy.enabled:
        max_deg = policy.bucket_dim(max_deg)
    neighbors = np.zeros((n_vars, max_deg), dtype=np.int32)
    neighbor_mask = np.zeros((n_vars, max_deg), dtype=bool)
    if len(pairs):
        nstarts = np.zeros(n_vars, dtype=np.int64)
        nstarts[1:] = np.cumsum(ncounts)[:-1]
        nrank = np.arange(len(pairs), dtype=np.int64) - nstarts[pairs[:, 0]]
        neighbors[pairs[:, 0], nrank] = pairs[:, 1]
        neighbor_mask[pairs[:, 0], nrank] = True

    # arity buckets: concatenate each arity's runs in run order; edge
    # slots are pure arithmetic on the run layout
    by_arity: Dict[int, List[int]] = {}
    for ri, (k, _, _, _) in enumerate(runs):
        by_arity.setdefault(k, []).append(ri)
    buckets: Dict[int, ArityBucket] = {}
    for k, run_ids in sorted(by_arity.items()):
        tparts, sparts, slparts = [], [], []
        any_shared = any(
            _is_shared(runs[ri][1], runs[ri][2]) for ri in run_ids
        )
        if any_shared and len(run_ids) > 1:
            raise ValueError(
                "shared-table runs must be the only run of their "
                "arity (materialize before shard-major layout)"
            )
        for ri in run_ids:
            _, sc, tb, _ = runs[ri]
            m = sc.shape[0]
            tparts.append(tb)
            sparts.append(sc)
            slparts.append(
                run_edge_base[ri]
                + np.arange(m, dtype=np.int32)[:, None]
                + np.arange(k, dtype=np.int32)[None, :] * m
            )
        btables = np.concatenate(tparts).astype(np.float32)
        bscopes = np.concatenate(sparts)
        bslots = np.concatenate(slparts)
        buckets[k] = ArityBucket(
            tables=jnp.asarray(btables, dtype=dtype),
            tables_t=jnp.asarray(
                np.moveaxis(btables, 0, -1), dtype=dtype
            ),
            scopes=jnp.asarray(bscopes),
            edge_slot=jnp.asarray(bslots),
        )

    return dict(
        tables_flat=jnp.asarray(tables_flat, dtype=dtype),
        con_offset=jnp.asarray(offsets),
        con_scopes=jnp.asarray(con_scopes),
        con_strides=jnp.asarray(con_strides),
        edge_var=jnp.asarray(edge_var),
        edge_con=jnp.asarray(edge_con),
        edge_offset=jnp.asarray(edge_offset),
        edge_stride=jnp.asarray(edge_stride),
        edge_covars=jnp.asarray(edge_covars),
        edge_costrides=jnp.asarray(edge_costrides),
        neighbors=jnp.asarray(neighbors),
        neighbor_mask=jnp.asarray(neighbor_mask),
        var_edges=jnp.asarray(var_edges),
        buckets=buckets,
        var_slot_counts=var_slot_counts,
    )


class AutoNames:
    """Compact, lazily-materialized name sequence for array-built
    problems: slot ``i`` is named ``f"{prefix}{ids[i]}"`` (``ids`` is
    the degree-sort permutation — original id order is what callers
    index by).  O(1) memory instead of a million-string tuple, with a
    stable hash/eq so it is safe as jit-static CompiledProblem
    metadata."""

    __slots__ = ("prefix", "ids", "_inv", "_hash")

    def __init__(self, prefix: str, ids: np.ndarray):
        self.prefix = prefix
        self.ids = np.asarray(ids)
        inv = np.empty(len(self.ids), dtype=np.int64)
        inv[self.ids] = np.arange(len(self.ids))
        self._inv = inv
        self._hash = hash((prefix, len(self.ids), self.ids.tobytes()))

    def __len__(self) -> int:
        return len(self.ids)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return tuple(
                f"{self.prefix}{int(j)}" for j in self.ids[i]
            )
        return f"{self.prefix}{int(self.ids[i])}"

    def __iter__(self):
        return (f"{self.prefix}{int(j)}" for j in self.ids)

    def __contains__(self, name) -> bool:
        try:
            self.index(name)
            return True
        except ValueError:
            return False

    def index(self, name: str) -> int:
        if not isinstance(name, str) or not name.startswith(self.prefix):
            raise ValueError(f"{name!r} is not in names")
        suffix = name[len(self.prefix):]
        # strict digits only: int() alone would accept 'v 1', 'v+1',
        # 'v1_0' and silently resolve a typo to the WRONG variable
        if not suffix.isdigit() or str(int(suffix)) != suffix:
            raise ValueError(f"{name!r} is not in names")
        j = int(suffix)
        if not 0 <= j < len(self.ids):
            raise ValueError(f"{name!r} is not in names")
        return int(self._inv[j])

    def __eq__(self, other) -> bool:
        if isinstance(other, AutoNames):
            return (
                self.prefix == other.prefix
                and np.array_equal(self.ids, other.ids)
            )
        if isinstance(other, tuple):
            return len(other) == len(self) and tuple(self) == other
        return NotImplemented

    def __hash__(self) -> int:
        return self._hash

    def __repr__(self) -> str:  # compact + content-stable (fingerprint)
        import hashlib

        digest = hashlib.sha256(self.ids.tobytes()).hexdigest()[:12]
        return (
            f"AutoNames({self.prefix!r}, n={len(self.ids)}, ids={digest})"
        )


class UniformLabels:
    """All ``n`` variables share one label tuple — O(1) stand-in for
    ``domain_labels`` on uniform-domain array-built problems."""

    __slots__ = ("labels", "n")

    def __init__(self, labels: Tuple[Any, ...], n: int):
        self.labels = tuple(labels)
        self.n = n

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return tuple([self.labels] * len(range(*i.indices(self.n))))
        if not -self.n <= i < self.n:
            raise IndexError(i)
        return self.labels

    def __iter__(self):
        return iter([self.labels] * self.n)

    def __eq__(self, other) -> bool:
        if isinstance(other, UniformLabels):
            return self.labels == other.labels and self.n == other.n
        if isinstance(other, tuple):
            return len(other) == self.n and all(
                t == self.labels for t in other
            )
        return NotImplemented

    def __hash__(self) -> int:
        return hash((self.labels, self.n))

    def __repr__(self) -> str:
        return f"UniformLabels({self.labels!r} x {self.n})"


def compile_from_arrays(
    scopes,
    tables,
    n_values: int,
    *,
    n_vars: Optional[int] = None,
    unary: Optional[np.ndarray] = None,
    init_idx: Optional[np.ndarray] = None,
    domain_values: Optional[Sequence[Any]] = None,
    maximize: bool = False,
    n_shards: int = 1,
    var_prefix: str = "v",
    con_prefix: str = "c",
    dtype=jnp.float32,
    pad_policy="none",
    table_dtype=None,
    table_format=None,
) -> CompiledProblem:
    """Array-level problem construction — the fast path for big
    generated instances.

    The Python model layer (``DCOP``/``Variable``/``Constraint`` +
    ``compile_dcop``) costs ~35 s per 100k variables building and
    tabulating per-constraint Python objects; this entry point builds
    the identical :class:`CompiledProblem` pytree straight from numpy
    arrays in well under a second per million edges.  It exists for
    generators and benchmarks (reference-scale parity: pyDcop's
    biggest experiments are generated, not hand-written YAML).

    Parameters
    ----------
    scopes:
        ``i32[m, k]`` variable ids per constraint (uniform arity), or a
        list of such arrays for mixed arities.
    tables:
        Cost tables matching ``scopes``: ``f32[(n_values,)*k]`` (one
        table SHARED by all m constraints) or ``f32[m, (n_values,)*k]``
        (per-constraint).  A list when ``scopes`` is a list.
    n_values:
        Uniform domain size d (every variable shares it).
    n_vars:
        Number of variables; default ``max(scopes) + 1``.
    unary:
        Optional ``f32[n_vars, n_values]`` value costs in ORIGINAL
        variable-id order.
    init_idx:
        Optional ``i32[n_vars]`` initial value indices (original order).
    domain_values:
        Domain labels (default ``range(n_values)``).
    maximize:
        Compile a max objective (costs negated internally).
    n_shards:
        Shard-major layout over this many mesh shards (ghost-padded
        per arity, round-robin balanced — same layout contract as
        :func:`compile_dcop`).
    pad_policy:
        Shape bucketing (``ops/padding.py``): quantize every array
        dimension so similar problem sizes share compiled
        executables.  NOTE: an enabled policy materializes a
        shared-table group when ghosts must be appended to it (ghost
        padding cannot share a nonzero table); a group already on a
        bucket boundary keeps the shared-table memory win.

    Variable ``i`` is named ``f"{var_prefix}{i}"``; assignments in and
    out are keyed by those names exactly as with :func:`compile_dcop`.
    ``table_dtype`` (``"f32"`` | ``"bf16"``) overrides ``dtype`` with
    the shared string vocabulary (:func:`compile_dcop`);
    ``table_format`` must stay ``"dense"`` here (:func:`compile_dcop`).
    """
    _reject_sparse_format(table_format)
    dtype = _resolve_table_dtype(table_dtype, dtype)
    if not isinstance(scopes, (list, tuple)):
        scopes = [scopes]
        tables = [tables]
    if len(scopes) != len(tables):
        raise ValueError("scopes and tables lists must match")
    scopes = [np.ascontiguousarray(s, dtype=np.int32) for s in scopes]
    if any(s.ndim != 2 for s in scopes):
        raise ValueError("each scopes entry must be [m, k]")
    for s in scopes:
        if s.shape[1] > MAX_ARITY:
            raise ValueError(
                f"arity {s.shape[1]} > MAX_ARITY={MAX_ARITY}"
            )
    d = int(n_values)
    max_id = max((int(s.max()) for s in scopes if s.size), default=-1)
    min_id = min((int(s.min()) for s in scopes if s.size), default=0)
    if min_id < 0:
        raise ValueError(
            f"scope references negative variable id {min_id}"
        )
    if n_vars is None:
        n_vars = max_id + 1
    elif max_id >= n_vars:
        raise ValueError(
            f"scope references variable {max_id} >= n_vars={n_vars}"
        )
    if domain_values is not None and len(domain_values) != d:
        raise ValueError(
            f"domain_values has {len(domain_values)} labels, "
            f"n_values={d}"
        )
    sign = -1.0 if maximize else 1.0

    pol = as_pad_policy(pad_policy)
    n_real_vars = n_vars
    n_pad_vars = 0
    if pol.enabled:
        n_pad_vars = pol.bucket(n_vars) - n_vars
        n_vars += n_pad_vars

    # normalize tables: shared ``f32[(d,)*k]`` stays ONE copy (leading
    # dim 1 — the packer stores it once and consumers broadcast);
    # per-constraint tables keep ``f32[m, (d,)*k]``
    norm_tables: List[np.ndarray] = []
    for s, t in zip(scopes, tables):
        m, k = s.shape
        t = np.asarray(t, dtype=np.float32) * sign
        if t.shape == (d,) * k:
            t = t[None]  # shared: [1, (d,)*k]
        elif t.shape != (m,) + (d,) * k:
            raise ValueError(
                f"table shape {t.shape} matches neither {(d,) * k} "
                f"nor {(m,) + (d,) * k}"
            )
        norm_tables.append(t)

    # degree-descending relabel (same invariant as compile_dcop): slot
    # order is internal; names carry original ids
    deg = np.zeros(n_vars, dtype=np.int64)
    for s in scopes:
        if s.shape[1] >= 2 and s.size:
            np.add.at(deg, s.reshape(-1), 1)
    perm = np.argsort(-deg, kind="stable")  # slot -> original id
    inv = np.empty(n_vars, dtype=np.int64)
    inv[perm] = np.arange(n_vars)
    scopes = [inv[s].astype(np.int32) for s in scopes]

    n_real_edges = sum(s.shape[0] * s.shape[1] for s in scopes)

    # build (segment, arity) runs: shard-major when n_shards > 1 (ghost
    # padding + round-robin, the _shard_major_layout contract), else
    # arity-major.  Same-arity entries MUST merge into ONE run — the
    # factor phase reads each bucket position's q as one contiguous
    # slice of the whole (segment, arity) group (_pack_runs contract)
    by_k: Dict[int, Tuple[List[np.ndarray], List[np.ndarray]]] = {}
    for s, t in zip(scopes, norm_tables):
        by_k.setdefault(s.shape[1], ([], []))
        by_k[s.shape[1]][0].append(s)
        by_k[s.shape[1]][1].append(t)

    def _merge_arity(ss, ts):
        """One (scopes, tables) per arity.  Sharedness survives only
        when the whole group is one shared entry on a single shard —
        mixed groups and the shard-major layout (zero-table ghosts)
        materialize per-constraint tables."""
        sc = np.concatenate(ss) if len(ss) > 1 else ss[0]
        if (
            len(ts) == 1
            and ts[0].shape[0] == 1
            and sc.shape[0] > 1
            and n_shards <= 1
            # a pad policy appends zero-table ghosts to the group, so
            # the one table cannot be shared by all rows — materialize
            # only when ghosts will actually be appended (a group
            # already on a bucket boundary keeps the shared table)
            and pol.bucket(sc.shape[0]) == sc.shape[0]
        ):
            return sc, ts[0]
        mats = [
            np.broadcast_to(t, (s.shape[0],) + t.shape[1:])
            if t.shape[0] != s.shape[0]
            else t
            for s, t in zip(ss, ts)
        ]
        if len(mats) > 1:
            return sc, np.concatenate(mats)
        m0 = mats[0]
        # a broadcast view must be materialized before downstream
        # concatenations in the shard-major path copy it repeatedly
        return sc, (np.ascontiguousarray(m0) if not m0.flags.owndata else m0)

    merged = [
        _merge_arity(ss, ts) for _, (ss, ts) in sorted(by_k.items())
    ]
    scopes = [sc for sc, _ in merged]
    norm_tables = [tb for _, tb in merged]

    # pad-policy ghost constraints scope the ghost variable SLOTS
    # (the tail of the slot order — ghosts have degree 0)
    ghost_slots = list(range(n_real_vars, n_vars))

    def _ghost_rows(g: int, k: int) -> np.ndarray:
        return ghost_scopes(ghost_slots, g, k)

    runs: List[Tuple[int, np.ndarray, np.ndarray, int]] = []
    auto_con_ids: List[np.ndarray] = []
    cid_base = 0
    if n_shards <= 1:
        for s, t in zip(scopes, norm_tables):
            m, k = s.shape
            gtail = pol.bucket(m) - m if pol.enabled else 0
            if gtail:
                s = np.concatenate([s, _ghost_rows(gtail, k)])
                t = np.concatenate(
                    [t, np.zeros((gtail,) + (d,) * k, dtype=np.float32)]
                )
            runs.append((k, s, t, gtail))
            auto_con_ids.append(
                np.arange(cid_base, cid_base + s.shape[0], dtype=np.int64)
            )
            cid_base += s.shape[0]
    else:
        import math

        per_shard_parts: List[
            List[Tuple[np.ndarray, np.ndarray, np.ndarray, int]]
        ] = [[] for _ in range(n_shards)]
        for s, t in zip(scopes, norm_tables):
            m, k = s.shape
            per_shard = math.ceil(m / n_shards)
            if pol.enabled:
                per_shard = pol.bucket(per_shard)
            tgt = per_shard * n_shards
            if tgt > m:  # ghost constraints: zero tables
                gs = (
                    _ghost_rows(tgt - m, k)
                    if pol.enabled
                    else np.zeros((tgt - m, k), dtype=np.int32)
                )
                s = np.concatenate([s, gs])
                t = np.concatenate(
                    [t, np.zeros((tgt - m,) + (d,) * k, dtype=np.float32)]
                )
            ids = np.arange(cid_base, cid_base + tgt, dtype=np.int64)
            cid_base += tgt
            # ghosts occupy indices [m, tgt): ascending strided slices
            # keep them tail-contiguous per shard
            ghost_mark = np.arange(tgt) >= m
            for sh in range(n_shards):
                per_shard_parts[sh].append(
                    (
                        s[sh::n_shards],
                        t[sh::n_shards],
                        ids[sh::n_shards],
                        int(ghost_mark[sh::n_shards].sum()),
                    )
                )
        for sh in range(n_shards):
            for s, t, ids, gcount in per_shard_parts[sh]:
                runs.append((s.shape[1], s, t, gcount))
                auto_con_ids.append(ids)

    packed = _pack_runs(
        runs, n_vars, d, dtype,
        policy=pol, drop_ghost_edges=pol.enabled,
    )

    # unary / init in original id order -> slot order.  Ghost variables
    # (original ids [n_real_vars, n_vars), slots at the tail) pin to
    # value 0: zero cost there, BIG everywhere else.
    if unary is None:
        unary_np = np.zeros((n_real_vars, d), dtype=np.float32)
    else:
        unary_np = np.asarray(unary, dtype=np.float32) * sign
        if unary_np.shape != (n_real_vars, d):
            raise ValueError(
                f"unary shape {unary_np.shape} != {(n_real_vars, d)}"
            )
    if n_pad_vars:
        unary_np = np.concatenate([unary_np, ghost_unary(n_pad_vars, d)])
    unary_np = unary_np[perm]
    if init_idx is None:
        init_np = np.zeros(n_vars, dtype=np.int32)
    else:
        init_np = np.asarray(init_idx, dtype=np.int32)
        if n_pad_vars:
            init_np = np.concatenate(
                [init_np, np.zeros(n_pad_vars, dtype=np.int32)]
            )
        init_np = init_np[perm]

    domain_sizes_np = np.full(n_vars, d, dtype=np.int32)
    if n_pad_vars:  # ghost slots are the tail of the slot order
        domain_sizes_np[n_real_vars:] = 1

    labels = tuple(
        domain_values if domain_values is not None else range(d)
    )
    con_ids = (
        np.concatenate(auto_con_ids)
        if auto_con_ids
        else np.zeros(0, dtype=np.int64)
    )
    return CompiledProblem(
        domain_sizes=jnp.asarray(domain_sizes_np),
        unary=jnp.asarray(unary_np, dtype=dtype),
        init_idx=jnp.asarray(init_np),
        var_names=AutoNames(var_prefix, perm),
        domain_labels=UniformLabels(labels, n_vars),
        con_names=AutoNames(con_prefix, con_ids),
        maximize=maximize,
        n_shards=n_shards,
        n_real_edges=n_real_edges,
        n_pad_vars=n_pad_vars,
        **packed,
    )


def _shard_major_layout(
    multi_cons,
    n_shards: int,
    d_max: int,
    policy: PadPolicy = NO_PADDING,
    ghost_vars: Sequence[int] = (),
):
    """Reorder constraints shard-major with equal per-shard, per-arity
    bucket sizes (padding with zero ghost constraints).

    Guarantees after reordering: for every arity k, shard s owns bucket
    rows [s·m_k, (s+1)·m_k); edges (emitted in constraint order) are
    contiguous per shard with equal counts.

    With an enabled ``policy`` the per-shard bucket size is quantized
    up to the policy's bucket and the ghosts scope the pad-policy
    ghost variables (cycled) instead of variable 0.
    """
    import math

    ghost_targets = list(ghost_vars) or [0]

    by_arity: Dict[int, List[Tuple[str, List[int], np.ndarray]]] = {}
    for item in multi_cons:
        by_arity.setdefault(len(item[1]), []).append(item)

    # a constraint-FREE problem must still shard: without this, the
    # (1,)-sized placeholder arrays cannot split over the mesh and
    # device_put fails — hit by dynamic/elastic runs whose surviving
    # variables share no constraint (every neighbor frozen), where the
    # reform then crash-loops.  One ghost binary constraint per shard
    # keeps every axis divisible; ghosts carry zero cost and are
    # excluded from message accounting (n_real_edges).
    if not by_arity and n_shards > 1:
        by_arity[2] = []

    shards: List[List[Tuple[str, List[int], np.ndarray]]] = [
        [] for _ in range(n_shards)
    ]
    for k in sorted(by_arity):
        items = by_arity[k]
        per_shard = max(1, math.ceil(len(items) / n_shards))
        if policy.enabled:
            per_shard = policy.bucket(per_shard)
        target = per_shard * n_shards
        gscopes = ghost_scopes(ghost_targets, target - len(items), k)
        for i in range(target - len(items)):
            ghost_table = np.zeros((d_max,) * k, dtype=np.float32)
            items.append(
                (f"__ghost_{k}_{i}", list(gscopes[i]), ghost_table)
            )
        # round-robin keeps real constraints balanced across shards
        for i, item in enumerate(items):
            shards[i % n_shards].append(item)

    # shard-major order; within a shard keep arity grouping stable
    # (items were appended arity-by-arity, so each shard's list is
    # already arity-sorted)
    out: List[Tuple[str, List[int], np.ndarray]] = []
    for s in shards:
        out.extend(s)
    return out


def _tabulate_padded(c: RelationProtocol, d_max: int) -> np.ndarray:
    """Dense table of a constraint over the padded domain grid.

    Cells involving padded values are 0 — they are unreachable as long
    as values stay in-domain (guaranteed by the BIG unary padding).
    """
    m = c.as_matrix()
    k = m.arity
    padded = np.zeros((d_max,) * k, dtype=np.float32)
    padded[tuple(slice(0, s) for s in m.shape)] = m.matrix
    return padded


def problem_fingerprint(problem: CompiledProblem) -> str:
    """Stable hash identifying the problem *instance* (names, domains,
    scopes and cost tables) — used to reject checkpoints written for a
    structurally identical problem with different costs."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr(problem.var_names).encode())
    h.update(repr(problem.domain_labels).encode())
    h.update(repr(problem.con_names).encode())
    h.update(np.asarray(problem.con_scopes).tobytes())
    h.update(np.asarray(problem.unary).tobytes())
    h.update(np.asarray(problem.tables_flat).tobytes())
    return h.hexdigest()[:16]


def encode_assignment(
    problem: CompiledProblem, assignment: Mapping[str, Any]
) -> jnp.ndarray:
    """Assignment dict → i32[n_vars] of domain indices (ghost padding
    slots stay at 0, their only value)."""
    idx = np.zeros(problem.n_vars, dtype=np.int32)
    for i, name in enumerate(problem.var_names[: problem.n_real_vars]):
        labels = problem.domain_labels[i]
        val = assignment[name]
        try:
            idx[i] = labels.index(val)
        except ValueError:
            # tolerate str-typed values (e.g. parsed CLI input)
            idx[i] = [str(l) for l in labels].index(str(val))
    return jnp.asarray(idx)


def decode_assignment(
    problem: CompiledProblem, values: jax.Array
) -> Dict[str, Any]:
    """i32[n_vars] of domain indices → assignment dict (ghost padding
    variables excluded)."""
    vals = np.asarray(values)
    return {
        name: problem.domain_labels[i][int(vals[i])]
        for i, name in enumerate(
            problem.var_names[: problem.n_real_vars]
        )
    }


def canonical_execution_problem(
    problem: CompiledProblem,
) -> CompiledProblem:
    """A copy of ``problem`` whose HOST-ONLY static metadata (names,
    labels, accounting counts) is replaced by shape-derived
    placeholders.

    The jit trace cache keys on the pytree structure — including every
    static field — so two problems with identical array shapes but
    different variable names would re-trace *and* re-compile the same
    XLA program.  None of those fields feed traced code (they exist
    for decode/accounting), so the engine runs its jitted chunk
    runners on this canonical copy and keeps the original for
    decoding: any two problems that agree on shapes, dtypes and the
    traced statics (``var_slot_counts``, ``n_shards``, bucket arities)
    then share one compiled executable.  This is what makes
    shape-bucketed dynamic-run segments (``pad_policy`` +
    ``engine/dynamic.py``) resume without a single new compile.

    Array leaves are passed through UNTOUCHED (same device buffers).
    """
    n = problem.n_vars
    return dataclasses.replace(
        problem,
        var_names=("__anon_vars__", n),
        domain_labels=("__anon_labels__", n, problem.d_max),
        con_names=("__anon_cons__", problem.n_cons),
        n_real_edges=problem.n_edges,
        n_pad_vars=0,
    )


@dataclasses.dataclass
class StackedProblem:
    """K same-bucket problems stacked along a leading instance axis.

    ``problem`` is a :class:`CompiledProblem` PYTREE whose array leaves
    carry an extra leading ``[K, ...]`` instance dimension and whose
    static metadata is the shared canonical form
    (:func:`canonical_execution_problem`) — it is NOT a valid
    single-instance problem (``n_vars`` etc. would read the instance
    count); it exists to ride through ``jax.vmap`` in one piece.
    ``template`` is the canonical single-instance member for host-side
    shape/static access, and ``host_problems`` keeps the original
    (named) problems for decode and message accounting, in stack
    order.  ``indices`` maps stack position -> position in the input
    sequence :func:`stack_problems` grouped.
    """

    problem: CompiledProblem  # stacked leaves [K, ...]
    template: CompiledProblem  # canonical single-instance member
    host_problems: List[CompiledProblem]  # originals, stack order
    indices: List[int]  # stack position -> input position

    @property
    def n_instances(self) -> int:
        return len(self.host_problems)


# Level-pack keys: the DPOP level-synchronous UTIL sweep buckets each
# pseudo-tree level's joined-table shapes on the same pow-2 lattice the
# problem compiler uses for whole-problem arrays (ops/padding.py).  The
# key function itself is numpy-only and lives in ops.padding so the
# host-path DPOP engines stay importable without jax; it is re-exported
# here because it is the UTIL-phase analogue of
# :func:`problem_group_key`: equal keys <=> one compiled join
# executable (``algorithms/dpop.py:_join_kernel``), the same
# key-equality-is-cache-identity contract the runner cache keys follow.
from pydcop_tpu.ops.padding import util_level_key  # noqa: E402,F401


def problem_group_key(problem: CompiledProblem):
    """Hashable batching-bucket key: two problems with equal keys have
    byte-compatible array shapes/dtypes AND equal traced statics
    (``var_slot_counts``, ``n_shards``, ``maximize``, bucket arities),
    so their canonical forms share one jitted executable — the
    grouping predicate of :func:`stack_problems`.

    Computed on the metadata-canonicalized copy: host-only names never
    split a group.  A ``pad_policy`` (``ops/padding.py``) is what
    steers similarly-sized problems onto equal keys.
    """
    canon = canonical_execution_problem(problem)
    leaves, treedef = jax.tree_util.tree_flatten(canon)
    return (
        treedef,
        tuple(
            (tuple(leaf.shape), jnp.result_type(leaf).name)
            for leaf in leaves
        ),
    )


def stack_problems(
    problems: Sequence[CompiledProblem],
) -> List[StackedProblem]:
    """Group same-bucket problems and stack each group's per-problem
    data arrays along a new leading ``instance`` axis.

    Returns one :class:`StackedProblem` per group, in order of first
    appearance; ``indices`` records which input positions landed in
    each group (a group of size 1 still stacks, with ``K = 1``).  Two
    problems group iff :func:`problem_group_key` agrees — identical
    array shapes/dtypes and traced statics — which is exactly the
    condition for the batched engine to run all of them under one
    ``jax.vmap``-ed chunk runner compiled once
    (``engine.run_many_batched``).
    """
    groups: Dict[Any, List[int]] = {}
    order: List[Any] = []
    for i, p in enumerate(problems):
        key = problem_group_key(p)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)
    out: List[StackedProblem] = []
    for key in order:
        idxs = groups[key]
        canon = [
            canonical_execution_problem(problems[i]) for i in idxs
        ]
        # stack on the HOST (numpy), one device put per leaf: an eager
        # per-leaf jnp.stack dispatches a K-way concat program per
        # array (~0.9 s for K=32 on CPU, measured) where the memcpy
        # path costs ~10 ms.  On accelerators this is one host round
        # trip per group — paid once per group, amortized over the
        # group's whole run.
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.asarray(
                np.stack([np.asarray(x) for x in xs])
            ),
            *canon,
        )
        out.append(
            StackedProblem(
                problem=stacked,
                template=canon[0],
                host_problems=[problems[i] for i in idxs],
                indices=list(idxs),
            )
        )
    return out


def enable_persistent_compilation_cache(
    cache_dir: str, min_compile_seconds: float = 0.0
) -> bool:
    """Route XLA executables through jax's on-disk compilation cache.

    Repeated processes (benchmark rounds, orchestrated sweeps, CI)
    then skip backend compilation entirely for programs they have
    compiled before — the third cache layer of
    ``docs/performance.md`` (runner cache → jit trace cache → this).
    Returns ``False`` (and changes nothing) on jax versions without
    the cache config; telemetry sessions count hits/misses as
    ``jit.persistent_cache_hits`` / ``jit.persistent_cache_misses``.
    """
    import os

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception as e:
        # the caller asked for a cache explicitly — a silent no-op
        # would let every run keep compiling from scratch unnoticed
        import logging

        logging.getLogger(__name__).warning(
            "persistent compilation cache DISABLED: cannot use %r "
            "(%s: %s)",
            cache_dir,
            type(e).__name__,
            e,
        )
        return False
    try:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(min_compile_seconds),
        )
    except Exception:
        pass  # older jax: threshold flag absent, cache still works
    return True
