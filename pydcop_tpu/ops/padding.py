"""Shape-bucketing pad policies for the problem compiler.

A :class:`PadPolicy` quantizes every shape-bearing dimension of a
:class:`~pydcop_tpu.ops.compile.CompiledProblem` — variable count,
per-arity constraint count, adjacency widths, flat-table length — up to
a small lattice of buckets (powers of two above a floor).  Two problems
whose true sizes differ slightly then compile to ARRAYS OF IDENTICAL
SHAPES, so they share one jitted executable instead of each paying an
XLA compile: the lever behind fast dynamic-run segment transitions
(``engine/dynamic.py``) and cheap parameter sweeps over instance sizes
(``docs/performance.md``).

Correctness contract: padding is invisible in COSTS, and invisible in
results for deterministic algorithms.  Padded (ghost) variables get a
1-value domain with ``BIG`` unary cost on every other value, so they
pin to value 0 at zero cost; ghost constraints carry all-zero tables
over ghost variables, so they contribute nothing to any cost or
message that reaches a real variable.  Ghost variables are excluded
from assignments in/out (``CompiledProblem.n_pad_vars``).  Caveat for
STOCHASTIC algorithms (dsa, noise-enabled maxsum, ...): per-round
random draws are shaped ``[padded n_vars]``, so padding changes the
real variables' random streams — same cost distribution, different
individual trajectories.  Deterministic runs (maxsum with ``noise=0``,
any algorithm resumed from carried state) are bit-identical padded vs
unpadded (tested).

Spec strings (``pad_policy=`` / ``--pad_policy``):

- ``"none"`` — no padding (the default everywhere).
- ``"pow2"`` — bucket to powers of two, floor 16.
- ``"pow2:<floor>"`` — same with an explicit floor, e.g. ``pow2:64``.

Memory trade: pow-2 bucketing can nearly double table/edge memory in
the worst case — it is an opt-in for recompile-bound workloads, not a
default.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Union

import numpy as np

# Cost assigned to padded (invalid) domain values; large enough to never
# be selected, small enough to leave f32 headroom when summed.
# (Re-exported by ops.compile — the compiler and every consumer read it
# from there; it lives here so the ghost-construction helpers below and
# the compiler share one definition without a circular import.)
BIG = 1e9


@dataclasses.dataclass(frozen=True)
class PadPolicy:
    """Bucket quantization for compiled-problem dimensions.

    ``floor`` bounds the variable/constraint-count buckets from below;
    ``deg_floor`` bounds the (much smaller) adjacency-width buckets
    (``var_edges`` / ``neighbors`` columns).  ``flat_block`` is the
    cell-count multiple ``tables_flat`` is padded to — a fixed block,
    not a power of two, so the flat pool never doubles.
    """

    kind: str = "none"  # "none" | "pow2"
    floor: int = 16
    deg_floor: int = 4
    flat_block: int = 1024

    @property
    def enabled(self) -> bool:
        return self.kind != "none"

    def bucket(self, n: int, floor: int | None = None) -> int:
        """Smallest power of two >= ``n``, clamped up to the floor."""
        if not self.enabled or n <= 0:
            return n
        b = 1
        while b < n:
            b <<= 1
        return max(b, floor if floor is not None else self.floor)

    def bucket_dim(self, n: int) -> int:
        """Bucket for adjacency widths (per-variable degree columns)."""
        return self.bucket(n, self.deg_floor)

    def bucket_cells(self, n: int) -> int:
        """Flat-table length rounded up to a ``flat_block`` multiple."""
        if not self.enabled or n <= 0:
            return n
        blk = self.flat_block
        return ((n + blk - 1) // blk) * blk


NO_PADDING = PadPolicy()


# -- table dtypes (mixed-precision table packs) ------------------------
#
# The ONE precision vocabulary of the contraction stack
# (docs/performance.md, "Mixed-precision table packs"): device-side
# table parts may be packed at f32 (the default), bf16 (half the HBM
# per cell, 2x MXU), or int8 (a quarter, with per-table scale/offset
# dequant params carried alongside).  Accumulators stay f32 on device
# and the exactness machinery re-scales per precision — callers never
# need to know more than the spelling.  Max-Sum's ``msg_dtype`` is the
# message-plane sibling of ``table_dtype`` and parses through the same
# helper (restricted to its supported subset).

#: canonical table dtype spellings, cheapest storage last
TABLE_DTYPES = ("f32", "bf16", "int8")

_TABLE_DTYPE_ALIASES = {
    "f32": "f32",
    "fp32": "f32",
    "float32": "f32",
    "bf16": "bf16",
    "bfloat16": "bf16",
    "int8": "int8",
    "i8": "int8",
}

#: bytes per packed cell, per canonical dtype
_TABLE_DTYPE_BYTES = {"f32": 4, "bf16": 2, "int8": 1}

# unit roundoff of the STORAGE format: bf16 keeps 8 significand bits
# (eps = 2^-7); int8 codes are exact integers dequantized in f32, so
# its roundoff is f32's — the quantization error is accounted
# separately (see int8_quant_bound).  Literals, not np.finfo: numpy
# cannot finfo ml_dtypes.bfloat16 on every supported version, and the
# host paths must stay importable without ml_dtypes loaded.
_TABLE_DTYPE_EPS = {
    "f32": float(np.finfo(np.float32).eps),
    "bf16": 2.0 ** -7,
    "int8": float(np.finfo(np.float32).eps),
}


def as_table_dtype(
    spec: Union[str, None],
    default: str = "f32",
    allowed: Sequence[str] = TABLE_DTYPES,
) -> str:
    """Normalize a ``table_dtype`` argument to its canonical spelling.

    ``None``/``""`` mean the default; ``"bfloat16"``/``"fp32"``-style
    aliases collapse to one spelling so cache keys and wire partition
    keys can compare strings directly.  Unknown names raise with a
    nearest-name suggestion (the semiring-registry convention);
    ``allowed`` lets restricted call sites (e.g. Max-Sum's bf16-only
    message plane) reject dtypes they cannot honor with the same
    error shape."""
    if spec is None:
        return default
    if not isinstance(spec, str):
        raise ValueError(
            f"table dtype must be a string, got {spec!r}"
        )
    s = spec.strip().lower()
    if not s:
        return default
    canon = _TABLE_DTYPE_ALIASES.get(s)
    if canon is None or canon not in allowed:
        import difflib

        hint = difflib.get_close_matches(
            s, sorted(set(_TABLE_DTYPE_ALIASES)), n=1
        )
        suggest = (
            f"; did you mean {hint[0]!r}?"
            if hint and _TABLE_DTYPE_ALIASES[hint[0]] in allowed
            else ""
        )
        raise ValueError(
            f"unknown table dtype {spec!r} (expected one of "
            f"{tuple(allowed)}{suggest})"
        )
    return canon


def table_dtype_bytes(table_dtype: str) -> int:
    """Per-cell byte width of a canonical table dtype — the number
    every byte budget (``ops/membound.py``), memo payload account
    (``engine/memo.py``) and telemetry ``table_bytes`` field sizes
    with."""
    return _TABLE_DTYPE_BYTES[as_table_dtype(table_dtype)]


def table_dtype_eps(table_dtype: str) -> float:
    """Unit roundoff of a canonical table dtype's STORAGE format —
    what the f32 certificate/ledger machinery swaps in for ``eps32``
    when tables are packed below f32 (int8 quantization error is a
    separate additive term, :func:`int8_quant_bound`)."""
    return _TABLE_DTYPE_EPS[as_table_dtype(table_dtype)]


# -- int8 table packs ---------------------------------------------------
#
# Affine 8-bit quantization with RESERVED infinity codes: hard-cap
# semantics (+/-inf guards, bnb noprune sentinels, pad-policy ghost
# masks) must survive packing EXACTLY, so the top/bottom codes encode
# the infinities and finite values clip to [-126, 126].  scale/offset
# ride alongside the codes (one pair per packed part) and the device
# kernel dequantizes into its f32 accumulator
# (``ops/semiring.py:contraction_kernel``).

INT8_POS_INF = 127  #: reserved code for +inf
INT8_NEG_INF = -128  #: reserved code for -inf
INT8_FINITE_MAX = 126  #: finite codes live in [-126, 126]
INT8_LEVELS = 2 * INT8_FINITE_MAX  #: finite quantization levels (252)


def quantize_table_int8(a: np.ndarray):
    """Pack a float table as ``(int8 codes, f32 scale, f32 offset)``.

    Finite values map affinely onto [-126, 126] —
    ``scale = (hi - lo) / 252`` (1.0 when the finite range is
    degenerate, where every finite cell dequantizes exactly to the
    offset) and ``offset = (hi + lo) / 2`` — and +/-inf take the
    reserved codes, so guards and hard caps round-trip bit-exactly.
    The quantization error of any finite cell is <= scale / 2
    <= max|finite| / 252 (:func:`int8_quant_bound`)."""
    a = np.asarray(a, dtype=np.float64)
    finite = np.isfinite(a)
    if finite.any():
        lo = float(a[finite].min())
        hi = float(a[finite].max())
    else:
        lo = hi = 0.0
    scale = (hi - lo) / INT8_LEVELS
    if not (scale > 0.0):
        scale = 1.0
    offset = (hi + lo) / 2.0
    with np.errstate(invalid="ignore"):
        q = np.clip(
            np.rint((a - offset) / scale),
            -INT8_FINITE_MAX,
            INT8_FINITE_MAX,
        )
    q = np.where(a == np.inf, INT8_POS_INF, q)
    q = np.where(a == -np.inf, INT8_NEG_INF, q)
    return (
        q.astype(np.int8),
        np.float32(scale),
        np.float32(offset),
    )


def dequantize_table_int8(
    q: np.ndarray, scale: float, offset: float
) -> np.ndarray:
    """Host-side (numpy) inverse of :func:`quantize_table_int8` — the
    reference the device kernel's in-trace dequant mirrors, shared by
    tests and host fallbacks."""
    q = np.asarray(q)
    f = q.astype(np.float32) * np.float32(scale) + np.float32(offset)
    f = np.where(q == INT8_POS_INF, np.float32(np.inf), f)
    f = np.where(q == INT8_NEG_INF, np.float32(-np.inf), f)
    return f.astype(np.float32)


def int8_quant_bound(parts_max: float) -> float:
    """Conservative per-joined-cell int8 quantization error bound.

    Each part's finite error is <= its ``scale/2 <= amax_p / 252``;
    a joined cell sums one value per part, and ``parts_max`` is the
    sweep's running sum of per-part finite amax values, so
    ``parts_max / 252`` bounds the total — pre-computable before any
    dispatch, which is what lets the tolerance gate and the bnb slack
    widen without touching device results."""
    return max(float(parts_max), 0.0) / INT8_LEVELS

# UTIL-table axes are DOMAIN-sized (a handful of values), not
# problem-sized: bucketing them against ``PadPolicy.floor`` (16) would
# inflate a d=5 axis 3x per dimension.  Level-pack keys therefore
# quantize axes against this much smaller floor — the bucket lattice
# for a d=5 domain is 5 -> 8, a ~1.6x per-axis pad that buys shape
# sharing across every level of the pseudo-tree (and across
# instances) instead of one compiled join kernel per exact shape.
UTIL_AXIS_FLOOR = 2


def bucket_util_shape(
    shape: Sequence[int], policy: PadPolicy
) -> tuple:
    """Quantize a UTIL joined-table shape axis-wise to the policy's
    pow-2 lattice (floor :data:`UTIL_AXIS_FLOOR`).  Identity under
    ``NO_PADDING``.  Size-1 axes STAY 1: they are conditioned or
    degenerate axes (singleton domains — ``memory_bound`` passes,
    the cut lanes of ``ops/membound.py``), and raising them to the
    floor would DOUBLE the table per conditioned axis for pure ghost
    compute — the exact opposite of what a memory budget is for."""
    if not policy.enabled:
        return tuple(shape)
    return tuple(
        s if s == 1 else policy.bucket(s, UTIL_AXIS_FLOOR)
        for s in shape
    )


def util_level_key(
    shape: Sequence[int],
    part_shapes: Sequence[Sequence[int]],
    policy: PadPolicy,
) -> tuple:
    """Level-pack bucket key for one DPOP UTIL join: the PADDED
    ``(joined shape, aligned part shapes)`` pair.

    Two nodes (of one pseudo-tree level or of different instances in a
    ``solve_many`` group) with equal keys execute as rows of ONE
    vmapped join dispatch and share one compiled executable
    (``algorithms/dpop.py:_join_kernel``).  Under ``NO_PADDING`` the
    key is the exact shapes — today's one-bucket-per-shape behavior;
    with a pow-2 policy, near-miss shapes land on the same lattice
    point so a level needs far fewer distinct kernels.

    Part axes of size 1 are broadcast axes and stay 1; real axes pad
    to the joined shape's bucket.  When the policy is enabled the key
    appends the shape of the ghost-guard MASK part (a row over the own
    axis: 0 on real values, +inf on padded ones) that
    :func:`pad_util_parts` adds so no argmin can land in a ghost cell
    — the mask is part of the kernel signature.

    A part with ONE MORE axis than the joined shape carries a
    structured-cell value axis (``ops/semiring.py`` kbest /
    expectation cells): its named axes bucket as usual and the
    trailing value axis is STATIC — kept verbatim, never padded (the
    cell width is part of the semiring, not of the problem size, so
    padding it would change the algebra).
    """
    pshape = bucket_util_shape(shape, policy)
    nd = len(pshape)
    pparts = tuple(
        tuple(
            (
                s
                if (len(ps) == nd + 1 and i == len(ps) - 1)
                else (1 if s == 1 else pshape[i])
            )
            for i, s in enumerate(ps)
        )
        for ps in part_shapes
    )
    if policy.enabled:
        mask_shape = (1,) * (len(pshape) - 1) + (pshape[-1],)
        pparts = pparts + (mask_shape,)
    return (pshape, pparts)


def pad_util_parts(
    aligned: Sequence[np.ndarray],
    shape: Sequence[int],
    pshape: Sequence[int],
    guard: float = np.inf,
    with_mask: bool = True,
) -> list:
    """Zero-pad aligned f32 UTIL parts up to the level-pack bucket and
    append the own-axis ghost mask (0 on real values, ``guard`` on
    padded ones).

    Real cells compute BIT-IDENTICALLY to the unpadded join: zero
    pads only fill cells outside the real region (sliced away by the
    caller), and adding the mask's exact 0.0 to a finite f32 is
    exact, so the certificate's error bound is unchanged.  The guard
    defaults to ``+inf`` — keeping every min-argmin/second-best
    inside the real domain (DPOP) — and semiring callers
    (``ops/semiring.py``) pass ``-inf`` for max/logsumexp ⊕, where
    it is absorbing for ``max`` and contributes ``exp(-inf)=0`` to a
    logsumexp.  ``with_mask=False`` skips the mask (a NO_PADDING
    bucket whose key carries no mask slot) and the call degenerates
    to the per-part f32 casts.  Parts carrying a trailing
    structured-cell value axis (one more axis than ``pshape``) pad
    their named axes only — the value axis is static, mirroring
    :func:`util_level_key`."""
    out = []
    for a in aligned:
        if a.ndim == len(pshape) + 1:
            target = tuple(
                1 if s == 1 else pshape[i]
                for i, s in enumerate(a.shape[:-1])
            ) + (a.shape[-1],)
        else:
            target = tuple(
                1 if s == 1 else pshape[i]
                for i, s in enumerate(a.shape)
            )
        if target == a.shape:
            # f64 inputs cast here so every returned part is kernel-
            # ready f32 (callers pass exact f64 aligned parts)
            out.append(np.asarray(a, dtype=np.float32))
        else:  # zeros + slice-assign: ~5x cheaper than np.pad,
            # and the assignment casts f64 -> f32 in the same pass
            b = np.zeros(target, dtype=np.float32)
            b[tuple(slice(0, s) for s in a.shape)] = a
            out.append(b)
    if with_mask:
        mask = np.zeros(
            (1,) * (len(pshape) - 1) + (pshape[-1],), dtype=np.float32
        )
        mask[..., shape[-1]:] = guard
        out.append(mask)
    return out


def stack_bucket(n: int) -> int:
    """Stack-height lattice for vmapped level dispatches: pow-2 up to
    32, multiples of 32 above.  Pure pow-2 wastes up to 2x device
    compute on ghost rows at large stacks (a K=8 ``solve_many`` group
    stacks hundreds of leaves); the multiple-of-32 tail caps the
    waste at one row block while keeping the number of distinct
    leading dims — and so of kernel retraces — small and stable.
    Shared by the DPOP UTIL sweep (``algorithms/dpop.py``) and the
    semiring contraction sweep (``ops/semiring.py``): the lattice is
    load-bearing for retrace counts in BOTH, so it has one
    definition."""
    if n <= 32:
        b = 1
        while b < n:
            b <<= 1
        return b
    return -(-n // 32) * 32


# -- ghost construction (the ONE definition of the padding contract) ---
#
# Ghost variables pin to value 0: zero cost there, BIG everywhere else.
# Ghost constraints carry all-zero tables scoped on ghost variables
# (cycled).  Every compile path builds its ghosts through these two
# helpers so the results-match-unpadded invariant cannot drift between
# paths.


def ghost_unary(n_pad: int, d_max: int) -> np.ndarray:
    """f32[n_pad, d_max] unary rows for ghost variables."""
    rows = np.full((n_pad, d_max), BIG, dtype=np.float32)
    rows[:, 0] = 0.0
    return rows


def ghost_scopes(
    targets: Sequence[int], count: int, k: int, start: int = 0
) -> np.ndarray:
    """i32[count, k] ghost-constraint scopes: row q repeats
    ``targets[(start + q) % len(targets)]`` k times (self-scoped, so
    the neighbor builder's a != b test drops the pairs)."""
    tg = list(targets) or [0]
    return np.asarray(
        [[tg[(start + q) % len(tg)]] * k for q in range(count)],
        dtype=np.int32,
    ).reshape(count, k)


def as_pad_policy(spec: Union[str, PadPolicy, None]) -> PadPolicy:
    """Normalize a ``pad_policy`` argument: a :class:`PadPolicy` passes
    through; ``None``/``"none"`` disable; ``"pow2"``/``"pow2:<floor>"``
    parse.  Raises ``ValueError`` on anything else."""
    if isinstance(spec, PadPolicy):
        return spec
    if spec is None:
        return NO_PADDING
    if not isinstance(spec, str):
        raise ValueError(
            f"pad_policy must be a string or PadPolicy, got {spec!r}"
        )
    s = spec.strip().lower()
    if s in ("", "none"):
        return NO_PADDING
    if s == "pow2":
        return PadPolicy(kind="pow2")
    if s.startswith("pow2:"):
        try:
            floor = int(s[len("pow2:"):])
        except ValueError:
            floor = -1
        if floor < 1:
            raise ValueError(
                f"pad_policy {spec!r}: floor must be a positive "
                "integer (e.g. 'pow2:64')"
            )
        return PadPolicy(kind="pow2", floor=floor)
    raise ValueError(
        f"unknown pad_policy {spec!r} (expected 'none', 'pow2' or "
        "'pow2:<floor>')"
    )
