"""Fused Pallas TPU kernels for Max-Sum's contiguous phases.

The round-3 TPU profile (tools/profile_maxsum.py, BASELINE.md) showed
the 10k-var Max-Sum round is dominated by fixed per-kernel overhead,
not data: the factor phase (~260 us) and the q update (~257 us) each
span many tiny XLA kernels over [d, E] arrays that hold well under
1 MB.  Both phases touch only *contiguous* blocks — the position-major
edge layout (ops/compile.py) means a binary factor's two q inputs are
two contiguous [d, m] slices and its r outputs two contiguous blocks —
so each phase collapses into ONE Pallas kernel over a 1-D grid of
edge blocks:

- :func:`factor_round_binary` — the arity-2 bucket's whole factor
  phase: S = table ⊕ q0 ⊕ q1 (d·d lane-vector adds, d is a small
  static constant), both min-projections, subtract-own-q, and the
  per-edge min-normalization, in one VMEM-resident pass.
- :func:`q_update` — q_new = norm(belief_e − r) damped against q.

The belief aggregation itself (per-variable gather over the edge
permutation) stays in XLA: TPU lane gathers are element-bound in the
Mosaic lowering (tools/bench_gather.py: every gather/scatter shape of
the aggregation costs 570-790 us at 10k vars) and Pallas has no
vectorized lane gather at all, so there is nothing to win by moving
it.

Used automatically by ``algorithms/maxsum.step`` on the TPU backend
for problems whose constraints are all binary (single shard);
``PYDCOP_TPU_NO_PALLAS=1`` forces the plain XLA path.  CPU tests run
these kernels in interpreter mode and assert bit-level parity with
the XLA phases.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp

try:  # pallas ships with jax on this image; guard for odd builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

# lanes per grid block: small enough that a [d, d, BLK] f32 table block
# (9 * BLK * 4 B = 72 KB at d=3) triple-buffers comfortably in VMEM,
# large enough that the ~15-block grid amortizes launch overhead.
# Scaled down for larger domains so the table block stays ≤ _BLK_BYTES.
_BLK = 2048
_BLK_BYTES = 2 << 20  # per-input VMEM budget for the [d, d, blk] block

# largest domain the fused factor kernel accepts: at blk=128 (the lane
# minimum) the table block is d*d*128*4 B — keep it inside the budget
MAX_D = 64


def _blk_for(d: int, m: int) -> int:
    blk = _BLK_BYTES // max(1, d * d * 4)
    blk = max(128, min(_BLK, (blk // 128) * 128))
    return min(blk, max(128, ((m + 127) // 128) * 128))


def available() -> bool:
    """Fused kernels are used on the real TPU backend only (the XLA
    path is faster under CPU emulation, and interpret mode is for
    tests)."""
    if os.environ.get("PYDCOP_TPU_NO_PALLAS"):
        return False
    if not _HAVE_PALLAS:
        return False
    return jax.default_backend() == "tpu"


def _pad_lanes(x: jax.Array, m_padded: int) -> jax.Array:
    m = x.shape[-1]
    if m == m_padded:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, m_padded - m)]
    return jnp.pad(x, pad)


def _factor_kernel(d: int, tab_ref, q0_ref, q1_ref, r0_ref, r1_ref):
    # S[a, b, :] = tab[a, b, :] + q0[a, :] + q1[b, :]; min-project over
    # the other axis, both directions in one pass.  d is a static
    # Python int, so this is d*d lane-vector adds — no reductions over
    # a traced axis.  Message refs may be bf16 (msg_dtype param): all
    # arithmetic upcasts to the table dtype (f32), outputs cast back.
    f = tab_ref.dtype
    m0 = [None] * d
    m1 = [None] * d
    for a in range(d):
        qa = q0_ref[a : a + 1, :].astype(f)  # [1, BLK]
        for b in range(d):
            s = tab_ref[a, b : b + 1, :] + qa + (
                q1_ref[b : b + 1, :].astype(f)
            )
            m0[a] = s if m0[a] is None else jnp.minimum(m0[a], s)
            m1[b] = s if m1[b] is None else jnp.minimum(m1[b], s)
    r0 = jnp.concatenate(m0, axis=0) - q0_ref[:].astype(f)  # [d, BLK]
    r1 = jnp.concatenate(m1, axis=0) - q1_ref[:].astype(f)
    r0_ref[:] = (r0 - jnp.min(r0, axis=0, keepdims=True)).astype(
        r0_ref.dtype
    )
    r1_ref[:] = (r1 - jnp.min(r1, axis=0, keepdims=True)).astype(
        r1_ref.dtype
    )


def factor_round_binary(
    tab: jax.Array,  # f32[d, d, m] — the arity-2 bucket's tables
    q0: jax.Array,  # f32[d, m] — position-0 variable→factor messages
    q1: jax.Array,  # f32[d, m]
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One fused kernel for the whole binary factor phase.

    Returns ``(r0, r1)``: min-normalized factor→variable messages for
    scope positions 0 and 1 (each [d, m]).
    """
    d, m = q0.shape
    blk = _blk_for(d, m)
    mp = ((m + blk - 1) // blk) * blk
    tab_p = _pad_lanes(tab, mp)
    q0_p = _pad_lanes(q0, mp)
    q1_p = _pad_lanes(q1, mp)
    grid = (mp // blk,)
    q_spec = pl.BlockSpec((d, blk), lambda i: (0, i))
    r0, r1 = pl.pallas_call(
        functools.partial(_factor_kernel, d),
        grid=grid,
        in_specs=[
            pl.BlockSpec((d, d, blk), lambda i: (0, 0, i)),
            q_spec,
            q_spec,
        ],
        out_specs=[q_spec, q_spec],
        out_shape=[
            jax.ShapeDtypeStruct((d, mp), q0.dtype),
            jax.ShapeDtypeStruct((d, mp), q0.dtype),
        ],
        interpret=interpret,
    )(tab_p, q0_p, q1_p)
    return r0[:, :m], r1[:, :m]


def _factor_kernel_shared(d: int, tab_ref, q0_ref, q1_ref, r0_ref, r1_ref):
    # Same math as _factor_kernel with the ONE shared [d, d] table in
    # SMEM: tab[a, b] is a scalar broadcast over the lane block, so the
    # kernel never streams table data from HBM at all.  bf16 message
    # refs upcast to the table dtype (f32) before any arithmetic.
    f = tab_ref.dtype
    m0 = [None] * d
    m1 = [None] * d
    for a in range(d):
        qa = q0_ref[a : a + 1, :].astype(f)  # [1, BLK]
        for b in range(d):
            s = tab_ref[a, b] + qa + q1_ref[b : b + 1, :].astype(f)
            m0[a] = s if m0[a] is None else jnp.minimum(m0[a], s)
            m1[b] = s if m1[b] is None else jnp.minimum(m1[b], s)
    r0 = jnp.concatenate(m0, axis=0) - q0_ref[:].astype(f)  # [d, BLK]
    r1 = jnp.concatenate(m1, axis=0) - q1_ref[:].astype(f)
    r0_ref[:] = (r0 - jnp.min(r0, axis=0, keepdims=True)).astype(
        r0_ref.dtype
    )
    r1_ref[:] = (r1 - jnp.min(r1, axis=0, keepdims=True)).astype(
        r1_ref.dtype
    )


def factor_round_binary_shared(
    tab: jax.Array,  # f32[d, d] — ONE table shared by all m factors
    q0: jax.Array,  # f32[d, m]
    q1: jax.Array,  # f32[d, m]
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Binary factor phase when every factor shares one cost table
    (shared-table arity buckets — see ops/compile.py ``_pack_runs``)."""
    d, m = q0.shape
    blk = _blk_for(d, m)
    mp = ((m + blk - 1) // blk) * blk
    q0_p = _pad_lanes(q0, mp)
    q1_p = _pad_lanes(q1, mp)
    grid = (mp // blk,)
    q_spec = pl.BlockSpec((d, blk), lambda i: (0, i))
    r0, r1 = pl.pallas_call(
        functools.partial(_factor_kernel_shared, d),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (d, d), lambda i: (0, 0), memory_space=pltpu.SMEM
            ),
            q_spec,
            q_spec,
        ],
        out_specs=[q_spec, q_spec],
        out_shape=[
            jax.ShapeDtypeStruct((d, mp), q0.dtype),
            jax.ShapeDtypeStruct((d, mp), q0.dtype),
        ],
        interpret=interpret,
    )(tab, q0_p, q1_p)
    return r0[:, :m], r1[:, :m]


def _qup_kernel(be_ref, r_ref, q_ref, damp_ref, out_ref):
    # bf16 message refs upcast to the damping scalar's dtype (f32)
    # before any arithmetic; the write casts back to storage
    f = damp_ref.dtype
    qn = be_ref[:].astype(f) - r_ref[:].astype(f)
    qn = qn - jnp.min(qn, axis=0, keepdims=True)
    dmp = damp_ref[0, 0]
    out_ref[:] = (dmp * q_ref[:].astype(f) + (1.0 - dmp) * qn).astype(
        out_ref.dtype
    )


def q_update(
    belief_e: jax.Array,  # f32[d, E] — belief gathered back per edge
    r_new: jax.Array,  # f32[d, E]
    q: jax.Array,  # f32[d, E] — previous q (damping)
    damping: jax.Array,  # scalar (traced — parameter sweeps don't retrace)
    interpret: bool = False,
) -> jax.Array:
    """Fused q update: subtract own r, min-normalize, damp."""
    d, e = q.shape
    blk = _blk_for(d, e)  # conservative (d² budget) — extra grid
    # steps at large d beat a VMEM overflow
    ep = ((e + blk - 1) // blk) * blk
    spec = pl.BlockSpec((d, blk), lambda i: (0, i))
    # damping stays f32: it doubles as the kernel's compute dtype, so
    # bf16 message storage never degrades the update arithmetic
    damp = jnp.asarray(damping, dtype=jnp.float32).reshape(1, 1)
    out = pl.pallas_call(
        _qup_kernel,
        grid=(ep // blk,),
        in_specs=[
            spec,
            spec,
            spec,
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((d, ep), q.dtype),
        interpret=interpret,
    )(
        _pad_lanes(belief_e, ep),
        _pad_lanes(r_new, ep),
        _pad_lanes(q, ep),
        damp,
    )
    return out[:, :e]
