"""Seeded, fully deterministic fault plans.

A :class:`FaultPlan` answers, for every outbound message on a directed
agent link, "what happens to this one?" — and answers it identically
on every run with the same seed: each decision is a pure hash of
``(seed, link, per-link message sequence number, fault kind)``.  No
wall-clock, no RNG stream shared across threads, no iteration-order
dependence — the properties that make a fault sequence replayable.

Plans are built programmatically or parsed from the compact
``--chaos`` spec string (see :meth:`FaultPlan.from_spec`)::

    drop=0.05,dup=0.02,reorder=0.1,delay=0.1:0.05,
    a1>a2:drop=0.5,partition=a1-a2@0.5+2,crash=a3@1.5

- bare ``key=value`` clauses set the DEFAULT probabilities for every
  link; ``SRC>DST:key=value`` overrides one directed link and
  ``A-B:key=value`` both directions;
- ``delay=P:S`` delays a message by ``S`` seconds with probability
  ``P``;
- ``partition=A-B@START+DURATION`` blocks the link(s) between ``A``
  and ``B`` (``A-*``: every link touching ``A``; ``A>B``: one
  direction) from ``START`` seconds into the run for ``DURATION``
  seconds — messages are HELD and released at heal time, unless the
  outage outlives the tolerance grace window (then the link is
  declared dead, the permanent-failure path);
- ``crash=AGENT@T`` hard-kills the agent's process ``T`` seconds into
  the run (the scripted analogue of SIGKILL, for exercising the
  replication/repair machinery on demand).

Device-layer fault kinds (below the message plane; injected at the
supervised-dispatch seam of ``engine/supervisor.py``, same
``--chaos SPEC --chaos_seed N`` contract):

- ``device_oom=W`` / ``device_oom=W:R`` — every device dispatch whose
  *width* (vmapped instance lanes × restarts, or a DPOP level-stack
  height) exceeds ``W`` raises ``RESOURCE_EXHAUSTED`` (``W='-'``: no
  width cap); with ``:R``, dispatches covering more than ``R`` rounds
  OOM too.  A capacity model, not a coin flip: it is what makes the
  supervisor's degradation ladder (halve chunks, split groups)
  *converge* — once a dispatch fits the injected capacity it succeeds,
  exactly like real HBM.
- ``device_oom_bytes=N`` — every device dispatch whose PER-LANE
  joined table exceeds ``N`` bytes raises ``RESOURCE_EXHAUSTED``.
  The capacity model for the width-EXPONENTIAL dimension: the
  memory-bounded sweeps (``ops/membound.py``) answer it by
  re-planning at half their ``max_util_bytes`` budget
  (``membound.replans``), converging the moment the planned tables
  fit; dispatches that report no table size (the batched hot loops)
  are exempt.
- ``device_transient=P`` / ``device_transient=P:AFTER`` — each
  dispatch *attempt* fails with a transient runtime error with
  probability ``P``, hashed on ``(seed, dispatch scope, attempt
  seq)``; retries draw fresh seqs, so ``P < 1`` eventually succeeds.
  With ``:AFTER``, only attempts with seq > ``AFTER`` can fail — the
  deterministic "run fine for N dispatches, then die" schedule the
  crash-resume tests use.
- ``nan_inject=P`` / ``nan_inject=P:I`` — at each chunk boundary,
  poison an instance's carry with NaN with probability ``P`` (hashed
  on ``(seed, instance, boundary seq)``); ``:I`` restricts the
  injection to stack lane ``I`` of a ``solve_many`` group.

Wire-level fault kinds (the serving boundary; injected in the solver
service's frame loop, ``engine/service.py`` ``ServiceServer`` — same
``--chaos SPEC --chaos_seed N`` contract):

- ``conn_drop=P`` / ``conn_drop=P:AFTER`` — after computing a reply,
  close the connection WITHOUT sending it with probability ``P``
  (hashed on ``(seed, connection scope, per-connection reply seq)``);
  with ``:AFTER``, the first ``AFTER`` replies of every connection are
  exempt.  A reconnecting client re-rolls (its new connection carries
  a fresh scope), so ``P < 1`` retries eventually get through — and an
  idempotency-keyed retry of a dropped-but-computed response is
  answered from the server's reply cache, never re-solved.
- ``slow_client=W`` — hold every reply ``W`` seconds before sending
  (the scripted slow-draining client, for exercising backpressure and
  client-side timeouts).
- ``frame_corrupt=P`` / ``frame_corrupt=P:AFTER`` — corrupt the bytes
  of a reply frame (framing preserved, payload garbage) with
  probability ``P``, same hashing/exemption contract as ``conn_drop``;
  the client's frame validation rejects it and takes the reconnect
  path.

Fleet-level fault kinds (the replicated serving fleet;
``pydcop_tpu fleet --chaos`` — ``engine/fleet.py`` /
``commands/fleet.py``):

- ``replica_kill=T`` / ``replica_kill=T:IDX`` — SIGKILL one serving
  replica ``T`` seconds into the fleet's run.  With ``:IDX`` the
  victim is replica index ``IDX``; without it the victim is a pure
  hash of the seed (:meth:`FaultPlan.decide_replica_kill`), so a
  re-run with the same seed kills the same replica at the same time
  and the failover soak replays bit-for-bit.  The process-level
  analogue of ``crash=AGENT@T`` for the fleet: the router re-pins the
  dead replica's ring arc to its standby, which already holds the
  replicated session state.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field, replace
from typing import Dict, List, NamedTuple, Optional, Tuple


class FaultSpecError(ValueError):
    """Malformed ``--chaos`` spec (a usage error, not a failure)."""


@dataclass(frozen=True)
class LinkFaults:
    """Per-link fault probabilities (all default off)."""

    drop: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    delay: float = 0.0  # probability
    delay_s: float = 0.05  # applied delay, seconds


@dataclass(frozen=True)
class Partition:
    """A timed outage between ``a`` and ``b`` (``b='*'``: every link
    touching ``a``); ``directed`` limits it to the a→b direction."""

    a: str
    b: str
    start: float
    duration: float
    directed: bool = False

    @property
    def end(self) -> float:
        return self.start + self.duration

    def covers(self, src: str, dst: str) -> bool:
        if self.a == src and self.b in (dst, "*"):
            return True
        if self.directed:
            return False
        return self.a == dst and self.b in (src, "*")


@dataclass(frozen=True)
class DeviceFaults:
    """Device-layer fault injection parameters (all default off).

    ``oom_width_cap``/``oom_rounds_cap`` model an HBM capacity: any
    supervised dispatch wider (more vmapped lanes) or longer (more
    scanned rounds) than the cap raises ``RESOURCE_EXHAUSTED`` —
    deterministically, so the supervisor's degradation ladder
    converges the moment a re-dispatch fits.  ``transient`` is a
    per-attempt failure probability (hashed, so retries with fresh
    sequence numbers can succeed); ``transient_after`` exempts the
    first N attempts of every scope (the deterministic
    "die mid-run" schedule).  ``nan`` poisons instance carries at
    chunk boundaries; ``nan_instance`` restricts it to one stack
    lane."""

    oom_width_cap: Optional[int] = None
    oom_rounds_cap: Optional[int] = None
    #: HBM capacity on the PER-LANE joined-table bytes of a dispatch
    #: (``device_oom_bytes=N``) — the width-exponential dimension the
    #: budgeted sweeps' replan ladder shrinks (``ops/membound.py``);
    #: dispatches that report no table size are exempt.
    oom_bytes_cap: Optional[int] = None
    transient: float = 0.0
    transient_after: int = 0
    nan: float = 0.0
    nan_instance: Optional[int] = None

    @property
    def configured(self) -> bool:
        return (
            self.oom_width_cap is not None
            or self.oom_rounds_cap is not None
            or self.oom_bytes_cap is not None
            or self.transient > 0.0
            or self.nan > 0.0
        )


@dataclass(frozen=True)
class WireFaults:
    """Wire-level fault injection parameters (all default off).

    ``conn_drop`` / ``frame_corrupt`` are per-reply probabilities
    hashed on ``(seed, connection scope, per-connection reply seq)``;
    their ``*_after`` fields exempt the first N replies of every
    connection (the deterministic "work, then fail" schedule —
    mirrors ``DeviceFaults.transient_after``).  ``slow_client`` delays
    every reply by that many seconds."""

    conn_drop: float = 0.0
    conn_drop_after: int = 0
    slow_client: float = 0.0
    frame_corrupt: float = 0.0
    frame_corrupt_after: int = 0

    @property
    def configured(self) -> bool:
        return (
            self.conn_drop > 0.0
            or self.slow_client > 0.0
            or self.frame_corrupt > 0.0
        )


@dataclass(frozen=True)
class FleetFaults:
    """Fleet-level fault injection parameters (all default off).

    ``replica_kill`` schedules a SIGKILL of one serving replica that
    many seconds into the fleet's run; ``replica_kill_instance`` pins
    the victim index (a kind MODIFIER — without it the victim is a
    pure hash of the seed, :meth:`FaultPlan.decide_replica_kill`)."""

    replica_kill: Optional[float] = None
    replica_kill_instance: Optional[int] = None

    @property
    def configured(self) -> bool:
        return self.replica_kill is not None


class Decision(NamedTuple):
    """The fate of one message (at most one fault fires per message —
    drop wins over dup over reorder over delay)."""

    drop: bool = False
    dup: bool = False
    reorder: bool = False
    delay: float = 0.0


_CLAUSE = re.compile(
    r"^(?:(?P<link>[^:=@]+):)?(?P<key>drop|dup|duplicate|reorder|delay)"
    r"=(?P<val>[^=]+)$"
)


def _u(seed: int, link: str, seq: int, kind: str) -> float:
    """Uniform [0, 1) from a keyed hash — the determinism core: the
    value depends on nothing but its four arguments."""
    h = hashlib.blake2b(
        f"{seed}|{link}|{seq}|{kind}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2.0**64


@dataclass
class FaultPlan:
    """A complete, serializable fault schedule for one run."""

    seed: int = 0
    default: LinkFaults = field(default_factory=LinkFaults)
    links: Dict[Tuple[str, str], LinkFaults] = field(default_factory=dict)
    partitions: List[Partition] = field(default_factory=list)
    crashes: Dict[str, float] = field(default_factory=dict)
    device: DeviceFaults = field(default_factory=DeviceFaults)
    wire: WireFaults = field(default_factory=WireFaults)
    fleet: FleetFaults = field(default_factory=FleetFaults)
    spec: Optional[str] = None  # the source text, for run metadata

    # -- construction ---------------------------------------------------

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse the compact ``--chaos`` spec string (module doc)."""
        plan = cls(seed=seed, spec=spec)
        overrides: Dict[Tuple[str, str], Dict[str, float]] = {}
        defaults: Dict[str, float] = {}
        device_fields: Dict[str, object] = {}
        wire_fields: Dict[str, object] = {}
        fleet_fields: Dict[str, object] = {}
        for raw in spec.split(","):
            clause = raw.strip()
            if not clause:
                continue
            if clause.startswith("partition="):
                plan.partitions.append(_parse_partition(clause[10:]))
                continue
            if clause.startswith("crash="):
                agent, t = _parse_at(clause[6:], "crash")
                plan.crashes[agent] = t
                continue
            if clause.startswith(
                (
                    "device_oom=",
                    "device_oom_bytes=",
                    "device_transient=",
                    "nan_inject=",
                )
            ):
                key, val = clause.split("=", 1)
                device_fields.update(
                    _parse_device_value(key, val, clause)
                )
                continue
            if clause.startswith(
                ("conn_drop=", "slow_client=", "frame_corrupt=")
            ):
                key, val = clause.split("=", 1)
                wire_fields.update(
                    _parse_wire_value(key, val, clause)
                )
                continue
            if clause.startswith("replica_kill="):
                key, val = clause.split("=", 1)
                fleet_fields.update(
                    _parse_fleet_value(key, val, clause)
                )
                continue
            m = _CLAUSE.match(clause)
            if not m:
                raise FaultSpecError(
                    f"chaos spec: cannot parse clause {clause!r} "
                    "(expected key=value, LINK:key=value, "
                    "partition=A-B@S+D or crash=AGENT@T)"
                )
            key = {"duplicate": "dup"}.get(m["key"], m["key"])
            fields = _parse_fault_value(key, m["val"], clause)
            if m["link"] is None:
                defaults.update(fields)
            else:
                for lk in _parse_link(m["link"]):
                    overrides.setdefault(lk, {}).update(fields)
        plan.default = LinkFaults(**defaults)
        for lk, fields in overrides.items():
            plan.links[lk] = replace(plan.default, **fields)
        if device_fields:
            plan.device = DeviceFaults(**device_fields)
        if wire_fields:
            plan.wire = WireFaults(**wire_fields)
        if fleet_fields:
            plan.fleet = FleetFaults(**fleet_fields)
        plan.validate()
        return plan

    def validate(self) -> None:
        for lf in [self.default, *self.links.values()]:
            for name in ("drop", "dup", "reorder", "delay"):
                p = getattr(lf, name)
                if not 0.0 <= p <= 1.0:
                    raise FaultSpecError(
                        f"chaos spec: {name} probability {p} outside "
                        "[0, 1]"
                    )
            if lf.delay_s < 0:
                raise FaultSpecError(
                    f"chaos spec: negative delay {lf.delay_s}s"
                )
        for p in self.partitions:
            if p.start < 0 or p.duration <= 0:
                raise FaultSpecError(
                    f"chaos spec: partition window @{p.start}+"
                    f"{p.duration} must have start >= 0, duration > 0"
                )
        for agent, t in self.crashes.items():
            if t < 0:
                raise FaultSpecError(
                    f"chaos spec: crash={agent}@{t} in the past"
                )
        d = self.device
        for name in ("transient", "nan"):
            p = getattr(d, name)
            if not 0.0 <= p <= 1.0:
                raise FaultSpecError(
                    f"chaos spec: device {name} probability {p} "
                    "outside [0, 1]"
                )
        for name in ("oom_width_cap", "oom_rounds_cap", "nan_instance"):
            v = getattr(d, name)
            if v is not None and v < 0:
                raise FaultSpecError(
                    f"chaos spec: device {name}={v} must be >= 0"
                )
        if d.transient_after < 0:
            raise FaultSpecError(
                f"chaos spec: device_transient AFTER="
                f"{d.transient_after} must be >= 0"
            )
        w = self.wire
        for name in ("conn_drop", "frame_corrupt"):
            p = getattr(w, name)
            if not 0.0 <= p <= 1.0:
                raise FaultSpecError(
                    f"chaos spec: wire {name} probability {p} "
                    "outside [0, 1]"
                )
        for name in ("conn_drop_after", "frame_corrupt_after"):
            v = getattr(w, name)
            if v < 0:
                raise FaultSpecError(
                    f"chaos spec: wire {name}={v} must be >= 0"
                )
        if w.slow_client < 0:
            raise FaultSpecError(
                f"chaos spec: slow_client={w.slow_client}s must be "
                ">= 0"
            )
        fl = self.fleet
        if fl.replica_kill is not None and fl.replica_kill < 0:
            raise FaultSpecError(
                f"chaos spec: replica_kill={fl.replica_kill} in the "
                "past"
            )
        if (
            fl.replica_kill_instance is not None
            and fl.replica_kill_instance < 0
        ):
            raise FaultSpecError(
                "chaos spec: replica_kill instance="
                f"{fl.replica_kill_instance} must be >= 0"
            )

    def referenced_agents(self) -> set:
        """Every agent name the plan targets (crash schedules,
        partition endpoints, per-link overrides; ``*`` wildcards
        excluded).  Runtimes check these against their real roster —
        a misspelled name would otherwise inject nothing while the
        run still records the plan as applied."""
        names = set(self.crashes)
        for p in self.partitions:
            names.add(p.a)
            if p.b != "*":
                names.add(p.b)
        for src, dst in self.links:
            names.update((src, dst))
        return names

    @property
    def message_faults_configured(self) -> bool:
        """True when anything beyond crash schedules is configured —
        engines without a message plane accept crash-only plans.
        Device-layer fault kinds are deliberately NOT message faults:
        they target the supervised device dispatch of the batched
        engine (``engine/supervisor.py``)."""
        return bool(
            self.partitions
            or self.links
            or self.default != LinkFaults()
        )

    @property
    def device_faults_configured(self) -> bool:
        """True when any device-layer fault kind (``device_oom``,
        ``device_transient``, ``nan_inject``) is configured."""
        return self.device.configured

    @property
    def wire_faults_configured(self) -> bool:
        """True when any wire-level fault kind (``conn_drop``,
        ``slow_client``, ``frame_corrupt``) is configured — these
        inject at the solver service's frame loop
        (``engine/service.py``), nowhere else."""
        return self.wire.configured

    @property
    def fleet_faults_configured(self) -> bool:
        """True when any fleet-level fault kind (``replica_kill``) is
        configured — these inject at the replicated serving fleet's
        process level (``commands/fleet.py``), nowhere else: a single
        service, solve, or host runtime has no replica to kill."""
        return self.fleet.configured

    # -- queries (all pure) ---------------------------------------------

    def link_faults(self, src: str, dst: str) -> LinkFaults:
        return self.links.get((src, dst), self.default)

    def decide(self, src: str, dst: str, seq: int) -> Decision:
        """The fate of message number ``seq`` (1-based, per directed
        link).  Pure: (seed, link, seq) fully determine the result."""
        lf = self.link_faults(src, dst)
        link = f"{src}>{dst}"
        if lf.drop and _u(self.seed, link, seq, "drop") < lf.drop:
            return Decision(drop=True)
        if lf.dup and _u(self.seed, link, seq, "dup") < lf.dup:
            return Decision(dup=True)
        if lf.reorder and _u(self.seed, link, seq, "reorder") < lf.reorder:
            return Decision(reorder=True)
        if lf.delay and _u(self.seed, link, seq, "delay") < lf.delay:
            return Decision(delay=lf.delay_s)
        return Decision()

    def decisions(self, src: str, dst: str, n: int) -> List[Decision]:
        """The first ``n`` decisions of a link — the replay/audit view
        (two plans with equal seed+spec return identical lists)."""
        return [self.decide(src, dst, i) for i in range(1, n + 1)]

    def partition_heal(
        self, src: str, dst: str, now: float
    ) -> Optional[float]:
        """If the link is partitioned at ``now`` (seconds into the
        run), the time the LAST covering window heals; else None."""
        ends = [
            p.end
            for p in self.partitions
            if p.covers(src, dst) and p.start <= now < p.end
        ]
        return max(ends) if ends else None

    def crash_at(self, agent: str) -> Optional[float]:
        return self.crashes.get(agent)

    # -- device-layer queries (all pure, engine/supervisor.py seam) ------

    def oom_injected(
        self,
        width: int,
        rounds: Optional[int] = None,
        table_bytes: Optional[int] = None,
    ) -> bool:
        """Whether a device dispatch of ``width`` vmapped lanes
        covering ``rounds`` scanned rounds with a ``table_bytes``
        per-lane joined table exceeds the injected capacity — a
        deterministic capacity model (no hashing), so a degraded
        re-dispatch that fits always succeeds: chunk halvings and
        group splits converge on the width/rounds caps, and the
        budgeted sweeps' budget-halving replans
        (``ops/membound.py``) converge on the bytes cap exactly
        like real HBM."""
        d = self.device
        if d.oom_width_cap is not None and width > d.oom_width_cap:
            return True
        if (
            d.oom_bytes_cap is not None
            and table_bytes is not None
            and table_bytes > d.oom_bytes_cap
        ):
            return True
        return (
            d.oom_rounds_cap is not None
            and rounds is not None
            and rounds > d.oom_rounds_cap
        )

    def decide_device_transient(self, scope: str, seq: int) -> bool:
        """Whether dispatch attempt number ``seq`` (1-based, per
        supervisor scope) fails transiently.  Pure in
        ``(seed, scope, seq)``; retry attempts draw fresh seqs, so
        probabilities < 1 eventually let a retry through."""
        d = self.device
        if not d.transient or seq <= d.transient_after:
            return False
        if d.transient >= 1.0:
            return True
        return (
            _u(self.seed, scope, seq, "device_transient") < d.transient
        )

    def decide_nan_inject(self, instance: int, seq: int) -> bool:
        """Whether stack lane ``instance`` gets its carry poisoned at
        chunk boundary ``seq``.  Pure in ``(seed, instance, seq)``."""
        d = self.device
        if not d.nan:
            return False
        if d.nan_instance is not None and instance != d.nan_instance:
            return False
        if d.nan >= 1.0:
            return True
        return (
            _u(self.seed, f"lane{instance}", seq, "nan_inject") < d.nan
        )

    # -- wire-level queries (all pure, engine/service.py frame loop) -----

    def decide_conn_drop(self, scope: str, seq: int) -> bool:
        """Whether reply number ``seq`` (1-based, per connection) of
        connection ``scope`` is dropped — computed but never sent, the
        connection closed.  Pure in ``(seed, scope, seq)``; a
        reconnect's scope is fresh, so probabilities < 1 let a retry
        through eventually."""
        w = self.wire
        if not w.conn_drop or seq <= w.conn_drop_after:
            return False
        if w.conn_drop >= 1.0:
            return True
        return _u(self.seed, scope, seq, "conn_drop") < w.conn_drop

    def decide_frame_corrupt(self, scope: str, seq: int) -> bool:
        """Whether reply number ``seq`` of connection ``scope`` has
        its frame bytes corrupted.  Same contract as
        :meth:`decide_conn_drop`."""
        w = self.wire
        if not w.frame_corrupt or seq <= w.frame_corrupt_after:
            return False
        if w.frame_corrupt >= 1.0:
            return True
        return (
            _u(self.seed, scope, seq, "frame_corrupt") < w.frame_corrupt
        )

    # -- fleet-level queries (all pure, commands/fleet.py seam) ----------

    def decide_replica_kill(
        self, n_replicas: int
    ) -> Optional[Tuple[float, int]]:
        """The fleet's scripted kill, if any: ``(T, victim index)``.
        The victim is the pinned ``:IDX`` when given (rejected when
        out of range), else a pure hash of the seed over the replica
        count — two fleets with the same seed, spec, and size kill
        the same replica at the same time, which is what lets the
        failover soak replay bit-for-bit."""
        fl = self.fleet
        if fl.replica_kill is None:
            return None
        if n_replicas < 1:
            raise FaultSpecError(
                "chaos spec: replica_kill needs at least one replica"
            )
        if fl.replica_kill_instance is not None:
            if fl.replica_kill_instance >= n_replicas:
                raise FaultSpecError(
                    "chaos spec: replica_kill instance="
                    f"{fl.replica_kill_instance} out of range for "
                    f"{n_replicas} replica(s)"
                )
            return fl.replica_kill, fl.replica_kill_instance
        victim = min(
            int(
                _u(self.seed, "fleet", 1, "replica_kill")
                * n_replicas
            ),
            n_replicas - 1,
        )
        return fl.replica_kill, victim

    def to_meta(self) -> Dict[str, object]:
        """The replay record for run metadata: spec + seed reconstruct
        the plan exactly (``FaultPlan.from_spec(spec, seed)``)."""
        return {"spec": self.spec, "seed": self.seed}


# -- spec parsing helpers ------------------------------------------------


def _parse_fault_value(key: str, val: str, clause: str) -> Dict[str, float]:
    try:
        if key == "delay":
            if ":" in val:
                p, s = val.split(":", 1)
                return {"delay": float(p), "delay_s": float(s)}
            return {"delay": float(val)}
        return {key: float(val)}
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: bad number in clause {clause!r}"
        ) from None


def _parse_device_value(
    key: str, val: str, clause: str
) -> Dict[str, object]:
    """Parse one device-layer clause into :class:`DeviceFaults`
    fields (``device_oom=W[:R]``, ``device_transient=P[:AFTER]``,
    ``nan_inject=P[:I]`` — module docstring)."""
    head, _, tail = val.partition(":")
    try:
        if key == "device_oom":
            out: Dict[str, object] = {}
            if head.strip() not in ("-", "*", ""):
                out["oom_width_cap"] = int(head)
            if tail:
                out["oom_rounds_cap"] = int(tail)
            if not out:
                raise ValueError("empty device_oom clause")
            return out
        if key == "device_oom_bytes":
            if tail:
                # reject rather than silently drop: a clause that
                # parses but means less than the user wrote would
                # fake chaos coverage (the wire-kind rule)
                raise ValueError(
                    "device_oom_bytes takes a single byte count"
                )
            return {"oom_bytes_cap": int(head)}
        if key == "device_transient":
            out = {"transient": float(head)}
            if tail:
                out["transient_after"] = int(tail)
            return out
        # nan_inject
        out = {"nan": float(head)}
        if tail:
            out["nan_instance"] = int(tail)
        return out
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: bad number in clause {clause!r} (expected "
            "device_oom=W[:R], device_oom_bytes=N, "
            "device_transient=P[:AFTER] or nan_inject=P[:INSTANCE])"
        ) from None


def _parse_wire_value(
    key: str, val: str, clause: str
) -> Dict[str, object]:
    """Parse one wire-level clause into :class:`WireFaults` fields
    (``conn_drop=P[:AFTER]``, ``slow_client=W``,
    ``frame_corrupt=P[:AFTER]`` — module docstring)."""
    head, _, tail = val.partition(":")
    try:
        if key == "slow_client":
            if tail:
                raise ValueError("slow_client takes one value")
            return {"slow_client": float(head)}
        out: Dict[str, object] = {key: float(head)}
        if tail:
            out[f"{key}_after"] = int(tail)
        return out
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: bad number in clause {clause!r} (expected "
            "conn_drop=P[:AFTER], slow_client=W or "
            "frame_corrupt=P[:AFTER])"
        ) from None


def _parse_fleet_value(
    key: str, val: str, clause: str
) -> Dict[str, object]:
    """Parse one fleet-level clause into :class:`FleetFaults` fields
    (``replica_kill=T[:IDX]`` — module docstring)."""
    head, _, tail = val.partition(":")
    try:
        out: Dict[str, object] = {key: float(head)}
        if tail:
            out[f"{key}_instance"] = int(tail)
        return out
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: bad number in clause {clause!r} (expected "
            "replica_kill=T[:IDX])"
        ) from None


def _parse_link(text: str) -> List[Tuple[str, str]]:
    if ">" in text:
        src, dst = text.split(">", 1)
        return [(src.strip(), dst.strip())]
    if "-" in text:
        a, b = (s.strip() for s in text.split("-", 1))
        return [(a, b), (b, a)]
    raise FaultSpecError(
        f"chaos spec: link {text!r} must be SRC>DST or A-B"
    )


def _parse_partition(text: str) -> Partition:
    try:
        link, window = text.split("@", 1)
        start, duration = window.split("+", 1)
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: partition {text!r} must be A-B@START+DURATION"
        ) from None
    directed = ">" in link
    if directed:
        a, b = link.split(">", 1)
    elif "-" in link:
        a, b = link.split("-", 1)
    else:
        raise FaultSpecError(
            f"chaos spec: partition link {link!r} must be A-B, A>B "
            "or A-*"
        )
    try:
        return Partition(
            a.strip(), b.strip(), float(start), float(duration),
            directed=directed,
        )
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: bad number in partition {text!r}"
        ) from None


def _parse_at(text: str, kind: str) -> Tuple[str, float]:
    try:
        name, t = text.split("@", 1)
        return name.strip(), float(t)
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: {kind}={text!r} must be {kind}=NAME@SECONDS"
        ) from None
