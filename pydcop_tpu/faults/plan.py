"""Seeded, fully deterministic fault plans.

A :class:`FaultPlan` answers, for every outbound message on a directed
agent link, "what happens to this one?" — and answers it identically
on every run with the same seed: each decision is a pure hash of
``(seed, link, per-link message sequence number, fault kind)``.  No
wall-clock, no RNG stream shared across threads, no iteration-order
dependence — the properties that make a fault sequence replayable.

Plans are built programmatically or parsed from the compact
``--chaos`` spec string (see :meth:`FaultPlan.from_spec`)::

    drop=0.05,dup=0.02,reorder=0.1,delay=0.1:0.05,
    a1>a2:drop=0.5,partition=a1-a2@0.5+2,crash=a3@1.5

- bare ``key=value`` clauses set the DEFAULT probabilities for every
  link; ``SRC>DST:key=value`` overrides one directed link and
  ``A-B:key=value`` both directions;
- ``delay=P:S`` delays a message by ``S`` seconds with probability
  ``P``;
- ``partition=A-B@START+DURATION`` blocks the link(s) between ``A``
  and ``B`` (``A-*``: every link touching ``A``; ``A>B``: one
  direction) from ``START`` seconds into the run for ``DURATION``
  seconds — messages are HELD and released at heal time, unless the
  outage outlives the tolerance grace window (then the link is
  declared dead, the permanent-failure path);
- ``crash=AGENT@T`` hard-kills the agent's process ``T`` seconds into
  the run (the scripted analogue of SIGKILL, for exercising the
  replication/repair machinery on demand).
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field, replace
from typing import Dict, List, NamedTuple, Optional, Tuple


class FaultSpecError(ValueError):
    """Malformed ``--chaos`` spec (a usage error, not a failure)."""


@dataclass(frozen=True)
class LinkFaults:
    """Per-link fault probabilities (all default off)."""

    drop: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    delay: float = 0.0  # probability
    delay_s: float = 0.05  # applied delay, seconds


@dataclass(frozen=True)
class Partition:
    """A timed outage between ``a`` and ``b`` (``b='*'``: every link
    touching ``a``); ``directed`` limits it to the a→b direction."""

    a: str
    b: str
    start: float
    duration: float
    directed: bool = False

    @property
    def end(self) -> float:
        return self.start + self.duration

    def covers(self, src: str, dst: str) -> bool:
        if self.a == src and self.b in (dst, "*"):
            return True
        if self.directed:
            return False
        return self.a == dst and self.b in (src, "*")


class Decision(NamedTuple):
    """The fate of one message (at most one fault fires per message —
    drop wins over dup over reorder over delay)."""

    drop: bool = False
    dup: bool = False
    reorder: bool = False
    delay: float = 0.0


_CLAUSE = re.compile(
    r"^(?:(?P<link>[^:=@]+):)?(?P<key>drop|dup|duplicate|reorder|delay)"
    r"=(?P<val>[^=]+)$"
)


def _u(seed: int, link: str, seq: int, kind: str) -> float:
    """Uniform [0, 1) from a keyed hash — the determinism core: the
    value depends on nothing but its four arguments."""
    h = hashlib.blake2b(
        f"{seed}|{link}|{seq}|{kind}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2.0**64


@dataclass
class FaultPlan:
    """A complete, serializable fault schedule for one run."""

    seed: int = 0
    default: LinkFaults = field(default_factory=LinkFaults)
    links: Dict[Tuple[str, str], LinkFaults] = field(default_factory=dict)
    partitions: List[Partition] = field(default_factory=list)
    crashes: Dict[str, float] = field(default_factory=dict)
    spec: Optional[str] = None  # the source text, for run metadata

    # -- construction ---------------------------------------------------

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse the compact ``--chaos`` spec string (module doc)."""
        plan = cls(seed=seed, spec=spec)
        overrides: Dict[Tuple[str, str], Dict[str, float]] = {}
        defaults: Dict[str, float] = {}
        for raw in spec.split(","):
            clause = raw.strip()
            if not clause:
                continue
            if clause.startswith("partition="):
                plan.partitions.append(_parse_partition(clause[10:]))
                continue
            if clause.startswith("crash="):
                agent, t = _parse_at(clause[6:], "crash")
                plan.crashes[agent] = t
                continue
            m = _CLAUSE.match(clause)
            if not m:
                raise FaultSpecError(
                    f"chaos spec: cannot parse clause {clause!r} "
                    "(expected key=value, LINK:key=value, "
                    "partition=A-B@S+D or crash=AGENT@T)"
                )
            key = {"duplicate": "dup"}.get(m["key"], m["key"])
            fields = _parse_fault_value(key, m["val"], clause)
            if m["link"] is None:
                defaults.update(fields)
            else:
                for lk in _parse_link(m["link"]):
                    overrides.setdefault(lk, {}).update(fields)
        plan.default = LinkFaults(**defaults)
        for lk, fields in overrides.items():
            plan.links[lk] = replace(plan.default, **fields)
        plan.validate()
        return plan

    def validate(self) -> None:
        for lf in [self.default, *self.links.values()]:
            for name in ("drop", "dup", "reorder", "delay"):
                p = getattr(lf, name)
                if not 0.0 <= p <= 1.0:
                    raise FaultSpecError(
                        f"chaos spec: {name} probability {p} outside "
                        "[0, 1]"
                    )
            if lf.delay_s < 0:
                raise FaultSpecError(
                    f"chaos spec: negative delay {lf.delay_s}s"
                )
        for p in self.partitions:
            if p.start < 0 or p.duration <= 0:
                raise FaultSpecError(
                    f"chaos spec: partition window @{p.start}+"
                    f"{p.duration} must have start >= 0, duration > 0"
                )
        for agent, t in self.crashes.items():
            if t < 0:
                raise FaultSpecError(
                    f"chaos spec: crash={agent}@{t} in the past"
                )

    def referenced_agents(self) -> set:
        """Every agent name the plan targets (crash schedules,
        partition endpoints, per-link overrides; ``*`` wildcards
        excluded).  Runtimes check these against their real roster —
        a misspelled name would otherwise inject nothing while the
        run still records the plan as applied."""
        names = set(self.crashes)
        for p in self.partitions:
            names.add(p.a)
            if p.b != "*":
                names.add(p.b)
        for src, dst in self.links:
            names.update((src, dst))
        return names

    @property
    def message_faults_configured(self) -> bool:
        """True when anything beyond crash schedules is configured —
        engines without a message plane accept crash-only plans."""
        return bool(
            self.partitions
            or self.links
            or self.default != LinkFaults()
        )

    # -- queries (all pure) ---------------------------------------------

    def link_faults(self, src: str, dst: str) -> LinkFaults:
        return self.links.get((src, dst), self.default)

    def decide(self, src: str, dst: str, seq: int) -> Decision:
        """The fate of message number ``seq`` (1-based, per directed
        link).  Pure: (seed, link, seq) fully determine the result."""
        lf = self.link_faults(src, dst)
        link = f"{src}>{dst}"
        if lf.drop and _u(self.seed, link, seq, "drop") < lf.drop:
            return Decision(drop=True)
        if lf.dup and _u(self.seed, link, seq, "dup") < lf.dup:
            return Decision(dup=True)
        if lf.reorder and _u(self.seed, link, seq, "reorder") < lf.reorder:
            return Decision(reorder=True)
        if lf.delay and _u(self.seed, link, seq, "delay") < lf.delay:
            return Decision(delay=lf.delay_s)
        return Decision()

    def decisions(self, src: str, dst: str, n: int) -> List[Decision]:
        """The first ``n`` decisions of a link — the replay/audit view
        (two plans with equal seed+spec return identical lists)."""
        return [self.decide(src, dst, i) for i in range(1, n + 1)]

    def partition_heal(
        self, src: str, dst: str, now: float
    ) -> Optional[float]:
        """If the link is partitioned at ``now`` (seconds into the
        run), the time the LAST covering window heals; else None."""
        ends = [
            p.end
            for p in self.partitions
            if p.covers(src, dst) and p.start <= now < p.end
        ]
        return max(ends) if ends else None

    def crash_at(self, agent: str) -> Optional[float]:
        return self.crashes.get(agent)

    def to_meta(self) -> Dict[str, object]:
        """The replay record for run metadata: spec + seed reconstruct
        the plan exactly (``FaultPlan.from_spec(spec, seed)``)."""
        return {"spec": self.spec, "seed": self.seed}


# -- spec parsing helpers ------------------------------------------------


def _parse_fault_value(key: str, val: str, clause: str) -> Dict[str, float]:
    try:
        if key == "delay":
            if ":" in val:
                p, s = val.split(":", 1)
                return {"delay": float(p), "delay_s": float(s)}
            return {"delay": float(val)}
        return {key: float(val)}
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: bad number in clause {clause!r}"
        ) from None


def _parse_link(text: str) -> List[Tuple[str, str]]:
    if ">" in text:
        src, dst = text.split(">", 1)
        return [(src.strip(), dst.strip())]
    if "-" in text:
        a, b = (s.strip() for s in text.split("-", 1))
        return [(a, b), (b, a)]
    raise FaultSpecError(
        f"chaos spec: link {text!r} must be SRC>DST or A-B"
    )


def _parse_partition(text: str) -> Partition:
    try:
        link, window = text.split("@", 1)
        start, duration = window.split("+", 1)
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: partition {text!r} must be A-B@START+DURATION"
        ) from None
    directed = ">" in link
    if directed:
        a, b = link.split(">", 1)
    elif "-" in link:
        a, b = link.split("-", 1)
    else:
        raise FaultSpecError(
            f"chaos spec: partition link {link!r} must be A-B, A>B "
            "or A-*"
        )
    try:
        return Partition(
            a.strip(), b.strip(), float(start), float(duration),
            directed=directed,
        )
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: bad number in partition {text!r}"
        ) from None


def _parse_at(text: str, kind: str) -> Tuple[str, float]:
    try:
        name, t = text.split("@", 1)
        return name.strip(), float(t)
    except ValueError:
        raise FaultSpecError(
            f"chaos spec: {kind}={text!r} must be {kind}=NAME@SECONDS"
        ) from None
