"""Deterministic fault injection for the message planes.

The source system's signature capability is *resilient* multi-agent
solving — but resilience you cannot reproduce on demand is a claim,
not a property.  This package is the robustness analogue of a perf
harness:

- :class:`~pydcop_tpu.faults.plan.FaultPlan` — a seeded, fully
  deterministic plan: per-link drop/duplicate/reorder/delay
  probabilities, timed link partitions with heal times, and
  crash-agent schedules.  Same seed ⇒ byte-identical fault sequence
  (decisions are a pure hash of ``(seed, link, message-seq)``, never
  of wall-clock or thread timing).
- :class:`~pydcop_tpu.faults.chaos.ChaosCommunicationLayer` — wraps
  any :class:`~pydcop_tpu.infrastructure.communication.CommunicationLayer`
  (in-process or TCP) and applies the plan to every outbound message.
- **Device-layer fault kinds** (``device_oom``, ``device_transient``,
  ``nan_inject``) extend the same seeded contract BELOW the message
  plane: they are injected at the supervised device-dispatch seam
  (:mod:`pydcop_tpu.engine.supervisor`) so the batched engine's
  recovery paths — transient retry, OOM chunk-halving and group
  splits, per-instance NaN quarantine — are exercised on demand.
- **Wire-level fault kinds** (``conn_drop``, ``slow_client``,
  ``frame_corrupt``) extend it to the serving boundary: they are
  injected in the solver service's frame loop
  (:mod:`pydcop_tpu.engine.service`) so the client's idempotent
  reconnect/retry path and the server's reply cache are exercised on
  demand (``pydcop_tpu serve --chaos``, ``docs/serving.md``).

Wired through ``--chaos SPEC --chaos_seed N`` on the ``solve``,
``run``, ``agent`` and ``orchestrator`` commands and through
``api.solve(chaos=..., chaos_seed=...)``; the plan is recorded in the
run's result metadata for replay.  See ``docs/faults.md``.
"""

from pydcop_tpu.faults.chaos import ChaosCommunicationLayer
from pydcop_tpu.faults.plan import (
    DeviceFaults,
    FaultPlan,
    FaultSpecError,
    FleetFaults,
    LinkFaults,
    Partition,
    WireFaults,
)

__all__ = [
    "ChaosCommunicationLayer",
    "DeviceFaults",
    "FaultPlan",
    "FaultSpecError",
    "FleetFaults",
    "LinkFaults",
    "Partition",
    "WireFaults",
]
