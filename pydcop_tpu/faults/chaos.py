"""ChaosCommunicationLayer: apply a :class:`FaultPlan` to any
communication layer.

The wrapper sits between an agent's computations and its real
transport (in-process queues or the TCP message plane) and gives every
outbound message to the plan: drop it, duplicate it, swap it with the
next one on the same link, delay it, hold it through a partition
window, or — past the tolerance grace window — declare the link dead
exactly the way a retried-out TCP channel would, so the runtimes'
permanent-failure paths (repair, graceful degradation) fire from
*injected* faults the same as from real ones.

Determinism contract: WHICH message suffers WHICH fault is a pure
function of ``(plan seed, link, per-link sequence number)`` — recorded
in :attr:`events` as ``(kind, link, seq)`` tuples, so two runs with
the same plan produce the identical per-link event sequence.  Delivery
*timing* of delayed messages naturally follows the wall clock; per-link
FIFO order is preserved through delays and holds (only an explicit
``reorder`` fault violates it, by design).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from pydcop_tpu.faults.plan import FaultPlan
from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    CommunicationLayer,
    UnknownComputation,
    UnreachableAgent,
)
from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.telemetry import get_metrics, get_tracer

logger = logging.getLogger(__name__)

# a reorder-held message is released after this long when no follow-up
# message arrives on its link to swap with (an unpaired hold must not
# strand the last message of a link forever)
REORDER_RELEASE = 0.25


class ChaosCommunicationLayer(CommunicationLayer):
    """Wrap ``inner`` and apply ``plan`` to every outbound message of
    agent ``src_agent``.

    ``grace`` is the transient-fault tolerance window: a partition
    whose remaining outage exceeds it flips the link from "hold and
    heal" to "dead" — reported once through ``on_send_error`` (the
    same hook the TCP plane's writer uses), after which messages to
    the dead link are recorded and dropped.  ``on_crash`` runs when
    the plan schedules this agent's crash (process runtimes pass a
    hard-exit; in-process runtimes reject crash clauses instead).

    Registration, discovery, addressing and the inbound path all
    delegate to ``inner`` — chaos is outbound-only, which is enough:
    every link has a chaos layer at its sending end.
    """

    def __init__(
        self,
        inner: CommunicationLayer,
        plan: FaultPlan,
        src_agent: str,
        grace: float = 5.0,
        on_send_error: Optional[Callable[[str, BaseException], None]] = None,
        on_crash: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        # no super().__init__: discovery is delegated to inner so the
        # transport's own inbound routing keeps working unchanged
        self.inner = inner
        self.plan = plan
        self.src_agent = src_agent
        self.grace = grace
        self.on_send_error = on_send_error
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._seq: Dict[str, int] = {}  # per-destination message count
        self._last_due: Dict[str, float] = {}  # per-dest FIFO fence
        self._dead: Dict[str, str] = {}  # dest -> reason
        self._reorder_held: Dict[str, List[tuple]] = {}
        self._in_flight = 0  # accepted but not yet handed to inner
        self.events: List[Tuple[str, str, int]] = []
        # scheduler: one timer wheel for delays, partition holds,
        # reorder releases and the crash schedule
        self._heap: List[tuple] = []
        self._heap_n = 0
        self._cond = threading.Condition(self._lock)
        self._closing = False
        self._thread = threading.Thread(
            target=self._scheduler_loop,
            name=f"chaos-{src_agent}",
            daemon=True,
        )
        self._thread.start()
        crash_t = plan.crash_at(src_agent)
        if crash_t is not None:
            self._schedule(self._t0 + crash_t, self._crash, on_crash)

    # -- delegation -----------------------------------------------------

    @property
    def discovery(self):
        return self.inner.discovery

    def register(self, agent_name: str, messaging) -> None:
        self.inner.register(agent_name, messaging)

    def unregister(self, agent_name: str) -> None:
        self.inner.unregister(agent_name)

    def set_addresses(self, directory) -> None:
        self.inner.set_addresses(directory)

    def forget_agent(self, name: str) -> None:
        self.inner.forget_agent(name)

    @property
    def address(self):
        return self.inner.address

    @property
    def count_sent(self) -> int:
        """Inner transport's ledger PLUS chaos-held messages: a frame
        waiting out a delay or partition must keep the orchestrator's
        two-counter quiescence rule (sent == delivered) from firing
        while it is invisible to both transport and destination."""
        with self._lock:
            held = self._in_flight
        return getattr(self.inner, "count_sent", 0) + held

    @property
    def in_flight(self) -> int:
        """Messages accepted from computations but not yet given to the
        transport (delayed / partition-held / reorder-held) — the
        in-process runtimes add this to their idle predicate."""
        with self._lock:
            return self._in_flight

    def close(self) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if hasattr(self.inner, "close"):
            self.inner.close()

    # -- event record ---------------------------------------------------

    def _record(self, kind: str, dest: str, seq: int) -> None:
        link = f"{self.src_agent}>{dest}"
        with self._lock:
            self.events.append((kind, link, seq))
        # injected faults land on the run's telemetry timeline (same
        # trace as cycle/message events) — only when a session is
        # active, and always outside the lock
        met = get_metrics()
        if met.enabled:
            met.inc(f"fault.{kind}")
        tr = get_tracer()
        if tr.enabled:
            tr.event(
                kind, cat="fault", link=link, seq=seq,
                seed=self.plan.seed,
            )

    def event_summary(self) -> Dict[str, int]:
        with self._lock:
            counts: Dict[str, int] = {}
            for kind, _, _ in self.events:
                counts[kind] = counts.get(kind, 0) + 1
            return counts

    # -- outbound -------------------------------------------------------

    def send_msg(
        self,
        dest_agent: str,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        if dest_agent == self.src_agent:
            # an agent's own loopback is process-internal memory, not a
            # network link — never faulted
            self.inner.send_msg(dest_agent, src_comp, dest_comp, msg, priority)
            return
        now = self._clock() - self._t0
        with self._lock:
            seq = self._seq[dest_agent] = self._seq.get(dest_agent, 0) + 1
            dead = self._dead.get(dest_agent)
        if dead is not None:
            self._record("unreachable", dest_agent, seq)
            return  # link already declared dead (reported once)
        heal = self.plan.partition_heal(self.src_agent, dest_agent, now)
        send = (dest_agent, src_comp, dest_comp, msg, priority)
        if heal is not None:
            if heal - now <= self.grace:
                # transient blip: hold, release at heal time (FIFO)
                self._record("hold", dest_agent, seq)
                self._defer(heal, send)
            else:
                # outlives the grace window: after grace actually
                # elapses (the time a retrying transport would spend),
                # the link is declared dead — the permanent-fault path
                self._record("partition", dest_agent, seq)
                with self._lock:
                    self._in_flight += 1
                self._schedule(
                    self._t0 + now + self.grace, self._give_up, dest_agent
                )
            return
        d = self.plan.decide(self.src_agent, dest_agent, seq)
        if d.drop:
            self._record("drop", dest_agent, seq)
            return
        if d.dup:
            self._record("dup", dest_agent, seq)
            self._dispatch(send)
            self._dispatch(send)
            return
        if d.reorder:
            # hold this message; the NEXT one on the link overtakes it
            self._record("reorder", dest_agent, seq)
            with self._lock:
                self._in_flight += 1
                self._reorder_held.setdefault(dest_agent, []).append(send)
            self._schedule(
                self._clock() + REORDER_RELEASE,
                self._release_reorder, dest_agent,
            )
            return
        if d.delay:
            self._record("delay", dest_agent, seq)
            self._defer(now + d.delay, send)
            return
        self._dispatch(send)

    # -- internals ------------------------------------------------------

    def _dispatch(self, send: tuple) -> None:
        """Hand one message to the transport, respecting the per-dest
        FIFO fence (a message may never overtake an earlier held one),
        then release any reorder-held message it overtakes."""
        dest = send[0]
        with self._lock:
            fence = self._last_due.get(dest, 0.0)
            now_abs = self._clock()
            if fence > now_abs:
                self._in_flight += 1
                self._push(fence, self._forward_scheduled, send)
                held = []
            else:
                held = self._reorder_held.pop(dest, [])
                if held:
                    self._in_flight -= len(held)
        if fence > now_abs:
            return
        self._forward(send)
        for h in held:
            self._forward(h)

    def _defer(self, due_rel: float, send: tuple) -> None:
        """Schedule a forward at ``due_rel`` (run-relative seconds),
        advancing the link's FIFO fence so later immediate messages
        queue up behind it instead of overtaking."""
        dest = send[0]
        due_abs = self._t0 + due_rel
        with self._lock:
            due_abs = max(due_abs, self._last_due.get(dest, 0.0))
            self._last_due[dest] = due_abs
            self._in_flight += 1
            self._push(due_abs, self._forward_scheduled, send)

    def _forward_scheduled(self, send: tuple) -> None:
        with self._lock:
            self._in_flight -= 1
        self._forward(send)

    def _forward(self, send: tuple) -> None:
        dest_agent, src_comp, dest_comp, msg, priority = send
        with self._lock:
            if dest_agent in self._dead:
                return  # a hold released after the link died: nothing
                # may be delivered on a dead link (reported already)
        try:
            self.inner.send_msg(dest_agent, src_comp, dest_comp, msg, priority)
        except (UnreachableAgent, UnknownComputation) as e:
            # the transport's own failure, surfaced the transport's way
            cb = self.on_send_error
            if cb is not None:
                cb(dest_agent, e)
            else:
                logger.warning(
                    "chaos: transport failure to %s: %s", dest_agent, e
                )

    def _give_up(self, dest_agent: str) -> None:
        with self._lock:
            already = dest_agent in self._dead
            self._dead[dest_agent] = "injected partition outlived grace"
            self._in_flight -= 1  # the frame that triggered this hold
            dropped = self._reorder_held.pop(dest_agent, [])
            self._in_flight -= len(dropped)
        if already:
            return
        err = UnreachableAgent(
            f"{dest_agent}: injected partition outlived the "
            f"{self.grace:.1f}s grace window"
        )
        cb = self.on_send_error
        if cb is not None:
            cb(dest_agent, err)
        else:
            logger.warning("chaos: %s", err)

    def _release_reorder(self, dest_agent: str) -> None:
        """No follow-up message arrived to swap with: release."""
        with self._lock:
            held = self._reorder_held.pop(dest_agent, [])
            self._in_flight -= len(held)
        for send in held:
            self._forward(send)

    def _crash(self, on_crash: Optional[Callable[[], None]]) -> None:
        self._record("crash", self.src_agent, 0)
        if on_crash is not None:
            on_crash()
        else:  # pragma: no cover — wiring always sets on_crash
            logger.warning(
                "chaos: crash scheduled for %s but no on_crash handler "
                "installed; ignoring", self.src_agent,
            )

    # -- timer wheel ----------------------------------------------------

    def _push(self, due_abs: float, fn, arg) -> None:
        """Caller holds the lock."""
        self._heap_n += 1
        heapq.heappush(self._heap, (due_abs, self._heap_n, fn, arg))
        self._cond.notify()

    def _schedule(self, due_abs: float, fn, arg) -> None:
        with self._lock:
            self._push(due_abs, fn, arg)

    def _scheduler_loop(self) -> None:
        while True:
            with self._cond:
                while not self._closing:
                    if self._heap:
                        wait = self._heap[0][0] - self._clock()
                        if wait <= 0:
                            break
                        self._cond.wait(wait)
                    else:
                        self._cond.wait()
                if self._closing:
                    return
                _, _, fn, arg = heapq.heappop(self._heap)
            try:
                fn(arg)  # outside the lock: may hit the real network
            except Exception:  # pragma: no cover — keep the wheel alive
                logger.exception("chaos scheduler action failed")
