"""Command-line interface (reference: ``pydcop/pydcop.py``).

``python -m pydcop_tpu <command> ...`` with one module per subcommand
under ``pydcop_tpu/commands/`` — the same layout as the reference CLI:
solve, run, graph, distribute, generate, batch, consolidate,
replica_dist, orchestrator, agent; plus infer (exact
marginals/log_z/MAP over the cost model, ``docs/semirings.md``),
serve (the resident continuous-batching solver service,
``docs/serving.md``) and trace-summary (telemetry trace aggregation,
``docs/observability.md``).
"""

from __future__ import annotations

import argparse
import importlib
import logging
import logging.config
import os
import sys

COMMANDS = [
    "solve",
    # exact inference (marginals / log_z / map) over the cost model —
    # the semiring contraction core (docs/semirings.md)
    "infer",
    "run",
    "graph",
    "distribute",
    "generate",
    "batch",
    "consolidate",
    "replica_dist",
    "orchestrator",
    "agent",
    "worker",
    # resident continuous-batching solver service (docs/serving.md)
    "serve",
    # self-healing replicated serving fleet: consistent-hash router +
    # N serve replicas with k-resilient session replication
    # (docs/serving.md, "The fleet")
    "fleet",
    # live terminal view of a serve --metrics_port exporter
    # (docs/observability.md, "Serving observability")
    "top",
    # graftlint invariant checks (tools/graftlint, docs/linting.md)
    "lint",
    # telemetry trace aggregation (module trace_summary registers the
    # subcommand as `trace-summary`)
    "trace_summary",
    # flight-recorder dump renderer (module flight_dump registers the
    # subcommand as `flight-dump`)
    "flight_dump",
    # performance-trajectory tooling over benchdata/ledger.jsonl
    # (tools/benchkeeper, docs/performance.md) — modules register the
    # subcommands as `bench-history` / `bench-compare`
    "bench_history",
    "bench_compare",
]


def _add_global_args(parser: argparse.ArgumentParser, suppress: bool) -> None:
    """Global options accepted both before and after the subcommand.

    At the sub level defaults are SUPPRESSed so a flag given before the
    subcommand is not clobbered by the subparser's default.
    """

    def d(value):
        return argparse.SUPPRESS if suppress else value

    parser.add_argument(
        "-v", "--verbosity", type=int, default=d(0), help="0..3"
    )
    parser.add_argument(
        "--log", type=str, default=d(None), help="logging config file"
    )
    parser.add_argument(
        "-t", "--timeout", type=float, default=d(None),
        help="wall-clock timeout (seconds)",
    )
    parser.add_argument(
        "--output", type=str, default=d(None),
        help="write the result JSON to this file as well as stdout",
    )


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pydcop_tpu",
        description="TPU-native DCOP solving (pyDcop-capability CLI)",
    )
    _add_global_args(parser, suppress=False)
    parser.add_argument("--version", action="version", version="0.1.0")
    global_parent = argparse.ArgumentParser(add_help=False)
    _add_global_args(global_parent, suppress=True)
    sub = parser.add_subparsers(dest="command", required=True)
    for name in COMMANDS:
        mod = importlib.import_module(f"pydcop_tpu.commands.{name}")
        mod.set_parser(_SubparsersProxy(sub, [global_parent]))
    return parser


class _SubparsersProxy:
    """Injects the global-options parent into every add_parser call."""

    def __init__(self, sub, parents):
        self._sub = sub
        self._parents = parents

    def add_parser(self, *args, **kwargs):
        parents = list(kwargs.pop("parents", [])) + self._parents
        return self._sub.add_parser(*args, parents=parents, **kwargs)


def _apply_platform_override() -> None:
    """Honor PYDCOP_TPU_PLATFORM (cpu|axon|tpu|...).

    The axon TPU plugin on this image overrides ``JAX_PLATFORMS``, so
    the pin must go through ``jax.config`` before any backend init.
    """
    plat = os.environ.get("PYDCOP_TPU_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def main(argv=None) -> int:
    _apply_platform_override()
    parser = build_parser()
    args = parser.parse_args(argv)
    levels = [logging.ERROR, logging.WARNING, logging.INFO, logging.DEBUG]
    logging.basicConfig(
        level=levels[min(args.verbosity, 3)],
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if args.log:
        logging.config.fileConfig(args.log, disable_existing_loggers=False)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
