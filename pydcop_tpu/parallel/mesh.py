"""Mesh / sharding helpers — the TPU-native "distributed backend".

Reference counterpart: ``pydcop/infrastructure/communication.py`` (the
HTTP/in-process message layers).  Here, "distribution" of the solve is
SPMD over a ``jax.sharding.Mesh``: constraints and their directed edges
are sharded across devices (shard-major layout produced by
``compile_dcop(n_shards=...)``), variables are replicated, and a
round's whole neighbor exchange compiles to one ``psum`` of the
[n_vars, d] accumulator over ICI — instead of N HTTP POSTs.

Multi-host runs use the same program under ``jax.distributed`` over
DCN: the mesh simply spans more devices; nothing else changes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from pydcop_tpu.ops.compile import ArityBucket, CompiledProblem

SHARD_AXIS = "shard"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    """Version-compat ``shard_map``: one call site shape for every jax
    this repo runs on.

    jax >= 0.6 exposes ``jax.shard_map`` with the ``check_vma`` kwarg;
    0.4.x only has ``jax.experimental.shard_map.shard_map`` where the
    same knob is spelled ``check_rep``.  Every sharded entry point
    (``engine/batched.py``, the sharded HLO guards) goes through this
    wrapper so a jax upgrade/downgrade is a one-line concern HERE, not
    thirteen failing tier-1 tests.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
            **kwargs,
        )
    from jax.experimental import shard_map as _sm

    return _sm.shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
        **kwargs,
    )


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all).

    Single-process fallback: when more devices are requested than the
    backend exposes, the error spells out the host-platform override
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N``) that turns
    one CPU into N virtual devices — the same mechanism the test suite
    uses (``tests/conftest.py``) — instead of leaving the user to
    reverse-engineer it from a bare count mismatch.
    """
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"Requested {n_devices} devices, only {len(devs)} "
                "available; on a single-process CPU host set "
                "XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{n_devices} before jax initializes to get virtual "
                "devices"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def problem_pspecs(problem: CompiledProblem) -> CompiledProblem:
    """A CompiledProblem-shaped pytree of PartitionSpecs.

    Constraint/edge/bucket arrays shard on axis 0 (the shard-major
    layout); per-variable arrays and the flat table pool are replicated.
    """
    sh, rp = P(SHARD_AXIS), P()
    return CompiledProblem(
        domain_sizes=rp,
        unary=rp,
        init_idx=rp,
        tables_flat=rp,
        con_offset=sh,
        con_scopes=sh,
        con_strides=sh,
        edge_var=sh,
        edge_con=sh,
        edge_offset=sh,
        edge_stride=sh,
        edge_covars=sh,
        edge_costrides=sh,
        neighbors=rp,
        neighbor_mask=rp,
        # global edge ids — only meaningful on the single-shard path,
        # replicated here so the pytree structure matches
        var_edges=rp,
        buckets={
            k: ArityBucket(
                tables=sh,
                # transposed layout: constraints ride the LAST axis
                tables_t=P(*([None] * k + [SHARD_AXIS])),
                scopes=sh,
                edge_slot=sh,
            )
            for k in problem.buckets
        },
        var_names=problem.var_names,
        domain_labels=problem.domain_labels,
        con_names=problem.con_names,
        maximize=problem.maximize,
        n_shards=problem.n_shards,
        n_real_edges=problem.n_real_edges,
        var_slot_counts=problem.var_slot_counts,
        n_pad_vars=problem.n_pad_vars,
    )


def state_pspecs(algo_module, problem: CompiledProblem) -> Dict[str, Any]:
    """State sharding for an algorithm: its own ``state_specs`` if
    declared, else fully replicated (values-only state)."""
    if hasattr(algo_module, "state_specs"):
        return algo_module.state_specs(problem)
    return {"values": P()}


def shard_problem(
    problem: CompiledProblem, mesh: Mesh
) -> CompiledProblem:
    """Place a (shard-major compiled) problem onto the mesh."""
    if problem.n_shards != mesh.devices.size:
        raise ValueError(
            f"Problem compiled for {problem.n_shards} shard(s) but mesh "
            f"has {mesh.devices.size} device(s); recompile with "
            f"compile_dcop(dcop, n_shards={mesh.devices.size})"
        )
    specs = problem_pspecs(problem)

    def place(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, problem, specs)
