from pydcop_tpu.parallel.mesh import (
    SHARD_AXIS,
    make_mesh,
    problem_pspecs,
    shard_map,
    shard_problem,
    state_pspecs,
)
