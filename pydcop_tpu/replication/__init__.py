"""k-resilient computation replication (reference: ``pydcop/replication/``).

``ucs_hostingcosts`` places k replicas of every active computation on
other agents, minimizing hosting + route costs (the reference's DRPM
distributed-UCS semantics, computed as a host-side control-plane step —
see the module docstring for the equivalence argument).  ``repair``
re-hosts orphaned computations after an agent departure by building a
small *reparation DCOP* and solving it with this framework's own
batched engine.
"""

from pydcop_tpu.replication.ucs_hostingcosts import (  # noqa: F401
    ReplicaDistribution,
    replica_distribution,
)
from pydcop_tpu.replication.repair import repair_placement  # noqa: F401
