"""Repair-on-departure: re-host orphaned computations via a reparation
DCOP solved by this framework's own batched engine.

Role-equivalent to the reference's repair protocol (orchestrator +
``ResilientAgent`` halves): when an agent leaves, the agents holding
replicas of its computations decide among themselves who takes each one
over, by solving a small DCOP.  The reference formulates it with binary
"do I host it?" variables solved by local search; here each orphaned
computation gets one *selection* variable whose domain is its candidate
agents — an equivalent encoding of the same decision problem (a binary
one-hot vector over candidates ≡ one categorical variable) that keeps
constraint arity bounded for the TPU compiler.

Costs mirror the reference's objective: hosting costs draw each
computation to its cheapest candidate, and a pairwise concentration
penalty (the soft form of the capacity constraint) spreads orphans
across agents.  After the solve, any remaining hard capacity violation
is projected out greedily (cheapest feasible alternative), which the
reference achieves by its hard constraints.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Mapping, Optional

import numpy as np


def build_reparation_dcop(
    candidates: Mapping[str, List[str]],
    agents: Mapping[str, "AgentDef"],
    footprint: Optional[Callable[[str], float]] = None,
    concentration_weight: float = 0.5,
):
    """Build the reparation DCOP.

    candidates: orphaned computation → candidate agent names (replica
    holders).  Returns the DCOP; its variables are named after the
    orphaned computations and their domains are the candidate agents.
    """
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    dcop = DCOP("reparation", objective="min")
    variables: Dict[str, Variable] = {}
    for comp, cands in sorted(candidates.items()):
        if not cands:
            continue
        dom = Domain(f"cands_{comp}", "agents", list(cands))
        v = Variable(comp, dom)
        variables[comp] = v
        dcop.add_variable(v)
        hosting = np.array(
            [agents[a].hosting_cost(comp) for a in cands],
            dtype=np.float32,
        )
        dcop.add_constraint(
            NAryMatrixRelation([v], hosting, name=f"host_{comp}")
        )

    comps = sorted(variables)
    foot = footprint or (lambda c: 1.0)
    for i in range(len(comps)):
        for j in range(i + 1, len(comps)):
            c1, c2 = comps[i], comps[j]
            shared = set(candidates[c1]) & set(candidates[c2])
            if not shared:
                continue
            v1, v2 = variables[c1], variables[c2]
            m = np.zeros((len(v1.domain), len(v2.domain)), dtype=np.float32)
            for a in shared:
                m[v1.domain.index(a), v2.domain.index(a)] = (
                    concentration_weight * (foot(c1) + foot(c2))
                )
            dcop.add_constraint(
                NAryMatrixRelation([v1, v2], m, name=f"conc_{c1}_{c2}")
            )
    return dcop


def repair_placement(
    candidates: Mapping[str, List[str]],
    agentsdef: Iterable,
    remaining_capacity: Optional[Mapping[str, float]] = None,
    footprint: Optional[Callable[[str], float]] = None,
    algo: str = "mgm",
    rounds: int = 50,
    seed: int = 0,
) -> Dict[str, str]:
    """Decide new hosts for orphaned computations.

    Returns computation → new agent.  Computations with an empty
    candidate list are omitted (lost — the caller decides how to degrade).
    """
    agents = {a.name: a for a in agentsdef}
    solvable = {c: a for c, a in candidates.items() if a}
    if not solvable:
        return {}

    if len(solvable) == 1 or all(len(a) == 1 for a in solvable.values()):
        # nothing to coordinate: cheapest (or only) candidate wins
        chosen = {
            c: min(cands, key=lambda a: (agents[a].hosting_cost(c), a))
            for c, cands in solvable.items()
        }
    else:
        from pydcop_tpu.api import solve

        dcop = build_reparation_dcop(solvable, agents, footprint)
        result = solve(
            dcop, algo, {}, rounds=rounds, seed=seed,
            convergence_chunks=1, chunk_size=16,
        )
        chosen = dict(result["assignment"])

    # hard-capacity projection (the reference's hard constraints)
    if remaining_capacity is not None:
        foot = footprint or (lambda c: 1.0)
        left = dict(remaining_capacity)
        final: Dict[str, str] = {}
        # place cheap-to-move computations last so big ones keep their slot
        for comp in sorted(chosen, key=lambda c: -foot(c)):
            agent = chosen[comp]
            if left.get(agent, 0.0) >= foot(comp):
                final[comp] = agent
                left[agent] -= foot(comp)
                continue
            alts = sorted(
                (
                    (agents[a].hosting_cost(comp), a)
                    for a in solvable[comp]
                    if left.get(a, 0.0) >= foot(comp)
                ),
            )
            if alts:
                final[comp] = alts[0][1]
                left[alts[0][1]] -= foot(comp)
            # else: truly no capacity anywhere → lost
        return final
    return chosen
