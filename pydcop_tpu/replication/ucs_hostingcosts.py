"""k-resilient replica placement by uniform-cost search over the agent
graph.

Role-equivalent to ``pydcop/replication/dist_ucs_hostingcosts.py``
(DRPM): for each active computation, place ``k`` replicas on agents
other than its host, minimizing ``route-path cost from the host`` +
``hosting cost on the target``, subject to agent capacity.

The reference runs this as a *distributed* uniform-cost search (each
agent expands its cheapest frontier edge and forwards the search token).
A uniform-cost search explores states in nondecreasing path-cost order
regardless of which process expands them, so the distributed run and
this host-side Dijkstra visit the same agents at the same costs and
select the same replica sites (ties broken by agent name, as the
reference breaks them by lexical computation/agent order).  On the TPU
build the control plane is host-side, so we keep the semantics and drop
the token protocol.
"""

from __future__ import annotations

import heapq
from typing import Callable, Dict, Iterable, List, Mapping, Optional

from pydcop_tpu.utils.simple_repr import SimpleRepr


class ReplicaDistribution(SimpleRepr):
    """Mapping computation name → list of agents hosting its replicas."""

    def __init__(self, mapping: Mapping[str, Iterable[str]]):
        self._mapping: Dict[str, List[str]] = {
            c: list(agents) for c, agents in mapping.items()
        }

    def replicas(self, computation: str) -> List[str]:
        return list(self._mapping.get(computation, []))

    def agents_for(self, computation: str) -> List[str]:
        return self.replicas(computation)

    @property
    def mapping(self) -> Dict[str, List[str]]:
        return {c: list(a) for c, a in self._mapping.items()}

    @property
    def computations(self) -> List[str]:
        return list(self._mapping)

    def __eq__(self, other):
        return (
            isinstance(other, ReplicaDistribution)
            and other._mapping == self._mapping
        )

    def __repr__(self) -> str:
        return f"ReplicaDistribution({self._mapping})"

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "mapping": simple_repr(self._mapping),
        }

    @classmethod
    def _from_repr(cls, r: dict):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(from_repr(r["mapping"]))


def _route_dijkstra(
    source: str, agents: Mapping[str, "AgentDef"]
) -> Dict[str, float]:
    """Cheapest route-path cost from ``source`` to every other agent
    (routes may make multi-hop paths cheaper than the direct edge)."""
    dist = {source: 0.0}
    heap = [(0.0, source)]
    done = set()
    while heap:
        d, a = heapq.heappop(heap)
        if a in done:
            continue
        done.add(a)
        for b, agent_b in agents.items():
            if b == a or b in done:
                continue
            nd = d + agents[a].route(b)
            if nd < dist.get(b, float("inf")):
                dist[b] = nd
                heapq.heappush(heap, (nd, b))
    return dist


def replica_distribution(
    distribution,
    agentsdef: Iterable,
    k: int,
    computations: Optional[Iterable[str]] = None,
    footprint: Optional[Callable[[str], float]] = None,
) -> ReplicaDistribution:
    """Place ``k`` replicas of each computation.

    Parameters
    ----------
    distribution:
        The active :class:`~pydcop_tpu.distribution.objects.Distribution`
        (gives each computation's current host).
    agentsdef:
        Live agents (hosting costs / routes / capacity).
    k:
        Resilience level: replicas per computation (k-resilience means
        the system survives any k simultaneous agent departures).
    computations:
        Which computations to replicate (default: all placed ones).
    footprint:
        Optional ``computation name -> memory`` callable; replicas
        consume capacity left after the agent's own hosted computations.
    """
    agents = {a.name: a for a in agentsdef}
    comps = sorted(
        computations if computations is not None else distribution.computations
    )
    foot = footprint or (lambda c: 0.0)

    remaining: Dict[str, float] = {}
    for name, agent in agents.items():
        hosted = (
            distribution.computations_hosted(name)
            if name in distribution.agents
            else []
        )
        remaining[name] = agent.capacity - sum(foot(c) for c in hosted)

    path_costs: Dict[str, Dict[str, float]] = {}
    mapping: Dict[str, List[str]] = {}
    for comp in comps:
        host = (
            distribution.agent_for(comp)
            if distribution.has_computation(comp)
            else None
        )
        if host not in agents:
            # hostless computation: replicate from the cheapest agent
            host = min(agents) if agents else None
        if host is None:
            mapping[comp] = []
            continue
        if host not in path_costs:
            path_costs[host] = _route_dijkstra(host, agents)
        dists = path_costs[host]
        candidates = sorted(
            (
                (
                    dists.get(a, float("inf"))
                    + agents[a].hosting_cost(comp),
                    a,
                )
                for a in agents
                if a != host and remaining[a] >= foot(comp)
            ),
        )
        chosen = [a for _, a in candidates[:k]]
        for a in chosen:
            remaining[a] -= foot(comp)
        mapping[comp] = chosen
    return ReplicaDistribution(mapping)
