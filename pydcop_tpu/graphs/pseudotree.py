"""Pseudo-tree construction for DPOP
(reference: ``computations_graph/pseudotree.py``).

A DFS traversal of the primal constraint graph yields a pseudo-tree:
tree edges (parent/children) plus back edges (pseudo-parents toward
ancestors, pseudo-children toward descendants).  Every constraint
connects variables on one root-to-leaf branch, which is what makes the
UTIL dynamic programming correct.

Construction is host-side (setup time); the DPOP UTIL/VALUE phases then
run as shaped array ops (see ``pydcop_tpu.algorithms.dpop``).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import RelationProtocol
from pydcop_tpu.graphs.objects import ComputationGraph, ComputationNode, Link

GRAPH_NODE_TYPE = "PseudoTreeNode"


class PseudoTreeLink(Link):
    """Typed link: ``tree`` (parent↔child) or ``back`` (pseudo)."""

    def __init__(self, link_type: str, source: str, target: str):
        super().__init__([source, target], link_type=link_type)
        self._source = source
        self._target = target

    @property
    def source(self) -> str:
        return self._source

    @property
    def target(self) -> str:
        return self._target


class PseudoTreeNode(ComputationNode):
    """One variable's node in the pseudo-tree."""

    def __init__(
        self,
        variable: Variable,
        constraints: Iterable[RelationProtocol],
    ):
        super().__init__(variable.name, node_type="PseudoTreeNode")
        self._variable = variable
        self._constraints = list(constraints)
        self.parent: Optional[str] = None
        self.pseudo_parents: List[str] = []
        self.children: List[str] = []
        self.pseudo_children: List[str] = []

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[RelationProtocol]:
        return list(self._constraints)

    @property
    def is_root(self) -> bool:
        return self.parent is None

    @property
    def is_leaf(self) -> bool:
        return not self.children


class PseudoTreeGraph(ComputationGraph):
    """ComputationGraph specialisation exposing roots and separators."""

    def __init__(self):
        super().__init__("pseudotree")
        self.roots: List[str] = []

    def node(self, name: str) -> PseudoTreeNode:  # narrowed type
        return super().node(name)  # type: ignore[return-value]

    def separator(self, name: str) -> List[str]:
        """Separator of a node: its parent plus pseudo-parents — the set
        of ancestors its UTIL message depends on.  UTIL table width is
        d^len(separator) (exponential in induced width)."""
        n = self.node(name)
        sep = ([] if n.parent is None else [n.parent]) + list(n.pseudo_parents)
        return sep

    def depth_first_order(self, root: str) -> List[str]:
        """Nodes of one tree in DFS pre-order (children order stable)."""
        order: List[str] = []
        stack = [root]
        while stack:
            cur = stack.pop()
            order.append(cur)
            stack.extend(reversed(self.node(cur).children))
        return order


def _primal_adjacency(
    variables: List[Variable], constraints: List[RelationProtocol]
) -> Dict[str, Set[str]]:
    adj: Dict[str, Set[str]] = {v.name: set() for v in variables}
    for c in constraints:
        scope = [n for n in c.scope_names if n in adj]
        for a in scope:
            for b in scope:
                if a != b:
                    adj[a].add(b)
    return adj


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[RelationProtocol]] = None,
    root: Optional[str] = None,
) -> PseudoTreeGraph:
    """DFS pseudo-tree build.

    Root selection: the given ``root``, else the highest-degree variable
    of each connected component (a standard heuristic that tends to
    reduce tree depth).  Disconnected problems produce a forest (one root
    per component), matching reference behavior.
    """
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    by_var: Dict[str, List[RelationProtocol]] = {
        v.name: [] for v in variables
    }
    for c in constraints:
        for vname in c.scope_names:
            if vname in by_var:
                by_var[vname].append(c)

    adj = _primal_adjacency(variables, constraints)

    graph = PseudoTreeGraph()
    nodes: Dict[str, PseudoTreeNode] = {}
    for v in variables:
        node = PseudoTreeNode(v, by_var[v.name])
        nodes[v.name] = node
        graph.add_node(node)

    visited: Set[str] = set()
    # deterministic component iteration: sort by (-degree, name)
    candidates = sorted(adj, key=lambda n: (-len(adj[n]), n))
    if root is not None:
        if root not in adj:
            raise ValueError(f"Unknown root variable {root!r}")
        candidates = [root] + [c for c in candidates if c != root]

    for start in candidates:
        if start in visited:
            continue
        graph.roots.append(start)
        # iterative DFS with ancestor tracking
        visited.add(start)
        in_progress: Dict[str, List[str]] = {
            start: sorted(adj[start], key=lambda n: (-len(adj[n]), n))
        }
        ancestors: List[str] = [start]
        while ancestors:
            cur = ancestors[-1]
            todo = in_progress[cur]
            advanced = False
            while todo:
                nxt = todo.pop(0)
                if nxt not in visited:
                    # tree edge
                    visited.add(nxt)
                    nodes[nxt].parent = cur
                    nodes[cur].children.append(nxt)
                    link = PseudoTreeLink("tree", cur, nxt)
                    nodes[cur].add_link(link)
                    nodes[nxt].add_link(link)
                    in_progress[nxt] = sorted(
                        adj[nxt], key=lambda n: (-len(adj[n]), n)
                    )
                    ancestors.append(nxt)
                    advanced = True
                    break
                elif nxt in ancestors and nxt != nodes[cur].parent:
                    # back edge to a strict ancestor → pseudo relation
                    if nxt not in nodes[cur].pseudo_parents:
                        nodes[cur].pseudo_parents.append(nxt)
                        nodes[nxt].pseudo_children.append(cur)
                        link = PseudoTreeLink("back", cur, nxt)
                        nodes[cur].add_link(link)
                        nodes[nxt].add_link(link)
                # else: cross/forward edge already handled from the
                # other endpoint (it was on the stack then), or the
                # plain tree edge back to the parent — skip
            if not advanced:
                ancestors.pop()
    return graph
