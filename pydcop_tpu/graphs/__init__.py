"""Computation-graph builders (reference: ``pydcop/computations_graph/``).

Each graph model module exports ``GRAPH_NODE_TYPE`` and
``build_computation_graph(dcop=None, variables=None, constraints=None)``.
Graph models are loaded by name through :func:`load_graph_module`, the
same extension seam the reference exposes.
"""

import importlib

_GRAPH_MODULES = {
    "constraints_hypergraph",
    "factor_graph",
    "pseudotree",
    "ordered_graph",
}


def load_graph_module(name: str):
    """Load a computation-graph module by name."""
    if name not in _GRAPH_MODULES:
        raise ValueError(
            f"Unknown graph model {name!r}; available: {sorted(_GRAPH_MODULES)}"
        )
    return importlib.import_module(f"pydcop_tpu.graphs.{name}")


def list_available_graph_models():
    return sorted(_GRAPH_MODULES)
