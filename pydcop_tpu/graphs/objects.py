"""Computation-graph abstraction: nodes, links, graph container.

Role-equivalent to ``pydcop/computations_graph/objects.py``: a
``ComputationGraph`` holds named ``ComputationNode``s connected by typed
``Link``s (links may be hyperedges).  Algorithm modules attach footprint
callbacks; the distribution layer consumes the topology.

The TPU engine consumes the same graphs through the problem compiler
(``pydcop_tpu.ops``): node order defines array indices, links define the
incidence/edge index arrays shipped to device.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set

from pydcop_tpu.utils.simple_repr import SimpleRepr


class Link(SimpleRepr):
    """A (hyper)edge between computation nodes, identified by names."""

    def __init__(self, nodes: Sequence[str], link_type: str = "link"):
        self._nodes = tuple(sorted(nodes))
        self._link_type = link_type

    @property
    def nodes(self) -> Sequence[str]:
        return self._nodes

    @property
    def type(self) -> str:
        return self._link_type

    def has_node(self, name: str) -> bool:
        return name in self._nodes

    def __eq__(self, other):
        return (
            isinstance(other, Link)
            and other._nodes == self._nodes
            and other._link_type == self._link_type
        )

    def __hash__(self):
        return hash((self._nodes, self._link_type))

    def __repr__(self) -> str:
        return f"Link({list(self._nodes)}, {self._link_type!r})"

    def _simple_repr(self) -> dict:
        from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "nodes": list(self._nodes),
            "link_type": self._link_type,
        }

    @classmethod
    def _from_repr(cls, r: dict):
        return cls(r["nodes"], r.get("link_type", "link"))


class ComputationNode(SimpleRepr):
    """A named unit of computation in the graph.

    ``node_type`` distinguishes roles within one graph model (e.g.
    ``VariableComputationNode`` vs ``FactorComputationNode`` in a factor
    graph).  Subclasses carry model objects (variable, constraints).
    """

    def __init__(
        self,
        name: str,
        node_type: str = "computation",
        links: Optional[Iterable[Link]] = None,
    ):
        self._name = name
        self._node_type = node_type
        self._links = list(links) if links else []

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._node_type

    @property
    def links(self) -> List[Link]:
        return list(self._links)

    def add_link(self, link: Link) -> None:
        self._links.append(link)

    @property
    def neighbors(self) -> List[str]:
        out: List[str] = []
        seen: Set[str] = {self._name}
        for l in self._links:
            for n in l.nodes:
                if n not in seen:
                    seen.add(n)
                    out.append(n)
        return out

    def __eq__(self, other):
        return (
            isinstance(other, ComputationNode)
            and other._name == self._name
            and other._node_type == self._node_type
        )

    def __hash__(self):
        return hash((self._name, self._node_type))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._name!r})"


class ComputationGraph:
    """Container of nodes + links for one graph model instance."""

    def __init__(
        self,
        graph_type: str,
        nodes: Optional[Iterable[ComputationNode]] = None,
    ):
        self._graph_type = graph_type
        self._nodes: Dict[str, ComputationNode] = {}
        for n in nodes or ():
            self.add_node(n)

    @property
    def graph_type(self) -> str:
        return self._graph_type

    def add_node(self, node: ComputationNode) -> None:
        if node.name in self._nodes:
            raise ValueError(f"Duplicate computation node {node.name}")
        self._nodes[node.name] = node

    @property
    def nodes(self) -> List[ComputationNode]:
        return list(self._nodes.values())

    def node(self, name: str) -> ComputationNode:
        return self._nodes[name]

    def __contains__(self, name: str) -> bool:
        return name in self._nodes

    @property
    def links(self) -> List[Link]:
        seen: Set[Link] = set()
        out: List[Link] = []
        for n in self._nodes.values():
            for l in n.links:
                if l not in seen:
                    seen.add(l)
                    out.append(l)
        return out

    def computations(self) -> List[ComputationNode]:
        return self.nodes

    def density(self) -> float:
        """2·|links| / (|nodes|·(|nodes|−1)) — same definition the
        reference's ``pydcop graph`` command reports."""
        n = len(self._nodes)
        if n < 2:
            return 0.0
        return 2 * len(self.links) / (n * (n - 1))

    def __repr__(self) -> str:
        return (
            f"ComputationGraph({self._graph_type!r}, "
            f"{len(self._nodes)} nodes, {len(self.links)} links)"
        )
