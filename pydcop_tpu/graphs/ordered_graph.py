"""Ordered (chain) graph for SyncBB
(reference: ``computations_graph/ordered_graph.py``).

A total ordering of the variables; each node links to its predecessor
and successor.  The branch-and-bound token walks this chain.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import RelationProtocol
from pydcop_tpu.graphs.objects import ComputationGraph, ComputationNode, Link

GRAPH_NODE_TYPE = "OrderedVariableNode"


class OrderedVariableNode(ComputationNode):
    def __init__(
        self,
        variable: Variable,
        constraints: Iterable[RelationProtocol],
        position: int,
    ):
        super().__init__(variable.name, node_type="OrderedVariableNode")
        self._variable = variable
        self._constraints = list(constraints)
        self._position = position
        # chain neighbors by DIRECTION (Link sorts its endpoints, so
        # the ordering cannot be recovered from links alone); set by
        # build_computation_graph, consumed by the SyncBB token walk
        self.prev: Optional[str] = None
        self.next: Optional[str] = None

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[RelationProtocol]:
        return list(self._constraints)

    @property
    def position(self) -> int:
        return self._position


class OrderedGraph(ComputationGraph):
    def __init__(self, ordering: List[str]):
        super().__init__("ordered_graph")
        self.ordering = list(ordering)

    def next_node(self, name: str) -> Optional[str]:
        i = self.ordering.index(name)
        return self.ordering[i + 1] if i + 1 < len(self.ordering) else None

    def previous_node(self, name: str) -> Optional[str]:
        i = self.ordering.index(name)
        return self.ordering[i - 1] if i > 0 else None


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[RelationProtocol]] = None,
    ordering: Optional[List[str]] = None,
) -> OrderedGraph:
    """Chain the variables, by default in lexicographic name order."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    by_name: Dict[str, Variable] = {v.name: v for v in variables}
    if ordering is None:
        ordering = sorted(by_name)
    else:
        missing = set(by_name) - set(ordering)
        if missing:
            raise ValueError(f"Ordering misses variable(s) {sorted(missing)}")
        unknown = set(ordering) - set(by_name)
        if unknown:
            raise ValueError(
                f"Ordering contains unknown variable(s) {sorted(unknown)}"
            )

    by_var: Dict[str, List[RelationProtocol]] = {n: [] for n in by_name}
    for c in constraints:
        for vname in c.scope_names:
            if vname in by_var:
                by_var[vname].append(c)

    graph = OrderedGraph(ordering)
    nodes = []
    for i, vname in enumerate(ordering):
        node = OrderedVariableNode(by_name[vname], by_var[vname], i)
        nodes.append(node)
        graph.add_node(node)
    for a, b in zip(nodes, nodes[1:]):
        link = Link([a.name, b.name], link_type="ordering")
        a.add_link(link)
        b.add_link(link)
        a.next = b.name
        b.prev = a.name
    return graph
