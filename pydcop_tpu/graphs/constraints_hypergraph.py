"""Constraints hypergraph: one computation per variable, one hyperedge
per constraint (reference: ``computations_graph/constraints_hypergraph.py``).

Used by the local-search family: DSA/A-DSA, MGM/MGM-2, DBA/GDBA.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import RelationProtocol
from pydcop_tpu.graphs.objects import ComputationGraph, ComputationNode, Link

GRAPH_NODE_TYPE = "VariableComputationNode"


class VariableComputationNode(ComputationNode):
    """A computation responsible for one decision variable, knowing the
    constraints whose scope contains it."""

    def __init__(
        self,
        variable: Variable,
        constraints: Iterable[RelationProtocol],
    ):
        super().__init__(variable.name, node_type="VariableComputationNode")
        self._variable = variable
        self._constraints = list(constraints)

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[RelationProtocol]:
        return list(self._constraints)


class ConstraintLink(Link):
    """Hyperedge for one constraint, connecting its scope's computations."""

    def __init__(self, constraint_name: str, nodes):
        super().__init__(nodes, link_type="constraint_link")
        self._constraint_name = constraint_name

    @property
    def constraint_name(self) -> str:
        return self._constraint_name

    def __eq__(self, other):
        return (
            isinstance(other, ConstraintLink)
            and super().__eq__(other)
            and other._constraint_name == self._constraint_name
        )

    def __hash__(self):
        return hash((self.nodes, self.type, self._constraint_name))


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[RelationProtocol]] = None,
) -> ComputationGraph:
    """Build the hypergraph from a DCOP (or explicit variables+constraints)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    by_var = {v.name: [] for v in variables}
    for c in constraints:
        for vname in c.scope_names:
            if vname in by_var:
                by_var[vname].append(c)

    graph = ComputationGraph("constraints_hypergraph")
    nodes = {}
    for v in variables:
        node = VariableComputationNode(v, by_var[v.name])
        nodes[v.name] = node
        graph.add_node(node)

    for c in constraints:
        scope = [n for n in c.scope_names if n in nodes]
        link = ConstraintLink(c.name, scope)
        for vname in scope:
            nodes[vname].add_link(link)
    return graph
