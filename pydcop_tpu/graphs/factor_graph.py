"""Factor graph: bipartite variable/factor computations
(reference: ``computations_graph/factor_graph.py``).

Used by Max-Sum / A-Max-Sum.  On the TPU engine the edges of this graph
become the directed-edge message arrays (``f32[n_edges, d]``) the batched
Max-Sum kernel updates each round.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import RelationProtocol
from pydcop_tpu.graphs.objects import ComputationGraph, ComputationNode, Link

GRAPH_NODE_TYPE = "factor_graph_node"


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable):
        super().__init__(variable.name, node_type="VariableComputationNode")
        self._variable = variable

    @property
    def variable(self) -> Variable:
        return self._variable


class FactorComputationNode(ComputationNode):
    def __init__(self, factor: RelationProtocol):
        super().__init__(factor.name, node_type="FactorComputationNode")
        self._factor = factor

    @property
    def factor(self) -> RelationProtocol:
        return self._factor

    @property
    def variables(self) -> List[Variable]:
        return self._factor.dimensions


class FactorGraphLink(Link):
    """Edge between one factor and one variable computation."""

    def __init__(self, factor_name: str, variable_name: str):
        super().__init__([factor_name, variable_name], link_type="factor_link")
        self._factor_name = factor_name
        self._variable_name = variable_name

    @property
    def factor_name(self) -> str:
        return self._factor_name

    @property
    def variable_name(self) -> str:
        return self._variable_name


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[Iterable[Variable]] = None,
    constraints: Optional[Iterable[RelationProtocol]] = None,
) -> ComputationGraph:
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    graph = ComputationGraph("factor_graph")
    var_nodes = {}
    for v in variables:
        node = VariableComputationNode(v)
        var_nodes[v.name] = node
        graph.add_node(node)

    for c in constraints:
        fnode = FactorComputationNode(c)
        graph.add_node(fnode)
        for vname in c.scope_names:
            if vname not in var_nodes:
                continue
            link = FactorGraphLink(c.name, vname)
            fnode.add_link(link)
            var_nodes[vname].add_link(link)
    return graph
