"""Elastic cross-process runtime: survive agent death mid-solve.

The static orchestrator (``infrastructure/orchestrator.py``) fails the
run when an agent process dies — the right default for batch
experiments.  This module is the *resilient* deployment the reference
is known for (SURVEY §3.5: discovery removal events → reparation →
resume), rebuilt for the SPMD engine:

- Every participant (the orchestrator included) is a **supervisor**
  that hosts a disposable **worker subprocess**.  Workers run the
  actual jax.distributed SPMD solve; supervisors never import jax, so
  the control plane can never wedge in a dead collective.
- Workers barrier with the orchestrator at every chunk boundary (the
  lockstep protocol of the static runtime); the rank-0 worker's acks
  carry the current values, so the orchestrator always holds the last
  consistent assignment.
- On a worker or agent death (immediate EOF on its control
  connection), the orchestrator **re-forms**: kills all workers of
  the epoch, applies the failure to the problem — the dead agent's
  partition of DCOP agents is removed exactly like a scenario
  ``remove_agent`` (replicas migrate computations when ``k_target``
  > 0, computations without a live replica freeze their variable at
  its last value) — and starts a new epoch on the survivors with a
  fresh ``jax.distributed`` cluster, the remaining round budget, and
  the carried values.  A dead *worker* whose supervisor survives is
  simply respawned (crash recovery without capacity loss).
- A :class:`~pydcop_tpu.infrastructure.discovery.Discovery` instance
  on the orchestrator receives register/removal events; the reform
  logic and the optional UI feed are its subscribers.

Partitioning: the problem's DCOP agents are split round-robin over the
control participants at start; dying participants take their DCOP
agents with them, matching the reference's agent-process = agents
mapping without requiring one OS process per DCOP agent.
"""

from __future__ import annotations

import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from pydcop_tpu.infrastructure.discovery import Discovery
from pydcop_tpu.infrastructure.orchestrator import (
    AgentFailureError,
    _arm_watchdog,
    _free_port,
    _Peer,
    _recv,
    _send,
)

# both timing floors are env-overridable (deployment knobs that used
# to be hardcoded): defaults unchanged, a bad value fails at import
# with a clear message instead of deep inside a run
def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a number (seconds expected)"
        ) from None


_HEARTBEAT = _env_float("PYDCOP_TPU_ELASTIC_HEARTBEAT", 120.0)

# first barrier of an epoch additionally covers jax import +
# compile_dcop + the cold XLA compile on every worker — give it at
# least this much regardless of the configured heartbeat
_FIRST_BARRIER_MIN = _env_float(
    "PYDCOP_TPU_ELASTIC_FIRST_BARRIER_MIN", 600.0
)


def _spawn_worker(
    orchestrator_addr: str, epoch: int, process_id: int
) -> subprocess.Popen:
    """The one place the worker subprocess command is built (used by
    the orchestrator for its local worker and by agent supervisors)."""
    return subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "worker",
            "--orchestrator", orchestrator_addr,
            "--epoch", str(epoch),
            "--process-id", str(process_id),
        ],
        env=dict(os.environ),
    )


# ---------------------------------------------------------------------------
# orchestrator (supervisor + control plane)
# ---------------------------------------------------------------------------


class _Participant:
    """One control participant: the orchestrator itself or a remote
    agent supervisor, plus its current worker connection/process."""

    def __init__(self, name: str, peer: Optional[_Peer]):
        self.name = name
        self.peer = peer  # None for the orchestrator itself
        self.worker_peer: Optional[_Peer] = None
        self.worker_proc: Optional[subprocess.Popen] = None  # local only
        self.alive = True


def run_elastic_orchestrator(
    dcop_yaml: str,
    algo: str,
    params: Dict[str, Any],
    port: int,
    nb_agents: int = 1,
    rounds: int = 200,
    seed: int = 0,
    chunk_size: int = 64,
    timeout: Optional[float] = None,
    host: str = "0.0.0.0",
    advertise_host: str = "localhost",
    heartbeat_timeout: float = _HEARTBEAT,
    k_target: int = 0,
    ui_port: Optional[int] = None,
    abort_grace: float = 10.0,
    first_barrier_min: Optional[float] = None,
) -> Dict[str, Any]:
    """Run an elastic cross-process solve; returns the result dict with
    an ``events`` log of reforms.  The run only fails outright if ALL
    agents die or the orchestrator's own worker cannot run.

    ``heartbeat_timeout`` and ``first_barrier_min`` (the extra budget
    the FIRST barrier of an epoch gets for jax import + cold XLA
    compile) default to the module floors, themselves overridable via
    ``PYDCOP_TPU_ELASTIC_HEARTBEAT`` /
    ``PYDCOP_TPU_ELASTIC_FIRST_BARRIER_MIN`` — CI on slow shared
    runners raises them, short-window tests lower them; defaults are
    unchanged."""
    if first_barrier_min is None:
        first_barrier_min = _FIRST_BARRIER_MIN
    from pydcop_tpu.dcop.yamldcop import dcop_yaml as dump_yaml
    from pydcop_tpu.dcop.yamldcop import load_dcop

    t_start = time.monotonic()
    base_dcop = load_dcop(dcop_yaml)

    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((host, port))
    server.listen(16)
    ctrl_port = server.getsockname()[1]

    inbox: "queue.Queue" = queue.Queue()
    done_evt = threading.Event()
    discovery = Discovery()
    events_log: List[Dict[str, Any]] = []
    ui = None
    if ui_port is not None:
        from pydcop_tpu.infrastructure.ui import UiServer

        ui = UiServer(ui_port)
        discovery.subscribe(
            lambda kind, ev, name, detail: ui.publish(
                0, None, None, discovery_event=f"{kind}:{ev}:{name}"
            )
        )

    def on_msg_factory(peer_box):
        def on_msg(msg):
            inbox.put((peer_box[0], msg))

        return on_msg

    def on_eof_factory(peer_box):
        def on_eof(_name):
            inbox.put((peer_box[0], None))

        return on_eof

    def accept_loop():
        while not done_evt.is_set():
            try:
                conn, _ = server.accept()
            except OSError:
                return
            # registration is bounded; AFTER it the connection must
            # have NO read timeout: supervisors are silent between
            # reforms and workers are silent through long XLA
            # compiles — liveness is EOF (kernel-signalled death) +
            # the main loop's barrier deadlines, never read idleness
            conn.settimeout(heartbeat_timeout)
            reader = conn.makefile("rb")
            try:
                msg = _recv(reader)
            except OSError:
                conn.close()
                continue
            if not msg or msg.get("type") != "register":
                conn.close()
                continue
            conn.settimeout(None)
            box: list = [None]
            peer = _Peer(
                msg.get("name", "?"), conn, done_evt,
                on_eof=on_eof_factory(box), on_msg=on_msg_factory(box),
                reader=reader,
            )
            box[0] = peer
            inbox.put((peer, {"__register__": True, **msg}))

    threading.Thread(target=accept_loop, daemon=True).start()

    # -- wait for agent registrations --------------------------------
    participants: List[_Participant] = [_Participant("_orchestrator", None)]
    discovery.register_agent("_orchestrator")
    deadline = time.monotonic() + heartbeat_timeout
    while len(participants) < nb_agents + 1:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            done_evt.set()
            server.close()
            raise AgentFailureError(
                f"only {len(participants) - 1}/{nb_agents} agents "
                f"registered within {heartbeat_timeout:.0f}s"
            )
        try:
            peer, msg = inbox.get(timeout=remaining)
        except queue.Empty:
            continue
        if msg and msg.get("__register__") and msg.get("role") != "worker":
            p = _Participant(msg.get("name", f"a{len(participants)}"), peer)
            participants.append(p)
            discovery.register_agent(p.name)

    # -- partition the computations (variables) over participants -----
    # the reference maps computations to agent processes via a
    # distribution; round-robin is the oneagent-style default here
    comps = sorted(base_dcop.variables)
    partition: Dict[str, List[str]] = {p.name: [] for p in participants}
    for i, v in enumerate(comps):
        owner = participants[i % len(participants)]
        partition[owner.name].append(v)
        discovery.register_computation(v, owner.name)

    # -- mutable run state -------------------------------------------
    frozen: Dict[str, Any] = {}
    carried_values: Dict[str, Any] = {}
    rounds_left = rounds
    epoch = 0
    status = "finished"

    def active_yaml() -> str:
        """Current problem: frozen variables become externals pinned at
        their last value; removed DCOP agents dropped."""
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import ExternalVariable

        d = DCOP(base_dcop.name, objective=base_dcop.objective)
        for v in base_dcop.variables.values():
            if v.name in frozen:
                d.add_variable(
                    ExternalVariable(v.name, v.domain, frozen[v.name])
                )
            else:
                d.add_variable(v)
        for ev in base_dcop.external_variables.values():
            d.add_variable(ev)
        for c in base_dcop.constraints.values():
            d.add_constraint(c)
        d.add_agents(base_dcop.agents.values())
        return dump_yaml(d)

    def remove_participant(part: _Participant) -> None:
        """Apply a participant death: its DCOP agents leave; their
        variables freeze at the carried values (k_target replication
        migrates nothing here because the batched state is globally
        replicated — every survivor already holds it, so 'repair' is
        simply re-partitioning; variables owned by nobody freeze)."""
        part.alive = False
        orphan_vars = partition.pop(part.name, [])
        survivors = [p for p in participants if p.alive]
        migrated: List[str] = []
        if k_target > 0 and survivors:
            # replicated state means any survivor can adopt: round-robin
            # the orphaned variables onto survivors (up to k_target per
            # survivor per reform, the replica budget)
            budget = {p.name: k_target for p in survivors}
            for i, v in enumerate(orphan_vars):
                tgt = survivors[i % len(survivors)]
                if budget[tgt.name] > 0:
                    partition[tgt.name].append(v)
                    budget[tgt.name] -= 1
                    migrated.append(v)
                    discovery.register_computation(v, tgt.name)
        for v in orphan_vars:
            if v not in migrated:
                frozen[v] = carried_values.get(
                    v, base_dcop.variables[v].domain[0]
                )
        discovery.unregister_agent(part.name)
        events_log.append(
            {
                "type": "participant_lost",
                "participant": part.name,
                "migrated": sorted(migrated),
                "frozen": sorted(
                    v for v in orphan_vars if v not in migrated
                ),
                "epoch": epoch,
            }
        )

    def spawn_local_worker(process_id: int) -> subprocess.Popen:
        return _spawn_worker(f"localhost:{ctrl_port}", epoch, process_id)

    def kill_workers(live: List[_Participant]) -> None:
        for p in live:
            if p.worker_proc is not None:
                if p.worker_proc.poll() is None:
                    p.worker_proc.send_signal(signal.SIGKILL)
                    p.worker_proc.wait()
                p.worker_proc = None
            if p.worker_peer is not None:
                p.worker_peer.close()
                p.worker_peer = None

    result: Optional[Dict[str, Any]] = None
    try:
        while True:
            epoch += 1
            live = [p for p in participants if p.alive]
            if len(live) < 1 or not any(
                p.peer is None for p in live
            ):  # pragma: no cover — orchestrator always participant 0
                raise AgentFailureError("no live participants left")
            coord_port = _free_port()
            num_processes = len(live)
            cur_yaml = active_yaml()
            deploy = {
                "type": "deploy",
                "elastic": True,
                "epoch": epoch,
                "dcop_yaml": cur_yaml,
                "algo": algo,
                "params": params,
                "rounds": rounds_left,
                "seed": seed + 1000 * epoch,
                "chunk_size": chunk_size,
                "num_processes": num_processes,
                "coordinator": f"{advertise_host}:{coord_port}",
                "heartbeat_timeout": heartbeat_timeout,
                "abort_grace": abort_grace,
                "initial_values": carried_values or None,
            }
            # process ids: orchestrator's worker = 0, agents 1..
            pid = 0
            for p in live:
                p.worker_pid = pid  # type: ignore[attr-defined]
                if p.peer is None:
                    p.worker_proc = spawn_local_worker(0)
                else:
                    # supervisors only spawn workers: ship them the
                    # slim header, not the full problem + values (the
                    # worker receives its own complete deploy when it
                    # registers)
                    p.peer.send(
                        {
                            "type": "deploy",
                            "elastic": True,
                            "epoch": epoch,
                            "process_id": pid,
                        }
                    )
                pid += 1
            # local worker gets its deploy when it registers (below)

            # -- wait for all workers of this epoch ------------------
            live_workers: Dict[int, _Peer] = {}
            wd = time.monotonic() + max(heartbeat_timeout, 60.0)
            failed: Optional[_Participant] = None
            while len(live_workers) < num_processes and failed is None:
                remaining = wd - time.monotonic()
                if remaining <= 0:
                    raise AgentFailureError(
                        f"epoch {epoch}: workers failed to register "
                        f"({len(live_workers)}/{num_processes})"
                    )
                try:
                    peer, msg = inbox.get(timeout=remaining)
                except queue.Empty:
                    continue
                failed = _handle_common(peer, msg, live)
                if failed is not None:
                    break
                if (
                    msg
                    and msg.get("__register__")
                    and msg.get("role") == "worker"
                    and msg.get("epoch") == epoch
                ):
                    wpid = int(msg["process_id"])
                    live_workers[wpid] = peer
                    for p in live:
                        if p.worker_pid == wpid:  # type: ignore
                            p.worker_peer = peer
                    peer.send({**deploy, "process_id": wpid})

            # -- barrier loop ----------------------------------------
            completed = 0
            first_barrier = True
            while failed is None:
                acks: Dict[int, Dict] = {}
                # the first barrier also covers jax import +
                # compile_dcop + cold XLA compile on every worker
                bd = time.monotonic() + (
                    max(heartbeat_timeout, first_barrier_min)
                    if first_barrier
                    else heartbeat_timeout
                )
                first_barrier = False
                while len(acks) < num_processes and failed is None:
                    remaining = bd - time.monotonic()
                    if remaining <= 0:
                        raise AgentFailureError(
                            f"epoch {epoch}: chunk barrier timed out"
                        )
                    try:
                        peer, msg = inbox.get(timeout=remaining)
                    except queue.Empty:
                        continue
                    failed = _handle_common(peer, msg, live)
                    if failed is not None:
                        break
                    if msg is None:
                        # unmatched EOF: a stale connection from a
                        # previous epoch (e.g. the dead agent's
                        # orphaned worker finally exiting) — ignore
                        continue
                    t = msg.get("type")
                    if t == "chunk" and msg.get("epoch") == epoch:
                        acks[int(msg["pid"])] = msg
                    elif t == "result" and msg.get("epoch") == epoch:
                        acks[int(msg["pid"])] = msg
                if failed is not None:
                    break
                if all(a.get("type") == "result" for a in acks.values()):
                    # epoch solved to completion: cross-check + done
                    costs = [a["cost"] for a in acks.values()]
                    if max(costs) - min(costs) > 1e-5:
                        raise AgentFailureError(
                            f"SPMD divergence across workers: {costs}"
                        )
                    r0 = acks[0]
                    completed = int(r0["cycle"])
                    result = dict(r0.get("result", {}))
                    break
                # interior barrier: record rank-0 values, decide go/halt
                r0 = acks.get(0, {})
                if "values" in r0:
                    carried_values.update(r0["values"])
                completed = max(
                    int(a.get("n", 0)) for a in acks.values()
                )
                if ui is not None:
                    ui.publish(
                        completed, None, r0.get("cost"), epoch=epoch
                    )
                if (
                    timeout is not None
                    and time.monotonic() - t_start > timeout
                ):
                    # the halted status flows back in the workers'
                    # result messages
                    for w in live_workers.values():
                        w.send({"type": "halt", "status": "timeout"})
                else:
                    for w in live_workers.values():
                        w.send({"type": "go"})

            if failed is not None:
                # -- reform ------------------------------------------
                if (
                    timeout is not None
                    and time.monotonic() - t_start > timeout
                ):
                    raise AgentFailureError(
                        "wall-clock timeout reached during reform"
                    )
                reforms = sum(
                    1 for e in events_log
                    if e["type"] in ("participant_lost", "worker_crash")
                )
                # crash-loop cap: a worker that deterministically dies
                # before its first barrier would otherwise respawn on
                # the identical problem forever
                if reforms >= 2 * (nb_agents + 1):
                    raise AgentFailureError(
                        f"giving up after {reforms} reforms "
                        "(crash-looping worker?)"
                    )
                rounds_left = max(1, rounds_left - completed)
                kill_workers(live)
                if isinstance(failed, _WorkerOnlyFailure):
                    # crash recovery: the supervisor is alive, only
                    # its worker died — respawn on the same partition
                    events_log.append(
                        {
                            "type": "worker_crash",
                            "participant": failed.name,
                            "epoch": epoch,
                        }
                    )
                else:
                    remove_participant(failed)
                for p in participants:
                    if p.alive and p.peer is not None:
                        p.peer.send({"type": "reform", "epoch": epoch})
                # drain stale messages of the dead epoch
                time.sleep(0.2)
                while not inbox.empty():
                    try:
                        peer, msg = inbox.get_nowait()
                    except queue.Empty:
                        break
                    if msg and msg.get("__register__"):
                        inbox.put((peer, msg))  # late register: keep
                        break
                continue
            break  # result collected

        assert result is not None
        if status == "finished" and result.get("status"):
            status = result["status"]
        # frozen variables re-enter the assignment at their pinned value
        assignment = dict(result.get("assignment", {}))
        for v, val in frozen.items():
            assignment[v] = val
        cost = base_dcop.solution_cost(
            {
                **assignment,
                **{
                    n: ev.value
                    for n, ev in base_dcop.external_variables.items()
                },
            }
        )
        if ui is not None:
            ui.publish(
                int(result.get("cycle", 0)), cost, cost,
                values=assignment, status=status, epoch=epoch,
            )
        return {
            "assignment": assignment,
            "cost": cost,
            "cycle": int(result.get("cycle", 0)),
            "msg_count": int(result.get("msg_count", 0)),
            "msg_size": int(result.get("msg_count", 0)),
            "status": status,
            "time": time.monotonic() - t_start,
            "events": events_log,
            "epochs": epoch,
            "agents": [p.name for p in participants if p.peer is not None],
            "agents_final": [
                p.name for p in participants
                if p.alive and p.peer is not None
            ],
            "lost_computations": sorted(frozen),
            "num_processes": len([p for p in participants if p.alive]),
        }
    finally:
        done_evt.set()
        if ui is not None:
            ui.close()
        for p in participants:
            if p.peer is not None:
                p.peer.send({"type": "stop"})
        kill_workers(participants)
        for p in participants:
            if p.peer is not None:
                p.peer.close()
        server.close()


def _handle_common(peer, msg, live):
    """Shared inbox handling: detects participant/worker death on EOF.
    Returns the failed participant (a plain _Participant for a
    supervisor death → partition removal, a _WorkerOnlyFailure when
    only the worker died → respawn without capacity loss), else None.
    """
    if msg is not None:
        return None
    for p in live:
        if peer is p.peer:
            return p
    for p in live:
        if peer is p.worker_peer:
            return _WorkerOnlyFailure(p)
    return None


class _WorkerOnlyFailure(_Participant):
    """Wrapper marking 'worker died, supervisor alive'."""

    def __init__(self, part: _Participant):
        self.part = part
        self.name = part.name
        self.peer = part.peer
        self.worker_peer = part.worker_peer
        self.worker_proc = part.worker_proc
        self.alive = True


# ---------------------------------------------------------------------------
# agent supervisor loop (called from run_agent on an elastic deploy)
# ---------------------------------------------------------------------------


def elastic_agent_loop(conn, peer, first_deploy, name, orchestrator_addr):
    """Supervise workers for an elastic run: spawn one per deploy/
    reform, kill on reform/stop.  Returns a small summary dict."""
    worker: Optional[subprocess.Popen] = None
    deploys = 0

    def spawn(msg):
        nonlocal worker, deploys
        kill()
        deploys += 1
        worker = _spawn_worker(
            orchestrator_addr, msg["epoch"], msg["process_id"]
        )

    def kill():
        nonlocal worker
        if worker is not None and worker.poll() is None:
            worker.send_signal(signal.SIGKILL)
            worker.wait()
        worker = None

    try:
        spawn(first_deploy)
        while True:
            try:
                msg = peer.get(timeout=60.0)
            except queue.Empty:
                continue  # idle between reforms is the normal state
            if msg is None:
                break  # orchestrator died
            t = msg.get("type")
            if t == "deploy":
                spawn(msg)
            elif t == "reform":
                kill()  # next deploy will respawn
            elif t == "stop":
                break
    finally:
        kill()
        conn.close()
    return {"agent": name, "deploys": deploys, "status": "stopped"}


# ---------------------------------------------------------------------------
# worker process
# ---------------------------------------------------------------------------


def run_worker(orchestrator_addr: str, epoch: int, process_id: int) -> int:
    """One epoch's SPMD participant: register, receive config, run ONE
    continuous batched solve in lockstep with the orchestrator
    (message state is preserved across barriers — no per-chunk
    restarts), and report the result."""
    ohost, oport = orchestrator_addr.rsplit(":", 1)
    conn = socket.create_connection((ohost, int(oport)), timeout=30)
    # no read timeout: a worker legitimately waits at a barrier while
    # its peers pay long XLA compiles; liveness is the orchestrator's
    # job (EOF + barrier deadlines)
    conn.settimeout(None)
    _send(
        conn,
        {
            "type": "register",
            "role": "worker",
            "name": f"worker{process_id}e{epoch}",
            "epoch": epoch,
            "process_id": process_id,
        },
    )
    reader = conn.makefile("rb")
    cfg = _recv(reader)
    if not cfg or cfg.get("type") != "deploy":
        return 1

    # from here on a reader thread owns the socket: if the control
    # connection dies while this process is wedged inside a collective
    # whose peer died (it may never return from XLA), a watchdog
    # force-exits after the deployed grace — otherwise the orphan
    # would hold the accelerator forever
    done_evt = threading.Event()
    grace = float(cfg.get("abort_grace", 10.0))
    peer = _Peer(
        "orchestrator", conn, done_evt,
        on_eof=lambda _n: _arm_watchdog(
            done_evt, grace, "worker control connection lost"
        ),
        reader=reader,
    )

    import dataclasses as dc

    import jax

    if cfg["num_processes"] > 1:
        jax.distributed.initialize(
            cfg["coordinator"],
            num_processes=cfg["num_processes"],
            process_id=process_id,
        )

    import numpy as np
    from jax.sharding import Mesh

    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops.compile import (
        compile_dcop,
        decode_assignment,
        encode_assignment,
    )
    from pydcop_tpu.parallel.mesh import SHARD_AXIS

    dcop = load_dcop(cfg["dcop_yaml"])
    module = load_algorithm_module(cfg["algo"])
    params = dict(
        prepare_algo_params(cfg["params"], module.algo_params)
    )

    n_shards = jax.device_count()
    problem = compile_dcop(dcop, n_shards=n_shards)
    if cfg.get("initial_values"):
        known = {
            n: v
            for n, v in cfg["initial_values"].items()
            if n in set(problem.var_names)
        }
        if len(known) == len(problem.var_names):
            problem = dc.replace(
                problem, init_idx=encode_assignment(problem, known)
            )
            params["initial"] = "declared"
    mesh = Mesh(np.array(jax.devices()), (SHARD_AXIS,))

    def cb(done_rounds, best_cost, values_arr):
        ack = {
            "type": "chunk",
            "epoch": epoch,
            "pid": process_id,
            "n": done_rounds,
        }
        if process_id == 0:
            # rank 0 ships the replicated CURRENT values (the
            # orchestrator's carry point for cluster re-forms) and the
            # anytime cost (the UI feed)
            ack["values"] = decode_assignment(problem, values_arr)
            ack["cost"] = float(best_cost)
        _send(conn, ack)
        while True:
            try:
                msg = peer.get(timeout=30.0)
            except queue.Empty:
                continue
            if msg is None:
                raise AgentFailureError("orchestrator died")
            t = msg.get("type")
            if t == "go":
                return None
            if t == "halt":
                return msg.get("status", "halted")
            if t == "stop":
                raise AgentFailureError("stopped mid-epoch")

    cb.wants_values = True  # type: ignore[attr-defined]

    r = run_batched(
        problem,
        module,
        params,
        rounds=int(cfg["rounds"]),
        seed=int(cfg["seed"]),
        chunk_size=int(cfg["chunk_size"]),
        mesh=mesh,
        chunk_callback=cb,
    )

    _send(
        conn,
        {
            "type": "result",
            "epoch": epoch,
            "pid": process_id,
            "cost": float(r.best_cost),
            "cycle": int(r.cycles),
            "result": {
                "assignment": r.best_assignment,
                "cost": float(r.best_cost),
                "cycle": int(r.cycles),
                "msg_count": int(r.messages),
                "status": r.status,
            },
        },
    )
    try:
        while True:
            try:
                msg = peer.get(timeout=60.0)
            except queue.Empty:
                continue
            if msg is None or msg.get("type") in ("stop", "reform"):
                break
    except OSError:
        pass
    done_evt.set()
    conn.close()
    return 0
