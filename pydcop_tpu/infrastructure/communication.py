"""Communication layer + per-agent Messaging router (reference:
``pydcop/infrastructure/communication.py``).

The reference ships two interchangeable layers: in-process queues and
HTTP+JSON.  Here the in-process layer backs ``--mode thread``; the
cross-machine story is TPU-native instead (XLA collectives over
ICI/DCN, see ``pydcop_tpu.parallel``) with a socket control plane for
cross-process runs (``pydcop_tpu.infrastructure.orchestrator``), so no
HTTP server per agent is needed.

``Messaging`` preserves the reference's observable behavior: priority
classes (management messages preempt algorithm messages), per-message
count/size metrics, and failure surfacing for unknown computations.
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, Optional, Tuple

from pydcop_tpu.infrastructure.computations import Message

# priority classes: lower value = delivered first
MSG_MGT = 10
MSG_VALUE = 15
MSG_ALGO = 20


class UnknownComputation(Exception):
    pass


class UnreachableAgent(Exception):
    pass


class CommunicationLayer:
    """Transport abstraction: routes a message to the agent hosting the
    destination computation."""

    def __init__(self):
        self.discovery: Dict[str, "Messaging"] = {}

    def register(self, agent_name: str, messaging: "Messaging") -> None:
        self.discovery[agent_name] = messaging

    def unregister(self, agent_name: str) -> None:
        self.discovery.pop(agent_name, None)

    def send_msg(
        self,
        dest_agent: str,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        raise NotImplementedError


class InProcessCommunicationLayer(CommunicationLayer):
    """Direct queue delivery between agents of one process."""

    def send_msg(
        self,
        dest_agent: str,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        messaging = self.discovery.get(dest_agent)
        if messaging is None:
            raise UnreachableAgent(dest_agent)
        messaging.deliver(src_comp, dest_comp, msg, priority)


class Messaging:
    """Per-agent message router with priority queues and metrics.

    One consumer (the agent thread) pops with :meth:`next_msg`; any
    thread may :meth:`deliver`.  Counts every message and its logical
    size (``Message.size``), split by priority class — the counters the
    reference's msgs/sec metric is derived from.

    A popped message stays accounted in :attr:`pending` until the
    consumer calls :meth:`task_done`: the pop and the in-flight mark
    happen under one lock, so a quiescence monitor reading ``pending``
    can never observe the gap between "message dequeued" and "handler
    started" (that gap once let thread-mode runs terminate with a
    message in flight).
    """

    def __init__(self, agent_name: str):
        self.agent_name = agent_name
        self._heap: list = []
        self._seq = 0  # FIFO tie-break within a priority class
        self._cond = threading.Condition()
        self._in_flight = False
        self.count_msg = 0
        self.size_msg = 0
        self.count_by_priority: Dict[int, int] = {}

    def deliver(
        self,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        with self._cond:
            self._seq += 1
            self.count_msg += 1
            self.size_msg += msg.size
            self.count_by_priority[priority] = (
                self.count_by_priority.get(priority, 0) + 1
            )
            heapq.heappush(
                self._heap, (priority, self._seq, src_comp, dest_comp, msg)
            )
            self._cond.notify()

    def next_msg(
        self, timeout: Optional[float] = None
    ) -> Optional[Tuple[str, str, Message]]:
        """Pop the next (src, dest, msg), or None on timeout.

        Atomically marks the popped message in-flight; the consumer
        must call :meth:`task_done` when its handler finishes.
        """
        with self._cond:
            if not self._heap:
                self._cond.wait(timeout)
            if not self._heap:
                return None
            _, _, src, dest, msg = heapq.heappop(self._heap)
            self._in_flight = True
            return src, dest, msg

    def task_done(self) -> None:
        """Mark the last popped message as fully handled."""
        with self._cond:
            self._in_flight = False

    @property
    def pending(self) -> int:
        """Queued messages + the in-flight one (if any)."""
        with self._cond:
            return len(self._heap) + (1 if self._in_flight else 0)
