"""Communication layer + per-agent Messaging router (reference:
``pydcop/infrastructure/communication.py``).

The reference ships two interchangeable layers: in-process queues and
HTTP+JSON.  Here the in-process layer backs ``--mode thread``; the
cross-machine story is TPU-native instead (XLA collectives over
ICI/DCN, see ``pydcop_tpu.parallel``) with a socket control plane for
cross-process runs (``pydcop_tpu.infrastructure.orchestrator``), so no
HTTP server per agent is needed.

``Messaging`` preserves the reference's observable behavior: priority
classes (management messages preempt algorithm messages), per-message
count/size metrics, and failure surfacing for unknown computations.
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, Optional, Tuple

from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.telemetry import get_metrics, get_tracer

# priority classes: lower value = delivered first
MSG_MGT = 10
MSG_VALUE = 15
MSG_ALGO = 20


class UnknownComputation(Exception):
    pass


class UnreachableAgent(Exception):
    pass


class CommunicationLayer:
    """Transport abstraction: routes a message to the agent hosting the
    destination computation."""

    def __init__(self):
        self.discovery: Dict[str, "Messaging"] = {}

    def register(self, agent_name: str, messaging: "Messaging") -> None:
        self.discovery[agent_name] = messaging

    def unregister(self, agent_name: str) -> None:
        self.discovery.pop(agent_name, None)

    def send_msg(
        self,
        dest_agent: str,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        raise NotImplementedError


class InProcessCommunicationLayer(CommunicationLayer):
    """Direct queue delivery between agents of one process."""

    def send_msg(
        self,
        dest_agent: str,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        messaging = self.discovery.get(dest_agent)
        if messaging is None:
            raise UnreachableAgent(dest_agent)
        messaging.deliver(src_comp, dest_comp, msg, priority)


class MessageLog:
    """Full-message-content log (reference parity: the reference's
    ``Messaging`` can dump every message for debugging a distributed
    run — SURVEY §5 tracing row).  One JSON line per delivered
    message: ``{t, agent, src, dest, type, size, content}`` with the
    content in ``simple_repr`` form (the wire format), so a log line
    is exactly what the TCP plane would have carried.

    One run per file: the path is truncated on open, so rerunning
    against the same path cannot silently interleave two runs' lines.
    Thread-safe append; logging failures never break delivery."""

    def __init__(self, path: str):
        import threading as _threading

        self._f = open(path, "w", encoding="utf-8")
        self._lock = _threading.Lock()

    def log(self, agent: str, src: str, dest: str, msg: Message) -> None:
        import json as _json
        import time as _time

        from pydcop_tpu.utils.simple_repr import simple_repr

        try:
            line = _json.dumps(
                {
                    "t": _time.time(),
                    "agent": agent,
                    "src": src,
                    "dest": dest,
                    "type": msg.type,
                    "size": msg.size,
                    "content": simple_repr(msg),
                },
                default=str,
            )
            with self._lock:
                self._f.write(line + "\n")
        except Exception:
            pass  # a malformed message must not break delivery

    def flush(self) -> None:
        """Push buffered lines to the OS — called at agent shutdown so
        the tail of a log survives even an abrupt process exit after
        stop (close() also flushes, but a shared log may outlive one
        agent's stop)."""
        try:
            with self._lock:
                if not self._f.closed:
                    self._f.flush()
        except Exception:
            pass

    def close(self) -> None:
        try:
            with self._lock:
                self._f.close()
        except Exception:
            pass


class Messaging:
    """Per-agent message router with priority queues and metrics.

    One consumer (the agent thread) pops with :meth:`next_msg`; any
    thread may :meth:`deliver`.  Counts every message and its logical
    size (``Message.size``), split by priority class — the counters the
    reference's msgs/sec metric is derived from.

    A popped message stays accounted in :attr:`pending` until the
    consumer calls :meth:`task_done`: the pop and the in-flight mark
    happen under one lock, so a quiescence monitor reading ``pending``
    can never observe the gap between "message dequeued" and "handler
    started" (that gap once let thread-mode runs terminate with a
    message in flight).
    """

    def __init__(self, agent_name: str, msg_log: Optional[MessageLog] = None):
        self.agent_name = agent_name
        self._heap: list = []
        self._seq = 0  # FIFO tie-break within a priority class
        self._cond = threading.Condition()
        self._in_flight = False
        self.count_msg = 0
        self.size_msg = 0
        self.count_by_priority: Dict[int, int] = {}
        self.msg_log = msg_log

    def deliver(
        self,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        with self._cond:
            self._seq += 1
            self.count_msg += 1
            self.size_msg += msg.size
            self.count_by_priority[priority] = (
                self.count_by_priority.get(priority, 0) + 1
            )
            heapq.heappush(
                self._heap, (priority, self._seq, src_comp, dest_comp, msg)
            )
            self._cond.notify()
        # telemetry outside the lock: one attribute check when disabled
        # (docs/observability.md overhead notes)
        met = get_metrics()
        if met.enabled:
            met.inc("msg.delivered")
            met.inc("msg.size", msg.size)
        tr = get_tracer()
        if tr.detailed:
            tr.event(
                "deliver", cat="message", agent=self.agent_name,
                src=src_comp, dest=dest_comp, type=msg.type,
            )
        if self.msg_log is not None:  # outside the lock: file IO
            self.msg_log.log(self.agent_name, src_comp, dest_comp, msg)

    def next_msg(
        self, timeout: Optional[float] = None
    ) -> Optional[Tuple[str, str, Message]]:
        """Pop the next (src, dest, msg), or None on timeout.

        Atomically marks the popped message in-flight; the consumer
        must call :meth:`task_done` when its handler finishes.
        """
        with self._cond:
            if not self._heap:
                self._cond.wait(timeout)
            if not self._heap:
                return None
            _, _, src, dest, msg = heapq.heappop(self._heap)
            self._in_flight = True
            return src, dest, msg

    def task_done(self) -> None:
        """Mark the last popped message as fully handled."""
        with self._cond:
            self._in_flight = False

    @property
    def pending(self) -> int:
        """Queued messages + the in-flight one (if any)."""
        with self._cond:
            return len(self._heap) + (1 if self._in_flight else 0)

    @property
    def queued(self) -> int:
        """Waiting messages only, excluding the in-flight one.

        The island flush probe needs "anything still to deliver?"
        regardless of whether it is asked from inside a handler (one
        in-flight message — the one that triggered the probe) or from
        ``on_start`` (none): counting the heap alone answers both
        without the caller guessing the in-flight state.
        """
        with self._cond:
            return len(self._heap)
