"""Live observability bridge (reference: ``pydcop/infrastructure/ui.py``).

The reference runs one websocket server per agent feeding the external
``pydcop-ui`` front-end with live value/graph events.  Here solving is
batched, so ONE server observes the whole run: a tiny dependency-free
HTTP server exposing

- ``GET /events`` — **Server-Sent Events** stream; one ``data:`` line
  per engine chunk with ``{"cycle", "cost", "best_cost", "values"}``
  (SSE is websocket-equivalent for a one-way feed and consumable from
  a browser with three lines of ``EventSource`` JS — no extra
  dependency in this zero-egress image, where the reference's
  ``websocket-server`` package is unavailable).
- ``GET /state`` — current snapshot as one JSON object (poll-style).
- ``GET /`` — a minimal built-in live page (cost curve + assignment),
  so the bridge is usable without the external front-end.

Wire-up: ``solve(..., ui_port=N)`` / CLI ``--uiport N`` starts the
server and the engine publishes at every chunk boundary via its
``chunk_callback`` seam; ``pydcop_tpu orchestrator --uiport N`` serves
the same feed for cross-process runs (events relayed from its own
lockstep callback).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

_PAGE = """<!doctype html>
<html><head><title>pydcop_tpu live</title></head><body>
<h3>pydcop_tpu live run</h3>
<div>cycle: <span id="cy">-</span> cost: <span id="co">-</span>
 best: <span id="be">-</span></div>
<pre id="vals"></pre>
<script>
const es = new EventSource('/events');
es.onmessage = (e) => {
  const d = JSON.parse(e.data);
  document.getElementById('cy').textContent = d.cycle;
  document.getElementById('co').textContent = d.cost;
  document.getElementById('be').textContent = d.best_cost;
  if (d.values) document.getElementById('vals').textContent =
    JSON.stringify(d.values, null, 1);
};
</script></body></html>"""


class UiServer:
    """One SSE publisher for a run.  Thread-safe ``publish()``; every
    connected ``/events`` client receives all events from connect time
    on (plus one replay of the latest event so late joiners render
    immediately)."""

    def __init__(self, port: int, host: str = "0.0.0.0"):
        self._clients: List["queue.Queue"] = []
        self._lock = threading.Lock()
        self._last: Optional[Dict[str, Any]] = None
        self.events_published = 0

        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/":
                    body = _PAGE.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/state":
                    body = json.dumps(ui._last or {}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path != "/events":
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                q = ui._attach()
                try:
                    while True:
                        evt = q.get()
                        if evt is None:  # server closing
                            break
                        self.wfile.write(
                            b"data: " + json.dumps(evt).encode() + b"\n\n"
                        )
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    ui._detach(q)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def _attach(self):
        import queue

        q: "queue.Queue" = queue.Queue()
        with self._lock:
            if self._last is not None:
                q.put(self._last)
            self._clients.append(q)
        return q

    def _detach(self, q) -> None:
        with self._lock:
            if q in self._clients:
                self._clients.remove(q)

    def publish(
        self,
        cycle: int,
        cost: float,
        best_cost: float,
        values: Optional[Dict[str, Any]] = None,
        **extra: Any,
    ) -> None:
        evt = {
            "t": time.time(),
            "cycle": int(cycle),
            "cost": None if cost is None else float(cost),
            "best_cost": None if best_cost is None else float(best_cost),
            **extra,
        }
        if values is not None:
            evt["values"] = values
        with self._lock:
            self._last = evt
            self.events_published += 1
            for q in self._clients:
                q.put(evt)

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients)
        for q in clients:
            q.put(None)
        self._httpd.shutdown()
        self._httpd.server_close()


def chunk_publisher(ui: "UiServer", prev_callback=None):
    """Adapt a :class:`UiServer` to the engine's ``chunk_callback``
    seam: publishes ``{cycle, best_cost}`` per chunk, chaining any
    existing callback (e.g. the orchestrator's lockstep barrier)."""

    def cb(done_rounds: int, best_cost: float):
        ui.publish(done_rounds, None, best_cost)
        if prev_callback is not None:
            return prev_callback(done_rounds, best_cost)
        return None

    return cb
