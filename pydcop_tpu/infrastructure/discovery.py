"""Dynamic discovery service (reference:
``pydcop/infrastructure/discovery.py``).

The reference's Discovery is a directory agents and computations
register with AND subscribe to: registration/removal events propagate
to subscribers and drive the resilience machinery.  Here the directory
is a small thread-safe in-process service:

- the host runtime registers agents/computations as it deploys them;
- the elastic cross-process runtime (``infrastructure/elastic.py``)
  keeps one Discovery on the orchestrator, feeds it register events at
  agent registration and removal events when an agent process dies,
  and its subscribers (the reform logic, the UI feed) react — the
  exact role the reference's discovery plays for its orchestrator.

Events are delivered synchronously on the calling thread (callbacks
must be cheap/non-blocking, like the reference's).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

AGENT = "agent"
COMPUTATION = "computation"

# event kinds
ADDED = "added"
REMOVED = "removed"

Callback = Callable[[str, str, str, Optional[str]], None]
# signature: (kind, event, name, detail) where kind is AGENT or
# COMPUTATION, event ADDED/REMOVED, detail = hosting agent for
# computations (or None)


class Discovery:
    """Thread-safe directory with add/remove subscriptions."""

    def __init__(self):
        self._lock = threading.RLock()
        self._agents: Dict[str, Dict] = {}
        self._computations: Dict[str, str] = {}  # comp -> agent
        self._subs: List[Tuple[Optional[str], Callback]] = []

    # -- registration ------------------------------------------------

    def register_agent(self, name: str, **info) -> None:
        with self._lock:
            self._agents[name] = dict(info)
            self._emit(AGENT, ADDED, name, None)

    def unregister_agent(self, name: str) -> List[str]:
        """Remove an agent and all its computations; returns the
        orphaned computation names (removal events fire for each)."""
        with self._lock:
            self._agents.pop(name, None)
            orphans = [
                c for c, a in self._computations.items() if a == name
            ]
            for c in orphans:
                del self._computations[c]
                self._emit(COMPUTATION, REMOVED, c, name)
            self._emit(AGENT, REMOVED, name, None)
            return orphans

    def register_computation(self, comp: str, agent: str) -> None:
        with self._lock:
            if agent not in self._agents:
                raise ValueError(
                    f"computation {comp!r} registered on unknown agent "
                    f"{agent!r}"
                )
            self._computations[comp] = agent
            self._emit(COMPUTATION, ADDED, comp, agent)

    def unregister_computation(self, comp: str) -> None:
        with self._lock:
            agent = self._computations.pop(comp, None)
            self._emit(COMPUTATION, REMOVED, comp, agent)

    # -- queries -----------------------------------------------------

    def agents(self) -> List[str]:
        with self._lock:
            return sorted(self._agents)

    def agent_info(self, name: str) -> Optional[Dict]:
        with self._lock:
            info = self._agents.get(name)
            return dict(info) if info is not None else None

    def computations(self, agent: Optional[str] = None) -> List[str]:
        with self._lock:
            return sorted(
                c
                for c, a in self._computations.items()
                if agent is None or a == agent
            )

    def computation_agent(self, comp: str) -> Optional[str]:
        with self._lock:
            return self._computations.get(comp)

    # -- subscriptions -----------------------------------------------

    def subscribe(
        self, callback: Callback, kind: Optional[str] = None
    ) -> Callable[[], None]:
        """Subscribe to add/remove events (optionally of one kind).
        Returns an unsubscribe function."""
        entry = (kind, callback)
        with self._lock:
            self._subs.append(entry)

        def unsubscribe():
            with self._lock:
                if entry in self._subs:
                    self._subs.remove(entry)

        return unsubscribe

    def _emit(
        self, kind: str, event: str, name: str, detail: Optional[str]
    ) -> None:
        for sub_kind, cb in list(self._subs):
            if sub_kind is None or sub_kind == kind:
                cb(kind, event, name, detail)
