"""Thread-per-agent execution container (reference:
``pydcop/infrastructure/agents.py``).

One :class:`Agent` = one daemon thread + one :class:`Messaging` router
+ the computations the distribution placed on it.  This is the
``--mode thread`` execution path; production solving uses the batched
TPU engine instead (``pydcop_tpu.engine``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    CommunicationLayer,
    Messaging,
    UnknownComputation,
    UnreachableAgent,
)
from pydcop_tpu.infrastructure.computations import (
    Message,
    MessagePassingComputation,
)


class Agent:
    """Hosts computations and pumps their messages on its own thread.

    Routing goes through a shared :class:`Discovery` directory
    (registration/removal events flow to its subscribers, the
    reference's dynamic-discovery behavior); a private one is created
    when none is given.
    """

    def __init__(
        self,
        name: str,
        comm: CommunicationLayer,
        on_error: Optional[Callable[[str, BaseException], None]] = None,
        discovery=None,
        msg_log=None,
        on_unreachable: Optional[
            Callable[[str, BaseException], None]
        ] = None,
    ):
        if discovery is None:
            from pydcop_tpu.infrastructure.discovery import Discovery

            discovery = Discovery()
        self.name = name
        self._comm = comm
        self._discovery = discovery
        discovery.register_agent(name)
        self._computations: Dict[str, MessagePassingComputation] = {}
        self.messaging = Messaging(name, msg_log=msg_log)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._comps_started = threading.Event()
        self._on_error = on_error
        # resilient runtimes (hostnet k_target) set this: a send to a
        # dead/unknown peer is then reported here and DROPPED instead
        # of raising into the posting computation's handler — the
        # distributed best-effort semantics migration needs (the dead
        # peer's computations are being re-deployed elsewhere)
        self._on_unreachable = on_unreachable
        self._busy = False  # a handler is mid-execution
        self.activity_time = 0.0  # seconds spent handling messages
        comm.register(name, self.messaging)

    # -- deployment ----------------------------------------------------

    def deploy_computation(self, comp: MessagePassingComputation) -> None:
        comp.message_sender = self._send
        self._computations[comp.name] = comp
        self._discovery.register_computation(comp.name, self.name)

    @property
    def computations(self) -> Dict[str, MessagePassingComputation]:
        return dict(self._computations)

    def _send(self, src_comp: str, dest_comp: str, msg: Message) -> None:
        dest_agent = self._discovery.computation_agent(dest_comp)
        if self._on_unreachable is not None:
            try:
                if dest_agent is None:
                    raise UnknownComputation(dest_comp)
                self._comm.send_msg(
                    dest_agent, src_comp, dest_comp, msg, MSG_ALGO
                )
            except (UnknownComputation, UnreachableAgent) as e:
                self._on_unreachable(dest_agent or dest_comp, e)
            return
        if dest_agent is None:
            raise UnknownComputation(dest_comp)
        self._comm.send_msg(dest_agent, src_comp, dest_comp, msg, MSG_ALGO)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"agent-{self.name}", daemon=True
        )
        self._thread.start()

    def start_computations(self) -> None:
        for comp in self._computations.values():
            comp.start()
        self._comps_started.set()

    def stop(self) -> None:
        """Orderly end-of-run stop.  Does NOT unregister from the
        directory: sibling agent threads may still be draining late
        in-flight messages addressed to this agent's computations —
        removal here would turn those sends into UnknownComputation
        failures during a successful shutdown."""
        self._stop_evt.set()
        for comp in self._computations.values():
            if comp.is_running:
                comp.stop()
        # the message log (when one is attached) is flushed — not
        # closed: other agents may share the file — so the tail is on
        # disk even if the process exits right after stop
        if self.messaging.msg_log is not None:
            self.messaging.msg_log.flush()

    def leave(self) -> None:
        """DEPART the system (the dynamic/resilience event): stop and
        unregister, publishing computation + agent removal events to
        the directory's subscribers."""
        self.stop()
        self._discovery.unregister_agent(self.name)

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def is_idle(self) -> bool:
        """No queued AND no in-flight message.  ``Messaging.pending``
        counts a popped message until ``task_done``, with the pop and
        the in-flight mark under one lock — so a handler that is about
        to run (and may post more messages) is never invisible to the
        quiescence monitor."""
        return self.messaging.pending == 0

    # -- message pump --------------------------------------------------

    def _run(self) -> None:
        # gate the pump until this agent's computations have started:
        # a faster peer's opening messages then simply WAIT in the
        # thread-safe Messaging queue instead of being popped into
        # not-yet-running computations (whose pre-start buffers would
        # replay them on the starter's thread — measured pathological
        # under a 100-agent message flood)
        while not self._stop_evt.is_set():
            if self._comps_started.wait(timeout=0.05):
                break
        while not self._stop_evt.is_set():
            item = self.messaging.next_msg(timeout=0.05)
            if item is None:
                continue
            src, dest, msg = item
            comp = self._computations.get(dest)
            if comp is None:
                self.messaging.task_done()
                continue  # computation moved/stopped mid-flight
            t0 = time.perf_counter()
            self._busy = True
            try:
                comp.on_message(src, msg, t0)
            except BaseException as e:  # surface, don't kill the pump
                if self._on_error is not None:
                    self._on_error(dest, e)
                else:
                    raise
            finally:
                self._busy = False
                self.messaging.task_done()
                self.activity_time += time.perf_counter() - t0
