"""Message-passing computation base classes (reference:
``pydcop/infrastructure/computations.py``).

Everything that runs on the host runtime is a
:class:`MessagePassingComputation`: it receives messages through
``on_message`` (dispatched to ``@register``-decorated handlers), and
sends through ``post_msg``, which the hosting agent/runtime wires to
its router.  Messages are :class:`SimpleRepr` objects, so the same
classes serialize for the cross-process orchestrator protocol.

This runtime exists for *async-semantics parity* (VERDICT r1 item 6):
A-DSA / A-Max-Sum are validated against these independent
message-driven implementations, while production solving runs on the
batched TPU engine.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from pydcop_tpu.utils.simple_repr import _CLASS_KEY, _MODULE_KEY, SimpleRepr


class Message(SimpleRepr):
    """Base class for all messages exchanged between computations."""

    def __init__(self, msg_type: str, content: Any = None):
        self._msg_type = msg_type
        self._content = content

    @property
    def type(self) -> str:
        return self._msg_type

    @property
    def content(self) -> Any:
        return self._content

    @property
    def size(self) -> int:
        """Logical size used by the Messaging metrics (1 by default;
        subclasses override, e.g. a cost table counts its cells)."""
        return 1

    def __eq__(self, other):
        return (
            isinstance(other, Message)
            and self._msg_type == other._msg_type
            and self._content == other._content
        )

    def __hash__(self):
        return hash((self._msg_type, repr(self._content)))

    def __repr__(self) -> str:
        return f"Message({self._msg_type!r}, {self._content!r})"


def message_type(name: str, fields: List[str]):
    """Build a message dataclass-like subclass with named ``fields``
    (the reference's ``message_type`` factory).

    >>> ValueMsg = message_type("value", ["value"])
    >>> m = ValueMsg(value=3)
    >>> m.type, m.value
    ('value', 3)
    """

    def _init(self, *args, **kwargs):
        named = dict(zip(fields, args))
        overlap = set(named) & set(kwargs)
        if overlap:
            raise TypeError(f"duplicate argument(s): {sorted(overlap)}")
        named.update(kwargs)
        unknown = set(named) - set(fields)
        if unknown:
            raise TypeError(f"unknown field(s): {sorted(unknown)}")
        missing = set(fields) - set(named)
        if missing:
            raise TypeError(f"missing field(s): {sorted(missing)}")
        Message.__init__(self, name, dict(named))

    def _getter(field):
        return property(lambda self: self._content[field])

    def _simple_repr(self):
        from pydcop_tpu.utils.simple_repr import simple_repr

        return {
            _CLASS_KEY: type(self).__qualname__,
            _MODULE_KEY: type(self).__module__,
            "fields": simple_repr(self._content),
        }

    @classmethod
    def _from_repr(cls, r):
        from pydcop_tpu.utils.simple_repr import from_repr

        return cls(**from_repr(r["fields"]))

    namespace: Dict[str, Any] = {
        "__init__": _init,
        "_simple_repr": _simple_repr,
        "_from_repr": _from_repr,
    }
    for f in fields:
        namespace[f] = _getter(f)
    cls = type(f"{name.capitalize()}Message", (Message,), namespace)
    return cls


def stable_seed(seed: int, name: str) -> int:
    """Mix a run seed with a computation name, stably across processes
    (``hash()`` is salted per interpreter; crc32 is not)."""
    import zlib

    return (seed * 0x9E3779B1) ^ zlib.crc32(name.encode())


def register(msg_type: str):
    """Decorator marking a method as the handler for ``msg_type``."""

    def deco(fn: Callable):
        fn._handles_msg_type = msg_type
        return fn

    return deco


class MessagePassingComputation:
    """A named computation driven entirely by messages.

    The hosting runtime assigns ``message_sender`` (a callable
    ``(src_comp, dest_comp, msg) -> None``) before ``start()``.
    Handlers are declared with ``@register("msg-type")``; ``footprint``
    is the memory estimate the distribution layer uses.
    """

    def __init__(self, name: str):
        self._name = name
        self._running = False
        self._started = False
        # algorithm messages that arrive before start(): a peer whose
        # start raced ahead may legitimately send first (the
        # cross-process runtimes broadcast 'start' sequentially) —
        # buffered and replayed instead of dropped
        self._pre_start: List[Tuple[str, Message, float]] = []
        self.message_sender: Optional[Callable[[str, str, Message], None]] = None
        # collect @register handlers from the class hierarchy
        self._handlers: Dict[str, Callable] = {}
        for klass in reversed(type(self).__mro__):
            for attr in vars(klass).values():
                mt = getattr(attr, "_handles_msg_type", None)
                if mt is not None:
                    self._handlers[mt] = attr

    @property
    def name(self) -> str:
        return self._name

    @property
    def is_running(self) -> bool:
        return self._running

    def start(self) -> None:
        """Enter the running state, fire ``on_start``, then replay any
        messages that arrived before the start."""
        self._running = True
        self._started = True
        self.on_start()
        buffered, self._pre_start = self._pre_start, []
        for sender, msg, t in buffered:
            self.on_message(sender, msg, t)

    def stop(self) -> None:
        self._running = False
        self.on_stop()

    def on_start(self) -> None:  # override point
        pass

    def on_stop(self) -> None:  # override point
        pass

    def post_msg(self, target: str, msg: Message) -> None:
        if self.message_sender is None:
            raise RuntimeError(
                f"Computation {self._name} is not attached to a runtime"
            )
        self.message_sender(self._name, target, msg)

    def on_message(self, sender: str, msg: Message, t: float = 0.0) -> None:
        """Dispatch one message to its ``@register``-ed handler."""
        if not self._running:
            if not self._started:  # early message: replayed by start()
                self._pre_start.append((sender, msg, t))
            return  # stopped: late messages are dropped
        handler = self._handlers.get(msg.type)
        if handler is None:
            raise ValueError(
                f"Computation {self._name} has no handler for message "
                f"type {msg.type!r} (handlers: {sorted(self._handlers)})"
            )
        handler(self, sender, msg, t)

    def footprint(self) -> float:
        return 1.0

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._name!r})"


class DcopComputation(MessagePassingComputation):
    """A computation attached to a computation-graph node: knows its
    neighbors and its algorithm-estimated footprint."""

    def __init__(self, name: str, comp_def):
        super().__init__(name)
        self.computation_def = comp_def
        self._neighbors: List[str] = (
            list(comp_def.node.neighbors) if comp_def is not None else []
        )

    @property
    def neighbors(self) -> List[str]:
        return self._neighbors

    def post_to_all_neighbors(self, msg: Message) -> None:
        for n in self._neighbors:
            self.post_msg(n, msg)

    # -- resilience hook (replica migration, hostnet k_target) --------
    #
    # When a neighboring computation dies with its agent and is
    # re-deployed on a replica holder, the fresh instance knows
    # nothing this computation ever told it.  The runtime posts a
    # ``_peer_restarted`` message (through the normal pump, so the
    # hook runs on the computation thread like any handler) and
    # algorithms override :meth:`on_peer_restarted` to re-send their
    # current view to that one peer.  Default: no-op — an algorithm
    # without the override still works, it just relies on its own
    # periodic traffic to re-sync the migrated neighbor.

    @register("_peer_restarted")
    def _on_peer_restarted_msg(
        self, sender: str, msg: Message, t: float
    ) -> None:
        self.on_peer_restarted(msg.content)

    def on_peer_restarted(self, peer: str) -> None:  # override point
        pass

    def footprint(self) -> float:
        if self.computation_def is None:
            return 1.0
        from pydcop_tpu.algorithms import load_algorithm_module

        module = load_algorithm_module(self.computation_def.algo.algo)
        return module.computation_memory(self.computation_def.node)


class VariableComputation(DcopComputation):
    """A computation that owns one decision variable and selects values
    for it (reference: ``VariableComputation.value_selection``)."""

    def __init__(self, variable, comp_def):
        super().__init__(variable.name, comp_def)
        self._variable = variable
        self.current_value: Any = None
        self.value_history: List[Any] = []
        # replica migration (hostnet k_target): the runtime sets this
        # BEFORE start() to the variable's last orchestrator-sampled
        # value, so a migrated computation resumes from the
        # pre-failure assignment instead of a fresh random draw.
        # Algorithms honor it in on_start where an initial draw exists.
        self.restart_value: Any = None

    def initial_value_or(self, default_fn) -> Any:
        """``restart_value`` when set and in-domain, else
        ``default_fn()`` — the one-line way for an algorithm's
        ``on_start`` to support migration restarts."""
        rv = self.restart_value
        if rv is not None and rv in self._variable.domain:
            return rv
        return default_fn()

    @property
    def variable(self):
        return self._variable

    def value_selection(self, value: Any) -> None:
        if value != self.current_value:
            self.current_value = value
            self.value_history.append(value)

    def random_value(self, rnd) -> Any:
        return self._variable.domain[rnd.randrange(len(self._variable.domain))]
