"""``solve_host()`` — run a DCOP on the host message-driven runtime
(reference: ``pydcop/infrastructure/run.py:run_local_thread_dcop``).

Two execution modes over the SAME computations:

- ``mode='sim'``: a deterministic single-thread event loop.  Pending
  messages live in per-(src, dest) FIFO channels; each step picks a
  random nonempty channel (seeded) and delivers its head.  This models
  asynchrony (any interleaving ACROSS channels, in-order within one,
  matching the reference's queue delivery) while staying reproducible —
  the workhorse of the async-parity tests.
- ``mode='thread'``: one real thread per agent
  (``infrastructure.agents.Agent``), in-process queue delivery — the
  reference's ``--mode thread`` execution model.

Termination: quiescence (no pending messages — host algorithms stop
re-sending stable messages), a message budget, or wall-clock timeout.
Algorithms with tie-moves (DSA B/C) never quiesce under asynchrony, so
the runtime tracks the ANYTIME BEST assignment (as the reference
orchestrator does) and reports it as ``cost``/``assignment``, with the
last state in ``final_*``.  Result dict matches the reference surface:
``{assignment, cost, cycle, msg_count, msg_size, status, time}``
(``cycle`` reports delivered messages, the async analogue of rounds).
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple, Union

from pydcop_tpu.algorithms import (
    AlgorithmDef,
    ComputationDef,
    load_algorithm_module,
    prepare_algo_params,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.graphs import load_graph_module
from pydcop_tpu.infrastructure.computations import (
    Message,
    MessagePassingComputation,
    VariableComputation,
)


def _placement(
    dcop: DCOP, comp_names: List[str], distribution
) -> Dict[str, List[str]]:
    """agent -> [computation names]: given Distribution, else dcop
    agents round-robin, else one agent per computation (the
    reference's oneagent default)."""
    placement: Dict[str, List[str]] = {}
    if distribution is not None:
        # same validation the hostnet orchestrator applies: a stale
        # placement must fail loudly, not KeyError mid-build or drop
        # entries silently
        hosted = set(distribution.computations)
        missing = [c for c in comp_names if c not in hosted]
        if missing:
            raise ValueError(
                f"placement leaves computation(s) {missing} unhosted"
            )
        extra = sorted(hosted - set(comp_names))
        if extra:
            raise ValueError(
                f"placement names unknown computation(s) {extra} "
                "(not in this problem's graph)"
            )
        for cname in comp_names:
            placement.setdefault(
                distribution.agent_for(cname), []
            ).append(cname)
    elif dcop.agents:
        agent_names = sorted(dcop.agents)
        for i, cname in enumerate(comp_names):
            placement.setdefault(
                agent_names[i % len(agent_names)], []
            ).append(cname)
    else:
        for cname in comp_names:
            placement.setdefault(f"a_{cname}", []).append(cname)
    return placement


def _build_computations(
    dcop: DCOP,
    algo_name: str,
    params: Dict[str, Any],
    seed: int,
    distribution=None,
    accel: Optional[set] = None,
    pending_refs: Optional[Dict[str, Dict[str, Any]]] = None,
    graph=None,
) -> Tuple[List[MessagePassingComputation], Optional[Dict[str, List[str]]]]:
    """Build one computation per graph node; agents named in ``accel``
    get their whole placed sub-graph as ONE compiled island
    (``build_island`` proxies) instead of per-node host computations.
    Returns ``(computations, placement)`` — placement is None unless
    islands forced it to be computed here (one graph build either way).
    ``pending_refs[agent]['fn']`` is the island's late-bound
    inbox-drained probe — the runtime rebinds it once its delivery
    structure exists."""
    module = load_algorithm_module(algo_name)
    if not hasattr(module, "build_computation"):
        raise ValueError(
            f"{algo_name}: no host build_computation — only the batched "
            "TPU engine supports this algorithm"
        )
    if graph is None:
        graph = load_graph_module(module.GRAPH_TYPE).build_computation_graph(
            dcop
        )
    algo_def = AlgorithmDef(algo_name, params, dcop.objective)
    defs = {
        node.name: ComputationDef(node, algo_def) for node in graph.nodes
    }
    accel = accel or set()
    if not accel:
        return [
            module.build_computation(defs[n], seed=seed) for n in defs
        ], None
    placement = _placement(dcop, list(defs), distribution)
    unknown = accel - set(placement)
    if unknown:
        raise ValueError(
            f"accel_agents {sorted(unknown)} have no computations "
            f"placed on them (agents: {sorted(placement)})"
        )
    computations: List[MessagePassingComputation] = []
    for aname, cnames in placement.items():
        if aname in accel:
            ref = {"fn": lambda: 0, "comps": set(cnames)}
            pending_refs[aname] = ref
            computations.extend(
                module.build_island(
                    [defs[c] for c in sorted(cnames)],
                    dcop,
                    seed=seed,
                    pending_fn=lambda ref=ref: ref["fn"](),
                )
            )
        else:
            computations.extend(
                module.build_computation(defs[c], seed=seed)
                for c in cnames
            )
    return computations, placement


def solve_host(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    algo_params: Optional[Mapping[str, Any]] = None,
    mode: str = "sim",
    timeout: Optional[float] = None,
    max_msgs: Optional[int] = None,
    seed: int = 0,
    distribution=None,
    rounds: Optional[int] = None,
    msg_log: Optional[str] = None,
    accel_agents=None,
    chaos: Optional[str] = None,
    chaos_seed: int = 0,
) -> Dict[str, Any]:
    """Solve ``dcop`` with the host message-driven runtime.

    ``msg_log`` writes every delivered message's full content to a
    JSONL file (the reference's per-message log option — one line per
    message in ``simple_repr`` wire form).

    ``chaos``/``chaos_seed`` (thread mode): apply a deterministic
    fault-injection plan (``pydcop_tpu.faults``, ``docs/faults.md``)
    to every agent's outbound messages.  Crash schedules need killable
    OS processes (``mode='process'``); sim needs no chaos layer at all
    — its event loop is already a seeded, controlled schedule.

    The budget is ``max_msgs`` delivered messages; when only ``rounds``
    is given it is converted as rounds × number of computations (one
    activation per computation ≈ one synchronous round), so a CLI
    ``--rounds`` budget stays meaningful across engines.

    The run normally ends by *quiescence* (no queued or in-flight
    messages — algorithms stop re-sending stable messages), the
    host-engine analogue of the reference's stable-message stop
    conditions; see ``docs/termination.md`` for the full mapping
    across engines.
    """
    t0 = time.perf_counter()
    from pydcop_tpu.algorithms import resolve_algo
    from pydcop_tpu.telemetry import get_tracer

    tracer = get_tracer()
    algo_name, params_in = resolve_algo(algo, algo_params)
    module = load_algorithm_module(algo_name)
    params = prepare_algo_params(params_in, module.algo_params)

    chaos_plan = None
    if chaos:
        if mode != "thread":
            raise ValueError(
                "chaos fault injection needs a communication layer to "
                "wrap — use mode='thread' (in-process) or "
                "mode='process' (TCP); the sim event loop is already "
                "a seeded, fully controlled schedule"
            )
        from pydcop_tpu.faults import FaultPlan

        chaos_plan = FaultPlan.from_spec(chaos, chaos_seed)
        if tracer.enabled:
            # the plan lands on the trace timeline so injected-fault
            # events downstream carry their seed/spec provenance
            tracer.event(
                "chaos-plan", cat="fault", spec=chaos, seed=chaos_seed
            )
        if chaos_plan.crashes:
            raise ValueError(
                "chaos crash=AGENT@T schedules hard-kill an agent OS "
                "process — use mode='process' (thread-mode agents "
                "share this interpreter)"
            )

    # compiled islands (heterogeneous deployment, as in the hostnet
    # runtime): agents named in accel_agents host their placed
    # sub-graph as one array-engine island behind per-node proxies
    accel = set(accel_agents or ())
    if accel:
        from pydcop_tpu.algorithms import require_island_support

        require_island_support(module, algo_name)
    pending_refs: Dict[str, Dict[str, Any]] = {}

    # a strategy NAME resolves here, over the one graph this run
    # builds anyway (placement files / Distribution objects arrive
    # already resolved from the embedding layer).  Sim without islands
    # has no agent containers — a strategy's result would be
    # discarded, so don't compute it (and don't error on undeclared
    # agents for a call that never needed them)
    graph = None
    if mode == "sim" and not accel:
        distribution = None
    if isinstance(distribution, str):
        if not hasattr(module, "GRAPH_TYPE"):
            raise ValueError(
                f"{algo_name}: no GRAPH_TYPE — cannot compute a "
                f"distribution strategy for it"
            )
        graph = load_graph_module(module.GRAPH_TYPE).build_computation_graph(
            dcop
        )
        if not dcop.agents:
            raise ValueError(
                f"distribution={distribution!r} needs declared agents "
                "(the dcop has none); declare AgentDefs or pass a "
                "placement file"
            )
        from pydcop_tpu.distribution import compute_distribution

        distribution = compute_distribution(
            distribution, graph, list(dcop.agents.values()),
            hints=dcop.dist_hints, algo_module=module,
        )

    with tracer.span("build-computations", cat="phase", algo=algo_name):
        computations, placement = _build_computations(
            dcop, algo_name, params, seed,
            distribution=distribution, accel=accel,
            pending_refs=pending_refs, graph=graph,
        )

    if max_msgs is None:
        max_msgs = (
            rounds * len(computations) if rounds else 100_000
        )

    # anytime-best tracking (what the reference orchestrator records):
    # async variants with tie-moves (DSA B/C) never quiesce, so the
    # budget-stopped run's meaningful result is the best state seen
    var_comps = [
        c for c in computations if isinstance(c, VariableComputation)
    ]
    sign = -1.0 if dcop.objective == "max" else 1.0
    best = {"cost": float("inf"), "assignment": {}}
    trace: List[float] = []  # anytime cost stream (--collect_on CSVs)
    trace_msgs: List[int] = []  # delivered count at each snapshot

    def snapshot(delivered: int = 0) -> None:
        assignment = {c.variable.name: c.current_value for c in var_comps}
        if any(v is None for v in assignment.values()):
            return
        cost = dcop.solution_cost(assignment)
        trace.append(cost)
        trace_msgs.append(delivered)
        if tracer.enabled:
            tracer.event(
                "snapshot", cat="cycle", cost=cost, delivered=delivered
            )
        if sign * cost < best["cost"]:
            best["cost"] = sign * cost
            best["assignment"] = assignment

    log = None
    if msg_log is not None:
        from pydcop_tpu.infrastructure.communication import MessageLog

        log = MessageLog(msg_log)
    chaos_info: Dict[str, Any] = {}  # filled by _run_threads (events)
    try:
        with tracer.span("deliver-loop", cat="phase", mode=mode):
            if mode == "sim":
                status, delivered, size = _run_sim(
                    computations, timeout, max_msgs, seed, t0, snapshot,
                    msg_log=log, pending_refs=pending_refs,
                )
            elif mode == "thread":
                status, delivered, size = _run_threads(
                    dcop, computations, timeout, max_msgs, distribution,
                    t0, snapshot, msg_log=log, placement=placement,
                    pending_refs=pending_refs, chaos_plan=chaos_plan,
                    chaos_info=chaos_info,
                )
            else:
                raise ValueError(f"solve_host: unknown mode {mode!r}")
    finally:
        if log is not None:
            log.close()

    snapshot(delivered)
    assignment = {c.variable.name: c.current_value for c in var_comps}
    if any(v is None for v in assignment.values()):
        # stopped before every computation selected a value (short
        # timeout/budget mid-UTIL for dpop/syncbb): fall back to the
        # best sampled assignment — same guard as the hostnet
        # orchestrator's final collect — instead of crashing inside
        # constraint evaluation
        assignment = dict(best["assignment"])
        cost = sign * best["cost"] if assignment else None
    else:
        cost = dcop.solution_cost(assignment)
    best_cost = sign * best["cost"] if best["assignment"] else None
    return {
        "assignment": best["assignment"],
        "cost": best_cost,  # back to the native sign
        "final_assignment": assignment,
        "final_cost": cost,
        "cycle": delivered,
        "msg_count": delivered,
        "msg_size": size,
        "status": status,
        "time": time.perf_counter() - t0,
        "cost_trace": trace,
        "trace_subsampled": True,  # one entry per snapshot, not cycle
        # actual delivered count per snapshot, so the metrics CSVs can
        # label rows exactly instead of reconstructing proportionally
        "trace_msgs": trace_msgs,
        # fault-injection replay record (spec + seed + event counts)
        **(
            {"chaos": {**chaos_plan.to_meta(), **chaos_info}}
            if chaos_plan is not None
            else {}
        ),
    }


def _run_sim(
    computations: List[MessagePassingComputation],
    timeout: Optional[float],
    max_msgs: int,
    seed: int,
    t0: float,
    snapshot,
    msg_log=None,
    pending_refs: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Tuple[str, int, int]:
    rnd = random.Random(seed)
    # per-(src, dest) FIFO channels: asynchrony means ANY interleaving
    # ACROSS channels, but messages within one channel stay ordered
    # (the reference's queue/TCP delivery guarantees this; violating it
    # lets a stale message clobber a newer one and breaks convergence)
    from collections import deque

    channels: Dict[Tuple[str, str], "deque"] = {}
    nonempty: List[Tuple[str, str]] = []
    by_name = {c.name: c for c in computations}

    # islands flush when THEIR inbox drains — the same per-agent probe
    # as the hostnet/thread runtimes (a global in-flight count would
    # let an unrelated queued message suppress the island's final
    # flush and quiesce with unpropagated boundary beliefs).  The
    # delivered message is decremented before its handler runs, so 0
    # really means drained.
    dest_ref: Dict[str, Dict[str, Any]] = {}
    for ref in (pending_refs or {}).values():
        ref["queued"] = 0
        ref["fn"] = lambda ref=ref: ref["queued"]
        for cname in ref["comps"]:
            dest_ref[cname] = ref

    def sender(src: str, dest: str, msg: Message) -> None:
        if dest not in by_name:
            raise ValueError(f"message to unknown computation {dest!r}")
        ch = (src, dest)
        q = channels.get(ch)
        if q is None:
            q = channels[ch] = deque()
        if not q:
            nonempty.append(ch)
        q.append(msg)
        r = dest_ref.get(dest)
        if r is not None:
            r["queued"] += 1

    for c in computations:
        c.message_sender = sender
    # start in randomized order — part of the modeled asynchrony
    order = list(computations)
    rnd.shuffle(order)
    for c in order:
        c.start()

    # sim delivers straight off its channels (no Messaging router), so
    # the message-plane telemetry hooks live here; guards are one
    # attribute check each (docs/observability.md overhead notes)
    from pydcop_tpu.telemetry import get_metrics, get_tracer

    met = get_metrics()
    tr = get_tracer()
    delivered = 0
    size = 0
    status = "finished"  # quiescence
    snap_every = max(1, len(computations))
    while nonempty:
        if delivered % snap_every == 0:
            snapshot(delivered)
        if delivered >= max_msgs:
            status = "msg_budget"
            break
        if timeout is not None and time.perf_counter() - t0 > timeout:
            status = "timeout"
            break
        i = rnd.randrange(len(nonempty))
        nonempty[i], nonempty[-1] = nonempty[-1], nonempty[i]
        ch = nonempty[-1]
        q = channels[ch]
        msg = q.popleft()
        if not q:
            nonempty.pop()
        src, dest = ch
        r = dest_ref.get(dest)
        if r is not None:
            r["queued"] -= 1
        delivered += 1
        size += msg.size
        if met.enabled:
            met.inc("msg.delivered")
            met.inc("msg.size", msg.size)
        if tr.detailed:
            tr.event(
                "deliver", cat="message", agent="_sim",
                src=src, dest=dest, type=msg.type,
            )
        if msg_log is not None:
            msg_log.log("_sim", src, dest, msg)
        by_name[dest].on_message(src, msg)
    for c in computations:
        c.stop()
    return status, delivered, size


def _run_threads(
    dcop: DCOP,
    computations: List[MessagePassingComputation],
    timeout: Optional[float],
    max_msgs: int,
    distribution,
    t0: float,
    snapshot,
    msg_log=None,
    placement: Optional[Dict[str, List[str]]] = None,
    pending_refs: Optional[Dict[str, Dict[str, Any]]] = None,
    chaos_plan=None,
    chaos_info: Optional[Dict[str, Any]] = None,
) -> Tuple[str, int, int]:
    from pydcop_tpu.infrastructure.agents import Agent
    from pydcop_tpu.infrastructure.communication import (
        InProcessCommunicationLayer,
    )

    if placement is None:
        placement = _placement(
            dcop, [c.name for c in computations], distribution
        )

    if len(placement) > 512:
        import logging

        logging.getLogger(__name__).warning(
            "thread mode with %d agents: one OS thread per agent "
            "starves the GIL well before 1000 agents (the classic "
            "thread-per-agent scaling wall, measured in BASELINE.md) "
            "— prefer mode='sim', fewer agents via a distribution, or "
            "the batched engine",
            len(placement),
        )

    from pydcop_tpu.infrastructure.discovery import Discovery

    comm = InProcessCommunicationLayer()
    discovery = Discovery()  # dynamic directory: add/remove events
    by_name = {c.name: c for c in computations}
    errors: List[Tuple[str, BaseException]] = []
    agents = []
    # fault injection: each agent sends through its OWN chaos wrapper
    # over the shared in-process layer (the plan keys faults by
    # directed agent link, and the wrapper needs to know its sender)
    if chaos_plan is not None:
        unknown = chaos_plan.referenced_agents() - set(placement)
        if unknown:
            raise ValueError(
                f"chaos spec names unknown agent(s) {sorted(unknown)} "
                f"(this run's agents: {sorted(placement)}) — those "
                "faults would never fire"
            )
    chaos_layers = []
    for aname, comp_names in placement.items():
        plane = comm
        if chaos_plan is not None:
            from pydcop_tpu.faults import ChaosCommunicationLayer

            plane = ChaosCommunicationLayer(comm, chaos_plan, aname)
            chaos_layers.append(plane)
        agent = Agent(
            aname, plane,
            on_error=lambda comp, e: errors.append((comp, e)),
            discovery=discovery,
            msg_log=msg_log,
        )
        for cname in comp_names:
            agent.deploy_computation(by_name[cname])
        agents.append(agent)
        if pending_refs and aname in pending_refs:
            # island flush probe: drained when nothing is WAITING —
            # Messaging.queued excludes the in-flight message, so the
            # probe is exact both inside a handler and from on_start
            pending_refs[aname]["fn"] = (
                lambda a=agent: a.messaging.queued
            )

    for a in agents:
        a.start()
    for a in agents:
        a.start_computations()

    # run until quiescent (all queues empty twice in a row), message
    # budget, or timeout
    status = "finished"
    idle_checks = 0
    while True:
        time.sleep(0.02)
        total = sum(a.messaging.count_msg for a in agents)
        snapshot(total)  # values are plain attributes; a torn read at
        # worst yields a mix of valid values, whose cost is still a
        # valid anytime sample
        if timeout is not None and time.perf_counter() - t0 > timeout:
            status = "timeout"
            break
        if total >= max_msgs:
            status = "msg_budget"
            break
        # a chaos-held message (delay / partition hold) is in flight
        # but invisible to every Messaging queue — quiescence must
        # wait for it or a delayed message would arrive after "done"
        if all(a.is_idle for a in agents) and not any(
            w.in_flight for w in chaos_layers
        ):
            idle_checks += 1
            if idle_checks >= 3:
                break
        else:
            idle_checks = 0
    for a in agents:
        a.stop()
    for a in agents:
        a.join(timeout=1.0)
    for w in chaos_layers:
        w.close()  # stop the timer wheels (inner layer has no close)
    if chaos_info is not None and chaos_layers:
        events: Dict[str, int] = {}
        for w in chaos_layers:
            for kind, n in w.event_summary().items():
                events[kind] = events.get(kind, 0) + n
        chaos_info["events"] = events
    if errors:
        comp, err = errors[0]
        raise RuntimeError(
            f"computation {comp!r} failed in thread mode: {err!r}"
        ) from err
    delivered = sum(a.messaging.count_msg for a in agents)
    size = sum(a.messaging.size_msg for a in agents)
    return status, delivered, size
