"""Host message-driven runtime (reference: ``pydcop/infrastructure/``).

The TPU batched engine (``pydcop_tpu.engine``) is the production solve
path; this package is the reference-shaped *host* runtime that the
asynchronous algorithms' semantics are anchored to:

- ``computations``: ``Message`` / ``MessagePassingComputation`` base
  classes with ``@register`` handler dispatch — the reference's
  ``infrastructure/computations.py`` seam.
- ``communication``: in-process communication layer + per-agent
  ``Messaging`` router with priority classes and message metrics —
  the reference's ``infrastructure/communication.py`` (the HTTP
  layer's TPU-native replacement is ``pydcop_tpu.parallel``).
- ``agents``: the thread-per-agent execution container.
- ``runtime``: ``solve_host()`` — run a DCOP on this runtime, either
  with real agent threads (``mode='thread'``) or on a deterministic
  seeded single-thread event loop (``mode='sim'``) used by the
  async-parity tests (VERDICT r1 item 6).
"""

from pydcop_tpu.infrastructure.computations import (  # noqa: F401
    DcopComputation,
    Message,
    MessagePassingComputation,
    VariableComputation,
    message_type,
    register,
)
from pydcop_tpu.infrastructure.runtime import solve_host  # noqa: F401
