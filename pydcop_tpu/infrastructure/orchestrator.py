"""Cross-process orchestrator/agent control plane (reference:
``pydcop/infrastructure/orchestrator.py`` + ``commands/agent.py``).

The reference runs one HTTP server per agent and POSTs every algorithm
message between processes.  The TPU-native design needs none of that on
the solve path: all processes run the SAME sharded SPMD program
(``engine.run_batched`` over a global ``jax.sharding.Mesh``), and the
per-round neighbor exchange is an XLA collective over ICI/DCN
(Gloo on CPU hosts) — not application-level messaging.  What remains is
a thin *management* plane, which this module provides over plain TCP
JSON lines:

1. agents connect and register with the orchestrator;
2. the orchestrator ships each agent a deploy message (the problem
   YAML inline, algorithm + params, run budget, its process id, and
   the ``jax.distributed`` coordinator address);
3. every process joins ``jax.distributed`` (the orchestrator is
   process 0 and hosts the coordinator) and runs the sharded solve —
   one process = one mesh segment, results replicated;
4. **lockstep control**: at every interior chunk boundary each agent
   sends a ``chunk`` message and waits for the orchestrator's
   ``go``/``halt`` decision.  This is simultaneously (a) the heartbeat
   that detects hung agents, (b) the only place a wall-clock
   ``timeout`` is decided — by the orchestrator alone, so every
   ``jax.distributed`` process stops at the same chunk boundary (a
   per-process wall-clock check would diverge and trip the SPMD
   cross-check), and (c) the point where a run can be halted early;
5. agents report their result; the orchestrator cross-checks all
   reported costs agree (SPMD determinism check), replies ``stop``,
   and returns the assembled result dict.

Failure handling (reference parity: the orchestrator surfaces agent
failure, SURVEY.md §2.5): a reader thread per connection turns peer
death into an immediate EOF event — a SIGKILLed process's sockets are
closed by the kernel, so detection is sub-second, not a socket-timeout
wait.  On failure the orchestrator notifies the surviving agents
(``abort``), fails the solve with a clean error naming the dead agent,
and — because a process wedged inside a collective whose peer died may
never return from XLA — a watchdog force-exits the process after
``abort_grace`` seconds with exit code 70.  Agents mirror the same
logic when the orchestrator dies.  ``stop``/``abort`` is always sent
in a ``finally`` so healthy peers never sit out the socket timeout.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional

_ENC = "utf-8"
_TIMEOUT = 120.0

# exit code for "force-killed while wedged in a collective whose peer
# died" — distinguishable from ordinary tracebacks in tests and scripts
ABORT_EXIT_CODE = 70


class AgentFailureError(RuntimeError):
    """An agent process died or stopped responding mid-solve."""


def _send(conn: socket.socket, obj: Dict[str, Any]) -> None:
    conn.sendall((json.dumps(obj) + "\n").encode(_ENC))


def _recv(reader) -> Optional[Dict[str, Any]]:
    line = reader.readline()
    if not line:
        return None
    return json.loads(line.decode(_ENC))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class _Peer:
    """One control-plane connection, pumped by a reader thread.

    All inbound messages land in :attr:`inbox`; EOF or a read error
    lands a ``None`` sentinel and fires ``on_eof`` (unless the run
    already finished).  This keeps the main thread free to block in
    XLA while death detection stays immediate.
    """

    def __init__(self, name: str, conn: socket.socket, done_evt,
                 on_eof=None, on_msg=None, reader=None):
        self.name = name
        self.conn = conn
        self.inbox: "queue.Queue" = queue.Queue()
        self._done_evt = done_evt
        self._on_eof = on_eof
        self._on_msg = on_msg
        # reuse the registration-phase reader when given: a second
        # makefile() on the same socket would race its buffer
        self._reader = reader if reader is not None else conn.makefile("rb")
        self._thread = threading.Thread(
            target=self._pump, name=f"ctl-{name}", daemon=True
        )
        self._thread.start()

    def _pump(self) -> None:
        try:
            while True:
                msg = _recv(self._reader)
                if msg is None:
                    break
                if self._on_msg is not None:
                    self._on_msg(msg)
                self.inbox.put(msg)
        except (OSError, ValueError):
            pass
        self.inbox.put(None)
        if self._on_eof is not None and not self._done_evt.is_set():
            self._on_eof(self.name)

    def send(self, obj: Dict[str, Any]) -> bool:
        try:
            _send(self.conn, obj)
            return True
        except OSError:
            return False

    def get(self, timeout: float) -> Optional[Dict[str, Any]]:
        """Next inbound message; None on peer EOF; raises on timeout."""
        return self.inbox.get(timeout=timeout)

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass


def _arm_watchdog(done_evt, grace: float, reason: str) -> None:
    """Force-exit the process if the main thread stays wedged (inside a
    collective whose peer died) past ``grace`` seconds."""

    def _watch():
        if not done_evt.wait(grace):
            print(
                f"pydcop_tpu: FATAL: {reason}; main thread did not "
                f"return within {grace:.0f}s (wedged in a collective?) "
                "— force-exiting",
                file=sys.stderr,
                flush=True,
            )
            os._exit(ABORT_EXIT_CODE)

    threading.Thread(target=_watch, daemon=True).start()


def run_orchestrator(
    dcop_yaml: str,
    algo: str,
    params: Dict[str, Any],
    port: int,
    nb_agents: int = 1,
    rounds: int = 200,
    seed: int = 0,
    chunk_size: int = 64,
    timeout: Optional[float] = None,
    host: str = "0.0.0.0",
    advertise_host: str = "localhost",
    heartbeat_timeout: float = _TIMEOUT,
    abort_grace: float = 5.0,
    scenario_yaml: Optional[str] = None,
    k_target: int = 0,
    ui_port: Optional[int] = None,
) -> Dict[str, Any]:
    """Serve the management plane, run the solve as process 0, and
    return the assembled result dict.

    With ``ui_port``, a live observability feed (SSE, see
    ``infrastructure/ui.py``) publishes the lockstep progress and the
    final result while the run is in flight.

    Raises :class:`AgentFailureError` (after notifying survivors) if an
    agent dies or stops heartbeating mid-solve.
    """
    coord_port = _free_port()
    num_processes = nb_agents + 1
    t_start = time.monotonic()

    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((host, port))
    server.listen(nb_agents)
    server.settimeout(heartbeat_timeout)

    done_evt = threading.Event()
    dead: List[str] = []  # names of agents whose connection dropped
    peers: List[_Peer] = []
    solve_started = False  # jax.distributed up → teardown can wedge

    def _on_peer_eof(name: str) -> None:
        dead.append(name)
        for p in peers:
            if p.name != name:
                p.send({"type": "abort", "reason": f"agent {name} died"})
        _arm_watchdog(done_evt, abort_grace, f"agent {name!r} died")

    def _broadcast(obj: Dict[str, Any]) -> None:
        for p in peers:
            p.send(obj)

    def _fail(why: str) -> AgentFailureError:
        # notify survivors before raising so they don't sit out the
        # socket timeout blocked on our next decision
        _broadcast({"type": "abort", "reason": why})
        return AgentFailureError(why)

    try:
        while len(peers) < nb_agents:
            conn, _ = server.accept()
            conn.settimeout(heartbeat_timeout)
            reader = conn.makefile("rb")
            msg = _recv(reader)
            if not msg or msg.get("type") != "register":
                conn.close()
                continue
            name = msg.get("name", f"agent_{len(peers) + 1}")
            peers.append(
                _Peer(name, conn, done_evt, on_eof=_on_peer_eof,
                      reader=reader)
            )

        deploy_base = {
            "type": "deploy",
            "dcop_yaml": dcop_yaml,
            "algo": algo,
            "params": params,
            "rounds": rounds,
            "seed": seed,
            "chunk_size": chunk_size,
            "num_processes": num_processes,
            "coordinator": f"{advertise_host}:{coord_port}",
            "heartbeat_timeout": heartbeat_timeout,
            "abort_grace": abort_grace,
        }
        if scenario_yaml is not None:
            deploy_base["scenario_yaml"] = scenario_yaml
            deploy_base["k_target"] = k_target
        for i, peer in enumerate(peers):
            peer.send({**deploy_base, "process_id": i + 1})

        def chunk_cb(done_rounds: int, best_cost: float) -> Optional[str]:
            # lockstep barrier: collect one `chunk` ack per agent,
            # then broadcast the shared go/halt decision
            deadline = time.monotonic() + heartbeat_timeout
            for peer in peers:
                while True:
                    if dead:
                        raise _fail(f"agent {dead[0]!r} died mid-solve")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise _fail(
                            f"agent {peer.name!r} missed the chunk "
                            f"heartbeat ({heartbeat_timeout:.0f}s)"
                        )
                    try:
                        msg = peer.get(timeout=min(remaining, 1.0))
                    except queue.Empty:
                        continue
                    if msg is None:
                        raise _fail(f"agent {peer.name!r} died mid-solve")
                    if msg.get("type") == "chunk":
                        break
            if (
                timeout is not None
                and time.monotonic() - t_start > timeout
            ):
                _broadcast({"type": "halt", "status": "timeout"})
                return "timeout"
            _broadcast({"type": "go"})
            return None

        ui = None
        cb = chunk_cb
        if ui_port is not None:
            from pydcop_tpu.infrastructure.ui import (
                UiServer,
                chunk_publisher,
            )

            ui = UiServer(ui_port)
            cb = chunk_publisher(ui, prev_callback=chunk_cb)

        solve_started = True
        try:
            result = _run_spmd(
                dcop_yaml, algo, params, rounds, seed, chunk_size,
                coordinator=f"localhost:{coord_port}",
                num_processes=num_processes,
                process_id=0,
                chunk_callback=cb,
                scenario_yaml=scenario_yaml,
                k_target=k_target,
            )
            if ui is not None:
                ui.publish(
                    result["cycle"], result["cost"], result["cost"],
                    values=result.get("assignment"),
                    status=result.get("status"),
                )
        finally:
            if ui is not None:
                ui.close()

        # collect + cross-check agent results (SPMD replication means
        # every process must report the identical cost)
        agent_results = []
        for peer in peers:
            deadline = time.monotonic() + heartbeat_timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise _fail(
                        f"agent {peer.name!r} sent no result within "
                        f"{heartbeat_timeout:.0f}s"
                    )
                try:
                    msg = peer.get(timeout=remaining)
                except queue.Empty:
                    continue
                if msg is None:
                    raise _fail(
                        f"agent {peer.name!r} disconnected without a "
                        "result"
                    )
                if msg.get("type") == "result":
                    break
                # late chunk acks from the final boundary: skip
            agent_results.append(msg)
            if abs(msg["cost"] - result["cost"]) > 1e-5:
                raise _fail(
                    f"agent {peer.name!r} reported cost {msg['cost']}, "
                    f"orchestrator computed {result['cost']} — SPMD "
                    "divergence"
                )
        result["agents"] = [p.name for p in peers]
        return result
    except BaseException as exc:
        # a peer death usually surfaces as a failed Gloo/XLA collective
        # before the chunk barrier notices — name the dead agent
        if dead and not isinstance(exc, AgentFailureError):
            exc = AgentFailureError(
                f"agent {dead[0]!r} died mid-solve "
                f"(collective failed: {type(exc).__name__})"
            )
        # after a MID-SOLVE failure the jax.distributed runtime is
        # unrecoverable and its atexit teardown can hang trying to
        # reach the dead peer: guarantee the process exits.  Pre-solve
        # failures (registration/deploy) leave nothing wedged — let
        # the caller handle the exception normally.
        if solve_started:
            _arm_watchdog(threading.Event(), abort_grace, str(exc))
        raise exc
    finally:
        done_evt.set()
        _broadcast({"type": "stop"})
        for peer in peers:
            peer.close()
        server.close()


def run_agent(
    orchestrator_addr: str,
    name: str,
    retry_for: float = 30.0,
) -> Dict[str, Any]:
    """Register with the orchestrator, run the deployed solve as one
    SPMD process in lockstep with the control plane, report the
    result, and return it."""
    ohost, oport = orchestrator_addr.rsplit(":", 1)
    deadline = time.monotonic() + retry_for
    conn = None
    while True:
        try:
            conn = socket.create_connection((ohost, int(oport)), timeout=5)
            break
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.3)
    conn.settimeout(_TIMEOUT)
    done_evt = threading.Event()
    abort_reason: List[str] = []
    grace = 5.0
    solve_started = False

    try:
        _send(conn, {"type": "register", "name": name})
        reader = conn.makefile("rb")
        deploy = _recv(reader)
        if not deploy or deploy.get("type") != "deploy":
            raise RuntimeError(f"agent {name}: bad deploy message {deploy}")
        if deploy.get("elastic"):
            # elastic runtime: this process becomes a worker SUPERVISOR
            # (spawns/kills SPMD worker subprocesses across reforms).
            # Supervisors are IDLE between reforms by design — the
            # read timeout must go or the pump thread mistakes quiet
            # for orchestrator death and kills its healthy worker
            from pydcop_tpu.infrastructure.elastic import (
                elastic_agent_loop,
            )

            conn.settimeout(None)
            peer = _Peer("orchestrator", conn, done_evt, reader=reader)
            try:
                return elastic_agent_loop(
                    conn, peer, deploy, name, orchestrator_addr
                )
            finally:
                done_evt.set()
        heartbeat = float(deploy.get("heartbeat_timeout", _TIMEOUT))
        grace = float(deploy.get("abort_grace", 5.0))

        # from here on, a reader thread owns the socket: an `abort`
        # (another agent died) or EOF (orchestrator died) must be able
        # to unwedge this process even while the main thread is blocked
        # inside a collective

        def _on_eof(_name: str) -> None:
            abort_reason.append("orchestrator died")
            _arm_watchdog(done_evt, grace, "orchestrator died")

        def _watch_abort(msg):
            if msg.get("type") == "abort":
                abort_reason.append(msg.get("reason", "aborted"))
                _arm_watchdog(
                    done_evt, grace, f"aborted: {abort_reason[-1]}"
                )

        peer = _Peer("orchestrator", conn, done_evt, on_eof=_on_eof,
                     on_msg=_watch_abort, reader=reader)

        def chunk_cb(done_rounds: int, best_cost: float) -> Optional[str]:
            peer.send({"type": "chunk", "n": done_rounds})
            deadline = time.monotonic() + heartbeat
            while True:
                if abort_reason:
                    raise AgentFailureError(
                        f"agent {name}: run aborted ({abort_reason[0]})"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise AgentFailureError(
                        f"agent {name}: no go/halt from orchestrator "
                        f"within {heartbeat:.0f}s"
                    )
                try:
                    msg = peer.get(timeout=min(remaining, 1.0))
                except queue.Empty:
                    continue
                if msg is None:
                    raise AgentFailureError(
                        f"agent {name}: orchestrator died mid-solve"
                    )
                t = msg.get("type")
                if t == "go":
                    return None
                if t == "halt":
                    return msg.get("status", "halted")
                if t == "abort":
                    raise AgentFailureError(
                        f"agent {name}: run aborted "
                        f"({msg.get('reason', '')})"
                    )
                # anything else (early stop) — keep waiting

        solve_started = True
        result = _run_spmd(
            deploy["dcop_yaml"],
            deploy["algo"],
            deploy["params"],
            deploy["rounds"],
            deploy["seed"],
            deploy["chunk_size"],
            coordinator=deploy["coordinator"],
            num_processes=deploy["num_processes"],
            process_id=deploy["process_id"],
            chunk_callback=chunk_cb,
            scenario_yaml=deploy.get("scenario_yaml"),
            k_target=int(deploy.get("k_target", 0)),
        )
        peer.send(
            {
                "type": "result",
                "name": name,
                "cost": result["cost"],
                "cycle": result["cycle"],
            }
        )
        # wait for stop (or EOF) so the orchestrator's cross-check
        # finishes before our socket goes away
        try:
            while True:
                msg = peer.get(timeout=heartbeat)
                if msg is None or msg.get("type") in ("stop", "abort"):
                    break
        except queue.Empty:
            pass
        return result
    except BaseException as exc:
        if abort_reason and not isinstance(exc, AgentFailureError):
            exc = AgentFailureError(
                f"agent {name}: run aborted ({abort_reason[0]}; "
                f"collective failed: {type(exc).__name__})"
            )
        if solve_started:  # see run_orchestrator: pre-solve failures
            # leave nothing wedged, don't force-exit the host process
            _arm_watchdog(threading.Event(), grace, str(exc))
        raise exc
    finally:
        done_evt.set()
        conn.close()


def _run_spmd(
    dcop_yaml: str,
    algo: str,
    params: Dict[str, Any],
    rounds: int,
    seed: int,
    chunk_size: int,
    coordinator: str,
    num_processes: int,
    process_id: int,
    timeout: Optional[float] = None,
    chunk_callback=None,
    scenario_yaml: Optional[str] = None,
    k_target: int = 0,
) -> Dict[str, Any]:
    """Join the jax.distributed cluster and run the sharded solve.

    Every process executes this identical function; arrays with
    replicated out-specs give every process the full result.  The
    wall-clock ``timeout`` is only honored on single-process runs —
    orchestrated runs stop via ``chunk_callback`` so all processes
    stop at the same chunk boundary.
    """
    import jax

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator, num_processes=num_processes, process_id=process_id
        )

    import numpy as np
    from jax.sharding import Mesh

    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop
    from pydcop_tpu.parallel.mesh import SHARD_AXIS

    dcop = load_dcop(dcop_yaml)
    module = load_algorithm_module(algo)
    full_params = prepare_algo_params(params, module.algo_params)

    n_shards = jax.device_count()  # global
    mesh = Mesh(np.array(jax.devices()), (SHARD_AXIS,))

    if scenario_yaml is not None:
        from pydcop_tpu.dcop.yamldcop import load_scenario
        from pydcop_tpu.engine.dynamic import run_dynamic

        scenario = load_scenario(scenario_yaml)
        # run_dynamic's segment schedule is a deterministic function of
        # (dcop, scenario, seed), so every SPMD process replays the
        # exact same recompile/resume sequence; no wall-clock timeout
        # here for the same reason
        r = run_dynamic(
            dcop,
            algo,
            params,
            scenario,
            k_target=k_target,
            final_rounds=rounds,
            seed=seed,
            mesh=mesh,
            n_shards=n_shards,
            chunk_size=chunk_size,
            chunk_callback=chunk_callback,
        )
        return {
            **r,
            "num_processes": num_processes,
            "n_shards": n_shards,
        }

    problem = compile_dcop(dcop, n_shards=n_shards)
    r = run_batched(
        problem,
        module,
        full_params,
        rounds=rounds,
        seed=seed,
        timeout=timeout,
        chunk_size=chunk_size,
        mesh=mesh,
        chunk_callback=chunk_callback,
    )
    return {
        "assignment": r.best_assignment,
        "cost": r.best_cost,
        "final_cost": r.cost,
        "cycle": r.cycles,
        "msg_count": r.messages,
        "msg_size": r.messages,
        "status": r.status,
        "time": r.time,
        "num_processes": num_processes,
        "n_shards": n_shards,
        # per-round anytime stream, same shape as api.solve's batched
        # result: feeds the --collect_on metrics CSVs
        "cost_trace": r.cost_trace.tolist(),
    }
