"""Cross-process orchestrator/agent control plane (reference:
``pydcop/infrastructure/orchestrator.py`` + ``commands/agent.py``).

The reference runs one HTTP server per agent and POSTs every algorithm
message between processes.  The TPU-native design needs none of that on
the solve path: all processes run the SAME sharded SPMD program
(``engine.run_batched`` over a global ``jax.sharding.Mesh``), and the
per-round neighbor exchange is an XLA collective over ICI/DCN
(Gloo on CPU hosts) — not application-level messaging.  What remains is
a thin *management* plane, which this module provides over plain TCP
JSON lines:

1. agents connect and register with the orchestrator;
2. the orchestrator ships each agent a deploy message (the problem
   YAML inline, algorithm + params, run budget, its process id, and
   the ``jax.distributed`` coordinator address);
3. every process joins ``jax.distributed`` (the orchestrator is
   process 0 and hosts the coordinator) and runs the sharded solve —
   one process = one mesh segment, results replicated;
4. agents report their result; the orchestrator cross-checks all
   reported costs agree (SPMD determinism check), replies ``stop``,
   and returns the assembled result dict.

Capability parity: `pydcop orchestrator` / `pydcop agent` let one
problem span multiple OS processes (and, with a reachable coordinator
address, multiple hosts) exactly like the reference's HTTP deployment,
while the heavy traffic rides collectives instead of HTTP.
"""

from __future__ import annotations

import json
import socket
import time
from typing import Any, Dict, List, Optional

_ENC = "utf-8"
_TIMEOUT = 120.0


def _send(conn: socket.socket, obj: Dict[str, Any]) -> None:
    conn.sendall((json.dumps(obj) + "\n").encode(_ENC))


def _recv(reader) -> Optional[Dict[str, Any]]:
    line = reader.readline()
    if not line:
        return None
    return json.loads(line.decode(_ENC))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def run_orchestrator(
    dcop_yaml: str,
    algo: str,
    params: Dict[str, Any],
    port: int,
    nb_agents: int = 1,
    rounds: int = 200,
    seed: int = 0,
    chunk_size: int = 64,
    timeout: Optional[float] = None,
    host: str = "0.0.0.0",
    advertise_host: str = "localhost",
) -> Dict[str, Any]:
    """Serve the management plane, run the solve as process 0, and
    return the assembled result dict."""
    coord_port = _free_port()
    num_processes = nb_agents + 1

    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((host, port))
    server.listen(nb_agents)
    server.settimeout(_TIMEOUT)

    conns: List[socket.socket] = []
    readers = []
    names: List[str] = []
    try:
        while len(conns) < nb_agents:
            conn, _ = server.accept()
            conn.settimeout(_TIMEOUT)
            reader = conn.makefile("rb")
            msg = _recv(reader)
            if not msg or msg.get("type") != "register":
                conn.close()
                continue
            conns.append(conn)
            readers.append(reader)
            names.append(msg.get("name", f"agent_{len(conns)}"))

        deploy_base = {
            "type": "deploy",
            "dcop_yaml": dcop_yaml,
            "algo": algo,
            "params": params,
            "rounds": rounds,
            "seed": seed,
            "chunk_size": chunk_size,
            "num_processes": num_processes,
            "coordinator": f"{advertise_host}:{coord_port}",
        }
        for i, conn in enumerate(conns):
            _send(conn, {**deploy_base, "process_id": i + 1})

        result = _run_spmd(
            dcop_yaml, algo, params, rounds, seed, chunk_size,
            coordinator=f"localhost:{coord_port}",
            num_processes=num_processes,
            process_id=0,
            timeout=timeout,
        )

        # collect + cross-check agent results (SPMD replication means
        # every process must report the identical cost)
        agent_results = []
        for name, reader in zip(names, readers):
            msg = _recv(reader)
            if not msg or msg.get("type") != "result":
                raise RuntimeError(
                    f"agent {name!r} disconnected without a result"
                )
            agent_results.append(msg)
            if abs(msg["cost"] - result["cost"]) > 1e-5:
                raise RuntimeError(
                    f"agent {name!r} reported cost {msg['cost']}, "
                    f"orchestrator computed {result['cost']} — SPMD "
                    "divergence"
                )
        for conn in conns:
            _send(conn, {"type": "stop"})
        result["agents"] = names
        return result
    finally:
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        server.close()


def run_agent(
    orchestrator_addr: str,
    name: str,
    retry_for: float = 30.0,
) -> Dict[str, Any]:
    """Register with the orchestrator, run the deployed solve as one
    SPMD process, report the result, and return it."""
    ohost, oport = orchestrator_addr.rsplit(":", 1)
    deadline = time.monotonic() + retry_for
    conn = None
    while True:
        try:
            conn = socket.create_connection((ohost, int(oport)), timeout=5)
            break
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.3)
    conn.settimeout(_TIMEOUT)
    reader = conn.makefile("rb")
    try:
        _send(conn, {"type": "register", "name": name})
        deploy = _recv(reader)
        if not deploy or deploy.get("type") != "deploy":
            raise RuntimeError(f"agent {name}: bad deploy message {deploy}")

        result = _run_spmd(
            deploy["dcop_yaml"],
            deploy["algo"],
            deploy["params"],
            deploy["rounds"],
            deploy["seed"],
            deploy["chunk_size"],
            coordinator=deploy["coordinator"],
            num_processes=deploy["num_processes"],
            process_id=deploy["process_id"],
            timeout=None,
        )
        _send(
            conn,
            {
                "type": "result",
                "name": name,
                "cost": result["cost"],
                "cycle": result["cycle"],
            },
        )
        _recv(reader)  # stop
        return result
    finally:
        conn.close()


def _run_spmd(
    dcop_yaml: str,
    algo: str,
    params: Dict[str, Any],
    rounds: int,
    seed: int,
    chunk_size: int,
    coordinator: str,
    num_processes: int,
    process_id: int,
    timeout: Optional[float],
) -> Dict[str, Any]:
    """Join the jax.distributed cluster and run the sharded solve.

    Every process executes this identical function; arrays with
    replicated out-specs give every process the full result.
    """
    import jax

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator, num_processes=num_processes, process_id=process_id
        )

    import numpy as np
    from jax.sharding import Mesh

    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop
    from pydcop_tpu.parallel.mesh import SHARD_AXIS

    dcop = load_dcop(dcop_yaml)
    module = load_algorithm_module(algo)
    full_params = prepare_algo_params(params, module.algo_params)

    n_shards = jax.device_count()  # global
    problem = compile_dcop(dcop, n_shards=n_shards)
    mesh = Mesh(np.array(jax.devices()), (SHARD_AXIS,))
    r = run_batched(
        problem,
        module,
        full_params,
        rounds=rounds,
        seed=seed,
        timeout=timeout,
        chunk_size=chunk_size,
        mesh=mesh,
    )
    return {
        "assignment": r.best_assignment,
        "cost": r.best_cost,
        "final_cost": r.cost,
        "cycle": r.cycles,
        "msg_count": r.messages,
        "msg_size": r.messages,
        "status": r.status,
        "time": r.time,
        "num_processes": num_processes,
        "n_shards": n_shards,
    }
