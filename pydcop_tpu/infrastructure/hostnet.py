"""Cross-process host runtime: message-driven agents over TCP.

This is the TPU build's equivalent of the reference's HTTP agent
deployment (``pydcop/infrastructure/communication.py``
``HttpCommunicationLayer`` + ``commands/agent.py``): real
``MessagePassingComputation`` agents spread over OS processes (or
hosts), exchanging algorithm messages as ``simple_repr`` JSON frames —
the reference's wire format — over persistent TCP connections instead
of per-message HTTP POSTs.

It complements the SPMD path (``infrastructure/orchestrator.py``):
that one runs the *batched* engine over a ``jax.distributed`` mesh
(homogeneous, lockstep); this one runs the *host* message-driven
engine with arbitrary per-agent placement — the heterogeneous-agent
deployment mode, where machines need nothing but Python + this
package.

Deployment protocol (control plane, newline-JSON over the agent's
orchestrator connection):

1. agents connect and ``register`` with their name + message-plane
   address (their ``TcpCommunicationLayer`` listener),
2. the orchestrator ships each agent ``deploy``: the DCOP yaml, algo
   + params, its computation placement, the full agent directory, and
   the seed — each agent rebuilds the problem locally and instantiates
   ONLY its computations through the algorithm registry
   (``build_computation``), the reference's deployment seam,
3. ``start`` begins message passing; the orchestrator polls ``status``
   (pending messages + delivered count per agent) and declares
   quiescence when every agent is idle and the global delivered count
   is stable across 3 consecutive polls (the distributed analogue of
   the in-process quiescence rule, see ``docs/termination.md``),
4. ``collect`` gathers each agent's variable values; the orchestrator
   assembles the assignment, evaluates the cost, and broadcasts
   ``stop``.

Failure handling: a dead agent connection aborts the run with a clean
``AgentFailureError`` (control connections double as liveness
monitors); surviving agents receive ``stop`` on the way out.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    CommunicationLayer,
    Messaging,
    UnreachableAgent,
)
from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.telemetry import get_metrics, get_tracer

_ENC = "utf-8"

# per-destination outbound queue bound (frames): the backpressure
# high-water mark that keeps a slow-but-alive peer from growing a
# sender's memory without limit
MAX_QUEUED_FRAMES = 10_000


class _DestChannel:
    """One destination's outbound state: pending frames, a condition
    sharing the layer lock (so only this destination's writer and
    backpressured senders are woken), the dead-link marker, and the
    frame sequence counter (the receiver's dedupe key across
    reconnect-resends)."""

    __slots__ = ("frames", "cond", "dead", "seq")

    def __init__(self, lock: threading.Lock):
        self.frames: List[bytes] = []
        self.cond = threading.Condition(lock)
        self.dead: Optional[str] = None
        self.seq = 0


class TcpCommunicationLayer(CommunicationLayer):
    """Message-plane transport: one listener per process, pooled
    outbound connections, ``simple_repr`` JSON frames.

    Frame format (one JSON object per line)::

        {"da": dest_agent, "sc": src_comp, "dc": dest_comp,
         "p": priority, "m": simple_repr(message)}
    """

    def __init__(
        self,
        bind_host: str = "127.0.0.1",
        port: int = 0,
        on_send_error=None,
        retry_window: float = 5.0,
    ):
        super().__init__()
        self.addresses: Dict[str, Tuple[str, int]] = {}
        # transient-fault tolerance: a failed connect/send is retried
        # with exponential backoff + jitter for this many seconds (the
        # grace window) before the link is declared dead — a short
        # partition or peer restart is then a blip, not a run failure
        self.retry_window = retry_window
        # retry-timing determinism: writer-loop backoff jitter is the
        # keyed-hash variant (utils/backoff.py) — pure in (seed, dest,
        # attempt) — so a chaos replay reproduces every link's retry
        # schedule bit-for-bit regardless of thread interleaving.
        # run_agent points this at the fault plan's seed; distinct
        # destination keys keep links decorrelated from each other.
        self.backoff_seed = 0
        # resend dedupe: highest frame seq delivered per sender id —
        # a reconnect resends its whole batch, and replaying a frame
        # into Messaging would double-count `delivered` and re-trigger
        # handlers (guarded by _lock)
        self._last_seq: Dict[str, int] = {}
        # outbound: one bounded FIFO queue + writer thread per
        # destination, so a slow or unresponsive peer (blocking
        # connect/sendall, up to 10s) only stalls ITS queue — the
        # sending (pump) thread never blocks on the network for other
        # destinations.  The bound restores the old blocking-send
        # backpressure per destination: a slow-but-alive peer blocks
        # senders to IT at MAX_QUEUED frames instead of growing the
        # queue without limit.
        self._channels: Dict[Tuple[str, int], "_DestChannel"] = {}
        self._lock = threading.Lock()
        # send failures are asynchronous now: surfaced through this
        # callback (agent → errors list → status reply → orchestrator
        # fails the run), preserving the old fail-fast behavior; with
        # no callback the failure is logged (never silent)
        self.on_send_error = on_send_error
        # messages handed to the transport (local + remote): one half
        # of the two-counter quiescence rule — the orchestrator may
        # declare quiescence only when global sent == global delivered,
        # otherwise a frame queued here or in flight on a slow TCP
        # link is invisible and the run can end mid-propagation.
        # Guarded by _lock: a lost increment would leave sent <
        # delivered forever and break quiescence.
        self.count_sent = 0
        self._server = socket.create_server(
            (bind_host, port), reuse_port=False
        )
        self.address: Tuple[str, int] = (
            bind_host, self._server.getsockname()[1]
        )
        # the id stamped on outbound frames ("sa"): unique per layer
        # within a run — the receiver's dedupe namespace
        self._sender_id = f"{self.address[0]}:{self.address[1]}"
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="hostnet-accept", daemon=True
        )
        self._accept_thread.start()

    # -- directory ------------------------------------------------------

    def set_addresses(self, directory: Dict[str, Any]) -> None:
        """Install the agent → (host, port) message-plane directory."""
        self.addresses.update(
            {a: (h, int(p)) for a, (h, p) in directory.items()}
        )

    def forget_agent(self, name: str) -> None:
        """Drop a dead agent: its address, and its outbound channel
        (queued frames are discarded and backpressured senders are
        released — they see ``UnreachableAgent``, which the resilient
        agent loop tolerates as a send error, not a computation
        error)."""
        addr = self.addresses.pop(name, None)
        if addr is None:
            return
        with self._lock:
            ch = self._channels.get(addr)
            if ch is not None:
                ch.dead = ch.dead or "agent removed (migration)"
                ch.frames = []
                ch.cond.notify_all()

    # -- inbound --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._recv_loop, args=(conn,),
                name="hostnet-recv", daemon=True,
            ).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        from pydcop_tpu.utils.simple_repr import from_repr

        reader = conn.makefile("rb")
        try:
            while True:
                line = reader.readline()
                if not line:
                    return
                frame = json.loads(line.decode(_ENC))
                met = get_metrics()
                if met.enabled:
                    met.inc("hostnet.recv_frames")
                sender = frame.get("sa")
                if sender is not None:
                    # reconnect-resend dedupe: a writer that lost its
                    # connection mid-batch resends the WHOLE batch;
                    # frames from one sender arrive in seq order (one
                    # writer thread, ordered TCP), so anything at or
                    # below the high-water mark was already delivered
                    sq = int(frame.get("sq", 0))
                    with self._lock:
                        duplicate = sq <= self._last_seq.get(sender, 0)
                        if not duplicate:
                            self._last_seq[sender] = sq
                    if duplicate:
                        if met.enabled:
                            met.inc("hostnet.dedupe_dropped")
                        tr = get_tracer()
                        if tr.enabled:
                            tr.event(
                                "dedupe-drop", cat="message",
                                sender=sender, seq=sq,
                            )
                        continue
                messaging = self.discovery.get(frame["da"])
                if messaging is None:
                    continue  # late frame for a stopped agent
                messaging.deliver(
                    frame["sc"], frame["dc"], from_repr(frame["m"]),
                    frame.get("p", MSG_ALGO),
                )
        except (OSError, ValueError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- outbound -------------------------------------------------------

    def send_msg(
        self,
        dest_agent: str,
        src_comp: str,
        dest_comp: str,
        msg: Message,
        priority: int = MSG_ALGO,
    ) -> None:
        met = get_metrics()
        if met.enabled:
            met.inc("hostnet.sent")
        local = self.discovery.get(dest_agent)
        if local is not None:  # same process: no serialization
            local.deliver(src_comp, dest_comp, msg, priority)
            with self._lock:
                self.count_sent += 1
            return
        addr = self.addresses.get(dest_agent)
        if addr is None:
            raise UnreachableAgent(dest_agent)
        from pydcop_tpu.utils.simple_repr import simple_repr

        # serialized OUTSIDE the lock (the payload can be arbitrarily
        # large and every destination shares this lock); only the
        # per-channel seq is spliced in under the lock, where it is
        # assigned — frames must enter the channel in seq order
        prefix = json.dumps(
            {
                "da": dest_agent,
                "sc": src_comp,
                "dc": dest_comp,
                "p": priority,
                "m": simple_repr(msg),
                "sa": self._sender_id,
            }
        )[:-1]  # strip the closing brace, "sq" is appended below
        with self._lock:
            ch = self._channels.get(addr)
            if ch is None:
                ch = self._channels[addr] = _DestChannel(self._lock)
                threading.Thread(
                    target=self._writer_loop,
                    args=(addr, ch, dest_agent),
                    name=f"hostnet-send-{addr[0]}:{addr[1]}",
                    daemon=True,
                ).start()
            # bounded queue = per-destination backpressure; only
            # senders to THIS peer ever block here
            while (
                len(ch.frames) >= MAX_QUEUED_FRAMES
                and ch.dead is None
                and not self._closing
            ):
                ch.cond.wait()
            if ch.dead is not None:
                raise UnreachableAgent(f"{dest_agent}: {ch.dead}")
            # counted at ENQUEUE: a queued-but-unsent frame must keep
            # sent > delivered so quiescence cannot fire mid-flight.
            # The seq is assigned under the same lock that appends, so
            # frames enter the channel in seq order — the property the
            # receiver's resend dedupe relies on.
            self.count_sent += 1
            ch.seq += 1
            ch.frames.append(
                f'{prefix},"sq":{ch.seq}}}\n'.encode(_ENC)
            )
            ch.cond.notify_all()

    def _writer_loop(
        self, addr: Tuple[str, int], ch: "_DestChannel", dest_agent: str
    ) -> None:
        """Drain one destination's queue over a persistent connection.

        Transient failures (connection refused/reset, short partitions)
        are retried — reconnect + resend with exponential backoff and
        jitter, bounded by :attr:`retry_window` — through the shared
        backoff helper.  A resend may replay frames the peer already
        received before the connection died; the receiver drops those
        by (sender id, frame seq), so retries are exactly-once at the
        Messaging layer.  Only a retried-out failure (the permanent
        case) marks the destination dead and reports it through
        ``on_send_error`` — the run is then failed, repaired, or
        degraded by the control plane."""
        from pydcop_tpu.utils.backoff import call_with_backoff

        conn_box: List[Optional[socket.socket]] = [None]

        def _attempt(payload: bytes) -> None:
            try:
                if conn_box[0] is None:
                    conn_box[0] = socket.create_connection(
                        addr, timeout=10
                    )
                conn_box[0].sendall(payload)
            except OSError:
                met = get_metrics()
                if met.enabled:
                    # every failed attempt becomes a backoff retry
                    # (unless the window is spent — the dead-link
                    # counter below records that outcome)
                    met.inc("hostnet.retries")
                c, conn_box[0] = conn_box[0], None
                if c is not None:
                    try:
                        c.close()
                    except OSError:
                        pass
                raise

        try:
            while True:
                with self._lock:
                    while not ch.frames and not self._closing:
                        ch.cond.wait()
                    if self._closing and not ch.frames:
                        return
                    if ch.dead is not None:
                        return  # peer forgotten (migration): stop
                    batch = ch.frames
                    ch.frames = []
                    ch.cond.notify_all()  # wake backpressured senders
                call_with_backoff(
                    lambda payload=b"".join(batch): _attempt(payload),
                    self.retry_window,
                    base=0.05,
                    max_delay=1.0,
                    seed=self.backoff_seed,
                    key=f"hostnet:{dest_agent}",
                    giving_up=lambda: self._closing
                    or ch.dead is not None,
                )
        except OSError as e:
            with self._lock:
                ch.dead = ch.dead or str(e)
                ch.frames = []
                ch.cond.notify_all()
            met = get_metrics()
            if met.enabled:
                met.inc("hostnet.dead_links")
            tr = get_tracer()
            if tr.enabled:
                tr.event(
                    "link-dead", cat="message", peer=dest_agent,
                    error=str(e),
                )
            cb = self.on_send_error
            if cb is not None:
                cb(dest_agent, e)
            else:
                import logging

                logging.getLogger(__name__).warning(
                    "hostnet: dropping messages to %s (%s): %s",
                    dest_agent, addr, e,
                )
        finally:
            if conn_box[0] is not None:
                try:
                    conn_box[0].close()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            self._closing = True
            for ch in self._channels.values():
                ch.cond.notify_all()
        try:
            self._server.close()
        except OSError:
            pass


# -- control-plane helpers (same framing as the SPMD orchestrator) ------


def _send(conn: socket.socket, obj: Dict[str, Any]) -> None:
    conn.sendall((json.dumps(obj) + "\n").encode(_ENC))


def _recv(reader) -> Optional[Dict[str, Any]]:
    line = reader.readline()
    if not line:
        return None
    return json.loads(line.decode(_ENC))


class AgentFailureError(RuntimeError):
    pass


class PlacementError(ValueError):
    """Invalid placement/distribution input (a usage error, not an
    internal failure — the CLI converts it to a clean exit)."""


def run_host_orchestrator(
    dcop,
    algo: str,
    params: Dict[str, Any],
    nb_agents: int,
    port: int,
    rounds: int = 200,
    timeout: Optional[float] = None,
    seed: int = 0,
    distribution: Optional[str] = None,
    placement: Optional[Dict[str, List[str]]] = None,
    register_timeout: float = 120.0,
    poll_timeout: float = 30.0,
    best_sample_period: float = 0.5,
    ui_port: Optional[int] = None,
    server: Optional[socket.socket] = None,
    accel_agents: Optional[List[str]] = None,
    k_target: int = 0,
    chaos: Optional[str] = None,
    chaos_seed: int = 0,
    grace_period: float = 5.0,
    degraded_ok: bool = True,
) -> Dict[str, Any]:
    """Wait for ``nb_agents`` host agents, deploy, run to quiescence /
    budget / timeout, and return the assembled result dict.

    ``k_target > 0`` enables k-resilience (the reference's
    ``ResilientAgent`` + replication machinery, SURVEY §2.6): after
    placement, ``replication.ucs_hostingcosts.replica_distribution``
    picks ``k_target`` replica-holder agents per computation; when an
    agent dies mid-run the orchestrator solves the reparation DCOP
    (``replication.repair``) over the LIVE replica holders, ships the
    orphaned computations to the chosen agents (with the variables'
    last sampled values as restart state), updates every agent's
    directory, and the run continues to quiescence.  A computation
    whose replica holders are all dead is lost and fails the run.
    After any migration the two-counter quiescence ledger is void
    (frames sent to the dead agent can never be reconciled), so the
    orchestrator falls back to idle + delivered-stability over a
    doubled window — the reference has no global ledger at all.
    An island (accel) agent's computations are re-deployed as PLAIN
    host computations on the replica holders: the compiled pytree
    state dies with its process, but the value restart carries the
    assignment, which is the state that matters to the run.

    Placement: an explicit ``placement`` (agent → computation names,
    the ``distribute --output`` yaml's ``distribution:`` mapping), or
    a ``distribution`` strategy name (computed over the REGISTERED
    agents through the distribution layer, using the dcop's AgentDef
    capacity/hosting data when the registered names match), else
    round-robin.

    ``poll_timeout`` bounds every control-plane read after
    registration: a wedged or partitioned agent (no RST, nothing to
    read) fails the run with :class:`AgentFailureError` instead of
    hanging it.  Anytime-best tracking: agent values are sampled every
    ``best_sample_period`` seconds and the best-cost sample is what
    ``cost``/``assignment`` report (``final_*`` is the last state) —
    the same semantics as the other engines.

    Transient-fault tolerance: ``grace_period`` is the window that
    separates blips from permanent death.  It is shipped to every
    agent as the message plane's retry window (failed sends are
    retried with backoff for that long before the link is declared
    dead), and bounds how long the orchestrator tolerates a sticky
    send failure before treating it as permanent.  A permanent
    message-plane failure with no repair path then *degrades* the run
    (``degraded_ok``, default on): the anytime-best assignment is
    returned with ``status="degraded"`` and a ``degraded`` record,
    instead of raising — control-plane agent death keeps its existing
    fail/repair semantics.

    Fault injection: ``chaos`` is a :class:`~pydcop_tpu.faults.FaultPlan`
    spec applied by every agent to its outbound message plane with the
    deterministic seed ``chaos_seed`` (``docs/faults.md``); the plan
    and the per-kind injected-event counts are recorded in the result
    under ``"chaos"`` for replay.
    """
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
        require_island_support,
    )
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.graphs import load_graph_module

    t0 = time.perf_counter()
    tracer = get_tracer()
    module = load_algorithm_module(algo)
    if not hasattr(module, "build_computation"):
        raise ValueError(
            f"{algo}: no host build_computation — use the SPMD "
            "orchestrator for batched-only algorithms"
        )
    accel_agents = set(accel_agents or ())
    if accel_agents:
        require_island_support(module, algo)
    if k_target > 0 and not getattr(module, "MIGRATION_SAFE", False):
        # phased round-barrier algorithms (mgm/mgm2/dba/gdba) and
        # single-shot protocols (dpop/syncbb) would deadlock or wedge
        # when a rebuilt computation rejoins at cycle 0: fail at
        # deploy time, not silently mid-run
        raise PlacementError(
            f"{algo}: k_target migration needs a quiescence-"
            "terminating algorithm that re-syncs migrated neighbors "
            "(dsa/adsa/dsatuto, maxsum/amaxsum); round-barrier and "
            "single-shot protocols would wedge at the cycle barrier"
        )
    params = prepare_algo_params(params, module.algo_params)
    chaos_plan = None
    if chaos:
        from pydcop_tpu.faults import FaultPlan, FaultSpecError

        try:  # fail fast on a malformed spec, before any registration
            chaos_plan = FaultPlan.from_spec(chaos, chaos_seed)
        except FaultSpecError as e:
            raise PlacementError(str(e)) from e
        if tracer.enabled:
            tracer.event(
                "chaos-plan", cat="fault", spec=chaos, seed=chaos_seed
            )
    graph = load_graph_module(module.GRAPH_TYPE).build_computation_graph(
        dcop
    )
    comp_names = sorted(n.name for n in graph.nodes)

    ui = None  # created after registration; closed in the finally
    if server is None:
        server = socket.create_server(("", port))
    # a caller may pass a PRE-BOUND listener (solve(mode='process')
    # does: it must know the port before forking the agents, and a
    # probe-then-rebind would race other port users)
    server.settimeout(register_timeout)
    peers: Dict[str, Tuple[socket.socket, Any]] = {}
    addresses: Dict[str, Tuple[str, int]] = {}

    def _ask_all(
        obj: Dict[str, Any],
        names: Optional[List[str]] = None,
        resilient: bool = False,
    ) -> Dict[str, Dict[str, Any]]:
        """Pipelined control round-trip: the request goes to EVERY
        agent before any reply is read, so a poll sweep costs one
        round-trip latency instead of n_agents of them (the round-3
        serial loop was a quadratic-ish drag at ~100 agents).

        ``resilient=True`` (k_target runs): a dead agent does not
        abort the sweep — the surviving replies are returned and the
        dead names land in the shared ``newly_dead`` list for the
        caller's migration handler; reply ``error`` fields are also
        returned (not raised) so send-errors toward a just-dead peer
        can be tolerated instead of failing the run."""
        names = list(peers) if names is None else names
        sent: List[str] = []
        for name in names:
            try:
                _send(peers[name][0], obj)
                sent.append(name)
            except OSError as e:
                if not resilient:
                    raise AgentFailureError(
                        f"agent {name} died mid-solve "
                        f"({type(e).__name__})"
                    ) from e
                newly_dead.append(name)
        replies: Dict[str, Dict[str, Any]] = {}
        for name in sent:
            try:
                reply = _recv(peers[name][1])
            except (OSError, ValueError) as e:
                if not resilient:
                    raise AgentFailureError(
                        f"agent {name} died mid-solve "
                        f"({type(e).__name__})"
                    ) from e
                newly_dead.append(name)
                continue
            if reply is None:
                if not resilient:
                    raise AgentFailureError(
                        f"agent {name} died mid-solve"
                    )
                newly_dead.append(name)
                continue
            # a reply-borne "error" field is NOT raised here: the run
            # loop owns that decision (computation errors are fatal,
            # send errors get the grace window / degraded path — for
            # both the resilient and the static mode)
            replies[name] = reply
        return replies

    # agents found dead during a resilient sweep, consumed by the run
    # loop's migration handler (duplicates possible across sweeps —
    # consumers de-dup against `peers`)
    newly_dead: List[str] = []

    try:
        t_reg = time.perf_counter()
        while len(peers) < nb_agents:
            try:
                conn, peer_addr = server.accept()
            except socket.timeout:
                raise AgentFailureError(
                    f"only {len(peers)}/{nb_agents} agents registered "
                    f"within {register_timeout:.0f}s"
                ) from None
            conn.settimeout(register_timeout)
            reader = conn.makefile("rb")
            try:
                reg = _recv(reader)
            except (OSError, ValueError):
                conn.close()
                continue
            if not reg or reg.get("type") != "register":
                conn.close()
                continue
            name = reg["agent"]
            if name in peers:  # fail the duplicate fast + accurately
                try:
                    _send(
                        conn,
                        {
                            "type": "error",
                            "reason": f"agent name {name!r} is already "
                            "registered",
                        },
                    )
                except OSError:
                    pass
                conn.close()
                continue
            conn.settimeout(poll_timeout)
            peers[name] = (conn, reader)
            # the message-plane port the agent listens on, reached at
            # the IP its control connection came from
            addresses[name] = (peer_addr[0], int(reg["msg_port"]))

        tracer.add_span(
            "register", "phase", t_reg,
            time.perf_counter() - t_reg, agents=len(peers),
        )
        agent_names = sorted(peers)

        # a chaos clause naming a nonexistent agent would silently
        # inject NOTHING while the result still records the plan as
        # applied — a resilience test that "passes" with zero faults;
        # reject misspellings against the registered roster instead
        if chaos_plan is not None:
            unknown_chaos = chaos_plan.referenced_agents() - set(
                agent_names
            )
            if unknown_chaos:
                raise PlacementError(
                    f"chaos spec names unregistered agent(s) "
                    f"{sorted(unknown_chaos)} (registered: "
                    f"{agent_names}) — those faults would never fire"
                )

        # placement: explicit map > distribution strategy > round-robin
        from pydcop_tpu.distribution import Distribution

        if placement is not None:
            unknown = set(placement) - set(agent_names)
            if unknown:
                raise PlacementError(
                    f"placement names unregistered agent(s) "
                    f"{sorted(unknown)} (registered: {agent_names})"
                )
        elif distribution is not None:
            from pydcop_tpu.dcop.objects import AgentDef
            from pydcop_tpu.distribution import compute_distribution

            agent_defs = [
                dcop.agents[a] if a in dcop.agents else AgentDef(a)
                for a in agent_names
            ]
            try:
                dist = compute_distribution(
                    distribution, graph, agent_defs,
                    hints=dcop.dist_hints, algo_module=module,
                )
            except ValueError as e:  # unknown/impossible strategy —
                # a usage/problem error, not an internal failure
                raise PlacementError(str(e)) from e
            placement = {
                a: dist.computations_hosted(a) for a in agent_names
            }
        else:
            placement = {a: [] for a in agent_names}
            for i, cname in enumerate(comp_names):
                placement[agent_names[i % len(agent_names)]].append(cname)

        # uniform validation whatever produced the placement:
        # Distribution() rejects a computation hosted twice; coverage
        # and name checks catch incomplete/bogus strategies and files
        try:  # Distribution() rejects a computation hosted twice
            placed = set(Distribution(placement).computations)
        except ValueError as e:
            raise PlacementError(str(e)) from e
        missing = set(comp_names) - placed
        if missing:
            raise PlacementError(
                f"placement leaves computation(s) {sorted(missing)} "
                "unhosted"
            )
        bogus = placed - set(comp_names)
        if bogus:
            raise PlacementError(
                f"placement names unknown computation(s) "
                f"{sorted(bogus)} (this problem/graph has: "
                f"{comp_names[:10]}...)"
            )
        placement = {a: list(placement.get(a, [])) for a in agent_names}

        unknown_accel = accel_agents - set(agent_names)
        if unknown_accel:
            raise PlacementError(
                f"accel_agents names unregistered agent(s) "
                f"{sorted(unknown_accel)} (registered: {agent_names})"
            )

        # k-resilience: pick replica-holder agents per computation
        # BEFORE the run (reference: replication happens at deploy
        # time, so a failure never has to plan from scratch)
        replica_map = None
        if k_target > 0:
            from pydcop_tpu.dcop.objects import AgentDef
            from pydcop_tpu.distribution import Distribution as _Dist
            from pydcop_tpu.replication.ucs_hostingcosts import (
                replica_distribution,
            )

            agent_defs = {
                a: dcop.agents[a] if a in dcop.agents else AgentDef(a)
                for a in agent_names
            }
            replica_map = replica_distribution(
                _Dist(placement), agent_defs.values(), k_target
            )

        t_dep = time.perf_counter()
        yaml_text = dcop_yaml(dcop)
        directory = {a: list(addresses[a]) for a in agent_names}
        for name, (conn, _) in peers.items():
            _send(
                conn,
                {
                    "type": "deploy",
                    "dcop_yaml": yaml_text,
                    "algo": algo,
                    "params": params,
                    "computations": placement[name],
                    "placement": placement,
                    "directory": directory,
                    "seed": seed,
                    "accel": name in accel_agents,
                    # robustness knobs: the message plane's transient-
                    # fault grace window, and the (optional) fault-
                    # injection plan every agent applies outbound
                    "grace": grace_period,
                    "chaos": chaos,
                    "chaos_seed": chaos_seed,
                },
            )
        for name in peers:
            conn, reader = peers[name]
            # deploy = yaml parse + graph build + computation
            # construction on the agent — a large DCOP legitimately
            # takes longer than a status poll, so the ack read gets
            # the registration budget, not poll_timeout
            conn.settimeout(register_timeout)
            try:
                ack = _recv(reader)
            except (OSError, ValueError) as e:
                raise AgentFailureError(
                    f"agent {name} died during deploy "
                    f"({type(e).__name__})"
                ) from e
            finally:
                conn.settimeout(poll_timeout)
            if not ack or ack.get("type") != "deployed":
                raise AgentFailureError(f"agent {name} failed to deploy")
        tracer.add_span(
            "deploy", "phase", t_dep, time.perf_counter() - t_dep,
            agents=len(peers),
        )

        for name in peers:
            try:
                _send(peers[name][0], {"type": "start"})
            except OSError as e:
                raise AgentFailureError(
                    f"agent {name} died at start"
                ) from e

        resilient = k_target > 0

        # per-agent CUMULATIVE injected-fault counts (collect replies
        # carry the running totals; keeping the latest per agent makes
        # repeated sampling idempotent)
        chaos_by_agent: Dict[str, Dict[str, int]] = {}

        def _collect() -> Tuple[Dict[str, Any], int, int]:
            assignment: Dict[str, Any] = {}
            delivered = size = 0
            for aname, res in _ask_all(
                {"type": "collect"}, resilient=resilient
            ).items():
                assignment.update(res["values"])
                delivered += res["delivered"]
                size += res["size"]
                if res.get("chaos"):
                    chaos_by_agent[aname] = res["chaos"]
            return assignment, delivered, size

        # anytime-best tracking (same semantics as the other engines:
        # ``cost``/``assignment`` = best sampled state, ``final_*`` =
        # last state).  A sample torn across agents is still a valid
        # assignment — just a mix of two instants (runtime.py snapshot
        # makes the same argument).
        sign = -1.0 if dcop.objective == "max" else 1.0
        best = {"cost": float("inf"), "assignment": {}}
        # most recent COMPLETE sample (not necessarily the best):
        # migration restores a dead agent's variables from here
        last_ok = {"assignment": {}}
        trace: List[float] = []
        trace_msgs: List[int] = []  # delivered count at each sample

        if ui_port is not None:
            from pydcop_tpu.infrastructure.ui import UiServer

            ui = UiServer(ui_port)

        def _complete(assignment: Dict[str, Any]) -> bool:
            """Every variable covered, every value selected — the one
            predicate both the sampler and the final collect use."""
            return set(assignment) == set(dcop.variables) and not any(
                v is None for v in assignment.values()
            )

        def _sample_best(delivered: int = 0) -> None:
            assignment, _, _ = _collect()
            if not _complete(assignment):
                return  # some variable has no selected value yet
            cost = dcop.solution_cost(assignment)
            last_ok["assignment"] = assignment
            trace.append(cost)  # anytime stream (--collect_on CSVs)
            trace_msgs.append(delivered)
            if sign * cost < best["cost"]:
                best["cost"] = sign * cost
                best["assignment"] = assignment
            if ui is not None:
                ui.publish(
                    delivered, cost, sign * best["cost"],
                    values=assignment,
                )

        # -- k-resilience: replica-based migration on agent death -----
        migrations: List[Dict[str, Any]] = []
        ledger_void = False  # post-migration: sent/delivered ledger
        # can never reconcile (frames to the dead peer are orphaned)
        suspects: Dict[Tuple[str, str], float] = {}
        dead_ever: set = set()  # every agent that has died this run:
        # ONLY send-errors toward these are tolerable — an error whose
        # "peer" is not a known-dead agent (e.g. an unroutable
        # computation name) is a real fault and must fail the run

        def _handle_failures() -> None:
            nonlocal ledger_void
            dead = sorted({d for d in newly_dead if d in peers})
            newly_dead.clear()
            if not dead:
                return
            t_rep = time.perf_counter()
            dead_ever.update(dead)
            from pydcop_tpu.dcop.objects import AgentDef
            from pydcop_tpu.replication.repair import repair_placement

            orphans: List[str] = []
            for d in dead:
                try:
                    peers[d][0].close()
                except OSError:
                    pass
                peers.pop(d)
                addresses.pop(d, None)
                orphans.extend(placement.pop(d, []))
                accel_agents.discard(d)
            if not peers:
                raise AgentFailureError(
                    f"all agents died (last: {dead})"
                )
            candidates = {
                c: [a for a in replica_map.replicas(c) if a in peers]
                for c in orphans
            }
            lost = sorted(c for c, cand in candidates.items() if not cand)
            if lost:
                raise AgentFailureError(
                    f"agent(s) {dead} died and computation(s) {lost} "
                    f"have no live replica holder (k_target={k_target})"
                )
            live_defs = [
                dcop.agents[a] if a in dcop.agents else AgentDef(a)
                for a in peers
            ]
            chosen = repair_placement(candidates, live_defs, seed=seed)
            for c, a in sorted(chosen.items()):
                placement[a].append(c)
            init_vals = {
                c: last_ok["assignment"][c]
                for c in chosen
                if c in dcop.variables and c in last_ok["assignment"]
            }
            msg = {
                "type": "reconfigure",
                "dead": dead,
                "migrated": chosen,
                "placement": placement,
                "directory": {a: list(addresses[a]) for a in peers},
                "initial_values": init_vals,
            }
            # phase 1: hosts GAINING computations deploy them first, so
            # the phase-2 re-announcements from neighbors can never
            # reach a not-yet-existing computation
            new_hosts = sorted(set(chosen.values()))
            _ask_all(msg, names=new_hosts, resilient=True)
            others = [a for a in peers if a not in set(new_hosts)]
            if others:
                _ask_all(msg, names=others, resilient=True)
            # a second failure DURING migration lands in newly_dead
            # and the next sweep handles it against the updated state
            migrations.append({"dead": dead, "moved": dict(chosen)})
            tracer.add_span(
                "repair", "repair", t_rep,
                time.perf_counter() - t_rep,
                dead=",".join(dead), moved=len(chosen),
            )
            suspects.clear()
            ledger_void = True

        # run loop: poll status until quiescent / budget / timeout
        t_run = time.perf_counter()
        max_msgs = rounds * max(len(comp_names), 1)
        status = "finished"
        degraded_info: Optional[Dict[str, Any]] = None
        stable = 0
        last_total = -1
        last_sample = 0.0
        while True:
            time.sleep(0.05)
            total = 0
            total_sent = 0
            all_idle = True
            replies = _ask_all({"type": "status?"}, resilient=resilient)
            now = time.perf_counter()
            if resilient and newly_dead:
                _handle_failures()
                stable, last_total = 0, -1
                continue
            for name, st in replies.items():
                if st.get("error"):
                    kind = st.get("error_kind")
                    peer_name = st.get("error_peer")
                    if kind != "send":
                        # a computation handler raised (or a legacy
                        # agent with no kind field): always fatal
                        raise AgentFailureError(
                            f"agent {name} failed: {st['error']}"
                        )
                    if not (resilient and peer_name in dead_ever):
                        # a send-error whose peer is NOT a known-dead
                        # agent (a live peer, or an unroutable
                        # computation name).  The agent's message
                        # plane already spent its retry window before
                        # surfacing this, so after the orchestrator's
                        # own grace (time for the control plane to
                        # notice a death / a heal to drain) it is
                        # PERMANENT: degrade to the anytime-best when
                        # allowed, else fail the run.
                        first = suspects.setdefault(
                            (name, peer_name), now
                        )
                        if now - first > grace_period:
                            if degraded_ok and best["assignment"]:
                                degraded_info = {
                                    "agent": name,
                                    "peer": peer_name,
                                    "error": st["error"],
                                }
                            else:
                                raise AgentFailureError(
                                    f"agent {name} send failure toward "
                                    f"{peer_name!r} outlived the "
                                    f"{grace_period:.1f}s grace "
                                    f"window: {st['error']}"
                                )
                        all_idle = False
                    # tolerated (dead peer / in-grace): the agent's
                    # totals still count — an agent with a sticky
                    # tolerated error must stay VISIBLE to quiescence
                total += st["delivered"]
                # missing field (older agent) degrades to the old
                # idle+stability rule instead of never quiescing
                total_sent += st.get("sent", st["delivered"])
                all_idle = all_idle and st["idle"]
            if now - last_sample >= best_sample_period:
                _sample_best(total)
                last_sample = now
            if degraded_info is not None:
                status = "degraded"
                break
            if timeout is not None and now - t0 > timeout:
                status = "timeout"
                break
            if total >= max_msgs:
                status = "msg_budget"
                break
            # two-counter quiescence: every agent idle, every SENT
            # frame also DELIVERED (nothing in flight on any TCP
            # link), and the totals stable across 3 polls — idle +
            # stability alone can declare quiescence mid-propagation
            # on a slow link (advisor r3, medium).  After a migration
            # the ledger is void (see _handle_failures), so fall back
            # to idle + stability over a DOUBLED window.
            if ledger_void:
                quiesced = all_idle and total == last_total
                need = 6
            else:
                quiesced = (
                    all_idle
                    and total_sent == total
                    and total == last_total
                )
                need = 3
            if quiesced:
                stable += 1
                if stable >= need:
                    break
            else:
                stable = 0
            last_total = total
        tracer.add_span(
            "deliver-loop", "phase", t_run,
            time.perf_counter() - t_run, status=status,
        )

        if degraded_info is not None:
            # graceful degradation: a permanent message-plane failure
            # with no repair path.  The control plane is still healthy
            # (a dead control connection raises AgentFailureError
            # elsewhere) — collect once for the traffic counters, but
            # the ASSIGNMENT is the anytime-best: post-partition agent
            # values are a torn mix trusted less than the best
            # complete sample.
            try:
                _, delivered, size = _collect()
            except AgentFailureError:
                delivered = trace_msgs[-1] if trace_msgs else 0
                size = 0
            final_assignment = dict(best["assignment"])
            final_cost = sign * best["cost"]
        else:
            final_assignment, delivered, size = _collect()
        # same guard as _sample_best: under a very short timeout or
        # budget an agent may report values before its computations
        # started (None) — solution_cost would crash inside constraint
        # evaluation; fall back to the best sampled assignment, or
        # fail cleanly when no complete snapshot ever existed
        if degraded_info is not None:
            pass  # assignment/cost already pinned to the anytime-best
        elif _complete(final_assignment):
            final_cost = dcop.solution_cost(final_assignment)
            trace.append(final_cost)  # the end state belongs in the
            # anytime stream too (a short run may never have hit a
            # complete periodic sample)
            trace_msgs.append(delivered)
            if sign * final_cost < best["cost"]:
                best["cost"] = sign * final_cost
                best["assignment"] = final_assignment
        elif best["assignment"]:
            final_assignment = best["assignment"]
            final_cost = sign * best["cost"]
        else:
            raise AgentFailureError(
                "run ended before any complete assignment was "
                "collected (timeout/message budget too short for the "
                "computations to start)"
            )
        if ui is not None:  # final event: the BEST pair (cost and
            # values belong together, matching the SPMD orchestrator)
            ui.publish(
                delivered, sign * best["cost"], sign * best["cost"],
                values=best["assignment"], status=status,
            )
        chaos_totals: Dict[str, int] = {}
        for counts in chaos_by_agent.values():
            for kind, n in counts.items():
                chaos_totals[kind] = chaos_totals.get(kind, 0) + n
        return {
            "assignment": best["assignment"],
            "cost": sign * best["cost"],
            "final_assignment": final_assignment,
            "final_cost": final_cost,
            "cycle": delivered,
            "msg_count": delivered,
            "msg_size": size,
            "status": status,
            "time": time.perf_counter() - t0,
            "cost_trace": trace,
            "trace_subsampled": True,  # one entry per 0.5s sample
            "trace_msgs": trace_msgs,  # exact delivered count per sample
            "agents": agent_names,
            "placement": {a: sorted(c) for a, c in placement.items()},
            # replica migrations performed (k_target resilience):
            # [{dead: [...], moved: {comp: new_agent}}, ...]
            "migrations": migrations,
            # fault-injection replay record: the plan (spec + seed
            # rebuild it exactly) and the per-kind injected counts
            **(
                {
                    "chaos": {
                        **chaos_plan.to_meta(),
                        "events": chaos_totals,
                    }
                }
                if chaos_plan is not None
                else {}
            ),
            # permanent message-plane failure the run degraded over
            **(
                {"degraded": degraded_info}
                if degraded_info is not None
                else {}
            ),
        }
    finally:
        if ui is not None:
            ui.close()
        for conn, _ in peers.values():
            try:
                _send(conn, {"type": "stop"})
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        server.close()


def run_host_agent(
    name: str,
    orchestrator: str,
    retry_for: float = 30.0,
    msg_log: Optional[str] = None,
    chaos: Optional[str] = None,
    chaos_seed: int = 0,
) -> Dict[str, Any]:
    """One host agent process: register, deploy, run until ``stop``.

    ``msg_log`` dumps every delivered message's full content to a
    JSONL file (the reference's per-message log option).  Returns a
    summary dict (delivered count, values) for logging.

    ``chaos``/``chaos_seed`` apply a local fault-injection plan to
    this agent's outbound message plane (``docs/faults.md``); when
    None, the plan the orchestrator shipped in the deploy message (if
    any) is used — a local spec overrides it, so one agent of a fleet
    can be singled out for faults."""
    from pydcop_tpu.algorithms import (
        AlgorithmDef,
        ComputationDef,
        load_algorithm_module,
    )
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.graphs import load_graph_module
    from pydcop_tpu.infrastructure.agents import Agent
    from pydcop_tpu.infrastructure.computations import (
        VariableComputation,
    )
    from pydcop_tpu.infrastructure.discovery import Discovery

    from pydcop_tpu.utils.backoff import call_with_backoff

    ohost, _, oport = orchestrator.partition(":")
    # control-plane connect: the same shared backoff-with-jitter
    # helper every retry loop uses (the old fixed 0.3s sleep hammered
    # a not-yet-listening orchestrator in lockstep across a fleet)
    conn = call_with_backoff(
        lambda: socket.create_connection((ohost, int(oport)), timeout=5),
        retry_for,
        base=0.1,
        max_delay=2.0,
        # keyed deterministic jitter (utils/backoff.py): per-agent
        # keys keep a fleet's connect storms decorrelated, while a
        # chaos replay (same chaos_seed) reproduces each agent's
        # connect timing exactly
        seed=chaos_seed,
        key=f"agent:{name}:connect",
    )
    conn.settimeout(None)
    reader = conn.makefile("rb")

    # handler/transport errors surface through the next status reply
    # (a dead pump or dead peer link must never masquerade as
    # quiescence) — shared by the agent pump and the async senders.
    # Entries are (kind, peer, text): the orchestrator's resilience
    # mode tolerates kind='send' toward a dead peer (and the
    # reconfigure that migrates its computations purges them), while
    # kind='comp' (a handler raised) always fails the run.
    errors: List[Tuple[str, str, str]] = []
    dead_peers: set = set()  # agents known dead (reconfigure msgs)
    comm = TcpCommunicationLayer(
        on_send_error=lambda dest, e: errors.append(
            ("send", str(dest), f"send to {dest}: {e!r}")
        )
    )
    _send(
        conn,
        {
            "type": "register",
            "agent": name,
            "msg_port": comm.address[1],
        },
    )
    dep = _recv(reader)
    if dep and dep.get("type") == "error":
        comm.close()
        raise AgentFailureError(
            f"agent {name}: rejected by orchestrator: {dep['reason']}"
        )
    if not dep or dep.get("type") != "deploy":
        comm.close()
        raise AgentFailureError(
            f"agent {name}: expected deploy, got {dep!r}"
        )

    tracer = get_tracer()
    t_dep = time.perf_counter()
    dcop = load_dcop(dep["dcop_yaml"])
    module = load_algorithm_module(dep["algo"])
    graph = load_graph_module(module.GRAPH_TYPE).build_computation_graph(
        dcop
    )
    algo_def = AlgorithmDef(dep["algo"], dep["params"], dcop.objective)
    mine = set(dep["computations"])
    by_name = {n.name: n for n in graph.nodes}
    comm.set_addresses(
        {a: tuple(addr) for a, addr in dep["directory"].items()}
    )
    # transient-fault grace window: the orchestrator's single knob —
    # the message plane retries failed sends with backoff for this
    # long before a link is declared dead (permanent)
    comm.retry_window = float(dep.get("grace", comm.retry_window))
    # fault injection: a local --chaos spec overrides the plan the
    # orchestrator shipped (so one agent of a fleet can be singled
    # out); the wrapper applies it to every outbound message
    chaos_spec = chaos if chaos is not None else dep.get("chaos")
    plane = comm
    chaos_layer = None
    if chaos_spec:
        import os as _os

        from pydcop_tpu.faults import ChaosCommunicationLayer, FaultPlan

        try:
            plan = FaultPlan.from_spec(
                chaos_spec,
                chaos_seed
                if chaos is not None
                else int(dep.get("chaos_seed", 0)),
            )
        except Exception:
            comm.close()  # a malformed LOCAL spec (the orchestrator
            # validates its own before deploying)
            raise
        if tracer.enabled:
            tracer.event(
                "chaos-plan", cat="fault",
                spec=plan.spec, seed=plan.seed, agent=name,
            )
        # the plan's seed also keys the message plane's retry-backoff
        # jitter, so the whole retry schedule replays with the faults
        comm.backoff_seed = plan.seed
        chaos_layer = ChaosCommunicationLayer(
            comm,
            plan,
            name,
            grace=comm.retry_window,
            on_send_error=lambda dest, e: errors.append(
                ("send", str(dest), f"send to {dest}: {e!r}")
            ),
            # a scheduled crash is the scripted SIGKILL: no cleanup,
            # no goodbye on the control plane — exactly what the
            # repair machinery must survive
            on_crash=lambda: _os._exit(23),
        )
        plane = chaos_layer
    # computation → agent routing for the messaging layer
    directory = Discovery()
    for aname, comps in dep["placement"].items():
        directory.register_agent(aname)
        for cname in comps:
            directory.register_computation(cname, aname)

    log = None
    if msg_log is not None:
        from pydcop_tpu.infrastructure.communication import MessageLog

        log = MessageLog(msg_log)
    agent = Agent(
        name, plane,
        on_error=lambda comp, e: errors.append(
            ("comp", str(comp), f"{comp}: {e!r}")
        ),
        discovery=directory,
        msg_log=log,
        # a send to a dead/unknown peer is a tolerated send-error (the
        # peer's computations are being migrated), never a computation
        # error that would fail the run
        on_unreachable=lambda dest, e: errors.append(
            ("send", str(dest), f"send to {dest}: {e!r}")
        ),
    )
    if dep.get("accel") and hasattr(module, "build_island"):
        # compiled island: this agent's whole sub-graph runs on the
        # array engine (TPU when present) behind per-node proxies —
        # the heterogeneous "one strong host" deployment
        computations = module.build_island(
            [
                ComputationDef(by_name[cname], algo_def)
                for cname in sorted(mine)
            ],
            dcop,
            seed=dep["seed"],
            # Messaging.queued excludes the in-flight message, so the
            # probe is exact both inside a proxy handler and from
            # on_start (where nothing is in flight)
            pending_fn=lambda: agent.messaging.queued,
        )
    else:
        computations = [
            module.build_computation(
                ComputationDef(by_name[cname], algo_def),
                seed=dep["seed"],
            )
            for cname in sorted(mine)
        ]
    for comp in computations:
        agent.deploy_computation(comp)
    tracer.add_span(
        "deploy", "phase", t_dep, time.perf_counter() - t_dep,
        agent=name, computations=len(computations),
    )
    _send(conn, {"type": "deployed", "n": len(computations)})

    delivered = 0
    try:
        while True:
            msg = _recv(reader)
            if msg is None:
                break  # orchestrator died: stop quietly
            mtype = msg.get("type")
            if mtype == "start":
                # the pump starts WITH the computations: inbound
                # frames that arrived early sit queued in Messaging
                # (and any popped before a computation's own start are
                # buffered by the computation itself)
                agent.start()
                agent.start_computations()
            elif mtype == "status?":
                # filter at READ time over a snapshot — never rewrite
                # the shared list (writer/pump threads append to it
                # concurrently, and a rewrite racing an append could
                # silently drop a fatal entry).  Send-errors toward a
                # migrated dead peer are expected noise; a computation
                # error (handler raised) is ALWAYS fatal and must
                # never be shadowed by a tolerable send entry that
                # happens to sit at index 0.
                snap = list(errors)
                err = next(
                    (e for e in snap if e[0] == "comp"),
                    next(
                        (
                            e
                            for e in snap
                            if not (
                                e[0] == "send" and e[1] in dead_peers
                            )
                        ),
                        None,
                    ),
                )
                _send(
                    conn,
                    {
                        "type": "status",
                        # held chaos frames count as sent-not-delivered
                        # (plane is the chaos wrapper when one is on),
                        # so injected delays/holds block quiescence
                        # exactly like real in-flight TCP frames
                        "idle": agent.is_idle,
                        "delivered": agent.messaging.count_msg,
                        "sent": plane.count_sent,
                        "error": err[2] if err else None,
                        "error_kind": err[0] if err else None,
                        "error_peer": err[1] if err else None,
                    },
                )
            elif mtype == "reconfigure":
                # replica migration (orchestrator k_target): deploy the
                # computations chosen for THIS agent, re-route the
                # migrated names, drop the dead peers, purge stale
                # send-errors toward them, and nudge every local
                # neighbor of a migrated computation to re-announce
                migrated: Dict[str, str] = msg["migrated"]
                init_vals = msg.get("initial_values", {})
                my_new = sorted(
                    c for c, a in migrated.items() if a == name
                )
                new_comps = []
                for cname in my_new:
                    comp = module.build_computation(
                        ComputationDef(by_name[cname], algo_def),
                        seed=dep["seed"],
                    )
                    if (
                        isinstance(comp, VariableComputation)
                        and cname in init_vals
                    ):
                        comp.restart_value = init_vals[cname]
                    new_comps.append(comp)
                # route the migrated names BEFORE unregistering the
                # dead agents, so a concurrent pump send never hits
                # an unregistration window
                for cname, aname in migrated.items():
                    directory.register_computation(cname, aname)
                for d in msg["dead"]:
                    directory.unregister_agent(d)
                    comm.forget_agent(d)
                dead_peers.update(msg["dead"])
                mine.update(my_new)
                comm.set_addresses(
                    {a: tuple(x) for a, x in msg["directory"].items()}
                )
                # (stale send-errors toward dead_peers are purged at
                # every status report — the only place they are read)
                for comp in new_comps:
                    agent.deploy_computation(comp)
                    computations.append(comp)
                    comp.start()
                # re-announce: each LOCAL computation neighboring a
                # migrated one re-sends its view, through the pump so
                # the hook runs on the computation thread
                for comp in computations:
                    nbrs = getattr(comp, "neighbors", ())
                    for m in migrated:
                        if m != comp.name and m in nbrs:
                            agent.messaging.deliver(
                                "_system",
                                comp.name,
                                Message("_peer_restarted", m),
                            )
                _send(conn, {"type": "reconfigured", "n": len(my_new)})
            elif mtype == "collect":
                values = {
                    c.variable.name: c.current_value
                    for c in computations
                    if isinstance(c, VariableComputation)
                }
                delivered = agent.messaging.count_msg
                _send(
                    conn,
                    {
                        "type": "result",
                        "values": values,
                        "delivered": delivered,
                        "size": agent.messaging.size_msg,
                        **(
                            {"chaos": chaos_layer.event_summary()}
                            if chaos_layer is not None
                            else {}
                        ),
                    },
                )
            elif mtype == "stop":
                break
    finally:
        agent.stop()
        plane.close()  # the chaos wrapper (when on) closes the inner
        # transport after stopping its timer wheel
        if log is not None:
            log.close()
        try:
            conn.close()
        except OSError:
            pass
    return {"agent": name, "delivered": delivered}


