"""Embedding API (reference: ``pydcop/infrastructure/run.py:solve``).

``solve()`` is the one-call in-process entry point: build / compile the
problem, run the selected algorithm on the TPU batched engine (or its
host path for DPOP/SyncBB-style algorithms), and return the result dict
with the same keys the reference's CLI/JSON surface exposes:
``{assignment, cost, cycle, msg_count, msg_size, status, time}``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional, Sequence, Union

from pydcop_tpu.algorithms import (
    AlgorithmDef,
    load_algorithm_module,
    prepare_algo_params,
    resolve_algo,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

# NOTE: ops.compile (and with it jax) is imported lazily inside the
# functions that compile problems — importing pydcop_tpu.api must stay
# light so CLI/bench cold starts don't pay the jax import before they
# know they need a device (tests/test_import_time.py pins this).

# The solver-service surface (docs/serving.md) re-exports lazily for
# the same reason: ``api.ServiceClient`` is a pure-socket client a
# jax-free process can use against a remote `pydcop_tpu serve`.
_SERVICE_EXPORTS = ("ServiceClient", "ServiceError", "SolverService")


def __getattr__(name):
    if name in _SERVICE_EXPORTS:
        from pydcop_tpu.engine import service as _service

        return getattr(_service, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


def solve(
    dcop: Union[DCOP, str],
    algo: Union[str, AlgorithmDef],
    algo_params: Optional[Mapping[str, Any]] = None,
    rounds: int = 200,
    timeout: Optional[float] = None,
    seed: int = 0,
    convergence_chunks: int = 0,
    chunk_size: int = 64,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1,
    resume: bool = False,
    mode: str = "batched",
    ui_port: Optional[int] = None,
    n_restarts: int = 1,
    nb_agents: Optional[int] = None,
    msg_log: Optional[str] = None,
    accel_agents: Optional[Sequence[str]] = None,
    distribution: Optional[Any] = None,
    k_target: int = 0,
    chaos: Optional[str] = None,
    chaos_seed: int = 0,
    trace: Optional[str] = None,
    trace_format: str = "jsonl",
    pad_policy: str = "none",
    compile_cache: Optional[str] = None,
    retry_budget: Optional[int] = None,
    chunk_floor: Optional[int] = None,
    on_numeric_fault: Optional[str] = None,
    max_util_bytes: Optional[int] = None,
    bnb: Optional[str] = None,
    table_dtype: Optional[str] = None,
    table_format: Optional[str] = None,
) -> Dict[str, Any]:
    """Solve a DCOP and return the result dict.

    Every call runs inside a telemetry session
    (``pydcop_tpu.telemetry``, ``docs/observability.md``): per-phase
    span totals, jit compile stats, and message-plane counters land in
    ``result["telemetry"]`` uniformly across engines.  ``trace`` also
    writes the full span/event timeline to that file —
    ``trace_format`` picks ``"jsonl"`` (one record per line) or
    ``"chrome"`` (open in chrome://tracing / Perfetto) — including
    per-message and injected-fault events.

    Parameters mirror the reference ``solve()``: the dcop (object or
    yaml path), the algorithm name (or AlgorithmDef carrying params),
    algorithm parameters, and stop conditions (round budget and/or
    wall-clock timeout).

    ``mode`` selects the execution engine: ``"batched"`` (default, the
    TPU engine), ``"thread"`` (reference-style thread-per-agent host
    runtime), ``"sim"`` (deterministic seeded async event loop — the
    parity-test schedule), or ``"process"`` (one OS process per agent
    over the TCP host runtime — the reference's
    ``run_local_process_dcop``; ``nb_agents`` caps the process count).
    In process, thread, and sim modes ``accel_agents`` names agents
    deployed as compiled array-engine islands
    (``algorithms/_island_maxsum.py``).  Process mode draws agent
    names from the dcop's declared AgentDefs (padded with
    ``agent_0, agent_1, …`` when it declares fewer than
    ``nb_agents``); thread/sim modes use the same placement as their
    runs (declared agents round-robin, or ``a_<computation>`` when
    the dcop declares none).

    Stop conditions differ per engine (round budget + optional
    ``convergence_chunks`` for batched; quiescence for thread/sim) —
    ``docs/termination.md`` maps them to the reference's
    stable-message / cycle-limit semantics and defines what ``cycle``
    and ``msg_count`` mean in each.

    ``chaos``/``chaos_seed`` inject deterministic message-plane faults
    (drops, duplicates, reorders, delays, timed partitions, crash
    schedules — ``pydcop_tpu.faults``, spec format in
    ``docs/faults.md``) into the message-driven modes: ``'thread'``
    wraps every agent's in-process sends, ``'process'`` ships the plan
    to each agent OS process.  Same seed ⇒ identical fault sequence;
    the plan is recorded in the result under ``"chaos"``.

    ``distribution`` (reference-parity) shapes the host runtimes'
    placement: a strategy name (``"adhoc"``, ``"heur_comhost"``, …), a
    ``distribute --output`` yaml path, or a ``Distribution`` object.
    thread mode groups computations onto agent threads with it; sim
    mode consults it only for ``accel_agents`` island grouping (the
    event loop has no agent containers); process mode hands it to the
    hostnet orchestrator; the batched engine accepts and ignores it
    (one device program solves regardless of placement).

    ``pad_policy`` (batched engine only) buckets the compiled
    problem's array shapes (``"pow2"``/``"pow2:<floor>"``,
    ``ops/padding.py``) so similarly-sized problems share jitted
    executables; ``compile_cache`` points jax's persistent compilation
    cache at a directory so repeated PROCESSES skip XLA compilation of
    programs they have built before.  Both are covered in
    ``docs/performance.md``.

    ``retry_budget``/``chunk_floor``/``on_numeric_fault`` (batched
    engine only) configure the supervised device-dispatch layer
    (``engine/supervisor.py``, ``docs/faults.md``): transient runtime
    errors retry up to ``retry_budget`` times per dispatch (default
    2), device OOM degrades adaptively — chunk halving down to
    ``chunk_floor`` rounds (default 8), instance-group splits for
    ``solve_many`` — and a NaN-poisoned run either degrades to its
    last-finite anytime best (``on_numeric_fault="quarantine"``, the
    default) or fails the call (``"raise"``).  In batched mode
    ``chaos`` accepts the DEVICE-layer fault kinds (``device_oom``,
    ``device_transient``, ``nan_inject``) injected at that seam,
    under the same seeded-determinism contract as the message-plane
    kinds.

    ``max_util_bytes`` (exact algorithms with a bounded-memory plan —
    DPOP) caps every UTIL/message table at that many device (f32)
    bytes via the memory-bounded contraction planner
    (``ops/membound.py``, ``docs/semirings.md``): domains are
    consistency-pruned, a minimal cut set of separator variables is
    conditioned and its assignments ride the level-pack stack as
    extra vmapped lanes, results stay exact, and a device OOM
    re-plans at half budget before abandoning the device.  The
    result carries a ``membound`` block (cut width/lanes, peak table
    bytes, replans).  Equivalent to
    ``algo_params={"max_util_bytes": N}``.

    >>> result = solve(my_dcop, "dsa", {"variant": "B"}, rounds=100)
    >>> result["assignment"], result["cost"]
    """
    from pydcop_tpu.telemetry import session

    if compile_cache is not None:
        from pydcop_tpu.ops.compile import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache(compile_cache)

    with session(trace, trace_format) as tel:
        result = _solve_dispatch(
            dcop, algo, algo_params, rounds=rounds, timeout=timeout,
            seed=seed, convergence_chunks=convergence_chunks,
            chunk_size=chunk_size, checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, resume=resume,
            mode=mode, ui_port=ui_port, n_restarts=n_restarts,
            nb_agents=nb_agents, msg_log=msg_log,
            accel_agents=accel_agents, distribution=distribution,
            k_target=k_target, chaos=chaos, chaos_seed=chaos_seed,
            pad_policy=pad_policy, retry_budget=retry_budget,
            chunk_floor=chunk_floor, on_numeric_fault=on_numeric_fault,
            max_util_bytes=max_util_bytes, bnb=bnb,
            table_dtype=table_dtype, table_format=table_format,
        )
        result["telemetry"] = tel.summary()
    return result


def _solve_dispatch(
    dcop,
    algo,
    algo_params,
    *,
    rounds,
    timeout,
    seed,
    convergence_chunks,
    chunk_size,
    checkpoint_path,
    checkpoint_every,
    resume,
    mode,
    ui_port,
    n_restarts,
    nb_agents,
    msg_log,
    accel_agents,
    distribution,
    k_target,
    chaos,
    chaos_seed,
    pad_policy="none",
    retry_budget=None,
    chunk_floor=None,
    on_numeric_fault=None,
    max_util_bytes=None,
    bnb=None,
    table_dtype=None,
    table_format=None,
) -> Dict[str, Any]:
    """Mode dispatch behind :func:`solve` (which owns the telemetry
    session and the ``result["telemetry"]`` attach)."""
    if isinstance(dcop, (str, list, tuple)):
        dcop = load_dcop_from_file(dcop)

    from pydcop_tpu.ops.padding import as_pad_policy

    if as_pad_policy(pad_policy).enabled and mode != "batched":
        raise ValueError(
            "pad_policy shapes the batched engine's compiled arrays; "
            f"mode={mode!r} does not compile the whole problem"
        )

    if mode != "batched" and (
        retry_budget is not None
        or chunk_floor is not None
        or on_numeric_fault is not None
    ):
        raise ValueError(
            "retry_budget/chunk_floor/on_numeric_fault configure the "
            "batched engine's supervised device dispatch "
            f"(engine/supervisor.py); mode={mode!r} has no device "
            "dispatch to supervise"
        )

    if mode != "batched" and max_util_bytes is not None:
        raise ValueError(
            "max_util_bytes bounds the batched engine's exact "
            "contraction sweeps (ops/membound.py); the "
            f"message-driven mode={mode!r} never builds whole UTIL "
            "tables to bound"
        )

    if mode != "batched" and chaos:
        # the mirror of the batched branch's message-kind rejection
        # below: a device-layer clause on a host runtime would no-op
        # silently (the chaos layer only reads message-plane fields)
        # and the caller would believe the recovery path was exercised
        from pydcop_tpu.faults import FaultPlan

        plan_probe = FaultPlan.from_spec(chaos, chaos_seed)
        if plan_probe.device_faults_configured:
            raise ValueError(
                "device-layer chaos kinds (device_oom/"
                "device_transient/nan_inject) inject at the batched "
                "engine's supervised device dispatch "
                f"(engine/supervisor.py); mode={mode!r} has no device "
                "dispatch — use mode='batched' (docs/faults.md)"
            )
        if plan_probe.wire_faults_configured:
            raise ValueError(
                "wire-level chaos kinds (conn_drop/slow_client/"
                "frame_corrupt) inject at the solver service's frame "
                f"loop (engine/service.py); mode={mode!r} has no "
                "serving wire — use `pydcop_tpu serve --chaos` "
                "(docs/serving.md)"
            )
        if plan_probe.fleet_faults_configured:
            raise ValueError(
                "fleet-level chaos kinds (replica_kill) act on a "
                "replicated serving fleet's processes "
                f"(engine/fleet.py); mode={mode!r} has no fleet — "
                "use `pydcop_tpu fleet --chaos` (docs/faults.md)"
            )

    if mode in ("thread", "sim"):
        if checkpoint_path is not None or resume:
            raise ValueError(
                "checkpoint/resume is only supported on the batched "
                f"engine, not mode={mode!r}"
            )
        if ui_port is not None:
            raise ValueError(
                "ui_port (live observability) is only supported on "
                f"the batched engine, not mode={mode!r}"
            )
        if n_restarts != 1:
            raise ValueError(
                "n_restarts (batched parallel restarts) is only "
                f"supported on the batched engine, not mode={mode!r}"
            )
        if nb_agents is not None:
            raise ValueError(
                "nb_agents is the process count of mode='process'; "
                f"mode={mode!r} decides its own parallelism"
            )
        if k_target:
            raise ValueError(
                "k_target (replica-based migration) needs killable "
                "agent OS processes — mode='process' only"
            )
        from pydcop_tpu.infrastructure import solve_host

        # sim consults placement only for island grouping — don't
        # resolve a distribution whose result would be discarded.
        # Strategy NAMES pass through as-is (the runtime computes
        # them over the graph it builds anyway); files/objects
        # resolve here.
        dist_obj = None
        if distribution is not None and (mode == "thread" or accel_agents):
            if _is_strategy_name(distribution):
                _validate_strategy_name(distribution)
                dist_obj = distribution
            else:
                dist_obj = _resolve_distribution(dcop, distribution)
        return solve_host(
            dcop, algo, algo_params, mode=mode, timeout=timeout,
            seed=seed, rounds=rounds, msg_log=msg_log,
            accel_agents=accel_agents, distribution=dist_obj,
            chaos=chaos, chaos_seed=chaos_seed,
        )
    if mode == "process":
        if checkpoint_path is not None or resume or n_restarts != 1:
            raise ValueError(
                "checkpoint/resume and n_restarts are only supported "
                "on the batched engine, not mode='process'"
            )
        return _solve_process(
            dcop, algo, algo_params, rounds=rounds, timeout=timeout,
            seed=seed, nb_agents=nb_agents, ui_port=ui_port,
            msg_log=msg_log, accel_agents=accel_agents,
            distribution=distribution, k_target=k_target,
            chaos=chaos, chaos_seed=chaos_seed,
        )
    if mode != "batched":
        raise ValueError(f"solve: unknown mode {mode!r}")
    plan = None
    if chaos:
        from pydcop_tpu.faults import FaultPlan

        plan = FaultPlan.from_spec(chaos, chaos_seed)
        if plan.message_faults_configured or plan.crashes:
            raise ValueError(
                "chaos message-plane faults and crash schedules "
                "target the message-driven runtimes — use "
                "mode='thread' or 'process' (crash schedules against "
                "the batched dynamic engine go through the `run` "
                "command's --chaos, which scripts them as scenario "
                "events).  The batched engine accepts the "
                "DEVICE-layer kinds only: device_oom, "
                "device_transient, nan_inject (docs/faults.md)"
            )
        if plan.wire_faults_configured:
            raise ValueError(
                "wire-level chaos kinds (conn_drop/slow_client/"
                "frame_corrupt) inject at the solver service's frame "
                "loop — use `pydcop_tpu serve --chaos` "
                "(docs/serving.md); a one-shot solve has no serving "
                "wire"
            )
        if plan.fleet_faults_configured:
            raise ValueError(
                "fleet-level chaos kinds (replica_kill) act on a "
                "replicated serving fleet's processes — use "
                "`pydcop_tpu fleet --chaos` (docs/faults.md); a "
                "one-shot solve has no fleet"
            )
    if k_target:
        raise ValueError(
            "k_target (replica-based migration) is a host-runtime "
            "mode — use mode='process' (the batched engine's "
            "resilience is engine-level: engine/dynamic.py)"
        )
    if accel_agents:
        raise ValueError(
            "accel_agents (compiled islands) deploys through the host "
            "runtimes' agents — use mode='sim', 'thread' or 'process' "
            "(or the orchestrator/agent CLI with --accel_agents); the "
            "batched engine is all-accelerator already"
        )
    if msg_log is not None:
        raise ValueError(
            "msg_log records individual message contents — only the "
            "message-driven modes (thread/sim/process) deliver them; "
            "the batched engine fuses a round into one device step "
            "and the exact host-path solvers (dpop/syncbb) are "
            "vectorized.  Run the algorithm with mode='thread'/'sim'/"
            "'process' to log its messages."
        )
    if nb_agents is not None:
        raise ValueError(
            "nb_agents is the process count of mode='process'; other "
            "modes decide their own parallelism"
        )

    algo_name, params_in = resolve_algo(algo, algo_params)

    module = load_algorithm_module(algo_name)
    if max_util_bytes is not None:
        if not any(
            p.name == "max_util_bytes" for p in module.algo_params
        ):
            raise ValueError(
                "max_util_bytes bounds the exact contraction "
                "engine's largest UTIL/message table — supported by "
                "algorithms with a bounded-memory plan (dpop) and "
                f"by api.infer; {algo_name!r} has no such table to "
                "bound"
            )
        if int(max_util_bytes) <= 0:
            # the algo-param route's 0 means "off" (the dataclass
            # default), but an EXPLICIT budget of <= 0 is a sizing
            # bug — silently running the naive sweep would be the
            # exact OOM the caller tried to prevent
            raise ValueError(
                f"max_util_bytes must be > 0, got {max_util_bytes}"
            )
        params_in = {
            **dict(params_in or {}),
            "max_util_bytes": int(max_util_bytes),
        }
    if bnb is not None:
        # branch-and-bound pruned contraction kernels — an algo
        # param of the algorithms with a device contraction phase
        # (dpop, maxsum); this keyword is the discoverable spelling,
        # like max_util_bytes (docs/semirings.md, "Branch-and-bound
        # pruning")
        if not any(p.name == "bnb" for p in module.algo_params):
            raise ValueError(
                "bnb selects the branch-and-bound pruned "
                "contraction kernels — supported by algorithms "
                "with a device contraction phase (dpop, maxsum); "
                f"{algo_name!r} has none"
            )
        params_in = {**dict(params_in or {}), "bnb": str(bnb)}
    if table_dtype is not None:
        # storage precision of the device-side contraction tables —
        # an algo param of the algorithms with a device contraction
        # phase (dpop); this keyword is the discoverable spelling,
        # like bnb (docs/performance.md, "Mixed-precision table
        # packs").  Parsed early so typos fail with the shared
        # nearest-name suggestion, not a generic param error.
        from pydcop_tpu.ops.padding import as_table_dtype as _as_dt

        if not any(
            p.name == "table_dtype" for p in module.algo_params
        ):
            raise ValueError(
                "table_dtype selects the storage precision of the "
                "device contraction tables — supported by "
                "algorithms with a device contraction phase "
                f"(dpop) and by api.infer; {algo_name!r} has none "
                "(maxsum's message-plane sibling is msg_dtype)"
            )
        params_in = {
            **dict(params_in or {}),
            "table_dtype": _as_dt(table_dtype),
        }
    if table_format is not None:
        # storage layout of the device contraction tables — sparse
        # COO packs + gather joins (docs/performance.md, "Sparse
        # constraint tables"); same early-parse discipline as
        # table_dtype above
        from pydcop_tpu.ops.sparse import as_table_format as _as_fmt

        if not any(
            p.name == "table_format" for p in module.algo_params
        ):
            raise ValueError(
                "table_format selects the storage layout of the "
                "device contraction tables — supported by "
                "algorithms with a device contraction phase "
                f"(dpop) and by api.infer; {algo_name!r} has none"
            )
        params_in = {
            **dict(params_in or {}),
            "table_format": _as_fmt(table_format),
        }
    params = prepare_algo_params(params_in, module.algo_params)

    # every batched-mode call runs under a per-call supervisor
    # (engine/supervisor.py): retries/degradation knobs, the
    # device-layer chaos plan, and per-call dispatch sequence
    # numbering (what makes the injected fault schedule replayable)
    from pydcop_tpu.engine.supervisor import make_supervisor, supervision

    sup = make_supervisor(
        retry_budget=retry_budget, chunk_floor=chunk_floor,
        on_numeric_fault=on_numeric_fault, plan=plan,
    )

    if hasattr(module, "solve_host"):
        # exact / sequential algorithms (DPOP, SyncBB)
        if checkpoint_path is not None or resume:
            raise ValueError(
                f"{algo_name}: checkpoint/resume is only supported on "
                "the batched engine, not host-path (exact) algorithms"
            )
        if n_restarts != 1:
            raise ValueError(
                f"{algo_name} is an exact host-path algorithm — "
                "n_restarts (best-of-K for stochastic solvers) does "
                "not apply"
            )
        if hasattr(module, "solve_host_many"):
            # the level-batching capability marker (same check
            # run_many_host uses): pad_policy buckets DPOP's UTIL
            # level dispatches on the pow-2 lattice (level-pack keys,
            # docs/performance.md "Level-synchronous DPOP") —
            # results bit-identical
            with supervision(sup):
                result = module.solve_host(
                    dcop, params, timeout=timeout,
                    pad_policy=pad_policy,
                )
        else:
            if as_pad_policy(pad_policy).enabled:
                raise ValueError(
                    f"{algo_name} runs on the host path and never "
                    "compiles the whole problem — pad_policy does "
                    "not apply"
                )
            with supervision(sup):
                result = module.solve_host(
                    dcop, params, timeout=timeout
                )
    else:
        from pydcop_tpu.ops.compile import compile_dcop

        problem = compile_dcop(dcop, pad_policy=pad_policy)
        with supervision(sup):
            result = _run_compiled(
                problem, module, params, rounds=rounds, seed=seed,
                timeout=timeout, chunk_size=chunk_size,
                convergence_chunks=convergence_chunks,
                checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every, resume=resume,
                ui_port=ui_port, n_restarts=n_restarts,
            )
    if plan is not None:
        # replay record, same as the message-plane chaos runs
        result["chaos"] = plan.to_meta()
    return result


def _is_strategy_name(distribution) -> bool:
    """A string that is not an existing file is a strategy name."""
    import os

    return isinstance(distribution, str) and not os.path.isfile(
        distribution
    )


def _validate_strategy_name(name: str) -> None:
    """Fail fast on an unloadable strategy (also catches mistyped
    placement-file paths, indistinguishable from names here)."""
    from pydcop_tpu.distribution import load_distribution_module

    try:
        load_distribution_module(name)
    except Exception as e:
        raise ValueError(
            f"distribution {name!r} is neither an existing placement "
            f"file nor a loadable strategy: {e}"
        )


def _resolve_distribution(dcop: DCOP, distribution):
    """Normalize a non-strategy ``solve(distribution=...)``: pass
    through a ``Distribution``, or load a ``distribute --output`` yaml
    path.  Strategy names are resolved by the runtime that owns the
    computation graph (``runtime.solve_host`` / hostnet)."""
    if distribution is None:
        return None
    from pydcop_tpu.distribution import Distribution

    if isinstance(distribution, Distribution):
        return distribution
    import os

    if not os.path.isfile(str(distribution)):
        raise ValueError(
            f"{distribution!r}: not a placement file (expected a yaml "
            "`distribution:` mapping of agent -> computation names, "
            "the `distribute --output` format)"
        )
    import yaml

    with open(distribution) as f:
        spec = yaml.safe_load(f)
    mapping = spec.get("distribution") if isinstance(spec, dict) else None
    if not isinstance(mapping, dict):
        raise ValueError(
            f"{distribution}: not a placement file (expected a "
            "yaml `distribution:` mapping of agent -> computation "
            "names, the `distribute --output` format)"
        )
    return Distribution(mapping)


def _solve_process(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    algo_params: Optional[Mapping[str, Any]],
    *,
    rounds: int,
    timeout: Optional[float],
    seed: int,
    nb_agents: Optional[int],
    ui_port: Optional[int],
    msg_log: Optional[str] = None,
    accel_agents: Optional[Sequence[str]] = None,
    distribution=None,
    k_target: int = 0,
    chaos: Optional[str] = None,
    chaos_seed: int = 0,
) -> Dict[str, Any]:
    """One-call multi-process solve (reference:
    ``pydcop/infrastructure/run.py:run_local_process_dcop``): spawn
    ``nb_agents`` local agent OS processes, run the hostnet
    orchestrator in THIS process, return its result dict.

    Default process count: one per declared AgentDef, capped at the
    machine's CPU count (and at 2 when the problem declares none) —
    the reference forks one process per agent the same way.
    """
    import os
    import socket
    import subprocess
    import sys

    from pydcop_tpu.infrastructure.hostnet import (
        AgentFailureError,
        run_host_orchestrator,
    )

    algo_name, params_in = resolve_algo(algo, algo_params)

    if chaos:
        from pydcop_tpu.faults import FaultPlan

        # fail fast on a malformed spec (FaultSpecError is a
        # ValueError), before forking nb_agents interpreters
        FaultPlan.from_spec(chaos, chaos_seed)

    # hostnet takes either a strategy NAME (computed over registered
    # agents at deploy time) or an explicit placement map; normalize
    # Distribution objects / placement files to the latter
    dist_name = None
    placement = None
    if distribution is not None:
        if _is_strategy_name(distribution):
            # fail fast, before forking nb_agents interpreters
            _validate_strategy_name(distribution)
            dist_name = distribution
        else:
            placement = _resolve_distribution(dcop, distribution).mapping

    if nb_agents is None:
        if placement is not None:
            nb_agents = len(placement)
        else:
            nb_agents = min(len(dcop.agents) or 2, os.cpu_count() or 2)
    if nb_agents < 1:
        raise ValueError(f"nb_agents must be >= 1, got {nb_agents}")

    if placement is not None:
        # explicit placement: the spawned processes must carry exactly
        # its agent names or the orchestrator can never deploy to them
        if nb_agents != len(placement):
            raise ValueError(
                f"nb_agents={nb_agents} conflicts with the "
                f"placement's {len(placement)} agents — omit "
                "nb_agents or make them match"
            )
        names = sorted(placement)
    else:
        # prefer the dcop's own agent names so hosting/capacity data
        # flows into the placement; pad with generated names when it
        # has fewer (skipping declared names the generator collides
        # with)
        names = sorted(dcop.agents)[:nb_agents]
        used = set(names)
        i = 0
        while len(names) < nb_agents:
            candidate = f"agent_{i}"
            i += 1
            if candidate not in used:
                names.append(candidate)
                used.add(candidate)

    unknown = set(accel_agents or ()) - set(names)
    if unknown:
        source = (
            "the placement's agent names"
            if placement is not None
            else "declared AgentDefs first, then generated "
            "agent_<i> padding"
        )
        raise ValueError(
            f"accel_agents {sorted(unknown)} are not among this "
            f"run's agent names {names} ({source})"
        )
    if accel_agents:
        # fail before forking nb_agents interpreters, mirroring the
        # orchestrator-side check (hostnet.run_host_orchestrator)
        from pydcop_tpu.algorithms import (
            load_algorithm_module,
            require_island_support,
        )

        require_island_support(load_algorithm_module(algo_name), algo_name)

    # pre-bound control-plane listener: the port must be known before
    # the agents fork, and a probe-then-rebind would race other port
    # users — run_host_orchestrator accepts the live socket instead
    server = socket.create_server(("", 0))
    port = server.getsockname()[1]

    # the children must find THIS package wherever the embedding
    # process imported it from (the parent may have extended sys.path
    # programmatically — env PYTHONPATH is how that survives the fork)
    import pydcop_tpu

    pkg_root = os.path.dirname(os.path.dirname(pydcop_tpu.__file__))
    path_entries = [pkg_root]
    # a dotted algo name resolves on the parent's sys.path (an external
    # plugin, docs/extending.md) — forward its top package's location
    # too, or every child fails the deploy with an import error
    if "." in algo_name:
        import importlib.util

        spec = importlib.util.find_spec(algo_name.split(".")[0])
        if spec and spec.submodule_search_locations:
            # every location: a PEP-420 namespace package may be split
            # across several sys.path entries
            for loc in spec.submodule_search_locations:
                parent = os.path.dirname(loc)
                if parent not in path_entries:
                    path_entries.append(parent)
        elif spec and spec.origin:
            path_entries.append(os.path.dirname(spec.origin))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        path_entries + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    # propagate the parent's jax platform pin: an embedding process
    # pinned to CPU (jax.config — the only pin the axon TPU plugin
    # cannot override) must not fork agent children that grab (or hang
    # on) an accelerator it explicitly avoided.  Matters for island
    # agents — plain host agents never initialize a backend.
    if "PYDCOP_TPU_PLATFORM" not in env:
        jax_mod = sys.modules.get("jax")
        parent_pin = (
            getattr(jax_mod.config, "jax_platforms", None)
            if jax_mod is not None
            else None
        )
        if parent_pin:
            env["PYDCOP_TPU_PLATFORM"] = parent_pin
    # children's stderr goes to tempfiles: a crashing agent must be
    # diagnosable from the parent's failure, not vanish into DEVNULL
    # and surface only as a registration timeout
    import tempfile

    err_files = []
    procs = []
    try:
        for name in names:
            ef = tempfile.NamedTemporaryFile(
                mode="w+", suffix=f".{name}.err", delete=False
            )
            err_files.append(ef)
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "pydcop_tpu", "agent",
                        "--names", name, "--runtime", "host",
                        "--orchestrator", f"127.0.0.1:{port}",
                    ]
                    + (
                        ["--msg_log", f"{msg_log}.{name}"]
                        if msg_log
                        else []
                    ),
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=ef,
                )
            )
        try:
            return run_host_orchestrator(
                dcop, algo_name, params_in, nb_agents=nb_agents,
                port=port, rounds=rounds, timeout=timeout, seed=seed,
                ui_port=ui_port, server=server,
                accel_agents=list(accel_agents or ()),
                distribution=dist_name, placement=placement,
                k_target=k_target,
                chaos=chaos, chaos_seed=chaos_seed,
                # the caller's timeout must also bound registration: a
                # child crashing at startup must not stall a short-
                # timeout call for the full default register window
                # floor of 30s: each child must exec a fresh
                # interpreter and import jax before it can register —
                # a shorter solve timeout must not turn startup into
                # a hard registration failure
                register_timeout=(
                    min(120.0, max(timeout, 30.0))
                    if timeout is not None
                    else 120.0
                ),
            )
        except AgentFailureError as e:
            tails = []
            for name, ef in zip(names, err_files):
                try:
                    with open(ef.name, "rb") as f:
                        f.seek(0, os.SEEK_END)
                        f.seek(max(0, f.tell() - 800))
                        tail = f.read().decode("utf-8", "replace").strip()
                except OSError:
                    tail = ""
                if tail:
                    tails.append(f"--- {name} stderr ---\n{tail}")
            if tails:
                raise AgentFailureError(
                    f"{e}\n" + "\n".join(tails)
                ) from e
            raise
    finally:
        # close() is idempotent: on the success path the orchestrator
        # already closed it, on every failure path (spawn error, an
        # exception before the orchestrator's own try) this is the
        # only close — and it EOFs lingering children so the reap
        # below is quick
        try:
            server.close()
        except OSError:
            pass
        for p in procs:  # orchestrator's stop already reached them;
            # this only reaps stragglers
            if p.poll() is None:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        for ef in err_files:
            try:
                ef.close()
                os.unlink(ef.name)
            except OSError:
                pass


def solve_many(
    dcops: Sequence[Union[DCOP, str]],
    algo: Union[str, AlgorithmDef],
    algo_params: Union[
        Mapping[str, Any], Sequence[Mapping[str, Any]], None
    ] = None,
    *,
    rounds: int = 200,
    timeout: Optional[float] = None,
    seed: Union[int, Sequence[int]] = 0,
    chunk_size: int = 64,
    convergence_chunks: int = 0,
    n_restarts: int = 1,
    pad_policy: str = "pow2",
    trace: Optional[str] = None,
    trace_format: str = "jsonl",
    compile_cache: Optional[str] = None,
    chaos: Optional[str] = None,
    chaos_seed: int = 0,
    retry_budget: Optional[int] = None,
    chunk_floor: Optional[int] = None,
    on_numeric_fault: Optional[str] = None,
) -> list:
    """Solve MANY DCOP instances, batching same-shaped ones into one
    device program each (cross-instance batching,
    ``docs/performance.md``).

    Every instance is compiled with ``pad_policy`` (default ``"pow2"``
    — shape bucketing is what makes similarly-sized instances land on
    identical array shapes), grouped by
    :func:`~pydcop_tpu.ops.compile.stack_problems` bucket key plus
    static (str/bool) algorithm params, and each group runs as ONE
    ``jax.vmap``-ed chunk runner over the instance axis
    (:func:`~pydcop_tpu.engine.batched.run_many_batched`): a 50-
    instance sweep becomes a handful of XLA programs instead of 50.
    Numeric algorithm params may differ per instance within a group.

    ``algo_params`` is one mapping shared by all instances or a
    sequence of one mapping per instance; ``seed`` likewise an int or
    a per-instance sequence.  Instance ``i`` consumes exactly the RNG
    stream ``solve(dcops[i], seed=seed_i, pad_policy=pad_policy)``
    would, so deterministic algorithms return bit-identical results
    either way (``tests/test_solve_many.py``).  ``n_restarts``
    composes: each instance runs K independent restarts inside the
    same program (axes ``[instance, restart, ...]``).

    Host-path (exact) algorithms batch too when they support it: DPOP
    instances sharing a bucket key merge their UTIL phases into ONE
    level-synchronous device sweep (one vmapped join dispatch per
    level-pack bucket, one compiled executable per bucket for the
    whole group — ``engine.host_batch.run_many_host`` /
    ``algorithms/dpop.py:solve_host_many``), with per-instance
    results bit-identical to sequential solves.  SyncBB stays
    sequential.

    ``timeout`` bounds the WHOLE call: groups share the budget, and a
    group that hits the remaining budget stops all its instances at a
    chunk boundary with ``status="timeout"``.

    Returns one result dict per input, in input order, with the same
    keys as :func:`solve` plus ``instances_batched`` (the size of the
    group the instance rode in — 1 when nothing else shared its
    bucket).  The ``time`` field is the instance's group wall-clock
    divided evenly across the group; telemetry is the aggregate of
    the whole call.

    The whole call runs under one supervised-dispatch layer
    (``engine/supervisor.py``, knobs ``retry_budget``/``chunk_floor``/
    ``on_numeric_fault`` as in :func:`solve`): a group that exhausts
    device memory SPLITS — each half re-dispatches with its own
    (smaller) vmapped program, stream-preserving, so per-instance
    results stay bit-identical to the fault-free run — and a
    NaN-poisoned instance is QUARANTINED out of its group alone
    (``status="degraded"`` with its last-finite anytime best) while
    the other K-1 instances finish untouched.  ``chaos``/
    ``chaos_seed`` accept the device-layer fault kinds
    (``device_oom``, ``device_transient``, ``nan_inject`` —
    ``docs/faults.md``) to exercise exactly those paths on demand.
    """
    import time as _time

    from pydcop_tpu.telemetry import session

    dcops = list(dcops)
    n = len(dcops)
    if n == 0:
        return []
    if n_restarts < 1:
        raise ValueError(f"n_restarts must be >= 1, got {n_restarts}")

    plan = None
    if chaos:
        from pydcop_tpu.faults import FaultPlan

        plan = FaultPlan.from_spec(chaos, chaos_seed)
        if plan.message_faults_configured or plan.crashes:
            raise ValueError(
                "solve_many runs on the batched engine, which has no "
                "message plane — chaos accepts the DEVICE-layer "
                "kinds only: device_oom, device_transient, "
                "nan_inject (docs/faults.md)"
            )
        if plan.wire_faults_configured:
            raise ValueError(
                "wire-level chaos kinds (conn_drop/slow_client/"
                "frame_corrupt) inject at the solver service's frame "
                "loop — use `pydcop_tpu serve --chaos` "
                "(docs/serving.md); solve_many has no serving wire"
            )
        if plan.fleet_faults_configured:
            raise ValueError(
                "fleet-level chaos kinds (replica_kill) act on a "
                "replicated serving fleet's processes — use "
                "`pydcop_tpu fleet --chaos` (docs/faults.md); "
                "solve_many has no fleet"
            )

    if compile_cache is not None:
        from pydcop_tpu.ops.compile import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache(compile_cache)

    # per-instance algorithm params (resolve AlgorithmDef-carried
    # params once, merge per-instance overrides)
    if algo_params is None or isinstance(algo_params, Mapping):
        algo_name, params_in = resolve_algo(algo, algo_params)
        params_in_list = [params_in] * n
    else:
        algo_params = list(algo_params)
        if len(algo_params) != n:
            raise ValueError(
                f"algo_params: got {len(algo_params)} mappings for "
                f"{n} dcops"
            )
        algo_name = None
        params_in_list = []
        for p in algo_params:
            algo_name, merged = resolve_algo(algo, p)
            params_in_list.append(merged)

    if isinstance(seed, (list, tuple, range)):
        seeds = [int(s) for s in seed]
        if len(seeds) != n:
            raise ValueError(
                f"seed: got {len(seeds)} seeds for {n} dcops"
            )
    else:
        seeds = [int(seed)] * n

    from pydcop_tpu.ops.padding import as_pad_policy

    as_pad_policy(pad_policy)  # fail fast on a malformed spec

    module = load_algorithm_module(algo_name)
    prepared = [
        prepare_algo_params(p, module.algo_params)
        for p in params_in_list
    ]

    # one supervised-dispatch layer for the whole call: every group's
    # device dispatches (and the merged DPOP sweeps on the host path)
    # share the retry/degradation knobs and the device chaos plan
    from pydcop_tpu.engine.supervisor import make_supervisor, supervision

    sup = make_supervisor(
        retry_budget=retry_budget, chunk_floor=chunk_floor,
        on_numeric_fault=on_numeric_fault, plan=plan,
    )

    # load yaml paths once per distinct path; DCOP objects pass through
    loaded: Dict[str, DCOP] = {}

    def _load(d):
        if isinstance(d, (str, list, tuple)):
            key = d if isinstance(d, str) else tuple(d)
            if key not in loaded:
                loaded[key] = load_dcop_from_file(d)
            return loaded[key]
        return d

    with session(trace, trace_format) as tel, supervision(sup):
        deadline = (
            _time.perf_counter() + timeout if timeout is not None else None
        )
        results: list = [None] * n
        if hasattr(module, "solve_host"):
            # exact host-path algorithms: same-bucket groups merge
            # into one level-synchronous sweep when the algorithm
            # supports it (DPOP solve_host_many); the rest solve
            # sequentially.  host_batch is the jax-free split of
            # engine.batched — a pure host run must not pay the jax
            # import chain.
            if n_restarts != 1:
                raise ValueError(
                    f"{algo_name} is an exact host-path algorithm — "
                    "n_restarts (best-of-K for stochastic solvers) "
                    "does not apply"
                )
            from pydcop_tpu.engine.host_batch import run_many_host

            host_dcops = [_load(d) for d in dcops]
            # the deadline covers the WHOLE call, including the yaml
            # loads above — hand run_many_host only what is left
            results = run_many_host(
                host_dcops,
                module,
                prepared,
                timeout=(
                    None
                    if deadline is None
                    else max(deadline - _time.perf_counter(), 0.01)
                ),
                pad_policy=pad_policy,
            )
        else:
            from pydcop_tpu.engine.batched import run_many_batched
            from pydcop_tpu.ops.compile import (
                compile_dcop,
                stack_problems,
            )

            # compile each distinct dcop once (repeated paths/objects
            # reuse the compiled arrays at several stack positions)
            compiled_by_id: Dict[int, Any] = {}
            problems = []
            for d in dcops:
                obj = _load(d)
                if id(obj) not in compiled_by_id:
                    compiled_by_id[id(obj)] = compile_dcop(
                        obj, pad_policy=pad_policy
                    )
                problems.append(compiled_by_id[id(obj)])

            # partition by static (str/bool) param signature — statics
            # are baked into the compiled step, so instances can only
            # share a runner when they agree on them (shared helper
            # with the host path: engine.host_batch.statics_signature)
            from pydcop_tpu.engine.host_batch import statics_signature

            partitions: Dict[Any, list] = {}
            for i, p in enumerate(prepared):
                partitions.setdefault(statics_signature(p), []).append(i)

            for part in partitions.values():
                for stacked in stack_problems(
                    [problems[i] for i in part]
                ):
                    group = [part[j] for j in stacked.indices]
                    remaining = (
                        None
                        if deadline is None
                        else max(deadline - _time.perf_counter(), 0.01)
                    )
                    group_results = run_many_batched(
                        stacked,
                        module,
                        [prepared[i] for i in group],
                        rounds=rounds,
                        seeds=[seeds[i] for i in group],
                        timeout=remaining,
                        chunk_size=chunk_size,
                        convergence_chunks=convergence_chunks,
                        n_restarts=n_restarts,
                    )
                    for i, rr in zip(group, group_results):
                        out = _result_dict(rr)
                        out["instances_batched"] = len(group)
                        # an even share of the group's wall-clock:
                        # summing per-instance times over a sweep then
                        # reflects the real cost of the batched call
                        out["time"] = rr.time / len(group)
                        results[i] = out
        summary = tel.summary()
    for r in results:
        r["telemetry"] = summary
        if plan is not None:
            # replay record, same as the message-plane chaos runs
            r["chaos"] = plan.to_meta()
    return results


def infer(
    dcop: Union[DCOP, str],
    query: str = "marginals",
    *,
    order: str = "pseudo_tree",
    beta: float = 1.0,
    tol: float = 1e-6,
    device: str = "auto",
    device_min_cells: int = 1 << 14,
    timeout: Optional[float] = None,
    pad_policy: str = "none",
    max_table_size: int = 1 << 26,
    trace: Optional[str] = None,
    trace_format: str = "jsonl",
    compile_cache: Optional[str] = None,
    retry_budget: Optional[int] = None,
    max_util_bytes: Optional[int] = None,
    map_vars: Optional[Sequence[str]] = None,
    external_dists: Optional[
        Mapping[str, Mapping[Any, float]]
    ] = None,
    bnb: str = "auto",
    table_dtype: str = "f32",
    table_format: str = "dense",
) -> Dict[str, Any]:
    """Exact probabilistic inference over a DCOP's cost model — the
    semiring-generic twin of :func:`solve` (``docs/semirings.md``).

    The DCOP's total cost is read as an energy ``E(x)`` defining the
    Gibbs distribution ``p(x) ∝ exp(-beta·E(x))``, and ``query``
    picks the semiring the contraction engine
    (``ops/semiring.py``) runs over the elimination order:

    - ``"marginals"`` — per-variable distributions ``p(x_v)`` (one
      list of probabilities per variable, in domain order) plus
      ``log_z``;
    - ``"log_z"`` — the log partition function
      ``log Σ_x exp(-beta·E(x))`` (weighted model counting);
    - ``"map"`` — the exact MAP assignment (``max/+`` — for
      ``beta``-independent problems this equals the DPOP argmin,
      certified exact the same way);
    - ``"kbest:<k>"`` — the k BEST assignments in cost order
      (structured top-K cells: ⊕ merges sorted k-vectors, ⊗
      cross-sums and truncates; certified per component and
      re-evaluated on host f64, so the list is exact like ``map``).
      The result carries ``solutions`` (``[{assignment, cost,
      energy}]``, best first, all distinct) and ``costs``;
    - ``"marginal_map"`` — maximize over ``map_vars`` of the summed
      weight of the rest: ``max_{x_M} log Σ_{x_S} exp(-beta·E)``.
      Both elimination-order heuristics honor the required two-block
      order (summed variables eliminated first); the result carries
      the ``assignment`` over ``map_vars`` and the ``value``;
    - ``"expectation"`` — ``E[cost]`` under the Gibbs distribution
      via first-order expectation pairs ``(log w, E[cost])``.
      ``external_dists={external: {value: prob}}`` turns stochastic
      externals into a MODELED expectation (the named externals are
      summed over their distribution instead of pinned to their
      current value); the result carries ``e_cost`` and ``log_z``.

    ``order`` picks the elimination-order heuristic:
    ``"pseudo_tree"`` (the DFS order DPOP uses — best on the wide
    shallow shapes the level-synchronous sweep batches well) or
    ``"min_fill"`` (greedy min-fill — often much narrower on loopy
    graphs, directly bounding the largest table).

    Large contractions run on the device under the same machinery as
    DPOP's UTIL sweep — level-pack bucketed vmapped dispatches
    (``pad_policy`` quantizes the buckets), the shape-keyed compiled-
    kernel cache, and the ambient supervisor
    (``engine/supervisor.py``; ``retry_budget`` as in
    :func:`solve`).  ``map`` stays EXACT on device via the f32
    argmax certificate; ``log_z``/``marginals`` use error-bound
    accounting — a contraction whose accumulated f32 bound would
    exceed ``tol`` runs on host f64 instead
    (``semiring.logsumexp_repairs``), and the result reports the
    final ``error_bound``.  ``device``: ``"auto"`` (tables >=
    ``device_min_cells`` cells), ``"never"`` (pure host f64),
    ``"always"``.

    ``max_util_bytes`` runs the sweep MEMORY-BOUNDED
    (``ops/membound.py``, ``docs/semirings.md`` "Memory-bounded
    contraction"): every contraction table is kept under the budget
    by conditioning a cut set whose assignments ride the level-pack
    stack as extra vmapped lanes — the same per-⊕ exactness
    contracts hold across the lane combine (``map`` stays certified
    exact; ``log_z``/``marginals`` report a sound cross-lane
    ``error_bound``), the result carries a ``membound`` block, and a
    device OOM re-plans at half budget before abandoning the device.
    An unplannable budget raises a sizing error (planned peak table
    bytes vs budget, cut width) instead of an order hint.

    ``bnb`` selects the branch-and-bound pruned two-pass kernels
    (``docs/semirings.md``, "Branch-and-bound pruning"):
    ``"auto"`` (default) prunes device dispatches whose per-row
    table clears a size threshold, ``"on"`` prunes every device
    dispatch, ``"off"`` keeps the single-pass kernels.  ``map``/
    ``kbest`` results are bit-identical either way; the mass
    queries account any discarded mass into ``error_bound``.

    ``table_dtype`` (``"f32"`` default, ``"bf16"``, ``"int8"``)
    picks the STORAGE precision of the device contraction tables
    (``docs/performance.md``, "Mixed-precision table packs"): the
    accumulator stays f32 and the certificate ladder re-scales to
    the storage precision, so ``map``/``kbest`` stay bit-identical
    to f32 (uncertain nodes repair bf16 → f32 → host f64;
    ``semiring.precision_repairs`` counts the demotions) while
    ``log_z``/``marginals`` report an honestly widened
    ``error_bound``.  bf16 halves and int8 quarters per-cell HBM —
    the same ``max_util_bytes`` budget fits a smaller cut.

    ``table_format`` (``"dense"`` default, ``"sparse"``) picks the
    STORAGE LAYOUT of the device contraction tables
    (``docs/performance.md``, "Sparse constraint tables"): sparse
    COO-packs the feasible tuples of hard-constraint-dominated
    tables (sorted flat indices + values, density <= 0.5) and joins
    them with gather/segment-reduce kernels over candidate lists.
    ``map``/``kbest`` stay bit-identical to dense (same certificate
    + host f64 repair); the mass queries fold any pack truncation
    into ``error_bound``.  Composes with ``table_dtype`` (packed
    values quantize like dense packs) and ``max_util_bytes`` (nodes
    are budgeted at their PACKED bytes — the same budget fits a
    smaller cut on sparse workloads).

    Returns a result dict with ``status``/``time``/``telemetry``
    plus the query's payload, ``cells``/``dispatches``/
    ``device_nodes``/``host_nodes`` contraction stats, and the
    plan's induced ``width``.
    """
    return infer_many(
        [dcop], query, order=order, beta=beta, tol=tol,
        device=device, device_min_cells=device_min_cells,
        timeout=timeout, pad_policy=pad_policy,
        max_table_size=max_table_size, trace=trace,
        trace_format=trace_format, compile_cache=compile_cache,
        retry_budget=retry_budget, max_util_bytes=max_util_bytes,
        map_vars=map_vars, external_dists=external_dists, bnb=bnb,
        table_dtype=table_dtype, table_format=table_format,
    )[0]


def infer_many(
    dcops: Sequence[Union[DCOP, str]],
    query: str = "marginals",
    *,
    order: str = "pseudo_tree",
    beta: float = 1.0,
    tol: float = 1e-6,
    device: str = "auto",
    device_min_cells: int = 1 << 14,
    timeout: Optional[float] = None,
    pad_policy: str = "pow2",
    max_table_size: int = 1 << 26,
    trace: Optional[str] = None,
    trace_format: str = "jsonl",
    compile_cache: Optional[str] = None,
    retry_budget: Optional[int] = None,
    max_util_bytes: Optional[int] = None,
    map_vars: Optional[Sequence[str]] = None,
    external_dists: Optional[
        Mapping[str, Mapping[Any, float]]
    ] = None,
    bnb: str = "auto",
    table_dtype: str = "f32",
    table_format: str = "dense",
) -> list:
    """Run one inference ``query`` over MANY instances with their
    contraction sweeps MERGED — the :func:`solve_many` batching
    contract applied to :func:`infer`: same-level-pack-bucket
    contractions from different instances ride ONE vmapped device
    dispatch and share one compiled kernel (``pad_policy`` defaults
    to ``"pow2"`` here so similarly-sized instances land in the same
    buckets), and per-instance results are identical to sequential
    :func:`infer` calls.  ``timeout`` bounds the whole call.
    Returns one result dict per input, in input order, each carrying
    ``instances_batched``.
    """
    from pydcop_tpu.telemetry import session

    dcops = list(dcops)
    if not dcops:
        return []
    if compile_cache is not None:
        from pydcop_tpu.ops.compile import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache(compile_cache)

    import time as _time

    from pydcop_tpu.engine.supervisor import make_supervisor, supervision
    from pydcop_tpu.ops.semiring import run_infer_many

    sup = make_supervisor(retry_budget=retry_budget)
    # the deadline covers the WHOLE call, yaml loads included (the
    # same contract solve_many keeps) — hand the engine only what is
    # left once the files are parsed
    deadline = (
        _time.perf_counter() + timeout if timeout is not None else None
    )
    loaded = [
        load_dcop_from_file(d)
        if isinstance(d, (str, list, tuple))
        else d
        for d in dcops
    ]
    with session(trace, trace_format) as tel, supervision(sup):
        results = run_infer_many(
            loaded, query, order=order, beta=beta, tol=tol,
            device=device, device_min_cells=device_min_cells,
            pad_policy=pad_policy, max_table_size=max_table_size,
            max_util_bytes=max_util_bytes,
            map_vars=map_vars, external_dists=external_dists,
            bnb=bnb, table_dtype=table_dtype,
            table_format=table_format,
            timeout=(
                None
                if deadline is None
                else max(deadline - _time.perf_counter(), 0.01)
            ),
        )
        summary = tel.summary()
    for r in results:
        r["telemetry"] = summary
    return results


def solve_compiled(
    problem,
    algo: Union[str, AlgorithmDef],
    algo_params: Optional[Mapping[str, Any]] = None,
    rounds: int = 200,
    timeout: Optional[float] = None,
    seed: int = 0,
    convergence_chunks: int = 0,
    chunk_size: int = 64,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1,
    resume: bool = False,
    ui_port: Optional[int] = None,
    n_restarts: int = 1,
) -> Dict[str, Any]:
    """Solve an already-compiled problem (same result dict as
    :func:`solve`).

    The entry point for array-built problems
    (:func:`pydcop_tpu.ops.compile.compile_from_arrays`) — generated
    instances beyond ~100k variables skip the Python model layer
    entirely.  Only batched-engine algorithms apply; exact host-path
    algorithms (DPOP, SyncBB) need the model/graph objects — use
    :func:`solve` for those.
    """
    algo_name, params_in = resolve_algo(algo, algo_params)
    module = load_algorithm_module(algo_name)
    if hasattr(module, "solve_host"):
        raise ValueError(
            f"{algo_name} runs on the host path and needs the DCOP "
            "model objects — use solve() instead of solve_compiled()"
        )
    params = prepare_algo_params(params_in, module.algo_params)
    return _run_compiled(
        problem, module, params, rounds=rounds, seed=seed,
        timeout=timeout, chunk_size=chunk_size,
        convergence_chunks=convergence_chunks,
        checkpoint_path=checkpoint_path,
        checkpoint_every=checkpoint_every, resume=resume,
        ui_port=ui_port, n_restarts=n_restarts,
    )


def _run_compiled(
    problem,
    module,
    params: Dict[str, Any],
    *,
    rounds: int,
    seed: int,
    timeout: Optional[float],
    chunk_size: int,
    convergence_chunks: int,
    checkpoint_path: Optional[str],
    checkpoint_every: int,
    resume: bool,
    ui_port: Optional[int],
    n_restarts: int = 1,
) -> Dict[str, Any]:
    from pydcop_tpu.engine.batched import run_batched

    ui = None
    chunk_callback = None
    if ui_port is not None:
        from pydcop_tpu.infrastructure.ui import UiServer, chunk_publisher

        ui = UiServer(ui_port)
        chunk_callback = chunk_publisher(ui)
    try:
        result = run_batched(
            problem,
            module,
            params,
            rounds=rounds,
            seed=seed,
            timeout=timeout,
            chunk_size=chunk_size,
            convergence_chunks=convergence_chunks,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every,
            resume=resume,
            chunk_callback=chunk_callback,
            n_restarts=n_restarts,
        )
        if ui is not None:  # final event carries the assignment
            ui.publish(
                result.cycles,
                result.cost,
                result.best_cost,
                values=result.best_assignment,
                status=result.status,
            )
    finally:
        if ui is not None:
            ui.close()
    return _result_dict(result)


def _result_dict(result) -> Dict[str, Any]:
    """RunResult → the public result-dict schema shared by
    :func:`solve` (batched mode) and :func:`solve_many`."""
    return {
        "assignment": result.best_assignment,
        "cost": result.best_cost,
        "final_assignment": result.assignment,
        "final_cost": result.cost,
        "cycle": result.cycles,
        "msg_count": result.messages,
        "msg_size": result.messages,  # 1 unit per logical message
        "status": result.status,
        "time": result.time,
        "cost_trace": result.cost_trace.tolist(),
        **(
            {"restart_costs": result.restart_costs.tolist()}
            if result.restart_costs is not None
            else {}
        ),
    }
