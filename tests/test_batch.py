"""Tests for the batch experiment runner and consolidate commands."""

import csv
import json

from tests.test_cli import run_cli


def _write_instances(tmp_path, n_files=2):
    inst = tmp_path / "instances"
    inst.mkdir()
    for f in range(n_files):
        lines = [
            f"name: p{f}",
            "objective: min",
            "domains:",
            "  colors: {values: [0, 1, 2]}",
            "variables:",
        ]
        for i in range(4):
            lines.append(f"  v{i}: {{domain: colors}}")
        lines.append("constraints:")
        for i in range(4):
            j = (i + 1) % 4
            lines.append(f"  c{i}:")
            lines.append("    type: intention")
            lines.append(f"    function: 1 if v{i} == v{j} else 0")
        lines.append("agents: [a0, a1, a2, a3]")
        (inst / f"coloring_{f}.yaml").write_text("\n".join(lines) + "\n")
    return inst


def _write_spec(tmp_path):
    spec = tmp_path / "spec.yaml"
    spec.write_text(
        "sets:\n"
        "  coloring:\n"
        '    path: "instances/coloring_*.yaml"\n'
        "    iterations: 2\n"
        "batches:\n"
        "  dsa_sweep:\n"
        "    algo: dsa\n"
        "    algo_params:\n"
        "      variant: [A, B]\n"
        "    rounds: 20\n"
    )
    return spec


def test_batch_simulate(tmp_path):
    _write_instances(tmp_path)
    spec = _write_spec(tmp_path)
    r = run_cli("batch", str(spec), "--simulate")
    assert r.returncode == 0, r.stderr
    # 2 files × 2 variants × 2 iterations
    assert "8 runs total" in r.stdout


def test_batch_run_and_resume(tmp_path):
    _write_instances(tmp_path)
    spec = _write_spec(tmp_path)
    out = tmp_path / "results.csv"
    r = run_cli("batch", str(spec), "--result_file", str(out))
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["executed"] == 8
    with open(out, newline="") as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 8
    assert {row["status"] for row in rows} == {"finished"}
    variants = {json.loads(row["params"])["variant"] for row in rows}
    assert variants == {"A", "B"}

    # resume: nothing re-executed
    r2 = run_cli("batch", str(spec), "--result_file", str(out))
    assert r2.returncode == 0, r2.stderr
    summary2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert summary2["executed"] == 0
    assert summary2["skipped"] == 8


def test_batch_vmap_cells(tmp_path):
    """--vmap_cells collapses all pending (problem x params x
    iteration) runs of a batch into vmapped solve_many groups: same
    rows/keys as the sequential mode, per-run seeds preserved, resume
    intact."""
    _write_instances(tmp_path, n_files=2)
    spec = _write_spec(tmp_path)
    out = tmp_path / "res.csv"
    r = run_cli(
        "batch", str(spec), "--result_file", str(out), "--vmap_cells",
    )
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["executed"] == 8  # 2 files x 2 variants x 2 iters
    assert summary["failed"] == 0
    with open(out, newline="") as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 8
    assert {row["status"] for row in rows} == {"finished"}
    for row in rows:
        assert float(row["cost"]) >= 0
        assert int(row["msg_count"]) > 0
        assert float(row["time"]) > 0
    # rows carry the standard keys, so a re-run skips them (the
    # done-key resume machinery itself is covered by
    # test_batch_run_and_resume / test_batch_vmap_iterations)
    iterations = {row["iteration"] for row in rows}
    assert iterations == {"0", "1"}


def test_batch_forwards_restarts_and_pad_policy(tmp_path):
    """The n_restarts / pad_policy batch options reach api.solve (the
    sweep can use PR-3 bucketing and best-of-K restarts)."""
    _write_instances(tmp_path, n_files=1)
    spec = tmp_path / "spec.yaml"
    spec.write_text(
        "sets:\n"
        "  coloring:\n"
        '    path: "instances/coloring_*.yaml"\n'
        "    iterations: 1\n"
        "batches:\n"
        "  dsa_restarts:\n"
        "    algo: dsa\n"
        "    algo_params:\n"
        "      variant: B\n"
        "    rounds: 16\n"
        "    n_restarts: 3\n"
        "    pad_policy: pow2:16\n"
    )
    out = tmp_path / "res.csv"
    r = run_cli("batch", str(spec), "--result_file", str(out))
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["executed"] == 1 and summary["failed"] == 0
    with open(out, newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows[0]["status"] == "finished"


def test_consolidate_merge_and_aggregate(tmp_path):
    _write_instances(tmp_path)
    spec = _write_spec(tmp_path)
    out = tmp_path / "results.csv"
    r = run_cli("batch", str(spec), "--result_file", str(out))
    assert r.returncode == 0, r.stderr

    merged = tmp_path / "merged.csv"
    r = run_cli(
        "consolidate", str(out), "--result_file", str(merged),
        "--group_by", "problem", "algo",
    )
    assert r.returncode == 0, r.stderr
    with open(merged, newline="") as f:
        rows = list(csv.DictReader(f))
    # 2 problems × 1 algo
    assert len(rows) == 2
    assert all(row["n_runs"] == "4" for row in rows)
    assert all(float(row["cost"]) >= 0 for row in rows)


def test_batch_vmap_iterations(tmp_path):
    """--vmap_iterations solves each (problem, params) cell's
    iterations as one multi-restart run: same row count and key set as
    the sequential mode, one valid cost sample per iteration row."""
    _write_instances(tmp_path, n_files=1)
    spec = _write_spec(tmp_path)
    out = tmp_path / "res.csv"
    r = run_cli(
        "batch", str(spec), "--result_file", str(out),
        "--vmap_iterations",
    )
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["executed"] == 4  # 1 file x 2 variants x 2 iters
    assert summary["failed"] == 0
    with open(out, newline="") as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 4
    assert {r_["iteration"] for r_ in rows} == {"0", "1"}
    for row in rows:
        assert row["status"] == "finished"
        assert float(row["cost"]) >= 0
        assert int(row["msg_count"]) > 0
    # resume: everything already recorded → nothing executed
    r2 = run_cli(
        "batch", str(spec), "--result_file", str(out),
        "--vmap_iterations",
    )
    summary2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert summary2["executed"] == 0
    assert summary2["skipped"] == 4
