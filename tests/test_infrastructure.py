"""Tests for the host message-driven runtime (infrastructure/)."""

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.infrastructure import (
    Message,
    MessagePassingComputation,
    message_type,
    register,
    solve_host,
)
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

D = Domain("colors", "", [0, 1, 2])


def ring_dcop(n=6):
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def tree_dcop(n=7):
    dcop = DCOP("tree")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        p = (i - 1) // 2
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{p} else 0", vs)
        )
    return dcop


# -- computations base classes -----------------------------------------


def test_message_type_factory():
    ValueMsg = message_type("value", ["value", "extra"])
    m = ValueMsg(value=3, extra="x")
    assert m.type == "value"
    assert m.value == 3
    assert m.extra == "x"
    with pytest.raises(TypeError):
        ValueMsg(value=1)  # missing field
    with pytest.raises(TypeError):
        ValueMsg(value=1, extra=2, nope=3)  # unknown field


def test_message_simple_repr_roundtrip():
    from pydcop_tpu.algorithms._host_dsa import DsaValueMessage
    from pydcop_tpu.algorithms._host_maxsum import MaxSumCostMessage

    m = DsaValueMessage(2)
    m2 = from_repr(simple_repr(m))
    assert m2.value == 2 and m2.type == "dsa_value"

    c = MaxSumCostMessage({0: 1.5, 1: 0.0})
    c2 = from_repr(simple_repr(c))
    assert c2.costs == {0: 1.5, 1: 0.0}
    assert c2.size == 2


def test_register_dispatch():
    log = []

    class Comp(MessagePassingComputation):
        @register("ping")
        def _on_ping(self, sender, msg, t):
            log.append(("ping", sender, msg.content))

        @register("pong")
        def _on_pong(self, sender, msg, t):
            log.append(("pong", sender, msg.content))

    c = Comp("c1")
    c.start()
    c.on_message("x", Message("ping", 1))
    c.on_message("y", Message("pong", 2))
    assert log == [("ping", "x", 1), ("pong", "y", 2)]
    with pytest.raises(ValueError, match="no handler"):
        c.on_message("z", Message("nope"))
    # messages to a stopped computation are dropped, not dispatched
    c.stop()
    c.on_message("x", Message("ping", 3))
    assert len(log) == 2


# -- sim mode ----------------------------------------------------------


@pytest.mark.parametrize("algo", ["adsa", "dsa", "dsatuto"])
def test_sim_dsa_reaches_optimum_on_ring(algo):
    r = solve_host(ring_dcop(), algo, mode="sim", seed=1)
    assert r["status"] == "finished"  # quiescent at a local optimum
    assert r["cost"] == 0
    assert r["msg_count"] > 0


@pytest.mark.parametrize("seed", range(5))
def test_sim_amaxsum_exact_on_tree(seed):
    """Async Max-Sum must be exact on trees for any async schedule."""
    r = solve_host(tree_dcop(), "amaxsum", mode="sim", seed=seed)
    assert r["status"] == "finished"
    assert r["cost"] == 0


def test_sim_is_deterministic():
    r1 = solve_host(ring_dcop(), "amaxsum", mode="sim", seed=3)
    r2 = solve_host(ring_dcop(), "amaxsum", mode="sim", seed=3)
    assert r1["assignment"] == r2["assignment"]
    assert r1["msg_count"] == r2["msg_count"]


def test_sim_msg_budget():
    r = solve_host(ring_dcop(), "amaxsum", mode="sim", seed=0, max_msgs=5)
    assert r["status"] == "msg_budget"
    assert r["msg_count"] == 5


def test_msg_log_dumps_full_contents(tmp_path):
    """The per-message content log (reference Messaging's full-message
    log option, VERDICT r3 missing #4): every delivered message lands
    in the JSONL file in simple_repr wire form, round-trippable, with
    a count matching the run's delivered total — in both host modes."""
    import json

    from pydcop_tpu.utils.simple_repr import from_repr

    for mode in ("sim", "thread"):
        path = str(tmp_path / f"msgs.{mode}.jsonl")
        r = solve_host(
            ring_dcop(), "maxsum", mode=mode, seed=1, timeout=15,
            msg_log=path,
        )
        lines = [
            json.loads(ln)
            for ln in open(path).read().splitlines()
            if ln.strip()
        ]
        assert len(lines) == r["msg_count"], (mode, len(lines))
        for entry in lines[:20]:
            assert {"t", "src", "dest", "type", "size", "content"} <= set(
                entry
            )
            msg = from_repr(entry["content"])  # wire-form round-trip
            assert msg.type == entry["type"]


# -- thread mode -------------------------------------------------------


def test_thread_mode_solves_ring():
    r = solve_host(ring_dcop(), "adsa", mode="thread", timeout=15)
    assert r["status"] == "finished"
    assert r["cost"] == 0
    assert r["msg_count"] > 0


def test_thread_mode_uses_declared_agents():
    dcop = ring_dcop()
    from pydcop_tpu.dcop.objects import AgentDef

    dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
    r = solve_host(dcop, "adsa", mode="thread", timeout=15)
    assert r["cost"] == 0


@pytest.mark.parametrize("algo", ["adsa", "amaxsum"])
def test_sim_respects_max_objective(algo):
    """'max' DCOPs must be maximized on the host path too (the batched
    engine negates costs at compile time; the host computations flip
    their comparison sign instead)."""
    dcop = DCOP("maxprob", objective="max")
    vs = [Variable(f"v{i}", D) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(3):
        # reward 5 when adjacent variables AGREE
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"5 if v{i} == v{i+1} else 0", vs
            )
        )
    r = solve_host(dcop, algo, mode="sim", seed=0)
    assert r["cost"] == 15, r  # all agree = maximal reward
    assert len(set(r["assignment"].values())) == 1


def test_api_solve_mode_thread_and_sim():
    from pydcop_tpu.api import solve

    r = solve(ring_dcop(), "adsa", mode="sim")
    assert r["cost"] == 0
    r = solve(ring_dcop(), "adsa", mode="thread", timeout=15)
    assert r["cost"] == 0
    with pytest.raises(ValueError, match="unknown mode"):
        solve(ring_dcop(), "adsa", mode="bogus")
    with pytest.raises(ValueError, match="checkpoint"):
        solve(ring_dcop(), "adsa", mode="sim", checkpoint_path="x.npz")
