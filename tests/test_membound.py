"""Memory-bounded contraction tests (``ops/membound.py``,
``docs/semirings.md`` "Memory-bounded contraction").

Bit-parity suite: budgeted solves/inference vs the unbounded device
and host-f64 references across min_sum / max_sum / log_sum_exp,
including budgets that force >= 2 nested cut variables; cross-edge
consistency pruning exactness; deterministic re-planning under
injected ``device_oom_bytes``; and the api/service surfaces of
``max_util_bytes``.
"""

from __future__ import annotations

import importlib.util
import os
from argparse import Namespace

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation

pytestmark = pytest.mark.membound

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "recompile_guard.py",
)
_spec = importlib.util.spec_from_file_location(
    "recompile_guard_membound", _TOOL
)
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)


def _overlap_secp(n_lights=12, n_models=10, levels=3, seed=77):
    """The guard's fixed-structure overlap-zone SECP
    (``tools/recompile_guard.py:_build_secp_overlap`` — ONE builder,
    so the compile guard and this parity suite can never drift onto
    different workloads): chained windows whose induced width forces
    cuts."""
    return _guard._build_secp_overlap(
        n_lights, n_models, levels, seed=seed
    )


def _hard_chain(n=5, d=3):
    """Chain of hard not-equal constraints plus a unary that forbids
    one value of the head — the cross-edge pruning workload."""
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("hard_chain")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    u0 = np.array([0.0, 0.5] + [np.inf] * (d - 2))
    dcop.add_constraint(NAryMatrixRelation([vs[0]], u0, name="u0"))
    neq = np.where(np.eye(d) > 0, np.inf, 0.0) + 0.1 * np.arange(d)[
        None, :
    ]
    for i in range(n - 1):
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], neq, name=f"c{i}")
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


# -- planner units -------------------------------------------------------


def test_plan_cut_deterministic_and_bounded():
    from pydcop_tpu.ops.membound import BYTES_PER_CELL, plan_cut
    from pydcop_tpu.ops.semiring import build_plan

    dcop = _overlap_secp()
    c1 = plan_cut(build_plan(dcop), 256)
    c2 = plan_cut(build_plan(dcop), 256)
    # pure function of (graph, budget) — the determinism that makes
    # OOM re-planning replayable
    assert c1 == c2
    assert c1.width >= 1
    assert c1.bounded_peak_cells <= 256 // BYTES_PER_CELL
    assert c1.naive_peak_cells > 256 // BYTES_PER_CELL
    tighter = plan_cut(build_plan(dcop), 64)
    assert tighter.width >= c1.width
    assert tighter.bounded_peak_cells <= 64 // BYTES_PER_CELL


def test_overlap_zone_layout_raises_induced_width():
    """The generator satellite: tiled zones are shallow by design;
    the overlap layout chains them into a band whose induced width
    grows with the overlap degree."""
    from pydcop_tpu.commands.generators.secp import generate
    from pydcop_tpu.ops.semiring import build_plan

    def spec(layout, overlap):
        return Namespace(
            nb_lights=48, nb_models=48, nb_rules=12, light_levels=5,
            model_arity=4, zone_size=6, zone_layout=layout,
            zone_overlap=overlap, efficiency_weight=0.1,
            capacity=100.0, seed=7,
        )

    w_tiled = build_plan(generate(spec("tiled", 0))).width()
    w_overlap = build_plan(generate(spec("overlap", 3))).width()
    assert w_overlap > w_tiled
    with pytest.raises(ValueError, match="zone_overlap"):
        generate(spec("overlap", 6))  # overlap >= zone never advances


# -- bit-parity: budgeted vs unbounded ----------------------------------


def test_budgeted_dpop_bit_parity_host():
    from pydcop_tpu.api import solve

    dcop = _overlap_secp()
    base = solve(dcop, "dpop", {"util_device": "never"})
    for budget in (256, 128):
        r = solve(
            dcop, "dpop", {"util_device": "never"},
            max_util_bytes=budget,
        )
        assert r["cost"] == base["cost"]
        assert r["assignment"] == base["assignment"]
        assert r["status"] == "finished"
        mb = r["membound"]
        assert mb["peak_table_bytes"] <= budget
        assert mb["naive_peak_table_bytes"] > budget
    # the tighter budget needs >= 2 nested cut variables
    tight = solve(
        dcop, "dpop", {"util_device": "never"}, max_util_bytes=64
    )
    assert tight["cost"] == base["cost"]
    assert tight["membound"]["cut_width"] >= 2
    assert tight["membound"]["cut_lanes"] >= 9


def test_budgeted_dpop_device_bit_parity():
    from pydcop_tpu.api import solve

    dcop = _overlap_secp()
    base = solve(dcop, "dpop", {"util_device": "never"})
    r = solve(
        dcop, "dpop", {"util_device": "always"},
        max_util_bytes=256, pad_policy="pow2",
    )
    assert r["cost"] == base["cost"]
    assert r["assignment"] == base["assignment"]
    assert r["util_device_nodes"] >= 1
    assert r["membound"]["on_device"] is True
    assert r["membound"]["cut_width"] >= 1


def test_budgeted_infer_parity_all_semirings():
    """max_sum (map) exact, log_sum_exp within the reported bound,
    marginals allclose — budgeted vs the unbounded host-f64
    reference, device forced on for the budgeted run."""
    from pydcop_tpu.api import infer

    dcop = _overlap_secp()
    kw = dict(
        device="always", pad_policy="pow2", max_util_bytes=128,
        tol=float("inf"),
    )
    mp0 = infer(dcop, "map", device="never")
    mp1 = infer(dcop, "map", **kw)
    assert mp1["cost"] == mp0["cost"]
    assert mp1["assignment"] == mp0["assignment"]
    assert mp1["membound"]["cut_width"] >= 2  # nested cut

    z0 = infer(dcop, "log_z", device="never")
    z1 = infer(dcop, "log_z", **kw)
    assert (
        abs(z1["log_z"] - z0["log_z"])
        <= z1["error_bound"] + z0["error_bound"] + 1e-9
    )

    m0 = infer(dcop, "marginals", device="never")
    m1 = infer(dcop, "marginals", device="never", max_util_bytes=128)
    assert set(m1["marginals"]) == set(m0["marginals"])
    for v in m0["marginals"]:
        assert np.allclose(
            m0["marginals"][v], m1["marginals"][v], atol=1e-8
        ), v


def test_infer_many_budgeted_merged_matches_sequential():
    from pydcop_tpu.api import infer, infer_many

    dcops = [_overlap_secp(seed=77), _overlap_secp(seed=78)]
    merged = infer_many(
        dcops, "log_z", device="never", max_util_bytes=256
    )
    for d, r in zip(dcops, merged):
        solo = infer(d, "log_z", device="never", max_util_bytes=256)
        assert r["log_z"] == solo["log_z"]
        assert r["membound"]["cut"] == solo["membound"]["cut"]
        assert r["instances_batched"] == 2


# -- cross-edge consistency pruning -------------------------------------


def test_cross_edge_pruning_exact_and_counted():
    from pydcop_tpu.api import infer, solve

    dcop = _hard_chain()
    big = 1 << 20  # budget met without cuts: pruning alone
    z0 = infer(dcop, "log_z", device="never")
    z1 = infer(dcop, "log_z", device="never", max_util_bytes=big)
    assert z1["membound"]["pruned_cells"] > 0
    assert abs(z1["log_z"] - z0["log_z"]) < 1e-9

    m0 = infer(dcop, "marginals", device="never")
    m1 = infer(
        dcop, "marginals", device="never", max_util_bytes=big
    )
    for v in m0["marginals"]:
        # full original-domain length, exactly 0 at pruned values
        assert len(m1["marginals"][v]) == len(m0["marginals"][v])
        assert np.allclose(
            m0["marginals"][v], m1["marginals"][v], atol=1e-12
        )
    assert m1["marginals"]["v0"][2] == 0.0

    r0 = solve(dcop, "dpop", {"util_device": "never"})
    r1 = solve(
        dcop, "dpop", {"util_device": "never"}, max_util_bytes=big
    )
    assert r1["cost"] == r0["cost"]
    assert r1["membound"]["pruned_cells"] > 0


# -- sizing error (the actionable over-width report) ---------------------


def test_membound_error_reports_sizing_not_a_retry_hint():
    from pydcop_tpu.api import infer
    from pydcop_tpu.ops.membound import MemboundError

    d = Domain("d", "", list(range(5)))
    dcop = DCOP("wide_chain")
    vs = [Variable(f"v{i}", d) for i in range(12)]
    for v in vs:
        dcop.add_variable(v)
    t = np.random.default_rng(0).random((5, 5))
    for i in range(11):
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[i + 1]], t, name=f"c{i}")
        )
    dcop.add_agents([AgentDef("a0")])
    with pytest.raises(MemboundError) as ei:
        infer(dcop, "log_z", device="never", max_util_bytes=4)
    e = ei.value
    assert e.max_util_bytes == 4
    assert e.naive_peak_bytes == 100  # 5*5 cells * 4 bytes
    assert e.cut_width >= 1
    msg = str(e)
    assert "bytes" in msg and "max_util_bytes=4" in msg
    assert "width" in msg


# -- OOM ladder: replanning ----------------------------------------------


def test_oom_replan_deterministic_and_stays_on_device():
    """Injected ``device_oom_bytes`` makes per-lane tables over the
    cap OOM: the budgeted sweep must RE-PLAN at half budget
    (``membound.replans`` >= 1) instead of falling straight to host,
    still bit-match the fault-free run, and replay identically."""
    from pydcop_tpu.api import solve

    # levels=4: d = 4 sits exactly on the pow-2 lattice, so planned
    # table bytes == dispatched table bytes and the injected bytes
    # cap reads directly against the plan
    dcop = _overlap_secp(levels=4)
    clean = solve(
        dcop, "dpop", {"util_device": "always"},
        max_util_bytes=1024, pad_policy="pow2",
    )
    runs = [
        solve(
            dcop, "dpop", {"util_device": "always"},
            max_util_bytes=1024, pad_policy="pow2",
            chaos="device_oom_bytes=500", chaos_seed=5,
        )
        for _ in range(2)
    ]
    for r in runs:
        assert r["cost"] == clean["cost"]
        assert r["assignment"] == clean["assignment"]
        assert r["membound"]["replans"] >= 1
        assert r["membound"]["on_device"] is True
        assert r["membound"]["budget_bytes"] < 1024
        counters = r["telemetry"]["counters"]
        assert counters.get("membound.replans", 0) >= 1
        assert counters.get("fault.device_oom", 0) >= 1
    # deterministic: the replayed run reproduces plan AND outcome
    assert runs[0]["membound"] == runs[1]["membound"]
    assert runs[0]["cost"] == runs[1]["cost"]


def test_oom_replan_bottoms_out_to_bounded_host():
    """A capacity no plan can fit (every device table > cap) walks
    the whole ladder and lands on bounded host f64 — still exact,
    never an exception."""
    from pydcop_tpu.api import solve

    dcop = _overlap_secp()
    base = solve(dcop, "dpop", {"util_device": "never"})
    r = solve(
        dcop, "dpop", {"util_device": "always"},
        max_util_bytes=256, pad_policy="pow2",
        chaos="device_oom_bytes=4", chaos_seed=1,
    )
    assert r["cost"] == base["cost"]
    assert r["membound"]["on_device"] is False
    assert r["membound"]["replans"] >= 1
    assert r["util_device_nodes"] == 0


# -- surfaces ------------------------------------------------------------


def test_pruning_keeps_neg_inf_optima():
    """-inf is an infinitely GOOD cost (a legitimate hard-constraint
    value — docs/faults.md): cross-edge pruning must only remove
    +inf-supported values, never the -inf optimum."""
    from pydcop_tpu.api import solve

    d = Domain("d", "", [0, 1])
    dcop = DCOP("mixed_inf")
    x, y = Variable("x", d), Variable("y", d)
    dcop.add_variable(x)
    dcop.add_variable(y)
    dcop.add_constraint(
        NAryMatrixRelation(
            [x, y],
            np.array([[np.inf, -np.inf], [0.0, 0.0]]),
            name="c",
        )
    )
    dcop.add_agents([AgentDef("a0")])
    base = solve(dcop, "dpop", {"util_device": "never"})
    r = solve(
        dcop, "dpop", {"util_device": "never"},
        max_util_bytes=1 << 20,
    )
    assert r["cost"] == base["cost"] == -np.inf
    assert r["assignment"] == base["assignment"]


def test_solve_rejects_budget_without_a_bounded_plan():
    from pydcop_tpu.api import solve

    with pytest.raises(ValueError, match="max_util_bytes"):
        solve(_overlap_secp(), "dsa", max_util_bytes=1024)


def test_non_positive_budget_rejected_everywhere():
    """An explicit budget of 0 must error, not silently run the
    naive unbounded sweep (the OOM the caller tried to prevent)."""
    from pydcop_tpu.api import infer, solve
    from pydcop_tpu.engine.service import SolverService

    dcop = _overlap_secp()
    with pytest.raises(ValueError, match="must be > 0"):
        solve(dcop, "dpop", max_util_bytes=0)
    with pytest.raises(ValueError, match="must be > 0"):
        infer(dcop, "log_z", device="never", max_util_bytes=0)
    with SolverService(max_wait=0.05) as svc:
        with pytest.raises(ValueError, match="must be > 0"):
            svc.submit(dcop, "dpop", max_util_bytes=0)


def test_memory_bound_and_max_util_bytes_are_exclusive():
    from pydcop_tpu.api import solve

    with pytest.raises(ValueError, match="bounded-memory"):
        solve(
            _overlap_secp(), "dpop",
            {"memory_bound": 4096, "max_util_bytes": 1024},
        )


def test_solve_many_budgeted_matches_sequential():
    from pydcop_tpu.api import solve, solve_many

    dcops = [_overlap_secp(seed=77), _overlap_secp(seed=78)]
    params = {"util_device": "never", "max_util_bytes": 256}
    many = solve_many(dcops, "dpop", params)  # pad defaults to pow2
    for d, r in zip(dcops, many):
        # the planner sizes targets on the PAD lattice, so the solo
        # reference must run under solve_many's pad default
        solo = solve(d, "dpop", params, pad_policy="pow2")
        assert r["cost"] == solo["cost"]
        assert r["assignment"] == solo["assignment"]
        assert r["membound"] == solo["membound"]


def test_service_request_schema_carries_max_util_bytes():
    from pydcop_tpu.api import solve
    from pydcop_tpu.engine.service import SolverService

    dcop = _overlap_secp()
    # the reference must run under the service's pad default (pow2):
    # the planner sizes targets on the pad lattice
    ref = solve(
        dcop, "dpop", {"util_device": "never"},
        max_util_bytes=256, pad_policy="pow2",
    )
    with SolverService(max_wait=0.05) as svc:
        out = svc.solve(
            dcop, "dpop", {"util_device": "never"},
            max_util_bytes=256,
        )
        with pytest.raises(ValueError, match="max_util_bytes"):
            svc.submit(dcop, "dsa", max_util_bytes=256)
    assert out["cost"] == ref["cost"]
    assert out["membound"] == ref["membound"]


def test_budgeted_bnb_composes_bit_identical_and_sizing_unchanged():
    """Budgeted membound sweeps COMPOSE with branch-and-bound
    pruning: ``run_bounded`` lanes build their incumbent per lane
    (each lane is an independent conditioned subproblem), so
    budgeted+bnb=on is bit-identical to unbounded+bnb=off for
    min_sum — and ``plan_cut``'s byte sizing ignores the mask
    entirely (pruning changes which rows are WORKED, never what the
    device allocates): the ``membound`` meta matches the unpruned
    budgeted solve field for field."""
    from pydcop_tpu.api import solve

    dcop = _guard._build_secp_overlap(
        12, 10, 4, seed=31, arity=5, stride=2, hard_cap=1.15,
    )
    kw = dict(pad_policy="pow2")
    base = solve(
        dcop, "dpop", {"util_device": "never", "bnb": "off"}, **kw
    )
    b_off = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "off"},
        max_util_bytes=1024, **kw
    )
    b_on = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "on"},
        max_util_bytes=1024, **kw
    )
    assert b_off["membound"]["cut_width"] >= 1  # budget really cut
    assert base["cost"] == b_off["cost"] == b_on["cost"]
    assert (
        base["assignment"]
        == b_off["assignment"]
        == b_on["assignment"]
    )
    # the mask never reaches the planner: identical cut, lanes,
    # budget and peak bytes whether pruning ran or not
    assert b_on["membound"] == b_off["membound"]


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
