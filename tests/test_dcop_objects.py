import pytest

from pydcop_tpu.dcop.objects import (
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    create_agents,
    create_binary_variables,
    create_variables,
)
from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


def test_domain_basics():
    d = Domain("colors", "color", ["R", "G", "B"])
    assert len(d) == 3
    assert d.index("G") == 1
    assert d[2] == "B"
    assert "R" in d
    assert list(d) == ["R", "G", "B"]
    with pytest.raises(ValueError):
        d.index("X")


def test_domain_to_domain_value_handles_str():
    d = Domain("nums", "", [1, 2, 3])
    assert d.to_domain_value("2") == 2
    assert d.to_domain_value(3) == 3


def test_domain_round_trip():
    d = Domain("colors", "color", ["R", "G"])
    assert from_repr(simple_repr(d)) == d


def test_variable_basics():
    d = Domain("d", "", [0, 1, 2])
    v = Variable("x", d, initial_value=1)
    assert v.name == "x"
    assert v.initial_value == 1
    assert v.cost_for_val(0) == 0
    with pytest.raises(ValueError):
        Variable("y", d, initial_value=9)


def test_variable_accepts_raw_list_domain():
    v = Variable("x", [0, 1])
    assert len(v.domain) == 2


def test_variable_round_trip():
    d = Domain("d", "", [0, 1])
    v = Variable("x", d, 1)
    v2 = from_repr(simple_repr(v))
    assert v2 == v and v2.initial_value == 1


def test_variable_with_cost_func():
    d = Domain("d", "", [0, 1, 2])
    v = VariableWithCostFunc("x", d, ExpressionFunction("x * 2"))
    assert v.cost_for_val(2) == 4
    assert v.has_cost
    v2 = from_repr(simple_repr(v))
    assert v2.cost_for_val(2) == 4


def test_variable_with_cost_dict():
    d = Domain("d", "", ["a", "b"])
    v = VariableWithCostDict("x", d, {"a": 1.0, "b": 0.5})
    assert v.cost_for_val("b") == 0.5


def test_noisy_cost_func_deterministic():
    d = Domain("d", "", [0, 1])
    f = ExpressionFunction("x * 1.0")
    v1 = VariableNoisyCostFunc("x", d, f, noise_level=0.1)
    v2 = VariableNoisyCostFunc("x", d, f, noise_level=0.1)
    assert v1.cost_for_val(1) == v2.cost_for_val(1)
    assert 1.0 <= v1.cost_for_val(1) <= 1.1


def test_binary_variable():
    b = BinaryVariable("b1")
    assert list(b.domain) == [0, 1]
    assert from_repr(simple_repr(b)) == b


def test_external_variable_subscription():
    d = Domain("d", "", ["on", "off"])
    e = ExternalVariable("sensor", d, "off")
    seen = []
    e.subscribe(seen.append)
    e.value = "on"
    assert e.value == "on"
    assert seen == ["on"]
    with pytest.raises(ValueError):
        e.value = "broken"


def test_agentdef_costs_and_routes():
    a = AgentDef(
        "a1",
        capacity=50,
        default_hosting_cost=2,
        hosting_costs={"v1": 5},
        default_route=1.5,
        routes={"a2": 0.5},
    )
    assert a.hosting_cost("v1") == 5
    assert a.hosting_cost("v9") == 2
    assert a.route("a2") == 0.5
    assert a.route("a3") == 1.5
    assert a.route("a1") == 0
    assert from_repr(simple_repr(a)) == a


def test_agentdef_extra_attrs():
    a = AgentDef("a1", foo="bar")
    assert a.foo == "bar"
    with pytest.raises(AttributeError):
        _ = a.nope


def test_create_variables_range_and_product():
    d = Domain("d", "", [0, 1])
    vs = create_variables("v", range(3), d)
    assert sorted(vs) == ["v0", "v1", "v2"]
    ms = create_variables("m", [[0, 1], [0, 1]], d)
    assert ("0", "1") in ms
    assert ms[("0", "1")].name == "m0_1"


def test_create_binary_variables():
    bs = create_binary_variables("b", range(2))
    assert all(list(b.domain) == [0, 1] for b in bs.values())


def test_create_agents():
    ags = create_agents("a", range(4), capacity=10)
    assert len(ags) == 4
    assert ags["a2"].capacity == 10


def test_agentdef_extra_attrs_round_trip():
    a = AgentDef("a1", foo="bar", num=3)
    a2 = from_repr(simple_repr(a))
    assert a2.foo == "bar" and a2.num == 3
