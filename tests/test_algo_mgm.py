"""MGM and MGM-2 on the batched engine: functional + property tests."""

import numpy as np
import pytest

from pydcop_tpu.algorithms import (
    AlgorithmDefError,
    load_algorithm_module,
    prepare_algo_params,
)
from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)


def coloring_ring(n=10, colors=3):
    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP(f"ring{n}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def coordination_trap():
    """Two binary variables where every unilateral move increases cost:
    cost(0,0)=1 is a strict 1-opt local minimum, cost(1,1)=0 is the
    optimum.  MGM can never leave (0,0); MGM-2's pair moves can."""
    d = Domain("b", "", [0, 1])
    dcop = DCOP("trap")
    v0 = Variable("v0", d, initial_value=0)
    v1 = Variable("v1", d, initial_value=0)
    dcop.add_variable(v0)
    dcop.add_variable(v1)
    m = NAryMatrixRelation(
        [v0, v1], np.array([[1.0, 2.0], [2.0, 0.0]]), name="c"
    )
    dcop.add_constraint(m)
    return dcop


def test_param_validation():
    mod = load_algorithm_module("mgm")
    params = prepare_algo_params({}, mod.algo_params)
    assert params["break_mode"] == "lexic"
    with pytest.raises(AlgorithmDefError):
        prepare_algo_params({"break_mode": "zz"}, mod.algo_params)
    mod2 = load_algorithm_module("mgm2")
    params2 = prepare_algo_params({"probability": 0.3}, mod2.algo_params)
    assert params2["probability"] == 0.3


def test_mgm_solves_ring_coloring():
    result = solve(coloring_ring(10, 3), "mgm", rounds=100, seed=2)
    assert result["cost"] == 0.0
    a = result["assignment"]
    for i in range(10):
        assert a[f"v{i}"] != a[f"v{(i + 1) % 10}"]
    assert result["msg_count"] == 100 * 2 * 2 * 10  # 2·Σdeg per round


def test_mgm_monotone_anytime():
    """The classic MGM guarantee: global cost never increases."""
    dcop = coloring_ring(20, 3)
    for seed in range(3):
        trace = np.asarray(
            solve(dcop, "mgm", rounds=60, seed=seed)["cost_trace"]
        )
        assert np.all(np.diff(trace) <= 1e-6)


def test_mgm_stuck_in_coordination_trap():
    result = solve(
        coordination_trap(), "mgm", {"initial": "declared"},
        rounds=50, seed=0,
    )
    assert result["cost"] == 1.0  # provably cannot move


def test_mgm2_escapes_coordination_trap():
    result = solve(
        coordination_trap(), "mgm2", {"initial": "declared"},
        rounds=50, seed=0,
    )
    assert result["cost"] == 0.0
    assert result["assignment"] == {"v0": 1, "v1": 1}


def test_mgm2_solves_ring_coloring():
    result = solve(coloring_ring(10, 3), "mgm2", rounds=150, seed=1)
    assert result["cost"] == 0.0
    a = result["assignment"]
    for i in range(10):
        assert a[f"v{i}"] != a[f"v{(i + 1) % 10}"]


def test_mgm2_monotone_anytime():
    """MGM-2 keeps MGM's monotonicity: movers beat all non-partner
    neighbors, and pair moves are jointly improving."""
    dcop = coloring_ring(16, 3)
    for seed in range(3):
        trace = np.asarray(
            solve(dcop, "mgm2", rounds=80, seed=seed)["cost_trace"]
        )
        assert np.all(np.diff(trace) <= 1e-6)


def test_mgm2_ternary_constraints():
    """Pair-shared tables must track current values of third parties."""
    d = Domain("t", "", [0, 1, 2])
    dcop = DCOP("tern")
    vs = [Variable(f"v{i}", d) for i in range(5)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(3):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}",
                f"abs(v{i} + v{i+1} - v{i+2})",
                vs,
            )
        )
    result = solve(dcop, "mgm2", rounds=100, seed=4)
    # optimum is 0 (e.g. all zeros); local search should find ≤ 1
    assert result["cost"] <= 1.0
    trace = np.asarray(result["cost_trace"])
    assert np.all(np.diff(trace) <= 1e-6)


@pytest.mark.parametrize("algo", ["mgm", "mgm2"])
def test_deterministic_given_seed(algo):
    dcop = coloring_ring(8, 3)
    r1 = solve(dcop, algo, rounds=40, seed=7)
    r2 = solve(dcop, algo, rounds=40, seed=7)
    assert r1["assignment"] == r2["assignment"]
