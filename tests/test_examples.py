"""The shipped examples/ files must stay loadable and solvable —
they are the documentation's executable surface."""

import os

import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.yamldcop import (
    dcop_yaml,
    load_dcop,
    load_dcop_from_file,
    load_scenario_from_file,
)

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


@pytest.mark.parametrize(
    "fname", ["graph_coloring_3.yaml", "meeting_preferences.yaml"]
)
def test_problem_examples_round_trip(fname):
    dcop = load_dcop_from_file(os.path.join(EXAMPLES, fname))
    again = load_dcop(dcop_yaml(dcop))
    assert set(again.variables) == set(dcop.variables)
    assert set(again.constraints) == set(dcop.constraints)
    # a fixed assignment costs the same through the round trip
    a = {
        n: v.domain.values[0] for n, v in dcop.variables.items()
    }
    assert dcop.solution_cost(a) == again.solution_cost(a)


def test_tutorial_example_solves_to_documented_optimum():
    r = solve(
        os.path.join(EXAMPLES, "graph_coloring_3.yaml"), "dpop"
    )
    assert r["cost"] == 0.0


def test_scenario_example_loads():
    s = load_scenario_from_file(
        os.path.join(EXAMPLES, "dynamic_scenario.yaml")
    )
    events = list(s)
    assert len(events) == 4
    kinds = [
        a.type for e in events if not e.is_delay for a in e.actions
    ]
    assert kinds == ["remove_agent", "add_agent"]


def test_batch_spec_example_expands(tmp_path):
    import subprocess
    import sys
    import json

    r = subprocess.run(
        [
            sys.executable, "-m", "pydcop_tpu", "batch",
            os.path.join(EXAMPLES, "batch_sweep.yaml"), "--simulate",
        ],
        capture_output=True, text=True, timeout=120,
        # isolate from any batch_results.csv in the invoking cwd (the
        # default --result_file would flip run: lines to skip:)
        cwd=str(tmp_path),
        env={
            **os.environ,
            "PYDCOP_TPU_PLATFORM": "cpu",
            "PYTHONPATH": os.path.dirname(EXAMPLES)
            + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        },
    )
    assert r.returncode == 0, r.stderr[-500:]
    assert "9 runs total" in r.stdout  # 3 variants x 3 iterations
    assert r.stdout.count("run: ") == 9