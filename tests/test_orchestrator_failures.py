"""Failure handling + scenario playback on the cross-process runtime.

VERDICT r2 items 4 and 5:

- SIGKILL one agent mid-solve → the orchestrator must fail cleanly
  (clean error naming the dead agent, or watchdog exit 70 if it was
  wedged in the dead collective) within a few seconds, never the 120 s
  socket timeout.
- a scenario replayed across 2 OS processes must assemble the same
  result as the in-process ``run_dynamic`` on the same seed.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ring_yaml(n=12, n_agents=None):
    # maxsum's factor graph has 2n computations (n variables +
    # n factors); the scenario test's oneagent distribution needs at
    # least that many agents
    n_agents = n_agents if n_agents is not None else n
    lines = [
        "name: ring",
        "objective: min",
        "domains:",
        "  colors: {values: [0, 1, 2]}",
        "variables:",
    ]
    for i in range(n):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(n):
        j = (i + 1) % n
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append(f"agents: [{', '.join(f'a{i}' for i in range(n_agents))}]")
    return "\n".join(lines) + "\n"


_SCENARIO = """
events:
  - id: w1
    delay: 0.5
  - id: e1
    actions:
      - type: remove_agent
        agent: a3
  - id: w2
    delay: 0.5
"""


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return env


def _parse_json_tail(text):
    start = text.index("{")
    return json.loads(text[start:])


# multi-process jax.distributed gauntlets — failing since seed on
# this CPU-only image ("Multiprocess computations aren't implemented
# on the CPU backend", ROADMAP open item 5), `slow` for the same
# reason as test_elastic's (PR 6) and test_orchestrator's: in tier-1
# they only burned budget re-reporting a known image limitation.


@pytest.mark.slow
def test_agent_sigkill_fails_orchestrator_fast(tmp_path):
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())
    env = _env()
    port = 9810 + (os.getpid() % 150)

    # a run long enough that the kill lands mid-solve: many small
    # chunks, each a lockstep barrier
    ui_port = port + 171
    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--port", str(port),
            "--nb_agents", "1", "--rounds", "200000",
            "--chunk_size", "8", "--seed", "5",
            # heartbeat must outlast the FIRST chunk's XLA compile on
            # a loaded box (ci_loaded: two suite halves + contention
            # stretched it past 30 s, and the agent was declared dead
            # before the kill even landed); SIGKILL detection is by
            # connection EOF, not heartbeat, so the <20 s bound below
            # is unaffected
            "--heartbeat_timeout", "75", "--abort_grace", "4",
            "--uiport", str(ui_port),
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    agent = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "agent",
            "--names", "a1", "--orchestrator", f"localhost:{port}",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # wait until chunks are actually flowing (/state shows cycle
        # progress) rather than sleeping a fixed 10s — registration +
        # jax init + compile stretch arbitrarily on a loaded box
        # (VERDICT r3 weak #4), then kill the agent mid-solve
        # bare-module import: pytest's prepend mode puts tests/ on
        # sys.path, not the repo root (tests/ has no __init__.py)
        from test_elastic import _wait_state

        _wait_state(
            ui_port, lambda s: s.get("cycle", 0) > 0, 240, "first chunk",
            proc=orch,
        )
        assert orch.poll() is None, (
            "orchestrator finished before the kill — raise rounds"
        )
        agent.send_signal(signal.SIGKILL)
        t_kill = time.monotonic()
        orc_out, orc_err = orch.communicate(timeout=60)
        detect = time.monotonic() - t_kill
        # clean AgentFailureError exit OR watchdog force-exit (70) —
        # never a success, never the 120 s socket timeout.  The bound
        # proves prompt detection (EOF/watchdog), with slack for a
        # loaded CI box.
        assert orch.returncode != 0
        assert detect < 20.0, f"took {detect:.1f}s to fail"
        assert ("died" in orc_err) or ("FATAL" in orc_err), orc_err[-2000:]
    finally:
        for p in (orch, agent):
            if p.poll() is None:
                p.kill()
                p.wait()


@pytest.mark.slow  # multi-process jax.distributed — see note above
def test_scenario_across_processes_matches_inprocess(tmp_path):
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml(n_agents=24))
    scen_file = tmp_path / "scen.yaml"
    scen_file.write_text(_SCENARIO)
    env = _env()
    port = 9960 + (os.getpid() % 30)

    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--port", str(port),
            "--nb_agents", "1", "--rounds", "32", "--chunk_size", "16",
            "--seed", "5", "--scenario", str(scen_file),
            "--ktarget", "1",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agent = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "agent",
            "--names", "a1", "--orchestrator", f"localhost:{port}",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    orc_out, orc_err = orch.communicate(timeout=240)
    ag_out, ag_err = agent.communicate(timeout=30)
    assert orch.returncode == 0, orc_err[-3000:]
    assert agent.returncode == 0, ag_err[-3000:]

    result = _parse_json_tail(orc_out)
    assert result["n_shards"] == 2
    # the scenario actually played: the remove event is in the log
    removes = [
        e for e in result["events"]
        if e.get("action") == "remove_agent"
    ]
    assert len(removes) == 1 and removes[0]["agent"] == "a3"

    # in-process run_dynamic, same seed, same 2-shard mesh
    from pydcop_tpu.dcop.yamldcop import (
        load_dcop_from_file,
        load_scenario,
    )
    from pydcop_tpu.engine.dynamic import run_dynamic
    from pydcop_tpu.parallel import make_mesh

    dcop = load_dcop_from_file(str(yaml_file))
    scenario = load_scenario(_SCENARIO)
    local = run_dynamic(
        dcop,
        "maxsum",
        {},
        scenario,
        k_target=1,
        final_rounds=32,
        seed=5,
        mesh=make_mesh(2),
        n_shards=2,
        chunk_size=16,
    )
    np.testing.assert_allclose(local["cost"], result["cost"], atol=1e-5)
    assert local["lost_computations"] == result["lost_computations"]
    assert local["agents_final"] == result["agents_final"]
