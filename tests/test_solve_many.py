"""Cross-instance batching tests (``ops.compile.stack_problems`` +
``engine.run_many_batched`` + ``api.solve_many``).

Covers the PR-4 acceptance criteria: K same-bucket instances group
into ONE vmapped device program (one runner compile — enforced in
tier-1 by ``tools/recompile_guard.py:run_many_guard``), results are
bit-identical to K sequential ``solve`` calls for deterministic
algorithms, mixed-bucket inputs split into the correct groups, and the
instance axis composes with the restart axis
(``[instance, restart, ...]``).
"""

import numpy as np
import pytest

from pydcop_tpu.api import solve, solve_many
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.ops.compile import (
    compile_dcop,
    problem_group_key,
    stack_problems,
)
from pydcop_tpu.telemetry import session

D = Domain("d", "", [0, 1, 2])


def ring_dcop(n=6, maximize=False):
    dcop = DCOP("ring%d" % n, objective="max" if maximize else "min")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs
            )
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


# -- grouping ----------------------------------------------------------


def test_group_key_same_bucket():
    """Ring sizes 5..8 under pow2:16 land on one bucket key; names
    never split a group."""
    keys = {
        problem_group_key(compile_dcop(ring_dcop(n), pad_policy="pow2:16"))
        for n in (5, 6, 7, 8)
    }
    assert len(keys) == 1


def test_group_key_splits_on_shape_and_objective():
    k5 = problem_group_key(
        compile_dcop(ring_dcop(5), pad_policy="pow2:16")
    )
    k40 = problem_group_key(
        compile_dcop(ring_dcop(40), pad_policy="pow2:16")
    )
    kmax = problem_group_key(
        compile_dcop(ring_dcop(5, maximize=True), pad_policy="pow2:16")
    )
    assert k5 != k40  # different bucket (16 vs 64 variables)
    assert k5 != kmax  # maximize is a traced static


def test_stack_problems_groups_and_indices():
    problems = [
        compile_dcop(ring_dcop(n), pad_policy="pow2:16")
        for n in (5, 40, 6, 48, 7)
    ]
    groups = stack_problems(problems)
    assert [g.indices for g in groups] == [[0, 2, 4], [1, 3]]
    small, big = groups
    assert small.n_instances == 3 and big.n_instances == 2
    # leaves carry the instance axis over the template's shape
    assert small.problem.unary.shape == (3,) + small.template.unary.shape
    # host problems keep the original (named) metadata, stack order
    assert small.host_problems[1].var_names == problems[2].var_names


def test_stack_single_problem_still_stacks():
    [g] = stack_problems([compile_dcop(ring_dcop(5))])
    assert g.n_instances == 1
    assert g.problem.unary.shape[0] == 1


# -- solve_many parity -------------------------------------------------


def test_solve_many_matches_sequential_mgm():
    """Deterministic algorithm (mgm, fixed seed): bit-identical to
    per-instance solve calls under the same pad policy."""
    dcops = [ring_dcop(n) for n in (5, 6, 8)]
    with session() as tel:
        many = solve_many(
            dcops, "mgm", rounds=24, chunk_size=24,
            pad_policy="pow2:16", seed=7,
        )
    counters = tel.summary()["counters"]
    assert counters.get("engine.batch_groups") == 1
    assert counters.get("engine.instances_batched") == 3
    for i, dcop in enumerate(dcops):
        seq = solve(
            dcop, "mgm", rounds=24, chunk_size=24,
            pad_policy="pow2:16", seed=7,
        )
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]
        assert many[i]["final_cost"] == seq["final_cost"]
        assert many[i]["cost_trace"] == seq["cost_trace"]
        assert many[i]["msg_count"] == seq["msg_count"]
        assert many[i]["instances_batched"] == 3


def test_solve_many_mixed_buckets_split_correctly():
    """40-var rings bucket apart from 5/6-var rings: two groups, each
    instance still solved against its own problem."""
    dcops = [ring_dcop(5), ring_dcop(40), ring_dcop(6)]
    with session() as tel:
        many = solve_many(
            dcops, "mgm", rounds=16, chunk_size=16,
            pad_policy="pow2:16", seed=2,
        )
    counters = tel.summary()["counters"]
    assert counters.get("engine.batch_groups") == 2
    assert counters.get("engine.instances_batched") == 3
    assert [r["instances_batched"] for r in many] == [2, 1, 2]
    for i, dcop in enumerate(dcops):
        seq = solve(
            dcop, "mgm", rounds=16, chunk_size=16,
            pad_policy="pow2:16", seed=2,
        )
        assert many[i]["assignment"] == seq["assignment"]
        # every real variable of the right problem is decoded
        assert len(many[i]["assignment"]) == len(dcop.variables)


def test_solve_many_instance_times_restart_axis():
    """n_restarts composes with the instance axis: per-instance
    restart_costs are bit-identical to the sequential restart runs
    (same per-instance seed => same [K, R] RNG streams)."""
    dcops = [ring_dcop(5), ring_dcop(7)]
    seeds = [3, 11]
    many = solve_many(
        dcops, "dsa", {"variant": "B", "probability": 0.7},
        rounds=24, chunk_size=24, pad_policy="pow2:16",
        seed=seeds, n_restarts=4,
    )
    for i, dcop in enumerate(dcops):
        seq = solve(
            dcop, "dsa", {"variant": "B", "probability": 0.7},
            rounds=24, chunk_size=24, pad_policy="pow2:16",
            seed=seeds[i], n_restarts=4,
        )
        assert many[i]["restart_costs"] == seq["restart_costs"]
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]


def test_solve_many_per_instance_numeric_params_share_group():
    """Numeric params may differ per instance inside one group (they
    ride the vmap as stacked arrays); statics must agree."""
    dcops = [ring_dcop(5), ring_dcop(6)]
    plist = [
        {"variant": "B", "probability": 0.5},
        {"variant": "B", "probability": 0.9},
    ]
    many = solve_many(
        dcops, "dsa", plist, rounds=16, chunk_size=16,
        pad_policy="pow2:16", seed=0,
    )
    assert [r["instances_batched"] for r in many] == [2, 2]
    for i, dcop in enumerate(dcops):
        seq = solve(
            dcop, "dsa", plist[i], rounds=16, chunk_size=16,
            pad_policy="pow2:16", seed=0,
        )
        assert many[i]["assignment"] == seq["assignment"]


def test_solve_many_static_params_split_groups():
    """Different static (str) params cannot share a compiled step —
    they partition into separate groups even in one shape bucket."""
    dcops = [ring_dcop(5), ring_dcop(6)]
    with session() as tel:
        many = solve_many(
            dcops, "dsa",
            [
                {"variant": "A", "probability": 0.7},
                {"variant": "B", "probability": 0.7},
            ],
            rounds=8, chunk_size=8, pad_policy="pow2:16",
        )
    assert tel.summary()["counters"].get("engine.batch_groups") == 2
    assert [r["instances_batched"] for r in many] == [1, 1]


def test_solve_many_host_path_dpop_batches():
    """DPOP rides solve_many too now: same-bucket instances merge
    their UTIL phases into one level-synchronous sweep
    (``engine.run_many_host`` → ``dpop.solve_host_many``), keeping the
    per-instance result contract bit-identical to solve.  Deeper
    coverage in tests/test_dpop_level.py and the tier-1 dpop
    recompile guard."""
    dcops = [ring_dcop(4), ring_dcop(5)]
    with session() as tel:
        many = solve_many(dcops, "dpop")
    for i, dcop in enumerate(dcops):
        seq = solve(dcop, "dpop")
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]
    # pow2 padding (the solve_many default) buckets both rings onto
    # one group key, so the two instances merged
    assert [r["instances_batched"] for r in many] == [2, 2]
    assert tel.summary()["counters"].get("dpop.instances_batched") == 2


def test_solve_many_host_path_fallback_syncbb():
    """Host-path algorithms WITHOUT a merged sweep (SyncBB) keep the
    sequential per-instance path."""
    dcops = [ring_dcop(4), ring_dcop(4)]
    many = solve_many(dcops, "syncbb")
    for i, dcop in enumerate(dcops):
        seq = solve(dcop, "syncbb")
        assert many[i]["assignment"] == seq["assignment"]
        assert many[i]["cost"] == seq["cost"]
        assert many[i]["instances_batched"] == 1


def test_solve_many_input_validation():
    assert solve_many([], "mgm") == []
    with pytest.raises(ValueError, match="seeds|seed"):
        solve_many([ring_dcop(5)], "mgm", seed=[1, 2], rounds=4)
    with pytest.raises(ValueError, match="algo_params"):
        solve_many(
            [ring_dcop(5)], "mgm", [{}, {}], rounds=4
        )
    with pytest.raises(ValueError, match="n_restarts"):
        solve_many([ring_dcop(5)], "dpop", n_restarts=3)


# -- engine level ------------------------------------------------------


def test_run_many_donation_off_matches_on():
    """donate=False is the same math (donation only changes buffer
    reuse, never results)."""
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_many_batched

    problems = [
        compile_dcop(ring_dcop(n), pad_policy="pow2:16")
        for n in (5, 6)
    ]
    [stacked] = stack_problems(problems)
    module = load_algorithm_module("mgm")
    params = prepare_algo_params({}, module.algo_params)
    kw = dict(rounds=16, seeds=[1, 2], chunk_size=16)
    on = run_many_batched(stacked, module, params, donate=True, **kw)
    off = run_many_batched(stacked, module, params, donate=False, **kw)
    for a, b in zip(on, off):
        assert a.best_cost == b.best_cost
        assert a.best_assignment == b.best_assignment
        assert np.array_equal(a.cost_trace, b.cost_trace)


def test_run_many_convergence_stops_whole_group():
    """convergence_chunks acts at group level: mgm on tiny rings
    freezes, and the whole stack stops early together."""
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_many_batched

    problems = [
        compile_dcop(ring_dcop(n), pad_policy="pow2:16")
        for n in (5, 6)
    ]
    [stacked] = stack_problems(problems)
    module = load_algorithm_module("mgm")
    params = prepare_algo_params({}, module.algo_params)
    results = run_many_batched(
        stacked, module, params, rounds=400, seeds=0, chunk_size=8,
        convergence_chunks=2,
    )
    assert all(r.status == "converged" for r in results)
    assert results[0].cycles < 400
    assert results[0].cycles == results[1].cycles


def test_run_many_rejects_mismatched_statics():
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_many_batched

    problems = [
        compile_dcop(ring_dcop(n), pad_policy="pow2:16")
        for n in (5, 6)
    ]
    [stacked] = stack_problems(problems)
    module = load_algorithm_module("dsa")
    plist = [
        prepare_algo_params(
            {"variant": v, "probability": 0.7}, module.algo_params
        )
        for v in ("A", "B")
    ]
    with pytest.raises(ValueError, match="static"):
        run_many_batched(stacked, module, plist, rounds=4)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
