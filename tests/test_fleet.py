"""Self-healing replicated serving fleet (``engine/fleet.py``,
``commands/fleet.py``, the replication hooks in
``engine/service.py`` — ``docs/serving.md`` "The fleet").

Covers the tentpole acceptance criteria at every layer:

- the :class:`HashRing` placement is pure, balanced, and keeps the
  FAILOVER target aligned with the REPLICATION target (both walk the
  sorted-name successor chain);
- session delta logs stream primary → standby (the ``standby`` /
  ``replicate`` wire ops) and apply incrementally (prefix-matched
  tail replay) or as a rebuild, with tombstones on close;
- the :class:`FleetRouter` re-pins a killed replica's sessions to
  the standby on the very next frame, and a failover retry of an
  ALREADY-ANSWERED request replays the replicated reply instead of
  re-solving (exactly-once);
- ``replica_kill`` joins the chaos symmetry table: accepted by the
  fleet CLI only, rejected with a pointer at every other entry
  point, victim choice a pure function of the seed;
- the ``serve`` satellites: per-process checkpoint/flight paths
  under a shared directory, structured ``--resume`` failures for
  all three broken-checkpoint shapes.

The 2-replica SIGKILL smoke (real subprocesses, real ``SIGKILL``) is
tier-1; the 4-replica / 32-client seeded chaos soak — zero lost
sessions, bit-identical to an unkilled control, seeded replay
bit-for-bit — is ``slow``.  The compile-side acceptance (takeover
replays ``compile.incremental``-only with ZERO XLA compiles on the
standby's warm cache) is counter-asserted in tier-1 by
``tests/test_recompile_guard.py::test_fleet_guard_failover_zero_xla_compiles``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from pydcop_tpu.engine.fleet import (
    FleetError,
    FleetRouter,
    HashRing,
    Replica,
    ring_key,
    standby_map,
)
from pydcop_tpu.engine.service import (
    ServiceClient,
    ServiceError,
    ServiceServer,
    SolverService,
)

pytestmark = pytest.mark.service

#: session segments are tiny on purpose — determinism, not quality
SKW = dict(rounds=8, chunk_size=8, seed=5)

SENSOR_YAML = """name: ext
objective: min
domains:
  colors: {values: [0, 1, 2]}
variables:
  v0: {domain: colors}
  v1: {domain: colors}
  v2: {domain: colors}
external_variables:
  sensor: {domain: colors, initial_value: 0}
constraints:
  c0: {type: intention, function: '1 if v0 == v1 else 0'}
  c1: {type: intention, function: '1 if v1 == v2 else 0'}
  track: {type: intention, function: '0 if v0 == sensor else 1'}
agents: [a1]
"""


def _svc(**kw):
    kw.setdefault("max_batch", 1)
    kw.setdefault("max_wait", 0.0)
    return SolverService(autostart=False, **kw)


def _seg(svc, sv=None, name="plant"):
    first = (
        name not in svc._sessions
        and name not in svc._standby_sessions
    )
    return svc.solve(
        SENSOR_YAML if first else None, "dsa", {"variant": "B"},
        session=name, set_values=sv, **SKW,
    )


def _addr(server) -> str:
    return "%s:%d" % server.address


def _raw_call(address, frame, timeout=120):
    """One frame over a fresh raw socket — for tests that pin the
    idempotency key across resends (a real client mints a new one
    per logical request)."""
    if isinstance(address, str):
        host, _, port = address.rpartition(":")
        address = (host, int(port))
    with socket.create_connection(address, timeout=timeout) as s:
        s.sendall((json.dumps(frame) + "\n").encode("utf-8"))
        line = s.makefile("rb").readline()
    return json.loads(line)


# -- the hash ring: pure placement, failover == replication -------------


def test_ring_placement_pure_balanced_failover_aligned():
    names = [f"r{i}" for i in range(4)]
    ring = HashRing(names)
    ring2 = HashRing(list(reversed(names)))  # order-insensitive
    keys = [f"s:sess-{i}" for i in range(400)]
    owners = [ring.lookup(k) for k in keys]
    assert owners == [ring2.lookup(k) for k in keys]
    counts = {n: owners.count(n) for n in names}
    assert all(counts[n] > 0 for n in names), counts
    # THE invariant the tentpole rides on: the replica a key fails
    # over to is the replica its owner replicates to — next_alive
    # and successors walk the same chain
    for k in keys[:64]:
        owner = ring.lookup(k)
        chain = ring.successors(owner, 2)
        assert ring.next_alive(owner, frozenset()) == owner
        assert (
            ring.next_alive(owner, frozenset({owner})) == chain[0]
        )
        assert (
            ring.next_alive(owner, frozenset({owner, chain[0]}))
            == chain[1]
        )
    assert standby_map(names, k=2) == {
        n: ring.successors(n, 2) for n in names
    }
    # the standby chain caps at the OTHER replicas that exist
    assert len(ring.successors("r0", 99)) == 3
    with pytest.raises(FleetError, match="all marked dead"):
        ring.next_alive("r0", frozenset(names))


def test_ring_key_pins_sessions_by_name_stateless_by_payload():
    k1, s1 = ring_key({"op": "solve", "session": "plant", "dcop": "x"})
    k2, s2 = ring_key({"op": "solve", "session": "plant"})
    assert k1 == k2 == "s:plant" and s1 == s2 == "plant"
    k3, s3 = ring_key({"op": "solve", "dcop": "yaml-a"})
    k4, _ = ring_key({"op": "solve", "dcop": "yaml-a"})
    k5, _ = ring_key({"op": "solve", "dcop": "yaml-b"})
    assert s3 is None
    assert k3 == k4 != k5


def test_router_pick_owner_is_sticky_then_walks_the_chain():
    router = FleetRouter(
        {"r0": "h:1", "r1": "h:2", "r2": "h:3"}, autostart=False
    )
    try:
        key = "s:plant"
        home = router.ring.lookup(key)
        assert router._pick_owner(key, None, frozenset()) == home
        # sticky: a session's recorded owner wins over the ring...
        prev = router.ring.successor(home)
        assert router._pick_owner(key, prev, frozenset()) == prev
        # ...until it dies, then the chain walks past it
        assert (
            router._pick_owner(key, prev, frozenset({prev}))
            == router.ring.successor(prev)
        )
    finally:
        router.close()


# -- session replication: entries, modes, promotion, tombstones ---------


def test_session_entry_apply_modes_promotion_and_parity():
    with _svc() as primary, _svc() as standby:
        _seg(primary)
        e1 = primary.session_entry("plant")
        assert e1["segments"] == 1 and e1["deltas"] == []
        assert standby.apply_replica_entry(e1)["mode"] == "rebuild"
        _seg(primary, {"sensor": 2})
        e2 = primary.session_entry("plant")
        assert e2["deltas"] == [{"sensor": 2}]
        # the delta log EXTENDS the copy: tail-only replay
        info = standby.apply_replica_entry(e2)
        assert info == {"mode": "incremental", "segments": 2}
        # a duplicate (at-least-once delivery) never regresses
        assert standby.apply_replica_entry(e2)["segments"] == 2
        # takeover: the standby's follow-up continues the segment
        # sequence bit-identically to the undisturbed primary
        got = _seg(standby, {"sensor": 1})
        ref = _seg(primary, {"sensor": 1})
        assert got["segment"] == ref["segment"] == 3
        assert got["cost"] == ref["cost"]
        assert got["assignment"] == ref["assignment"]
        assert standby.stats()["sessions_promoted"] == 1
        assert standby.stats()["replica_updates"] == 3
        # tombstone drops a standby copy that never promoted
        standby.apply_replica_entry(e1 | {"name": "other"})
        assert (
            standby.apply_replica_entry(
                {"name": "other", "closed": True}
            )["mode"]
            == "closed"
        )
        assert "other" not in standby._standby_sessions


def test_wire_replication_streams_segments_and_reply_cache():
    """The wire half: ``set_standbys`` + per-segment ``replicate``
    frames reach the standby BEFORE the primary's reply leaves (any
    client-observable answer is already recoverable), and the
    piggybacked reply cache answers a resend of the original ikey on
    the standby WITHOUT admitting a solve."""
    with _svc() as p_svc, _svc() as s_svc:
        with ServiceServer(p_svc, port=0) as p_srv, ServiceServer(
            s_svc, port=0
        ) as s_srv:
            assert p_svc.set_standbys([_addr(s_srv)]) == 0
            frame = {
                "op": "solve", "id": 1, "cid": "t",
                "ikey": "t:fleet:1", "dcop": SENSOR_YAML,
                "algo": "dsa", "params": {"variant": "B"},
                "session": "plant", **SKW,
            }
            r1 = _raw_call(_addr(p_srv), frame)
            assert r1["ok"] and r1["result"]["segment"] == 1
            # replication is synchronous with the reply: the copy is
            # already on the standby
            assert s_svc.stats()["standby_sessions"] == 1
            assert s_svc.stats()["replica_updates"] == 1
            assert p_svc.stats()["replicated_segments"] >= 1
            # exactly-once across failover: the SAME frame resent to
            # the standby replays the piggybacked reply — no solve
            # is admitted, the result is byte-identical
            r2 = _raw_call(_addr(s_srv), frame)
            assert {k: v for k, v in r2.items() if k != "id"} == {
                k: v for k, v in r1.items() if k != "id"
            }
            assert s_svc.stats()["requests"] == 0


# -- the router: failover, exactly-once, fleet ops ----------------------


def test_router_replays_by_ikey_and_answers_fleet_ops():
    with _svc() as svc:
        with ServiceServer(svc, port=0) as srv:
            with FleetRouter({"r0": _addr(srv)}) as router:
                addr = "%s:%d" % router.address
                assert _raw_call(
                    addr, {"op": "ping", "id": 1}
                ) == {"ok": True, "pong": True, "fleet": True,
                      "id": 1}
                frame = {
                    "op": "solve", "id": 2, "cid": "t",
                    "ikey": "t:router:1", "dcop": SENSOR_YAML,
                    "algo": "dsa", "params": {"variant": "B"},
                    **SKW,
                }
                r1 = _raw_call(addr, frame)
                assert r1["ok"]
                # a retry of an answered request replays at the
                # router without touching a replica again
                r2 = _raw_call(addr, frame)
                assert {
                    k: v for k, v in r2.items() if k != "id"
                } == {k: v for k, v in r1.items() if k != "id"}
                assert svc.stats()["requests"] == 1
                stats = router.stats()
                assert stats["replayed_replies"] == 1
                assert stats["requests"] == 2
                # aggregate stats op carries fleet + per-replica rows
                doc = _raw_call(addr, {"op": "stats", "id": 3})
                assert doc["stats"]["fleet"]["replicas"] == 1
                assert "r0" in doc["stats"]["replicas"]
                bad = _raw_call(addr, {"op": "nope", "id": 4})
                assert not bad["ok"] and "unknown op" in bad["error"]


def _mutual_pair():
    """Two service+server replicas wired as each other's standby,
    named so the ring can be asked who owns what."""
    p = _svc()
    p.start()
    s = _svc()
    s.start()
    p_srv = ServiceServer(p, port=0)
    s_srv = ServiceServer(s, port=0)
    p.set_standbys([_addr(s_srv)])
    s.set_standbys([_addr(p_srv)])
    return (p, p_srv), (s, s_srv)


def test_router_failover_repins_session_and_preserves_results():
    """A killed owner's session resumes on its standby on the very
    next frame — same segment sequence, results bit-identical to a
    service that never failed over, failover visible in stats."""
    (a_svc, a_srv), (b_svc, b_srv) = _mutual_pair()
    try:
        with FleetRouter(
            {"r0": _addr(a_srv), "r1": _addr(b_srv)}
        ) as router:
            owner = router.ring.lookup("s:plant")
            by_name = {
                "r0": (a_svc, a_srv), "r1": (b_svc, b_srv)
            }
            victim_svc, victim_srv = by_name[owner]
            with ServiceClient(
                "%s:%d" % router.address, retry_window=30.0
            ) as cli:
                r1 = cli.solve(
                    SENSOR_YAML, "dsa", {"variant": "B"},
                    session="plant", **SKW,
                )
                assert r1["segment"] == 1
                r2 = cli.solve(
                    algo="dsa", session="plant",
                    set_values={"sensor": 2}, **SKW,
                )
                assert r2["segment"] == 2
                assert victim_svc.stats()["sessions"] == 1
                # SIGKILL equivalent: the owner vanishes mid-life
                victim_srv.close()
                victim_svc.close()
                r3 = cli.solve(
                    algo="dsa", session="plant",
                    set_values={"sensor": 1}, **SKW,
                )
                assert r3["segment"] == 3
                assert cli.close_session("plant") is True
            stats = router.stats()
            assert stats["failovers"] >= 1
            assert stats["dead"] == [owner]
            assert stats["marked_dead"] == 1
        # bit-identical to the no-failure control
        with _svc() as control:
            _seg(control)
            _seg(control, {"sensor": 2})
            ref = _seg(control, {"sensor": 1})
        assert r3["cost"] == ref["cost"]
        assert r3["assignment"] == ref["assignment"]
    finally:
        for svc, srv in ((a_svc, a_srv), (b_svc, b_srv)):
            srv.close()
            svc.close()


def test_failover_retry_replays_replicated_reply_exactly_once():
    """The deep exactly-once path: the owner answers (and — before
    the reply leaves — piggybacks it onto the standby's reply
    cache), then dies; the client's retry of the SAME frame through
    the router lands on the standby and replays the replicated
    reply — the standby never admits a solve for it."""
    (a_svc, a_srv), (b_svc, b_srv) = _mutual_pair()
    try:
        with FleetRouter(
            {"r0": _addr(a_srv), "r1": _addr(b_srv)}
        ) as router:
            addr = "%s:%d" % router.address
            owner = router.ring.lookup("s:plant")
            victim_svc, victim_srv = {
                "r0": (a_svc, a_srv), "r1": (b_svc, b_srv)
            }[owner]
            standby_svc = b_svc if victim_svc is a_svc else a_svc
            frame = {
                "op": "solve", "id": 1, "cid": "t",
                "ikey": "t:eo:1", "dcop": SENSOR_YAML,
                "algo": "dsa", "params": {"variant": "B"},
                "session": "plant", **SKW,
            }
            r1 = _raw_call(addr, frame)
            assert r1["ok"] and r1["result"]["segment"] == 1
            victim_srv.close()
            victim_svc.close()
            # defeat the router's own reply cache so the retry MUST
            # go to the wire — the layer under test is the standby's
            # replicated cache
            with router._lock:
                router._replies.clear()
            r2 = _raw_call(addr, frame)
            assert {k: v for k, v in r2.items() if k != "id"} == {
                k: v for k, v in r1.items() if k != "id"
            }
            assert standby_svc.stats()["requests"] == 0
            assert router.stats()["failovers"] >= 1
            # a genuinely NEW follow-up then promotes the replica
            # copy and solves exactly once
            r3 = _raw_call(
                addr,
                {
                    "op": "solve", "id": 2, "cid": "t",
                    "ikey": "t:eo:2", "algo": "dsa",
                    "session": "plant",
                    "set_values": {"sensor": 2}, **SKW,
                },
            )
            assert r3["ok"] and r3["result"]["segment"] == 2
            assert standby_svc.stats()["requests"] == 1
            assert standby_svc.stats()["sessions_promoted"] == 1
    finally:
        for svc, srv in ((a_svc, a_srv), (b_svc, b_srv)):
            srv.close()
            svc.close()


def test_router_health_degrades_and_revives():
    router = FleetRouter(
        {"r0": "h:1", "r1": "h:2"}, autostart=False
    )
    try:
        assert router.health()["status"] == "ok"
        router.mark_dead("r0")
        h = router.health()
        assert h["status"] == "degraded" and h["fleet"] is True
        assert h["replicas"]["r0"]["alive"] is False
        router.mark_dead("r1")
        assert router.health()["status"] == "down"
        router.mark_alive("r0")
        assert router.dead() == ["r1"]
        assert router.stats()["revived"] == 1
        # idempotent transitions count once
        router.mark_alive("r0")
        assert router.stats()["revived"] == 1
    finally:
        router.close()


# -- chaos symmetry: replica_kill is fleet-only -------------------------


def test_replica_kill_is_seeded_pure_and_fleet_only(tmp_path):
    from pydcop_tpu.api import solve, solve_many
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.faults import FaultPlan, FaultSpecError

    plan = FaultPlan.from_spec("replica_kill=0.25", seed=7)
    assert plan.fleet_faults_configured
    # pure in (seed, spec, size): two plans agree, a pinned :IDX wins
    assert (
        plan.decide_replica_kill(4)
        == FaultPlan.from_spec(
            "replica_kill=0.25", seed=7
        ).decide_replica_kill(4)
    )
    t, victim = plan.decide_replica_kill(4)
    assert t == 0.25 and 0 <= victim < 4
    assert FaultPlan.from_spec(
        "replica_kill=0.25:2", seed=99
    ).decide_replica_kill(4) == (0.25, 2)
    with pytest.raises(FaultSpecError, match="out of range"):
        FaultPlan.from_spec(
            "replica_kill=0.25:2", seed=0
        ).decide_replica_kill(2)

    # rejected with a pointer at every non-fleet entry point
    dcop = load_dcop(SENSOR_YAML)
    with pytest.raises(ValueError, match="fleet --chaos"):
        solve(dcop, "dsa", {}, chaos="replica_kill=1")
    with pytest.raises(ValueError, match="fleet --chaos"):
        solve_many([dcop], "dsa", chaos="replica_kill=1")
    with pytest.raises(ValueError, match="fleet --chaos"):
        SolverService(chaos="replica_kill=1", autostart=False)

    from pydcop_tpu.cli import main

    dcop_file = tmp_path / "s.yaml"
    dcop_file.write_text(SENSOR_YAML)
    with pytest.raises(SystemExit, match="fleet --chaos"):
        main([
            "run", "-a", "dsa", "--chaos", "replica_kill=1",
            str(dcop_file),
        ])


def test_fleet_cli_rejects_foreign_chaos_and_bad_flags():
    from pydcop_tpu.cli import main

    with pytest.raises(SystemExit, match="serve --chaos"):
        main(["fleet", "--chaos", "conn_drop=0.5"])
    with pytest.raises(SystemExit, match="serve --chaos"):
        main(["fleet", "--chaos", "device_oom=4"])
    with pytest.raises(SystemExit, match="run/agent"):
        main(["fleet", "--chaos", "drop=0.5"])
    with pytest.raises(SystemExit, match="does not own attached"):
        main([
            "fleet", "--chaos", "replica_kill=1",
            "--attach", "127.0.0.1:1",
        ])
    with pytest.raises(SystemExit, match="replicas must be"):
        main(["fleet", "--replicas", "0"])
    with pytest.raises(SystemExit, match="resilience must be"):
        main(["fleet", "--resilience", "0"])
    with pytest.raises(SystemExit, match="not host:port"):
        main(["fleet", "--attach", "nonsense"])


# -- serve satellites: per-process paths, structured resume errors ------


def test_serve_per_process_path_resolution():
    from pydcop_tpu.commands.serve import _per_process_path

    assert _per_process_path(None, "sessions", 0) is None
    # an explicit FILE path is taken as-is (single-process usage)
    assert (
        _per_process_path("/x/sess.json", "sessions", 9000)
        == "/x/sess.json"
    )
    # a directory target derives a per-process file: the PORT when
    # one is pinned (stable across restarts, so --resume finds it)…
    got = _per_process_path("/tmp", "sessions", 9000)
    assert got == os.path.join("/tmp", "sessions-9000.json")
    # …and the pid for ephemeral ports (port 0: two replicas must
    # never clobber each other's checkpoints)
    got0 = _per_process_path("/tmp", "flight", 0)
    assert got0 == os.path.join(
        "/tmp", f"flight-pid{os.getpid()}.json"
    )
    # a trailing separator names a directory even before it exists
    assert _per_process_path(
        "/no/such/dir" + os.sep, "sessions", 7
    ) == os.path.join("/no/such/dir", "sessions-7.json")


def test_resume_structured_errors_for_broken_checkpoints(tmp_path):
    """The three broken-checkpoint shapes each fail FAST with a
    structured error naming the problem — never a hang, never a
    silently-empty service (a fleet health watcher then sees a dead
    replica, the failure mode the router is built to absorb)."""
    missing = str(tmp_path / "never-written.json")
    with pytest.raises(ServiceError, match="does not exist"):
        _svc(session_checkpoint=missing, resume=True)

    truncated = tmp_path / "truncated.json"
    truncated.write_text(
        '{"kind": "pydcop_tpu-service-sessions", "ver'
    )
    with pytest.raises(ServiceError, match="not valid JSON"):
        _svc(session_checkpoint=str(truncated), resume=True)

    drifted = tmp_path / "drifted.json"
    drifted.write_text(json.dumps({
        "kind": "pydcop_tpu-service-sessions", "version": 2,
        "sessions": [],
    }))
    with pytest.raises(ServiceError, match="schema version 2"):
        _svc(session_checkpoint=str(drifted), resume=True)

    not_ours = tmp_path / "other.json"
    not_ours.write_text('{"kind": "something-else"}')
    with pytest.raises(
        ServiceError, match="not a service session checkpoint"
    ):
        _svc(session_checkpoint=str(not_ours), resume=True)


# -- subprocess smokes (real processes, real signals) -------------------


def _spawn_serve(args, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu", "serve", *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )
    line = proc.stdout.readline()
    if not line:
        _, err = proc.communicate(timeout=30)
        return proc, None, err
    return proc, json.loads(line), None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_serve_resume_missing_checkpoint_dies_loudly(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc, head, err = _spawn_serve(
        [
            "--port", "0", "--resume",
            "--session_checkpoint", str(tmp_path / "absent.json"),
        ],
        env,
    )
    try:
        assert head is None, head  # startup failed, no serving line
        assert proc.wait(timeout=30) != 0
        assert "does not exist" in err
    finally:
        if proc.poll() is None:
            proc.kill()


def test_serve_directory_paths_are_per_process_and_resumable(
    tmp_path,
):
    """Directory targets for ``--session_checkpoint`` /
    ``--flight_dump`` derive per-process files (here: the pinned
    port), the head line reports the resolved paths, the drain
    writes THERE, and a ``--resume`` restart derives the SAME path
    and finds its own checkpoint."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    port = _free_port()
    args = [
        "--port", str(port),
        "--session_checkpoint", str(tmp_path),
        "--flight_dump", str(tmp_path),
        "--max_wait", "0.0", "--max_batch", "1",
    ]
    ckpt = str(tmp_path / f"sessions-{port}.json")
    proc, head, err = _spawn_serve(args, env)
    try:
        assert head is not None, err
        assert head["session_checkpoint"] == ckpt
        assert head["flight_dump"] == str(
            tmp_path / f"flight-{port}.json"
        )
        with ServiceClient(head["serving"], retry_window=5.0) as cli:
            r = cli.solve(
                SENSOR_YAML, "dsa", session="plant", timeout=120,
                **SKW,
            )
            assert r["segment"] == 1
        proc.send_signal(signal.SIGTERM)
        _, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, err
    doc = json.load(open(ckpt))
    assert [s["name"] for s in doc["sessions"]] == ["plant"]
    assert os.path.exists(tmp_path / f"flight-{port}.json")

    proc2, head2, err2 = _spawn_serve(args + ["--resume"], env)
    try:
        assert head2 is not None, err2
        assert head2["sessions_restored"] == 1
        with ServiceClient(
            head2["serving"], retry_window=5.0
        ) as cli:
            cli.shutdown()
        proc2.communicate(timeout=60)
    finally:
        if proc2.poll() is None:
            proc2.kill()


def test_fleet_sigkill_failover_smoke():
    """Tier-1 failover smoke against REAL processes: two serve
    replicas wired as mutual standbys behind an in-process router;
    the session's ring owner is ``SIGKILL``ed mid-session and the
    next follow-up resumes on the standby — zero lost sessions,
    continued segment sequence, bit-identical to a service that
    never failed over."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    base = [
        "--port", "0", "--max_wait", "0.0", "--max_batch", "1",
    ]
    procs = []
    heads = []
    try:
        for _ in range(2):
            proc, head, err = _spawn_serve(base, env)
            procs.append(proc)
            assert head is not None, err
            heads.append(head)
        addrs = [h["serving"] for h in heads]
        for i, addr in enumerate(addrs):
            with ServiceClient(addr, retry_window=5.0) as cli:
                cli._call("standby", standbys=[addrs[1 - i]])
        with FleetRouter(
            {"r0": addrs[0], "r1": addrs[1]}
        ) as router:
            owner = router.ring.lookup("s:plant")
            victim = procs[int(owner[1:])]
            with ServiceClient(
                "%s:%d" % router.address, retry_window=60.0
            ) as cli:
                r1 = cli.solve(
                    SENSOR_YAML, "dsa", {"variant": "B"},
                    session="plant", timeout=120, **SKW,
                )
                assert r1["segment"] == 1
                r2 = cli.solve(
                    algo="dsa", session="plant",
                    set_values={"sensor": 2}, timeout=120, **SKW,
                )
                assert r2["segment"] == 2
                victim.send_signal(signal.SIGKILL)
                victim.wait(timeout=30)
                r3 = cli.solve(
                    algo="dsa", session="plant",
                    set_values={"sensor": 1}, timeout=120, **SKW,
                )
                assert r3["segment"] == 3
            stats = router.stats()
            assert stats["failovers"] >= 1
            assert stats["dead"] == [owner]
        with _svc() as control:
            _seg(control)
            _seg(control, {"sensor": 2})
            ref = _seg(control, {"sensor": 1})
        assert r3["cost"] == ref["cost"]
        assert r3["assignment"] == ref["assignment"]
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for proc in procs:
            proc.wait(timeout=30)


# -- the seeded chaos soak (slow) ---------------------------------------

SOAK_CLIENTS = 32
SOAK_SEED = 7


def _fleet_soak_run(chaos=None):
    """One fleet life: spawn the CLI (4 replicas), run SOAK_CLIENTS
    sessions through three segments each, return the per-session
    outcome sequences plus the closing fleet stats."""
    args = [
        sys.executable, "-m", "pydcop_tpu", "fleet",
        "--replicas", "4", "--port", "0",
        "--pad_policy", "pow2:16",
        "--max_batch", "8", "--max_wait", "0.05",
    ]
    if chaos:
        args += ["--chaos", chaos, "--chaos_seed", str(SOAK_SEED)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=env, text=True,
    )
    outcomes = {i: [] for i in range(SOAK_CLIENTS)}
    errors = []
    try:
        head = json.loads(proc.stdout.readline())
        addr = head["fleet"]

        def phase(sv):
            def one(i):
                try:
                    with ServiceClient(
                        addr, client_id=f"c{i}",
                        retry_window=120.0, timeout=120.0,
                    ) as cli:
                        r = cli.solve(
                            SENSOR_YAML if sv is None else None,
                            "dsa",
                            {"variant": "B"} if sv is None else None,
                            session=f"sess{i}", set_values=sv,
                            timeout=300, **SKW,
                        )
                    outcomes[i].append((
                        r["segment"], r["cost"],
                        tuple(sorted(r["assignment"].items())),
                    ))
                except Exception as e:  # noqa: BLE001 — recorded,
                    # asserted empty below
                    errors.append((i, repr(e)))

            threads = [
                threading.Thread(target=one, args=(i,), daemon=True)
                for i in range(SOAK_CLIENTS)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(600)
            assert not any(t.is_alive() for t in threads), "hung"

        phase(None)
        phase({"sensor": 2})
        if chaos:
            # the seeded kill must be OBSERVED before the last
            # phase, so every victim-owned session provably fails
            # over at least once
            deadline = time.time() + 120
            while True:
                with ServiceClient(addr, retry_window=10.0) as cli:
                    stats = cli.stats()
                if stats["fleet"]["dead"]:
                    break
                assert time.time() < deadline, "kill never landed"
                time.sleep(0.25)
        phase({"sensor": 1})
        with ServiceClient(addr, retry_window=10.0) as cli:
            stats = cli.stats()
            cli.shutdown()
        proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    assert not errors, errors[:5]
    return outcomes, stats


@pytest.mark.slow
def test_fleet_chaos_soak_zero_lost_sessions_and_bit_replay():
    """The tentpole acceptance soak: a 4-replica fleet serving 32
    wire sessions takes a seeded mid-soak ``replica_kill`` and (1)
    loses ZERO sessions — every session completes all three
    segments, (2) every outcome is bit-identical to an UNKILLED
    control fleet, and (3) a second run with the same seed replays
    bit-for-bit.  Replication/promotion visible in the per-replica
    stats; the compile-side (incremental-only takeover) is pinned by
    the tier-1 fleet recompile guard."""
    # T=15: far enough in that the first two segments of every
    # session are live and replicated when the victim dies (the kill
    # is still OBSERVED before the last phase — the poll in
    # _fleet_soak_run gates on it), so the takeover exercises real
    # session state, not empty replicas
    killed, k_stats = _fleet_soak_run(chaos="replica_kill=15")
    control, _ = _fleet_soak_run(chaos=None)
    replay, r_stats = _fleet_soak_run(chaos="replica_kill=15")

    for i in range(SOAK_CLIENTS):
        assert [s for s, _, _ in killed[i]] == [1, 2, 3], (
            i, killed[i],
        )
    assert killed == control  # bit-identical to the unkilled fleet
    assert killed == replay  # seeded chaos replays bit-for-bit
    assert k_stats["fleet"]["dead"] == r_stats["fleet"]["dead"]
    assert len(k_stats["fleet"]["dead"]) == 1
    # NOTE: no failover-counter assertion here on purpose — when the
    # kill lands while the fleet is idle, the /healthz watcher marks
    # the victim dead before any frame can fail over, and phase 3
    # routes around it cleanly (transport-failure failovers are
    # pinned by the tier-1 mid-session kill tests above)
    promoted = sum(
        rep.get("sessions_promoted", 0)
        for rep in k_stats["replicas"].values()
        if isinstance(rep, dict) and "error" not in rep
    )
    assert promoted >= 1  # victim-owned sessions moved, not re-made
    replicated = sum(
        rep.get("replicated_segments", 0)
        for rep in k_stats["replicas"].values()
        if isinstance(rep, dict) and "error" not in rep
    )
    assert replicated >= SOAK_CLIENTS  # delta logs really streamed


# -- top: fleet roster expansion ----------------------------------------


def test_top_expands_fleet_roster_with_dead_rows_and_total():
    from pydcop_tpu.commands.top import (
        _collect_rows,
        format_fleet_top,
    )
    from pydcop_tpu.telemetry import get_metrics
    from pydcop_tpu.telemetry.export import MetricsExporter

    with _svc() as svc:
        rep_exp = MetricsExporter(
            get_metrics().snapshot, svc.health, port=0
        )
        router = FleetRouter(
            [
                Replica("r0", "h:1", "%s:%d" % rep_exp.address),
                Replica("r1", "h:2", None),
                Replica("r2", "h:3", "127.0.0.1:9"),
            ],
            autostart=False,
        )
        router.mark_dead("r1")
        rt_exp = MetricsExporter(
            get_metrics().snapshot, router.health, port=0
        )
        try:
            rh, rows = _collect_rows(["%s:%d" % rt_exp.address])
            assert rh is not None and rh["fleet"] is True
            assert [r[0] for r in rows] == ["r0", "r1", "r2"]
            by_name = {r[0]: r for r in rows}
            # live replica with an exporter: scraped from its OWN
            # endpoints
            assert by_name["r0"][1] is not None
            assert by_name["r0"][2]["status"] == "ok"
            # a dead replica still gets a row — the view never
            # narrows during an outage
            assert by_name["r1"][2] == {"status": "dead"}
            assert by_name["r2"][2] == {"status": "unreachable"}
            frame = format_fleet_top(rh, rows, {"r0": 1.5})
            assert "fleet: status=degraded" in frame
            assert "dead=['r1']" in frame
            assert frame.splitlines()[-1].startswith("TOTAL")
            assert "unreachable" in frame
        finally:
            router.close()
            rt_exp.close()
            rep_exp.close()


def test_top_single_address_keeps_the_single_serve_view(capsys):
    """One NON-fleet address renders the original single-process
    frame — the fleet view only kicks in for a roster or several
    addresses."""
    from pydcop_tpu.commands import top as top_mod
    from pydcop_tpu.telemetry import get_metrics
    from pydcop_tpu.telemetry.export import MetricsExporter

    with _svc() as svc:
        exp = MetricsExporter(
            get_metrics().snapshot, svc.health, port=0
        )
        try:
            parser_args = type(
                "A", (), {
                    "addresses": ["%s:%d" % exp.address],
                    "interval": 0.1, "count": 1,
                },
            )
            assert top_mod.run_cmd(parser_args) == 0
            out = capsys.readouterr().out
            assert "serve: status=" in out
            assert "TOTAL" not in out
        finally:
            exp.close()


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
