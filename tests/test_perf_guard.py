"""Perf-regression guards: HLO-text assertions on the hot path.

All functional tests run on the CPU backend (conftest), so a TPU
layout regression — e.g. a scatter sneaking into the gather-shaped
Max-Sum round, which cost ~4.6x in round 1 (BASELINE.md) — would pass
CI silently.  These tests pin the *compiled program shape* of the
**TPU lowering** instead: the Max-Sum test forces the gather path
(``CPU_SEGMENT_MIN_EDGES`` monkeypatch — on CPU the production code
deliberately chooses a segment-sum, which is faster THERE but is
exactly the scatter shape the accelerator must never get), and the
round must stay scatter-free within a bounded op count (VERDICT r1,
next-round item 8).

Bounds carry ~2x headroom over the measured values (519 HLO lines, 11
gathers for the step; 165 lines for total_cost, jax 0.8/CPU) so routine
jax upgrades don't trip them, while a structural regression (per-edge
scatter ≈ +E ops, or segment_sum lowering to scatter) does.
"""

import re

import jax
import pytest

from pydcop_tpu.algorithms import load_algorithm_module, prepare_algo_params
from pydcop_tpu.ops import compile_dcop
from pydcop_tpu.ops.costs import total_cost


@pytest.fixture(scope="module")
def coloring_problem():
    import __graft_entry__ as g

    return compile_dcop(g._make_coloring_dcop(64))


def _compiled_text(fn, *args):
    return jax.jit(fn).lower(*args).compile().as_text()


# HLO instruction usage looks like "f32[3,190]{1,0} scatter(...)" —
# or "(f32[..]{0}, s32[..]{0}) scatter(" for tuple-shaped (variadic)
# ops, or "f32[] op(" for scalars.  Match any shape terminator before
# the op name; a plain substring check would also hit op metadata
# (function names).
def _has_op(txt, op):
    return re.search(r"[\]})] %s\(" % op, txt) is not None


def _count_op(txt, op):
    return len(re.findall(r"[\]})] %s\(" % op, txt))


def test_maxsum_round_hlo_is_clean(coloring_problem, monkeypatch):
    problem = coloring_problem
    module = load_algorithm_module("maxsum")
    # pin the TPU lowering shape: on the CPU test backend the
    # aggregations would otherwise take the CPU segment-sum (scatter)
    # path, which is deliberately NOT what runs on the accelerator
    from pydcop_tpu.ops import costs as _costs

    monkeypatch.setattr(_costs, "CPU_SEGMENT_MIN_EDGES", 1 << 60)
    params = prepare_algo_params({"damping": 0.5}, module.algo_params)
    state = module.init_state(problem, jax.random.PRNGKey(0), params)

    def fn(problem, state, key):
        return module.step(problem, state, key, params)

    txt = _compiled_text(fn, problem, state, jax.random.PRNGKey(1))
    assert not _has_op(txt, "scatter"), (
        "single-shard Max-Sum round compiled to a scatter — the "
        "position-major edge layout (ops/compile.py edge_order) or the "
        "gather-based belief path (maxsum.belief_from_r) regressed"
    )
    n_lines = len(txt.splitlines())
    assert n_lines < 1200, (
        f"Max-Sum round HLO grew to {n_lines} lines (measured 519): "
        "op-count regression on the north-star hot path"
    )
    n_gather = _count_op(txt, "gather")
    assert n_gather <= 24, (
        f"Max-Sum round now has {n_gather} gathers (measured 11): "
        "a per-edge or per-degree-slot gather was likely reintroduced"
    )


def test_total_cost_hlo_is_clean(coloring_problem):
    problem = coloring_problem
    values = problem.init_idx
    txt = _compiled_text(lambda p, v: total_cost(p, v), problem, values)
    assert not _has_op(txt, "scatter")
    n_lines = len(txt.splitlines())
    assert n_lines < 500, (
        f"total_cost HLO grew to {n_lines} lines (measured 165)"
    )


@pytest.mark.parametrize(
    "algo,params,max_lines",
    [
        # measured (jax 0.8/CPU, 64-var coloring): dsa 962, mgm 312,
        # mgm2 2739 (5-phase), dba 569, gdba 629 — bounds ~2x
        ("dsa", {"variant": "B", "probability": 0.7}, 2000),
        ("mgm", {}, 700),
        ("mgm2", {"probability": 0.5}, 5500),
        ("dba", {}, 1200),
        ("gdba", {}, 1300),
    ],
)
def test_local_search_round_hlo_is_clean(
    coloring_problem, algo, params, max_lines, monkeypatch
):
    """VERDICT r2 weak #7: the DSA/MGM/MGM-2/DBA/GDBA hot paths had no
    HLO guard, so a scatter regression there passed CI silently."""
    from pydcop_tpu.ops import costs as _costs

    monkeypatch.setattr(_costs, "CPU_SEGMENT_MIN_EDGES", 1 << 60)
    problem = coloring_problem
    module = load_algorithm_module(algo)
    full = prepare_algo_params(params, module.algo_params)
    state = module.init_state(problem, jax.random.PRNGKey(0), full)

    def fn(problem, state, key):
        return module.step(problem, state, key, full)

    txt = _compiled_text(fn, problem, state, jax.random.PRNGKey(1))
    assert not _has_op(txt, "scatter"), (
        f"{algo} round compiled to a scatter — the gather-based "
        "neighbor exchange (ops/costs.py) regressed"
    )
    n_lines = len(txt.splitlines())
    assert n_lines < max_lines, (
        f"{algo} round HLO grew to {n_lines} lines (bound {max_lines}): "
        "op-count regression on a local-search hot path"
    )


def test_sharded_maxsum_round_hlo_is_clean():
    """The axis_name (shard_map) Max-Sum path: segment-sum + psum are
    expected (the sharded aggregation), but per-edge scatters are not,
    and the collective count must stay at one psum per round."""
    import __graft_entry__ as g
    from jax.sharding import PartitionSpec as P

    from pydcop_tpu.parallel import make_mesh
    from pydcop_tpu.parallel.mesh import (
        SHARD_AXIS,
        problem_pspecs,
        shard_problem,
        state_pspecs,
    )

    mesh = make_mesh(2)
    problem = compile_dcop(g._make_coloring_dcop(64), n_shards=2)
    problem = shard_problem(problem, mesh)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({"damping": 0.5}, module.algo_params)
    state = module.init_state(problem, jax.random.PRNGKey(0), params)

    def fn(problem, state, key):
        return module.step(
            problem, state, key, params, axis_name=SHARD_AXIS
        )

    sharded = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(problem_pspecs(problem), state_pspecs(module, problem), P()),
        out_specs=state_pspecs(module, problem),
        check_vma=False,
    )
    txt = _compiled_text(sharded, problem, state, jax.random.PRNGKey(1))
    n_allreduce = _count_op(txt, "all-reduce")
    assert 1 <= n_allreduce <= 2, (
        f"sharded Max-Sum round has {n_allreduce} all-reduces "
        "(design: ONE belief psum per round)"
    )
    n_lines = len(txt.splitlines())
    assert n_lines < 1500, (
        f"sharded Max-Sum round HLO grew to {n_lines} lines"
    )
