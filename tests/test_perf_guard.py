"""Perf-regression guards: HLO-text assertions on the hot path.

All functional tests run on the CPU backend (conftest), so a layout
regression — e.g. a ``segment_sum``/scatter sneaking back into the
single-shard Max-Sum round, which cost ~4.6x in round 1 (BASELINE.md) —
would pass CI silently.  These tests pin the *compiled program shape*
instead: the single-shard round must stay scatter-free and within a
bounded op count (VERDICT r1, next-round item 8).

Bounds carry ~2x headroom over the measured values (519 HLO lines, 11
gathers for the step; 165 lines for total_cost, jax 0.8/CPU) so routine
jax upgrades don't trip them, while a structural regression (per-edge
scatter ≈ +E ops, or segment_sum lowering to scatter) does.
"""

import re

import jax
import pytest

from pydcop_tpu.algorithms import load_algorithm_module, prepare_algo_params
from pydcop_tpu.ops import compile_dcop
from pydcop_tpu.ops.costs import total_cost


@pytest.fixture(scope="module")
def coloring_problem():
    import __graft_entry__ as g

    return compile_dcop(g._make_coloring_dcop(64))


def _compiled_text(fn, *args):
    return jax.jit(fn).lower(*args).compile().as_text()


# HLO instruction usage looks like "f32[3,190]{1,0} scatter(...)" —
# or "(f32[..]{0}, s32[..]{0}) scatter(" for tuple-shaped (variadic)
# ops, or "f32[] op(" for scalars.  Match any shape terminator before
# the op name; a plain substring check would also hit op metadata
# (function names).
def _has_op(txt, op):
    return re.search(r"[\]})] %s\(" % op, txt) is not None


def _count_op(txt, op):
    return len(re.findall(r"[\]})] %s\(" % op, txt))


def test_maxsum_round_hlo_is_clean(coloring_problem):
    problem = coloring_problem
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({"damping": 0.5}, module.algo_params)
    state = module.init_state(problem, jax.random.PRNGKey(0), params)

    def fn(problem, state, key):
        return module.step(problem, state, key, params)

    txt = _compiled_text(fn, problem, state, jax.random.PRNGKey(1))
    assert not _has_op(txt, "scatter"), (
        "single-shard Max-Sum round compiled to a scatter — the "
        "position-major edge layout (ops/compile.py edge_order) or the "
        "gather-based belief path (maxsum.belief_from_r) regressed"
    )
    n_lines = len(txt.splitlines())
    assert n_lines < 1200, (
        f"Max-Sum round HLO grew to {n_lines} lines (measured 519): "
        "op-count regression on the north-star hot path"
    )
    n_gather = _count_op(txt, "gather")
    assert n_gather <= 24, (
        f"Max-Sum round now has {n_gather} gathers (measured 11): "
        "a per-edge or per-degree-slot gather was likely reintroduced"
    )


def test_total_cost_hlo_is_clean(coloring_problem):
    problem = coloring_problem
    values = problem.init_idx
    txt = _compiled_text(lambda p, v: total_cost(p, v), problem, values)
    assert not _has_op(txt, "scatter")
    n_lines = len(txt.splitlines())
    assert n_lines < 500, (
        f"total_cost HLO grew to {n_lines} lines (measured 165)"
    )
