"""Perf-regression guards: HLO-text assertions on the hot path, plus
the tier-1 hook for ``tools/perf_guard.py`` (recorded work-counter
budgets — see the classes at the bottom).

All functional tests run on the CPU backend (conftest), so a TPU
layout regression — e.g. a scatter sneaking into the gather-shaped
Max-Sum round, which cost ~4.6x in round 1 (BASELINE.md) — would pass
CI silently.  These tests pin the *compiled program shape* of the
**TPU lowering** instead: the Max-Sum test forces the gather path
(``CPU_SEGMENT_MIN_EDGES`` monkeypatch — on CPU the production code
deliberately chooses a segment-sum, which is faster THERE but is
exactly the scatter shape the accelerator must never get), and the
round must stay scatter-free within a bounded op count (VERDICT r1,
next-round item 8).

Bounds carry ~2x headroom over the measured values (519 HLO lines, 11
gathers for the step; 165 lines for total_cost, jax 0.8/CPU) so routine
jax upgrades don't trip them, while a structural regression (per-edge
scatter ≈ +E ops, or segment_sum lowering to scatter) does.
"""

import re

import jax
import pytest

from pydcop_tpu.algorithms import load_algorithm_module, prepare_algo_params
from pydcop_tpu.ops import compile_dcop
from pydcop_tpu.ops.costs import total_cost


@pytest.fixture(scope="module")
def coloring_problem():
    import __graft_entry__ as g

    return compile_dcop(g._make_coloring_dcop(64))


def _compiled_text(fn, *args):
    return jax.jit(fn).lower(*args).compile().as_text()


# HLO instruction usage looks like "f32[3,190]{1,0} scatter(...)" —
# or "(f32[..]{0}, s32[..]{0}) scatter(" for tuple-shaped (variadic)
# ops, or "f32[] op(" for scalars.  Match any shape terminator before
# the op name; a plain substring check would also hit op metadata
# (function names).
def _has_op(txt, op):
    return re.search(r"[\]})] %s\(" % op, txt) is not None


def _count_op(txt, op):
    return len(re.findall(r"[\]})] %s\(" % op, txt))


def test_maxsum_round_hlo_is_clean(coloring_problem, monkeypatch):
    problem = coloring_problem
    module = load_algorithm_module("maxsum")
    # pin the TPU lowering shape: on the CPU test backend the
    # aggregations would otherwise take the CPU segment-sum (scatter)
    # path, which is deliberately NOT what runs on the accelerator
    from pydcop_tpu.ops import costs as _costs

    monkeypatch.setattr(_costs, "CPU_SEGMENT_MIN_EDGES", 1 << 60)
    params = prepare_algo_params({"damping": 0.5}, module.algo_params)
    state = module.init_state(problem, jax.random.PRNGKey(0), params)

    def fn(problem, state, key):
        return module.step(problem, state, key, params)

    txt = _compiled_text(fn, problem, state, jax.random.PRNGKey(1))
    assert not _has_op(txt, "scatter"), (
        "single-shard Max-Sum round compiled to a scatter — the "
        "position-major edge layout (ops/compile.py edge_order) or the "
        "gather-based belief path (maxsum.belief_from_r) regressed"
    )
    n_lines = len(txt.splitlines())
    assert n_lines < 1200, (
        f"Max-Sum round HLO grew to {n_lines} lines (measured 519): "
        "op-count regression on the north-star hot path"
    )
    n_gather = _count_op(txt, "gather")
    assert n_gather <= 24, (
        f"Max-Sum round now has {n_gather} gathers (measured 11): "
        "a per-edge or per-degree-slot gather was likely reintroduced"
    )


def test_total_cost_hlo_is_clean(coloring_problem):
    problem = coloring_problem
    values = problem.init_idx
    txt = _compiled_text(lambda p, v: total_cost(p, v), problem, values)
    assert not _has_op(txt, "scatter")
    n_lines = len(txt.splitlines())
    assert n_lines < 500, (
        f"total_cost HLO grew to {n_lines} lines (measured 165)"
    )


@pytest.mark.parametrize(
    "algo,params,max_lines",
    [
        # measured (jax 0.8/CPU, 64-var coloring): dsa 962, mgm 312,
        # mgm2 2739 (5-phase), dba 569, gdba 629 — bounds ~2x
        ("dsa", {"variant": "B", "probability": 0.7}, 2000),
        ("mgm", {}, 700),
        ("mgm2", {"probability": 0.5}, 5500),
        ("dba", {}, 1200),
        ("gdba", {}, 1300),
    ],
)
def test_local_search_round_hlo_is_clean(
    coloring_problem, algo, params, max_lines, monkeypatch
):
    """VERDICT r2 weak #7: the DSA/MGM/MGM-2/DBA/GDBA hot paths had no
    HLO guard, so a scatter regression there passed CI silently."""
    from pydcop_tpu.ops import costs as _costs

    monkeypatch.setattr(_costs, "CPU_SEGMENT_MIN_EDGES", 1 << 60)
    problem = coloring_problem
    module = load_algorithm_module(algo)
    full = prepare_algo_params(params, module.algo_params)
    state = module.init_state(problem, jax.random.PRNGKey(0), full)

    def fn(problem, state, key):
        return module.step(problem, state, key, full)

    txt = _compiled_text(fn, problem, state, jax.random.PRNGKey(1))
    assert not _has_op(txt, "scatter"), (
        f"{algo} round compiled to a scatter — the gather-based "
        "neighbor exchange (ops/costs.py) regressed"
    )
    n_lines = len(txt.splitlines())
    assert n_lines < max_lines, (
        f"{algo} round HLO grew to {n_lines} lines (bound {max_lines}): "
        "op-count regression on a local-search hot path"
    )


def test_sharded_maxsum_round_hlo_is_clean():
    """The axis_name (shard_map) Max-Sum path: segment-sum + psum are
    expected (the sharded aggregation), but per-edge scatters are not,
    and the collective count must stay at one psum per round."""
    import __graft_entry__ as g
    from jax.sharding import PartitionSpec as P

    from pydcop_tpu.parallel import make_mesh
    from pydcop_tpu.parallel.mesh import (
        SHARD_AXIS,
        problem_pspecs,
        shard_map,
        shard_problem,
        state_pspecs,
    )

    mesh = make_mesh(2)
    problem = compile_dcop(g._make_coloring_dcop(64), n_shards=2)
    problem = shard_problem(problem, mesh)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({"damping": 0.5}, module.algo_params)
    state = module.init_state(problem, jax.random.PRNGKey(0), params)

    def fn(problem, state, key):
        return module.step(
            problem, state, key, params, axis_name=SHARD_AXIS
        )

    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(problem_pspecs(problem), state_pspecs(module, problem), P()),
        out_specs=state_pspecs(module, problem),
        check_vma=False,
    )
    txt = _compiled_text(sharded, problem, state, jax.random.PRNGKey(1))
    n_allreduce = _count_op(txt, "all-reduce")
    assert 1 <= n_allreduce <= 2, (
        f"sharded Max-Sum round has {n_allreduce} all-reduces "
        "(design: ONE belief psum per round)"
    )
    n_lines = len(txt.splitlines())
    assert n_lines < 1500, (
        f"sharded Max-Sum round HLO grew to {n_lines} lines"
    )


# ---------------------------------------------------------------------------
# tools/perf_guard.py: recorded work-counter budgets (ISSUE 17)
# ---------------------------------------------------------------------------
# Wall-clock is noise on this box; util_cells / util_dispatches /
# bnb_pruned_cells / jit.compiles are deterministic functions of the
# problem + lowering (the FAQ cost-model sense of "work"), so drift in
# them is a real regression and fails HARD.  Wall-clock only warns
# (wall_ok) under a generous ratio bound.

import importlib.util
import os

_GUARD_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "perf_guard.py",
)


def _load_perf_guard():
    spec = importlib.util.spec_from_file_location(
        "perf_guard", _GUARD_PATH
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def perf_guard_mod():
    return _load_perf_guard()


class TestPerfGuardWorkCounters:
    def test_clean_run_matches_recorded_budgets(self, perf_guard_mod):
        """The blessed workload must reproduce its recorded counters
        exactly, and on a sane machine stay inside the loose
        wall-clock bound — see tools/perf_guard.py:run_perf_guard."""
        report = perf_guard_mod.run_perf_guard()
        assert report["ok"], report["error"]
        assert report["util_cells"] == perf_guard_mod.UTIL_CELLS_BUDGET
        assert (
            report["util_dispatches"]
            == perf_guard_mod.UTIL_DISPATCHES_BUDGET
        )
        assert (
            report["bnb_pruned_cells"]
            == perf_guard_mod.BNB_PRUNED_CELLS_BUDGET
        )
        assert report["jit_compiles"] <= perf_guard_mod.COMPILE_BUDGET
        # wall-clock warns rather than fails, but if the loose bound
        # trips the report must SAY so instead of hiding it
        if not report["wall_ok"]:
            assert "wall_warning" in report

    def test_forced_extra_dispatches_fail_deterministically(
        self, perf_guard_mod
    ):
        """util_batch='node' de-batches the level sweep: the guard
        must fail on the dispatch counter, not on wall-clock."""
        report = perf_guard_mod.run_perf_guard(
            util_batch="node", wall_reps=1
        )
        assert not report["ok"]
        assert "util_dispatches" in report["error"]
        assert (
            report["util_dispatches"]
            != perf_guard_mod.UTIL_DISPATCHES_BUDGET
        )

    def test_disabled_bnb_fails_on_pruned_cells(self, perf_guard_mod):
        """bnb='off' kills pruning: the pruned-cell counter reads 0
        and the guard must fail on it."""
        report = perf_guard_mod.run_perf_guard(bnb="off", wall_reps=1)
        assert not report["ok"]
        assert "bnb_pruned_cells" in report["error"]
        assert report["bnb_pruned_cells"] == 0

    def test_work_counters_are_deterministic(self, perf_guard_mod):
        """Two clean runs agree bit-for-bit on every work counter —
        the property that makes a hard gate on them sound."""
        a = perf_guard_mod.run_perf_guard(wall_reps=1)
        b = perf_guard_mod.run_perf_guard(wall_reps=1)
        for key in (
            "util_cells",
            "util_dispatches",
            "bnb_pruned_cells",
            "best_cost",
        ):
            assert a[key] == b[key], key


class TestDeltaPerfGuard:
    """The O(delta) serving-delta row (ISSUE 18): the blessed warm
    1-delta re-solve is judged on its deterministic re-contraction /
    memo-hit / dispatch counters (hard) and warm-segment compile
    count (hard, zero); wall-clock warns only."""

    def test_clean_run_matches_recorded_budgets(self, perf_guard_mod):
        report = perf_guard_mod.run_delta_perf_guard()
        assert report["ok"], report["error"]
        assert (
            report["memo_hits"]
            == perf_guard_mod.DELTA_MEMO_HITS_BUDGET
        )
        assert (
            report["recontracted"]
            == perf_guard_mod.DELTA_RECONTRACTED_BUDGET
        )
        assert (
            report["warm_dispatches"]
            == perf_guard_mod.DELTA_WARM_DISPATCHES_BUDGET
        )
        assert report["warm_jit_compiles"] == 0
        # hits + re-contractions partition the node set
        assert (
            report["memo_hits"] + report["recontracted"]
            == report["nodes"]
        )
        if not report["wall_ok"]:
            assert "wall_warning" in report

    def test_disabled_memo_fails_on_hit_counter(self, perf_guard_mod):
        """memo_bytes=0 kills memoization: every node re-contracts,
        zero hits — the guard must fail on the memo counters, not
        wall-clock."""
        report = perf_guard_mod.run_delta_perf_guard(
            memo_bytes=0, wall_reps=1
        )
        assert not report["ok"]
        assert "memo_hits" in report["error"]
        assert report["memo_hits"] == 0

    def test_delta_counters_are_deterministic(self, perf_guard_mod):
        a = perf_guard_mod.run_delta_perf_guard(wall_reps=1)
        b = perf_guard_mod.run_delta_perf_guard(wall_reps=1)
        for key in (
            "memo_hits",
            "recontracted",
            "warm_dispatches",
            "best_cost",
            "cold_cost",
        ):
            assert a[key] == b[key], key
