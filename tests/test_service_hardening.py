"""Production hardening of the solver service (``engine/service.py``,
``docs/serving.md`` §failure semantics): overload control (bounded
queue + deadline-aware shedding), graceful drain with session
checkpoint/restore, wire-level chaos (``conn_drop`` / ``slow_client``
/ ``frame_corrupt``) against the idempotent-retry client, frame
validation on both sides of the wire, and the combined wire + device
chaos soak.

Timing discipline matches ``tests/test_service.py``: deterministic
ticks come from ``max_batch == number of submitted requests`` with a
long ``max_wait``; the soak serializes ADMISSION order (each client
releases after the service has admitted its predecessor), which is
what makes stack-lane-keyed fault decisions — and with them the
per-request outcome sequence — reproducible for a fixed seed.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.service import (
    ServiceClient,
    ServiceError,
    ServiceServer,
    SolverService,
)
from pydcop_tpu.telemetry import session

pytestmark = pytest.mark.service

D = Domain("d", "", [0, 1, 2])

#: shared solve shape across this module: the same algo / rounds /
#: chunk / pad policy as tests/test_service.py's coalesce-parity
#: tests, so in-suite this file rides the runner compiles that file
#: already paid instead of adding its own
KW = dict(rounds=24, chunk_size=24)
PAD = "pow2:16"


def ring_yaml(n=6, name="ring"):
    return (
        f"name: {name}\n"
        "objective: min\n"
        "domains:\n"
        "  colors: {values: [0, 1, 2]}\n"
        "variables:\n"
        + "".join(f"  v{i}: {{domain: colors}}\n" for i in range(n))
        + "constraints:\n"
        + "".join(
            f"  c{i}: {{type: intention, "
            f"function: '1 if v{i} == v{(i + 1) % n} else 0'}}\n"
            for i in range(n)
        )
        + "agents: [a1]\n"
    )


RING_YAML = ring_yaml()

SENSOR_YAML = """name: ext
objective: min
domains:
  colors: {values: [0, 1, 2]}
variables:
  v0: {domain: colors}
  v1: {domain: colors}
  v2: {domain: colors}
external_variables:
  sensor: {domain: colors, initial_value: 0}
constraints:
  c0: {type: intention, function: '1 if v0 == v1 else 0'}
  c1: {type: intention, function: '1 if v1 == v2 else 0'}
  track: {type: intention, function: '0 if v0 == sensor else 1'}
agents: [a1]
"""


# -- chaos-kind routing (symmetric validation) --------------------------


def test_wire_chaos_kinds_route_to_the_service_only():
    """Wire kinds are accepted by the service (they inject in the
    frame loop) and rejected everywhere else — the same symmetric
    validation the device kinds got in PR 6."""
    from pydcop_tpu.api import solve, solve_many

    svc = SolverService(
        chaos="conn_drop=0.5,slow_client=0.01,frame_corrupt=0.1",
        autostart=False,
    )
    assert svc.chaos_plan.wire_faults_configured
    # message kinds still rejected by the service
    with pytest.raises(ValueError, match="WIRE"):
        SolverService(chaos="drop=0.5", autostart=False)
    # wire kinds rejected by one-shot solve paths, both modes
    with pytest.raises(ValueError, match="serve --chaos"):
        solve(_ring_dcop(), "dsa", {}, chaos="conn_drop=0.5")
    with pytest.raises(ValueError, match="serve --chaos"):
        solve(_ring_dcop(), "dsa", {}, mode="thread",
              chaos="conn_drop=0.5")
    with pytest.raises(ValueError, match="serve --chaos"):
        solve_many([_ring_dcop()], "dsa", chaos="slow_client=0.1")


def _ring_dcop(n=6, name="ring"):
    dcop = DCOP(name)
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"1 if v{i} == v{(i + 1) % n} else 0", vs
            )
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


# -- overload control ---------------------------------------------------


def test_overload_sheds_bounded_queue_and_deadline():
    """Overload acceptance: a full queue sheds immediately with
    status='shed' (reason queue-full), a request whose deadline the
    service knows it cannot meet sheds with reason deadline, the
    admission-to-reject p99 stays in the microsecond band, and the
    ACCEPTED requests' results are bit-identical to an unloaded
    sequential solve."""
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop

    svc = SolverService(
        pad_policy=PAD, max_queue=4, max_batch=4, max_wait=30.0,
        autostart=False,
    )
    admitted = [
        svc.submit(ring_yaml(name=f"r{i}"), "mgm", {}, seed=i, **KW)
        for i in range(4)
    ]
    over = svc.submit(RING_YAML, "mgm", {}, seed=9, **KW)
    shed = over.result(timeout=5)
    assert shed["status"] == "shed"
    assert shed["shed_reason"] == "queue-full"
    assert shed["queue_depth"] == 4

    # deadline-aware shedding needs a learned tick duration; pin the
    # estimate so the decision is deterministic: (4 queued // 4 per
    # tick) * 1.0s = 1.0s predicted WAIT >= 0.5s end-to-end budget
    # -> shed.  Only the wait triggers the shed — on an EMPTY queue
    # even a tight budget is admitted (the engine truncates it at
    # chunk boundaries instead of an idle service shedding it)
    svc.max_queue = 100
    svc._tick_med = 1.0
    tight = svc.submit(RING_YAML, "mgm", {}, timeout=0.5, seed=9, **KW)
    assert tight.result(timeout=5)["shed_reason"] == "deadline"
    svc2 = SolverService(autostart=False)
    svc2._tick_med = 50.0
    ok_empty = svc2.submit(RING_YAML, "mgm", {}, timeout=0.5, **KW)
    assert not ok_empty.done()  # admitted, not shed, at depth 0
    with svc2._cond:
        svc2._queue.clear()  # discard without dispatching
    svc2.close()

    svc.start()
    results = [p.result(timeout=300) for p in admitted]
    stats = svc.stats()
    svc.close()
    assert stats["shed"] == 2
    assert stats["shed_latency_s"]["p99"] < 0.05  # reject is cheap
    # accepted requests: bit-identical to the unloaded service
    for i, r in enumerate(results):
        seq = solve(
            load_dcop(ring_yaml(name=f"r{i}")), "mgm", {},
            pad_policy=PAD, seed=i, **KW,
        )
        assert r["cost"] == seq["cost"]
        assert r["assignment"] == seq["assignment"]
        assert r["cost_trace"] == seq["cost_trace"]


def test_draining_service_rejects_new_admissions():
    svc = SolverService(autostart=False)
    svc.close()
    with pytest.raises(ServiceError, match="closed"):
        svc.submit(RING_YAML, "mgm", {})


# -- frame validation (symmetric) ---------------------------------------


def test_malformed_and_oversized_frames_keep_the_connection():
    """Satellite: a malformed or oversized frame gets a structured
    error reply and the connection stays alive (newline framing
    resyncs) — it never strands the handler thread or the pipelined
    requests behind it."""
    from pydcop_tpu.engine import service as service_mod

    with SolverService(max_batch=1, autostart=False) as svc:
        with ServiceServer(svc, port=0) as server:
            s = socket.create_connection(server.address)
            r = s.makefile("rb")
            s.sendall(b"this is not json\n")
            rep = json.loads(r.readline())
            assert rep["ok"] is False and "malformed" in rep["error"]
            assert rep["frame_rejected"] is True
            s.sendall(b'"json, but not an object"\n')
            rep = json.loads(r.readline())
            assert "not a JSON object" in rep["error"]
            big = b"x" * (service_mod._MAX_FRAME_BYTES + 64)
            s.sendall(big + b"\n")
            rep = json.loads(r.readline())
            assert "exceeds" in rep["error"]
            # the connection survived all three
            s.sendall(b'{"op": "ping", "id": 1}\n')
            rep = json.loads(r.readline())
            assert rep["ok"] and rep["pong"] and rep["id"] == 1
            s.close()
            assert svc.stats()["frames_rejected"] == 3


def test_client_surfaces_own_rejected_frame_instead_of_hanging():
    """A frame_rejected reply carries id=null (the server could not
    parse an id) — with one request in flight per connection it
    unambiguously belongs to the pending request, so the client must
    surface it as THIS request's error, not skip it and block
    forever waiting for a matching id."""
    from pydcop_tpu.engine import service as service_mod

    with SolverService(max_batch=1, autostart=False) as svc:
        with ServiceServer(svc, port=0) as server:
            with ServiceClient(
                server.address, retry_window=5.0
            ) as cli:
                big = RING_YAML + "# " + "x" * service_mod._MAX_FRAME_BYTES
                with pytest.raises(ServiceError, match="rejected"):
                    cli.solve(big, "mgm", **KW)
                assert cli.ping()  # the connection survived


def test_client_rejects_garbage_reply_frames():
    """The symmetric half: a server sending a corrupt reply frame
    surfaces as a clean retryable failure on the client — with
    retries disabled it raises ServiceError instead of returning
    garbage or hanging."""
    srv = socket.create_server(("127.0.0.1", 0))
    addr = srv.getsockname()

    def fake_server():
        conn, _ = srv.accept()
        conn.makefile("rb").readline()  # the ping frame
        conn.sendall(b"\xff\xfe garbage, not json \xff\n")
        conn.close()

    t = threading.Thread(target=fake_server, daemon=True)
    t.start()
    cli = ServiceClient(addr, retry_window=0)
    with pytest.raises(ServiceError, match="service request failed"):
        cli.ping()
    cli.close()
    srv.close()
    t.join(5)


# -- wire chaos + idempotent retries ------------------------------------


def test_conn_drop_reply_is_replayed_never_resolved():
    """Wire-chaos acceptance: ``conn_drop`` closes the connection
    after the result was computed; the client reconnects with keyed
    backoff and resends under the same idempotency key; the server
    answers from the reply cache — requests counter stays at 1, no
    re-solve."""
    with session() as tel:
        with SolverService(
            pad_policy=PAD, max_batch=1, max_wait=0.0,
            autostart=False, chaos="conn_drop=1:1", chaos_seed=5,
        ) as svc:
            with ServiceServer(svc, port=0) as server:
                with ServiceClient(
                    server.address, client_id="c0", retry_window=30.0
                ) as cli:
                    assert cli.ping()  # reply seq 1: exempt (AFTER=1)
                    r = cli.solve(RING_YAML, "mgm", seed=1, **KW)
                    assert r["status"] == "finished"
                stats = svc.stats()
        counters = dict(tel.summary()["counters"])
    assert stats["requests"] == 1  # the retry never re-solved
    assert stats["replayed_replies"] >= 1
    assert counters.get("service.client_retries", 0) >= 1
    assert counters.get("fault.conn_drop", 0) >= 1


def test_frame_corrupt_and_slow_client_recover():
    """``frame_corrupt`` mangles the reply bytes (framing intact);
    the client's validation rejects it, reconnects, and replays from
    the cache.  ``slow_client`` delays every reply without breaking
    anything."""
    with session() as tel:
        with SolverService(
            pad_policy=PAD, max_batch=1, max_wait=0.0,
            autostart=False,
            chaos="frame_corrupt=1:1,slow_client=0.01", chaos_seed=5,
        ) as svc:
            with ServiceServer(svc, port=0) as server:
                with ServiceClient(
                    server.address, client_id="c1", retry_window=30.0
                ) as cli:
                    assert cli.ping()
                    r = cli.solve(RING_YAML, "mgm", seed=1, **KW)
                    assert r["status"] == "finished"
            assert svc.stats()["requests"] == 1
        counters = dict(tel.summary()["counters"])
    assert counters.get("fault.frame_corrupt", 0) >= 1
    assert counters.get("fault.slow_client", 0) >= 1


def test_inflight_cap_sheds_pipelined_frames():
    """Per-connection backpressure: frames pipelined past
    ``max_inflight`` are answered status='shed' immediately; the
    capped requests below the limit still complete."""
    with SolverService(
        pad_policy=PAD, max_batch=3, max_wait=30.0, autostart=False
    ) as svc:
        # cap 3 + tick at 3 pending: the tick cannot fire (and free
        # in-flight slots) before the handler has read frames 4 and 5
        # off the socket buffer, so exactly two sheds — and the three
        # accepted requests pad to the warm 4-lane runner
        with ServiceServer(svc, port=0, max_inflight=3) as server:
            s = socket.create_connection(server.address)
            r = s.makefile("rb")
            for i in range(5):
                s.sendall(
                    (
                        json.dumps(
                            {
                                "op": "solve", "id": i,
                                "dcop": ring_yaml(name=f"p{i}"),
                                "algo": "mgm", "seed": i, **KW,
                            }
                        )
                        + "\n"
                    ).encode()
                )
            replies = [json.loads(r.readline()) for _ in range(5)]
            s.close()
    # the two frames past the cap were shed, synchronously
    sheds = [
        rep
        for rep in replies
        if rep["ok"] and rep["result"].get("status") == "shed"
    ]
    assert len(sheds) == 2
    for rep in sheds:
        # machine-readable token (clients dispatch on shed_reason)
        assert rep["result"]["shed_reason"] == "inflight-cap"
        assert rep["result"]["max_inflight"] == 3
    done = [
        rep
        for rep in replies
        if rep["ok"] and rep["result"].get("status") == "finished"
    ]
    assert len(done) == 3


def test_retry_of_in_flight_request_attaches_never_resolves_twice():
    """'Never re-solved' covers the IN-FLIGHT window, not just
    completed replies: a retry arriving while the original solve is
    still running (client timeout shorter than the solve) attaches to
    the running PendingResult instead of submitting a duplicate —
    both connections get the answer, the service admits one
    request."""
    # the tick worker stays STOPPED while both frames arrive, so the
    # original is reliably still in flight when the retry lands
    svc = SolverService(
        pad_policy=PAD, max_batch=1, max_wait=0.0, autostart=False
    )
    server = ServiceServer(svc, port=0)
    try:
        frame = {
            "op": "solve", "id": 1, "cid": "r0",
            "ikey": "r0:abcd:1", "dcop": RING_YAML,
            "algo": "mgm", "seed": 3, **KW,
        }
        s1 = socket.create_connection(server.address)
        s1.sendall((json.dumps(frame) + "\n").encode())
        deadline = time.time() + 10
        while svc.stats()["requests"] < 1:
            assert time.time() < deadline
            time.sleep(0.01)
        # the retry, on a fresh connection, same idempotency key
        s2 = socket.create_connection(server.address)
        s2.sendall((json.dumps(frame) + "\n").encode())
        deadline = time.time() + 10
        while svc.stats()["replayed_replies"] < 1:
            assert time.time() < deadline
            time.sleep(0.01)
        svc.start()  # release the solve
        r1 = json.loads(s1.makefile("rb").readline())
        r2 = json.loads(s2.makefile("rb").readline())
        s1.close()
        s2.close()
        stats = svc.stats()
    finally:
        server.close()
        svc.close()
    assert r1["ok"] and r2["ok"]
    assert r1["result"]["cost"] == r2["result"]["cost"]
    assert stats["requests"] == 1  # ONE admitted solve, two replies


# -- drain / checkpoint / restore ---------------------------------------


def test_object_pinned_session_checkpoints_via_dcop_yaml(tmp_path):
    """A session pinned to an in-process DCOP *object* (no wire
    identity) still checkpoints: the drain serializes it through
    ``dcop_yaml`` and a resumed service replays it."""
    from pydcop_tpu.dcop.yamldcop import load_dcop

    ckpt = str(tmp_path / "sessions.json")
    dcop = load_dcop(SENSOR_YAML)  # a real object, not text
    kw = dict(rounds=48, chunk_size=48, seed=7)
    with SolverService(
        max_batch=1, max_wait=0.0, autostart=False,
        session_checkpoint=ckpt,
    ) as svc:
        r1 = svc.solve(dcop, "dsa", {"variant": "B"}, session="s", **kw)
        assert r1["segment"] == 1
        svc.solve(
            None, "dsa", {"variant": "B"}, session="s",
            set_values={"sensor": 2}, **kw,
        )
    doc = json.load(open(ckpt))
    assert len(doc["sessions"]) == 1
    assert doc["sessions"][0]["source"][0] == "yaml"
    assert doc["sessions"][0]["deltas"] == [{"sensor": 2}]
    assert doc["sessions"][0]["segments"] == 2

    svc2 = SolverService(
        max_batch=1, max_wait=0.0, autostart=False,
        session_checkpoint=ckpt, resume=True,
    )
    svc2.start()
    assert svc2.stats()["sessions_restored"] == 1
    r3 = svc2.solve(
        None, "dsa", {"variant": "B"}, session="s",
        set_values={"sensor": 1}, **kw,
    )
    svc2.close()
    assert r3["segment"] == 3
    assert r3["assignment"]["v0"] == 1  # the replayed state carried


def test_session_delta_log_stays_bounded():
    """A resident session streaming deltas forever must not grow its
    checkpoint (and resume replay) with session age: past the bound
    the oldest half folds into one cumulative delta that preserves
    the effective external state."""
    from pydcop_tpu.engine import service as sm

    sess = sm._Session(None, None, ("obj", 1))
    n = sm._DELTA_LOG_MAX + 10
    for i in range(n):
        sess.record_delta({"sensor": i % 3, f"k{i % 7}": i})
    assert len(sess.deltas) <= sm._DELTA_LOG_MAX
    effective: dict = {}
    for d in sess.deltas:
        effective.update(d)
    reference: dict = {}
    for i in range(n):
        reference.update({"sensor": i % 3, f"k{i % 7}": i})
    assert effective == reference


def test_resume_rejects_pad_policy_mismatch(tmp_path):
    ckpt = str(tmp_path / "sessions.json")
    with SolverService(
        max_batch=1, max_wait=0.0, autostart=False,
        session_checkpoint=ckpt,
    ) as svc:
        svc.solve(
            SENSOR_YAML, "dsa", {}, session="s", rounds=8,
            chunk_size=8,
        )
    with pytest.raises(ServiceError, match="pad_policy"):
        SolverService(
            pad_policy="none", autostart=False,
            session_checkpoint=ckpt, resume=True,
        )


# -- trace-summary hardening rows ---------------------------------------


def test_trace_summary_reports_shed_retry_drain_rows(tmp_path, capsys):
    from pydcop_tpu.cli import main
    from pydcop_tpu.telemetry.summary import load_trace, summarize

    path = tmp_path / "serve.jsonl"
    with session(str(path)):
        with SolverService(
            pad_policy=PAD, max_batch=1, max_wait=0.0, max_queue=1,
            autostart=False, chaos="conn_drop=1:1", chaos_seed=5,
        ) as svc:
            with ServiceServer(svc, port=0) as server:
                with ServiceClient(
                    server.address, client_id="t0", retry_window=30.0
                ) as cli:
                    assert cli.ping()
                    cli.solve(RING_YAML, "mgm", seed=1, **KW)
        # one shed after the wire work (queue bound 1, stopped worker)
        svc2 = SolverService(
            pad_policy=PAD, max_queue=1, autostart=False
        )
        svc2.submit(RING_YAML, "mgm", {}, **KW)
        assert (
            svc2.submit(RING_YAML, "mgm", {}, seed=1, **KW)
            .result(5)["status"]
            == "shed"
        )
        svc2.start()
        svc2.close()
    s = summarize(load_trace(str(path)))
    svc_s = s["service"]
    assert svc_s["shed"] == 1
    assert svc_s["client_retries"] >= 1
    assert svc_s["replayed_replies"] >= 1
    assert svc_s["drain_s"] >= 0
    assert main(["trace-summary", str(path)]) == 0
    out = capsys.readouterr().out
    assert "shed=1" in out and "client_retries=" in out


# -- the combined wire + device chaos soak ------------------------------

SOAK_N = 32
SOAK_CHAOS = "conn_drop=0.3,nan_inject=1:3,device_oom=16"
SOAK_SEED = 7


#: the deterministic span/event vocabulary of a stitched timeline —
#: jit-compile / backend-compile records are tagged too but depend on
#: warm-cache state (run 1 compiles, run 2 doesn't), so the replay
#: comparison is over the REQUEST-shaped records only
_SOAK_TIMELINE_NAMES = (
    "client.request", "client.attempt", "service.queue-wait",
    "service.request", "service.dispatch", "service-replay",
    "service-shed", "nan_inject", "device_oom", "device_transient",
)


def _normalized_timelines(trace_path):
    """Stitched per-request timelines reduced to their deterministic
    content: per trace id, the sorted multiset of (kind, name,
    selected args) — durations and wall-clock excluded, plus the
    attempts / server-solve / replay counts."""
    from pydcop_tpu.telemetry.summary import (
        load_trace,
        stitch_requests,
    )

    stitched = stitch_requests([load_trace(trace_path)])
    out = {}
    for tid, req in stitched.items():
        entries = []
        for e in req["timeline"]:
            if e["name"] not in _SOAK_TIMELINE_NAMES:
                continue
            args = e["args"]
            keep = tuple(
                (k, args[k])
                for k in ("attempt", "status", "instances", "reason")
                if k in args
            )
            entries.append((e["kind"], e["name"], keep))
        out[tid] = (
            tuple(sorted(entries)),
            req["attempts"],
            req["server_requests"],
            req["replays"],
        )
    return out


def _run_soak(trace_path=None):
    """One soak pass: SOAK_N concurrent wire clients, admission order
    serialized (client i+1 releases once request i is admitted), one
    32-wide tick under combined wire + device chaos.  Returns the
    per-request (status, cost) outcome sequence (plus the normalized
    stitched timelines when ``trace_path`` is given)."""
    yamls = [ring_yaml(5 + i % 3, name=f"q{i}") for i in range(SOAK_N)]
    results = [None] * SOAK_N
    errors = []
    gates = [threading.Event() for _ in range(SOAK_N)]
    gates[0].set()
    ctx = session(trace_path) if trace_path else _nullcontext()
    with ctx, SolverService(
        pad_policy="pow2:16", max_batch=SOAK_N, max_wait=60.0,
        autostart=False, chaos=SOAK_CHAOS, chaos_seed=SOAK_SEED,
    ) as svc:
        with ServiceServer(svc, port=0) as server:

            def client(i):
                try:
                    with ServiceClient(
                        server.address, client_id=f"c{i}",
                        retry_window=60.0,
                    ) as cli:
                        if not gates[i].wait(120):
                            raise TimeoutError(f"gate {i}")
                        results[i] = cli.solve(
                            yamls[i], "mgm", seed=7, rounds=16,
                            chunk_size=8,
                        )
                except Exception as e:  # noqa: BLE001 — recorded,
                    # asserted empty below
                    errors.append((i, repr(e)))

            threads = [
                threading.Thread(target=client, args=(i,), daemon=True)
                for i in range(SOAK_N)
            ]
            for t in threads:
                t.start()
            # serialized admission: deterministic queue order means
            # deterministic stack lanes, so lane-keyed fault decisions
            # replay per REQUEST, not just in aggregate
            for i in range(1, SOAK_N):
                deadline = time.time() + 120
                while svc.stats()["requests"] < i:
                    if time.time() > deadline:
                        raise TimeoutError(f"admission stalled at {i}")
                    time.sleep(0.002)
                gates[i].set()
            for t in threads:
                t.join(240)
            assert not any(t.is_alive() for t in threads), "hung client"
            # the service survived and still serves
            with ServiceClient(server.address, retry_window=5.0) as c:
                assert c.ping()
            stats = svc.stats()
    assert not errors, errors
    assert stats["requests"] == SOAK_N  # retries never re-admitted
    outcomes = [(r["status"], r["cost"]) for r in results]
    if trace_path is None:
        return outcomes
    return outcomes, _normalized_timelines(trace_path)


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


def test_chaos_soak_one_terminal_status_each_and_reproducible(tmp_path):
    """Chaos-soak acceptance: 32 concurrent clients under combined
    wire + device chaos (conn_drop + nan_inject + device_oom) — no
    client hangs, every request ends in exactly ONE terminal status,
    the service keeps serving throughout, and the same seed
    reproduces the same per-request outcome sequence AND (ISSUE 14)
    identical stitched per-request timelines."""
    first, tl_first = _run_soak(str(tmp_path / "soak1.jsonl"))
    assert len(first) == SOAK_N
    statuses = [s for s, _ in first]
    assert all(s in ("finished", "degraded", "shed") for s in statuses)
    # the faults COMPOSE deterministically: device_oom=16 splits the
    # 32-wide group into two 16-lane halves, and nan_inject=1:3
    # poisons stack lane 3 of each — exactly two degraded requests
    # (admission positions 3 and 19), every other one finished
    assert statuses.count("degraded") == 2
    assert [i for i, s in enumerate(statuses) if s == "degraded"] == [
        3, 19,
    ]
    # trace-context determinism groundwork: every request stitched,
    # and a conn_drop retry whose reply was replayed correlates to
    # the ORIGINAL server spans — exactly ONE service.request per
    # trace id, never a phantom re-solve
    assert len(tl_first) >= SOAK_N  # 32 solves (+ shutdown-less ops)
    retried = [
        tid
        for tid, (_e, attempts, _srv, _rp) in tl_first.items()
        if attempts > 1
    ]
    assert retried, "conn_drop=0.3 produced no retries to check"
    for tid in tl_first:
        _entries, attempts, server_requests, _replays = tl_first[tid]
        if attempts:  # a solve request (ops without traces drop out)
            assert server_requests == 1, (tid, attempts)
    second, tl_second = _run_soak(str(tmp_path / "soak2.jsonl"))
    assert second == first  # seeded chaos replays outcome-for-outcome
    # ISSUE 14 satellite: the telemetry plane replays too — same seed
    # + same admission order ⇒ identical stitched timelines (trace
    # ids, span multisets, attempt/server-solve/replay counts)
    assert tl_second == tl_first


# -- the serve CLI: SIGTERM drain + --resume ----------------------------


def _spawn_serve(args, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu", "serve", "--port", "0",
         *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )
    line = proc.stdout.readline()
    head = json.loads(line)
    return proc, head


def test_serve_sigterm_drains_checkpoints_and_flushes_stats(tmp_path):
    """Satellites: SIGTERM mid-traffic exits 0 through the graceful
    drain — the session checkpoint is written and the final stats
    line reaches stderr on this (previously silent) exit path; a
    restarted ``serve --resume`` reports the restored session and its
    ``set_values`` follow-up continues the segment sequence."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    ckpt = str(tmp_path / "sessions.json")
    cache = str(tmp_path / "xla-cache")
    flight = str(tmp_path / "flight.json")
    args = [
        "--session_checkpoint", ckpt, "--compile_cache", cache,
        "--max_wait", "0.0", "--max_batch", "1",
        "--flight_dump", flight,
    ]
    proc, head = _spawn_serve(args, env)
    try:
        with ServiceClient(head["serving"], retry_window=5.0) as cli:
            r = cli.solve(
                SENSOR_YAML, "dsa", session="plant", rounds=8,
                chunk_size=8, timeout=120,
            )
            assert r["segment"] == 1
            cli.solve(
                algo="dsa", session="plant",
                set_values={"sensor": 2}, rounds=8, chunk_size=8,
            )
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, err
    stats_line = [l for l in err.splitlines() if '"stats"' in l]
    assert stats_line, err  # the final stats flushed on SIGTERM
    stats = json.loads(stats_line[-1])["stats"]
    assert stats["requests"] == 2 and stats["drained"] is True
    doc = json.load(open(ckpt))
    assert [s["name"] for s in doc["sessions"]] == ["plant"]
    assert doc["sessions"][0]["deltas"] == [{"sensor": 2}]
    # ISSUE 14: the SIGTERM graceful drain also dumped the flight
    # recorder (no --trace configured), recent spans on board
    fdoc = json.load(open(flight))
    assert fdoc["kind"] == "pydcop_tpu-flight"
    assert fdoc["trigger"] == "drain"
    assert any(
        r.get("name") == "service.request" for r in fdoc["records"]
    )

    # restart with --resume: the session replays; a follow-up delta
    # continues the segment sequence with the carried state
    proc2, head2 = _spawn_serve(args + ["--resume"], env)
    try:
        assert head2["sessions_restored"] == 1
        with ServiceClient(head2["serving"], retry_window=5.0) as cli:
            r3 = cli.solve(
                algo="dsa", session="plant",
                set_values={"sensor": 1}, rounds=8, chunk_size=8,
                timeout=120,
            )
            assert r3["segment"] == 3
            assert r3["assignment"]["v0"] == 1
            cli.shutdown()
        out2, err2 = proc2.communicate(timeout=60)
    finally:
        if proc2.poll() is None:
            proc2.kill()
    assert proc2.returncode == 0, err2


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
