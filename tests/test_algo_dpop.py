"""DPOP: exactness tests against brute force, plus structure checks."""

import itertools

import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    Domain,
    ExternalVariable,
    Variable,
    VariableWithCostFunc,
)
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)


def brute_force(dcop):
    names = list(dcop.variables)
    doms = [list(dcop.variables[n].domain.values) for n in names]
    best, best_a = None, None
    sign = -1.0 if dcop.objective == "max" else 1.0
    for combo in itertools.product(*doms):
        a = dict(zip(names, combo))
        c = dcop.solution_cost(a)
        if best is None or sign * c < sign * best:
            best, best_a = c, a
    return best, best_a


def random_binary_dcop(n, d, p, seed, objective="min"):
    rng = np.random.RandomState(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP(f"rnd{seed}", objective=objective)
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        for j in range(i + 1, n):
            if rng.rand() < p:
                m = rng.uniform(0, 10, (d, d)).round(2)
                dcop.add_constraint(
                    NAryMatrixRelation([vs[i], vs[j]], m, name=f"c{i}_{j}")
                )
    return dcop


@pytest.mark.parametrize("seed", range(5))
def test_dpop_optimal_on_random_binary(seed):
    dcop = random_binary_dcop(7, 3, 0.45, seed)
    opt, _ = brute_force(dcop)
    result = solve(dcop, "dpop")
    assert result["cost"] == pytest.approx(opt, abs=1e-6)
    assert result["status"] == "finished"
    # the returned assignment really has the returned cost
    assert dcop.solution_cost(result["assignment"]) == pytest.approx(
        result["cost"], abs=1e-6
    )


def test_dpop_optimal_with_nary_constraints():
    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("nary")
    vs = [Variable(f"v{i}", dom) for i in range(5)]
    for v in vs:
        dcop.add_variable(v)
    dcop.add_constraint(
        constraint_from_str("t0", "abs(v0 + v1 - 2 * v2)", vs)
    )
    dcop.add_constraint(
        constraint_from_str("t1", "(v2 - v3) ** 2 + v4", vs)
    )
    dcop.add_constraint(constraint_from_str("b0", "v0 * v4", vs))
    opt, _ = brute_force(dcop)
    result = solve(dcop, "dpop")
    assert result["cost"] == pytest.approx(opt, abs=1e-6)


def test_dpop_max_objective():
    dcop = random_binary_dcop(6, 3, 0.5, 11, objective="max")
    opt, _ = brute_force(dcop)
    result = solve(dcop, "dpop")
    assert result["cost"] == pytest.approx(opt, abs=1e-6)


def test_dpop_disconnected_forest():
    dom = Domain("d", "", [0, 1])
    dcop = DCOP("forest")
    vs = [Variable(f"v{i}", dom) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    # two independent pairs
    dcop.add_constraint(
        NAryMatrixRelation(
            [vs[0], vs[1]], np.array([[0.0, 5.0], [5.0, 1.0]]), name="a"
        )
    )
    dcop.add_constraint(
        NAryMatrixRelation(
            [vs[2], vs[3]], np.array([[3.0, 0.0], [2.0, 9.0]]), name="b"
        )
    )
    result = solve(dcop, "dpop")
    assert result["cost"] == 0.0
    assert result["assignment"]["v0"] == 0 and result["assignment"]["v1"] == 0


def test_dpop_variable_costs_and_external():
    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("costs")
    v0 = VariableWithCostFunc("v0", dom, lambda x: x * 0.5)
    v1 = VariableWithCostFunc("v1", dom, lambda x: 2 - x)
    dcop.add_variable(v0)
    dcop.add_variable(v1)
    ext = ExternalVariable("e", dom, value=2)
    dcop.add_variable(ext)
    dcop.add_constraint(
        constraint_from_str("c", "(v0 + v1 - e) ** 2", [v0, v1, ext])
    )
    opt, _ = brute_force(dcop)
    result = solve(dcop, "dpop")
    assert result["cost"] == pytest.approx(opt, abs=1e-6)


def test_dpop_message_accounting():
    dcop = random_binary_dcop(8, 2, 0.4, 3)
    result = solve(dcop, "dpop")
    # 2 messages (UTIL + VALUE) per non-root node
    from pydcop_tpu.graphs.pseudotree import build_computation_graph

    graph = build_computation_graph(dcop)
    non_roots = len(dcop.variables) - len(graph.roots)
    assert result["msg_count"] == 2 * non_roots


def test_dpop_width_guard():
    from pydcop_tpu.algorithms.dpop import solve_host

    dcop = random_binary_dcop(12, 4, 0.9, 0)  # dense → huge width
    with pytest.raises(ValueError, match="max_util_size"):
        solve_host(dcop, {}, max_util_size=100)


# -- bounded-memory exact mode (memory_bound: conditioning search) ------


@pytest.mark.parametrize("seed", range(3))
def test_dpop_memory_bound_stays_exact(seed):
    """memory_bound caps UTIL tables via cut-set conditioning but the
    result stays the brute-force optimum (the MB-DPOP trade: memory
    for time)."""
    dcop = random_binary_dcop(7, 3, 0.6, seed)  # width > 2 w.h.p.
    opt, _ = brute_force(dcop)
    r = solve(dcop, "dpop", {"memory_bound": 27})
    assert r["cost"] == pytest.approx(opt, abs=1e-6)
    assert r["status"] == "finished"
    assert dcop.solution_cost(r["assignment"]) == pytest.approx(opt, abs=1e-6)
    # the run really conditioned: passes = ∏ cut domain sizes > 1
    assert r["conditioning_passes"] == 3 ** len(r["conditioned_vars"])
    assert r["conditioning_passes"] > 1


def test_dpop_memory_bound_solves_rejected_width():
    """An instance the plain width guard rejects solves exactly under
    a memory bound."""
    from pydcop_tpu.algorithms.dpop import solve_host

    dcop = random_binary_dcop(8, 3, 0.7, 1)
    with pytest.raises(ValueError, match="max_util_size"):
        solve_host(dcop, {}, max_util_size=100)
    opt, _ = brute_force(dcop)
    r = solve_host(dcop, {"memory_bound": 100}, max_util_size=100)
    assert r["cost"] == pytest.approx(opt, abs=1e-6)
    assert r["conditioning_passes"] >= 3


def test_dpop_memory_bound_tiny_degrades_to_enumeration():
    """A bound below one variable's row conditions everything —
    exhaustive conditioning search, still exact."""
    dcop = random_binary_dcop(5, 3, 0.8, 2)
    opt, _ = brute_force(dcop)
    r = solve(dcop, "dpop", {"memory_bound": 2})
    assert r["cost"] == pytest.approx(opt, abs=1e-6)
    assert len(r["conditioned_vars"]) >= 4


def test_dpop_memory_bound_max_objective():
    dcop = random_binary_dcop(6, 3, 0.7, 3, objective="max")
    opt, _ = brute_force(dcop)
    r = solve(dcop, "dpop", {"memory_bound": 27})
    assert r["cost"] == pytest.approx(opt, abs=1e-6)


# -- device UTIL phase (VERDICT r1 item 5) ------------------------------


def _random_chain(n=8, d=12, seed=0):
    import random

    rnd = random.Random(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("chain")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        t = np.array(
            [[rnd.uniform(0, 10) for _ in range(d)] for _ in range(d)]
        )
        dcop.add_constraint(
            NAryMatrixRelation([vs[i - 1], vs[i]], t, name=f"c{i}")
        )
    return dcop


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_dpop_device_util_matches_host(seed):
    """f32 device UTIL joins (error-certified) must reproduce the host
    f64 assignment exactly on random-cost problems."""
    dcop = _random_chain(seed=seed)
    r_host = solve(dcop, "dpop", {"util_device": "never"})
    r_dev = solve(dcop, "dpop", {"util_device": "always"})
    assert r_dev["util_backend"] == "device"
    assert r_dev["util_device_nodes"] > 0
    assert r_dev["assignment"] == r_host["assignment"]
    assert r_dev["cost"] == pytest.approx(r_host["cost"])


def test_dpop_device_util_falls_back_on_exact_ties():
    """Symmetric problems have zero decision margins: the certificate
    fails and each tie-heavy node is redone wholesale on host f64 —
    per NODE, so the sweep (and any healthy node's device result)
    keeps going instead of restarting the whole phase."""
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP("sym")
    ws = [Variable(f"w{i}", dom) for i in range(6)]
    for w in ws:
        dcop.add_variable(w)
    for i in range(1, 6):
        dcop.add_constraint(
            NAryMatrixRelation([ws[i - 1], ws[i]], np.eye(3), name=f"e{i}")
        )
    r = solve(dcop, "dpop", {"util_device": "always"})
    assert r["util_host_nodes"] > 0  # the tie-heavy joins fell back
    assert r["cost"] == 0  # and stayed exact


def test_dpop_device_util_repairs_sparse_ties():
    """A FEW exact-tie cells in an otherwise random table must be
    repaired in host f64 (not fall back wholesale) — and the repair
    writes into the argmin table, which must be a writable copy, not
    jax's read-only buffer (ADVICE r2, high)."""
    d = 50
    rnd = np.random.RandomState(7)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("sparse_ties")
    v0, v1 = Variable("v0", dom), Variable("v1", dom)
    dcop.add_variable(v0)
    dcop.add_variable(v1)
    t = rnd.uniform(2, 10, (d, d))
    # 3/50 rows with an exact tie between their two minima; distinct
    # per-row minima so the ROOT's own argmin keeps a healthy margin
    # (a root-level tie would legitimately force the full fallback)
    for row, m in ((3, 1.0), (17, 1.25), (29, 1.5)):
        t[row, 5] = m
        t[row, 31] = m
    dcop.add_constraint(NAryMatrixRelation([v0, v1], t, name="c01"))

    r_dev = solve(dcop, "dpop", {"util_device": "always"})
    r_host = solve(dcop, "dpop", {"util_device": "never"})
    assert r_dev["util_backend"] == "device"  # repaired, no fallback
    assert r_dev["assignment"] == r_host["assignment"]
    assert r_dev["cost"] == pytest.approx(r_host["cost"])
