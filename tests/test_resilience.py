"""Tests for replication (UCS replica placement), repair (reparation
DCOP), and dynamic scenario runs (reference: ``pydcop/replication/`` +
``pydcop run``)."""

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import NAryMatrixRelation, constraint_from_str
from pydcop_tpu.dcop.scenario import EventAction, Scenario, ScenarioEvent
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.engine.dynamic import run_dynamic
from pydcop_tpu.replication import (
    ReplicaDistribution,
    repair_placement,
    replica_distribution,
)
from pydcop_tpu.replication.repair import build_reparation_dcop

D = Domain("d", "", [0, 1, 2])


def ring_dcop(n=4):
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs)
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


# -- replica placement -------------------------------------------------


def test_replicas_prefer_cheap_hosting():
    agents = [
        AgentDef("h", default_hosting_cost=0.0),
        AgentDef("cheap", default_hosting_cost=1.0),
        AgentDef("mid", default_hosting_cost=5.0),
        AgentDef("dear", default_hosting_cost=50.0),
    ]
    dist = Distribution({"h": ["c1"], "cheap": [], "mid": [], "dear": []})
    rep = replica_distribution(dist, agents, k=2)
    # host excluded; two cheapest (route 1 everywhere) win
    assert rep.replicas("c1") == ["cheap", "mid"]


def test_replicas_respect_capacity():
    agents = [
        AgentDef("h", capacity=10),
        AgentDef("small", capacity=1.0, default_hosting_cost=0.0),
        AgentDef("big", capacity=10.0, default_hosting_cost=2.0),
    ]
    dist = Distribution({"h": ["c1", "c2"], "small": [], "big": []})
    rep = replica_distribution(
        dist, agents, k=2, footprint=lambda c: 1.0
    )
    # small takes one replica then is full; big takes the rest
    assert rep.replicas("c1") == ["small", "big"]
    assert rep.replicas("c2") == ["big"]


def test_replicas_multi_hop_route():
    # direct route h->far is 10, but h->relay->far is 1+1
    agents = [
        AgentDef("h", routes={"far": 10.0, "relay": 1.0}),
        AgentDef("relay", routes={"h": 1.0, "far": 1.0}),
        AgentDef(
            "far",
            routes={"h": 10.0, "relay": 1.0},
            default_hosting_cost=0.0,
        ),
    ]
    dist = Distribution({"h": ["c1"], "relay": [], "far": []})
    rep = replica_distribution(dist, agents, k=2)
    assert set(rep.replicas("c1")) == {"relay", "far"}
    # ordering by cost: relay at path 1 + hosting 0 = 1, far at 2 + 0 = 2
    assert rep.replicas("c1") == ["relay", "far"]


def test_replica_distribution_repr_roundtrip():
    from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

    rep = ReplicaDistribution({"c1": ["a1", "a2"], "c2": []})
    assert from_repr(simple_repr(rep)) == rep


# -- repair ------------------------------------------------------------


def test_reparation_dcop_shape():
    agents = {
        "a1": AgentDef("a1", default_hosting_cost=1.0),
        "a2": AgentDef("a2", default_hosting_cost=2.0),
    }
    dcop = build_reparation_dcop(
        {"c1": ["a1", "a2"], "c2": ["a1", "a2"]}, agents
    )
    assert sorted(dcop.variables) == ["c1", "c2"]
    # unary hosting costs + one concentration constraint
    assert "host_c1" in dcop.constraints
    assert "conc_c1_c2" in dcop.constraints


def test_repair_spreads_on_capacity():
    agents = [
        AgentDef("a1", default_hosting_cost=0.0),
        AgentDef("a2", default_hosting_cost=0.1),
    ]
    placed = repair_placement(
        {"c1": ["a1", "a2"], "c2": ["a1", "a2"]},
        agents,
        remaining_capacity={"a1": 1.0, "a2": 1.0},
        footprint=lambda c: 1.0,
        seed=1,
    )
    # both hosted, on different agents (capacity 1 each)
    assert sorted(placed) == ["c1", "c2"]
    assert placed["c1"] != placed["c2"]


def test_repair_lost_computation():
    placed = repair_placement(
        {"c1": ["a1"], "c2": []}, [AgentDef("a1")]
    )
    assert placed == {"c1": "a1"}


def test_repair_capacity_no_feasible_candidate():
    """Hard-capacity projection when an orphan has NO feasible
    candidate: it is dropped from the returned placement (lost — the
    caller degrades), while feasible orphans still land and never
    overfill an agent."""
    agents = [
        AgentDef("a1", default_hosting_cost=0.0),
        AgentDef("a2", default_hosting_cost=0.1),
    ]
    # big's footprint (3.0) exceeds every agent's remaining capacity;
    # small (1.0) fits exactly one agent
    placed = repair_placement(
        {"big": ["a1", "a2"], "small": ["a1", "a2"]},
        agents,
        remaining_capacity={"a1": 1.0, "a2": 0.0},
        footprint=lambda c: 3.0 if c == "big" else 1.0,
        seed=1,
    )
    assert placed == {"small": "a1"}

    # zero capacity everywhere: nothing can be re-hosted at all
    placed = repair_placement(
        {"c1": ["a1", "a2"]},
        agents,
        remaining_capacity={"a1": 0.0, "a2": 0.0},
        footprint=lambda c: 1.0,
        seed=1,
    )
    assert placed == {}

    # an agent missing from the capacity map counts as capacity 0,
    # not unlimited (the conservative reading of "unknown")
    placed = repair_placement(
        {"c1": ["a3"]},
        [AgentDef("a3")],
        remaining_capacity={},
        footprint=lambda c: 1.0,
    )
    assert placed == {}


def test_repair_single_candidate_no_engine():
    # all-singleton candidate lists take the fast path (no solve)
    placed = repair_placement(
        {"c1": ["a2"], "c2": ["a3"]},
        [AgentDef("a2"), AgentDef("a3")],
    )
    assert placed == {"c1": "a2", "c2": "a3"}


# -- dynamic runs ------------------------------------------------------


def test_dynamic_no_scenario():
    result = run_dynamic(
        ring_dcop(), "dsa", {"variant": "B"}, final_rounds=40, seed=2
    )
    assert result["status"] == "finished"
    assert sorted(result["assignment"]) == ["v0", "v1", "v2", "v3"]
    assert result["lost_computations"] == []


def test_dynamic_remove_agent_with_replica():
    scenario = Scenario(
        [
            ScenarioEvent("e1", actions=[EventAction("remove_agent", agent="a0")]),
            ScenarioEvent(delay=0.5),
        ]
    )
    result = run_dynamic(
        ring_dcop(),
        "dsa",
        {"variant": "B"},
        scenario=scenario,
        k_target=1,
        final_rounds=40,
        seed=3,
    )
    # v0's computation migrated to a replica holder: nothing lost
    assert result["lost_computations"] == []
    assert "a0" not in result["agents_final"]
    removal = [
        e for e in result["events"] if e.get("action") == "remove_agent"
    ][0]
    assert removal["orphaned"] == ["v0"]
    assert removal["migrated"]["v0"] in {"a1", "a2", "a3"}
    # the full assignment (ring is 3-colorable → cost 0 reachable)
    assert len(result["assignment"]) == 4
    assert result["cost"] == 0.0


def test_dynamic_remove_agent_without_replica_freezes():
    scenario = Scenario(
        [ScenarioEvent("e1", actions=[EventAction("remove_agent", agent="a0")])]
    )
    result = run_dynamic(
        ring_dcop(),
        "dsa",
        {"variant": "B"},
        scenario=scenario,
        k_target=0,
        final_rounds=40,
        seed=4,
    )
    assert result["lost_computations"] == ["v0"]
    # frozen variable still reported in the assignment
    assert "v0" in result["assignment"]
    # the others keep optimizing around the frozen value
    assert result["cost"] <= 1.0


def test_dynamic_cascading_removals():
    scenario = Scenario(
        [
            ScenarioEvent("e1", actions=[EventAction("remove_agent", agent="a0")]),
            ScenarioEvent(delay=0.2),
            ScenarioEvent("e2", actions=[EventAction("remove_agent", agent="a1")]),
            ScenarioEvent(delay=0.2),
        ]
    )
    result = run_dynamic(
        ring_dcop(),
        "dsa",
        {"variant": "B"},
        scenario=scenario,
        k_target=2,
        final_rounds=30,
        seed=5,
    )
    # k=2 replication survives two departures
    assert result["lost_computations"] == []
    assert sorted(result["agents_final"]) == ["a2", "a3"]
    assert result["cost"] == 0.0


def test_dynamic_add_agent_hosts_future_repairs():
    scenario = Scenario(
        [
            ScenarioEvent("e1", actions=[EventAction("add_agent", agent="fresh")]),
            ScenarioEvent(delay=0.2),
            ScenarioEvent("e2", actions=[EventAction("remove_agent", agent="a0")]),
        ]
    )
    result = run_dynamic(
        ring_dcop(),
        "dsa",
        {"variant": "B"},
        scenario=scenario,
        k_target=1,
        final_rounds=30,
        seed=6,
    )
    assert "fresh" in result["agents_final"]
    assert result["lost_computations"] == []


def test_dynamic_set_external_value():
    dcop = DCOP("ext")
    v = Variable("v", D)
    e = ExternalVariable("sensor", D, value=0)
    dcop.add_variable(v)
    dcop.add_variable(e)
    # v must track the sensor: cost 0 iff equal
    dcop.add_constraint(
        constraint_from_str("track", "0 if v == sensor else 1", [v, e])
    )
    dcop.add_agents([AgentDef("a0")])
    scenario = Scenario(
        [
            ScenarioEvent(
                "e1",
                actions=[
                    EventAction("set_value", variable="sensor", value=2)
                ],
            ),
        ]
    )
    result = run_dynamic(
        dcop, "dsa", {"variant": "B"}, scenario=scenario, final_rounds=30,
        seed=7,
    )
    assert result["assignment"]["v"] == 2
    assert result["cost"] == 0.0


# -- full-state transfer across segments (VERDICT r3 missing #3) -------


def test_state_transfer_preserves_messages_exactly():
    """run_batched(initial_state=...) must CONTINUE the trajectory,
    not restart it: Max-Sum's step is deterministic given its state,
    so 40 rounds + a 1-round carried continuation must equal a
    41-round continuous run byte-for-byte — the batched equivalent of
    the reference resuming a computation from its replicated state."""
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop

    problem = compile_dcop(ring_dcop(8))
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({}, module.algo_params)

    full = run_batched(
        problem, module, params, rounds=41, seed=4, chunk_size=41,
        return_state=True,
    )
    part = run_batched(
        problem, module, params, rounds=40, seed=4, chunk_size=40,
        return_state=True,
    )
    cont = run_batched(
        problem, module, params, rounds=1, seed=99, chunk_size=1,
        initial_state=part.state, return_state=True,
    )
    for key in ("q", "r", "values"):
        np.testing.assert_array_equal(
            cont.state[key], full.state[key], err_msg=key
        )


def test_state_transfer_rejects_foreign_states():
    """A state from a different algorithm, problem size, or restart
    count must fail loudly — continuing a foreign trajectory would
    silently produce wrong results (review-found gap: the resume path
    validated via checkpoint meta, the raw-pytree path not at all)."""
    import pytest

    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop

    problem = compile_dcop(ring_dcop(8))
    maxsum = load_algorithm_module("maxsum")
    mparams = prepare_algo_params({}, maxsum.algo_params)
    r = run_batched(
        problem, maxsum, mparams, rounds=4, seed=0, chunk_size=4,
        return_state=True,
    )

    # wrong algorithm: dsa's state has different leaves
    dsa = load_algorithm_module("dsa")
    dparams = prepare_algo_params({}, dsa.algo_params)
    with pytest.raises(ValueError, match="different algorithm"):
        run_batched(
            problem, dsa, dparams, rounds=1, seed=0, chunk_size=1,
            initial_state=r.state,
        )
    # wrong problem size
    small = compile_dcop(ring_dcop(6))
    with pytest.raises(ValueError, match="different problem"):
        run_batched(
            small, maxsum, mparams, rounds=1, seed=0, chunk_size=1,
            initial_state=r.state,
        )
    # wrong restart count
    with pytest.raises(ValueError, match="restart count|different"):
        run_batched(
            problem, maxsum, mparams, rounds=1, seed=0, chunk_size=1,
            n_restarts=4, initial_state=r.state,
        )
    # not a state pytree at all
    with pytest.raises(ValueError, match="'values' leaf"):
        run_batched(
            problem, maxsum, mparams, rounds=1, seed=0, chunk_size=1,
            initial_state={"nope": 1},
        )


def test_host_runtime_short_budget_returns_cleanly():
    """A budget/timeout that stops dpop/syncbb before any VALUE wave
    must return a clean result, not crash in solution_cost on None
    values (review-reproduced)."""
    import __graft_entry__ as g
    from pydcop_tpu.infrastructure import solve_host

    dcop = g._make_coloring_dcop(8, degree=2, seed=1)
    for algo in ("dpop", "syncbb"):
        r = solve_host(dcop, algo, mode="sim", max_msgs=3)
        assert r["status"] == "msg_budget"
        assert r["cost"] is None
        assert r["assignment"] == {}


def test_dynamic_run_carries_state_across_events():
    """Scenario segments reuse the full algorithm state whenever the
    recompiled problem is unchanged (delays, clean migrations), and
    drop to value-carry when it is reshaped (a lost variable freezes
    into an external)."""
    dcop = ring_dcop(6)
    scenario = Scenario(
        [
            ScenarioEvent(delay=0.2),
            ScenarioEvent(delay=0.2),
            # a0 dies with k_target=0: its variables freeze → the
            # problem reshapes → the next segment cannot carry state
            ScenarioEvent(
                "e1", actions=[EventAction("remove_agent", agent="a0")]
            ),
            ScenarioEvent(delay=0.2),
            ScenarioEvent(delay=0.2),
        ]
    )
    r = run_dynamic(
        dcop, "maxsum", {}, scenario=scenario, distribution="adhoc",
        k_target=0, final_rounds=20, seed=3, timeout=60,
    )
    delays = [e for e in r["events"] if e["type"] == "delay"]
    assert [e["state_carried"] for e in delays] == [
        True,   # after the initial settle, same problem
        True,
        False,  # first segment after the freeze: problem reshaped
        True,   # then the reshaped problem is stable again
    ]
    # 3 carried delay segments + the final settle segment
    assert r["state_transfers"] == 4
    assert r["lost_computations"]  # a0's variable froze
    assert len(r["assignment"]) == 6
