"""Unified telemetry (pydcop_tpu/telemetry, docs/observability.md):
tracer span/event schema in both formats, the metrics registry, the
profiled_jit compile/cache-hit detection, chaos faults landing on the
trace timeline with their seed, the trace-summary command, the
--trace CLI smoke, and the --run_metrics/--end_metrics CSV round-trip
(including the end-metrics header guard)."""

import csv
import json
from types import SimpleNamespace

import pytest

from pydcop_tpu.dcop.yamldcop import load_dcop

pytestmark = pytest.mark.telemetry


def _ring_yaml(n=6, agents=("a1", "a2")):
    lines = [
        "name: ring",
        "objective: min",
        "domains:",
        "  colors: {values: [R, G, B]}",
        "variables:",
    ]
    for i in range(n):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(n):
        j = (i + 1) % n
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append(f"agents: [{', '.join(agents)}]")
    return "\n".join(lines) + "\n"


@pytest.fixture(scope="module")
def ring_dcop():
    return load_dcop(_ring_yaml())


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_metrics_registry_counters_gauges_histograms():
    from pydcop_tpu.telemetry import NULL_METRICS, MetricsRegistry

    m = MetricsRegistry()
    m.inc("a")
    m.inc("a", 2)
    m.inc("b", 0.5)
    m.gauge("g", 7)
    m.observe("h", 0.0005)
    m.observe("h", 100.0)
    snap = m.snapshot()
    assert snap["counters"] == {"a": 3, "b": 0.5}
    assert snap["gauges"] == {"g": 7}
    h = snap["histograms"]["h"]
    assert h["count"] == 2 and h["sum"] == pytest.approx(100.0005)
    # one observation below the first bound, one in the +inf overflow
    assert h["counts"][0] == 1 and h["counts"][-1] == 1
    assert len(h["counts"]) == len(h["buckets"]) + 1
    # the snapshot is JSON-safe
    json.dumps(snap)

    # disabled singleton: no-ops behind one attribute check
    assert NULL_METRICS.enabled is False
    NULL_METRICS.inc("x")
    NULL_METRICS.gauge("x", 1)
    NULL_METRICS.observe("x", 1.0)
    assert NULL_METRICS.snapshot()["counters"] == {}


def test_no_session_means_null_singletons():
    from pydcop_tpu import telemetry

    assert telemetry.get_tracer().enabled is False
    assert telemetry.get_metrics().enabled is False
    with telemetry.session() as tel:
        assert telemetry.get_tracer().enabled is True
        telemetry.get_metrics().inc("k")
        # nested no-path session reuses the active one
        with telemetry.session() as inner:
            assert inner is tel
    assert telemetry.get_metrics().enabled is False
    assert tel.summary()["counters"] == {"k": 1}


# ---------------------------------------------------------------------------
# profiled_jit: compile vs cache-hit detection
# ---------------------------------------------------------------------------


def test_profiled_jit_compile_and_cache_hit_counts():
    import jax.numpy as jnp

    from pydcop_tpu import telemetry
    from pydcop_tpu.telemetry.jit import profiled_jit

    with telemetry.session() as tel:
        f = profiled_jit(lambda x: x * 2, label="tele-test-f")
        f(jnp.ones(3))
        f(jnp.ones(3))  # same shape: cache hit
        f(jnp.ones(5))  # new shape: recompile
        counters = tel.summary()["counters"]
    assert counters["jit.compiles"] == 2
    assert counters["jit.cache_hits"] == 1
    assert counters["jit.compile_seconds_total"] > 0
    phases = tel.summary()["phases"]
    assert phases["jit-compile"]["count"] == 2


# ---------------------------------------------------------------------------
# tracer: JSONL schema + chrome format, via a real batched solve
# ---------------------------------------------------------------------------


def test_solve_trace_jsonl_schema(ring_dcop, tmp_path):
    from pydcop_tpu.api import solve

    path = tmp_path / "t.jsonl"
    # chunk_size chosen to be unique in this process so the runner
    # cache misses and at least one jit-compile span is recorded
    result = solve(
        ring_dcop, "dsa", {"variant": "B"}, rounds=40, chunk_size=19,
        trace=str(path),
    )
    assert result["status"] in ("finished", "converged")
    records = [json.loads(line) for line in path.read_text().splitlines()]
    assert records[0]["kind"] == "meta" and records[0]["version"] == 1
    by_kind = {}
    for r in records:
        by_kind.setdefault(r["kind"], []).append(r)
    spans = by_kind["span"]
    for r in spans:  # schema: every span has the full field set
        assert set(r) >= {"kind", "name", "cat", "t", "dur", "tid", "args"}
        assert r["dur"] >= 0
    names = {r["name"] for r in spans}
    assert "cycle" in names, "batched chunk spans missing"
    assert "jit-compile" in names, "jit compile span missing"
    assert "compile-problem" in names
    # the metrics snapshot rides in the same file
    metrics = by_kind["metrics"][0]
    assert metrics["counters"]["engine.rounds"] == 40
    assert metrics["counters"]["jit.compiles"] >= 1
    # ... and in the result dict, uniformly
    tel = result["telemetry"]
    assert tel["phases"]["cycle"]["count"] >= 1
    assert tel["counters"]["engine.rounds"] == 40


def test_solve_trace_chrome_format(ring_dcop, tmp_path):
    from pydcop_tpu.api import solve
    from pydcop_tpu.telemetry.summary import load_trace, summarize

    path = tmp_path / "t.json"
    solve(
        ring_dcop, "dsa", {}, rounds=20, chunk_size=11,
        trace=str(path), trace_format="chrome",
    )
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    assert events, "no traceEvents"
    complete = [e for e in events if e["ph"] == "X"]
    assert any(e["name"] == "cycle" for e in complete)
    for e in complete:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
    # the chrome reader normalizes back to the same aggregates
    s = summarize(load_trace(str(path)))
    assert s["phases"]["cycle"]["count"] >= 1
    assert s["metrics"]["counters"]["engine.rounds"] == 20


def test_host_mode_telemetry_uniform(ring_dcop):
    """Host (sim) runs land per-phase timings in result["telemetry"]
    through the same session — no trace file needed."""
    from pydcop_tpu.api import solve

    result = solve(
        ring_dcop, "maxsum", {"damping": 0.5}, rounds=50, mode="sim",
        timeout=20,
    )
    tel = result["telemetry"]
    assert tel["phases"]["deliver-loop"]["count"] == 1
    assert tel["phases"]["build-computations"]["count"] == 1
    assert tel["counters"]["msg.delivered"] == result["msg_count"]


def test_exact_algorithms_phase_spans(ring_dcop):
    """DPOP/SyncBB replace their ad-hoc perf_counter blocks with
    tracer spans: util/value/search phases show up uniformly."""
    from pydcop_tpu.api import solve

    r = solve(ring_dcop, "dpop", {})
    assert r["telemetry"]["phases"]["util-phase"]["count"] == 1
    assert r["telemetry"]["phases"]["value-phase"]["count"] == 1
    r = solve(ring_dcop, "syncbb", {})
    assert r["telemetry"]["phases"]["search"]["count"] == 1


# ---------------------------------------------------------------------------
# chaos faults on the trace timeline
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_run_faults_in_trace_with_seed(ring_dcop, tmp_path):
    from pydcop_tpu.api import solve
    from pydcop_tpu.telemetry.summary import load_trace, summarize

    path = tmp_path / "chaos.jsonl"
    result = solve(
        ring_dcop, "maxsum", {"damping": 0.5}, rounds=60,
        mode="thread", chaos="drop=0.4", chaos_seed=3, timeout=30,
        trace=str(path),
    )
    # the replay record and the trace agree on the seed
    assert result["chaos"]["seed"] == 3
    records = load_trace(str(path))
    plan = [r for r in records if r.get("name") == "chaos-plan"]
    assert plan and plan[0]["args"]["seed"] == 3
    drops = [
        r
        for r in records
        if r.get("cat") == "fault" and r.get("name") == "drop"
    ]
    assert drops, "no injected-fault events in the trace"
    for r in drops:  # each event carries link, per-link seq, and seed
        assert r["args"]["seed"] == 3
        assert ">" in r["args"]["link"] and r["args"]["seq"] >= 1
    # trace count matches the chaos layers' own event record
    assert len(drops) == result["chaos"]["events"]["drop"]
    assert result["telemetry"]["counters"]["fault.drop"] == len(drops)
    # per-message deliver events are on (trace file => detailed)
    assert any(r.get("name") == "deliver" for r in records)
    s = summarize(records)
    assert s["faults"].get("drop") == len(drops)
    assert "chaos-plan" not in s["faults"]


# ---------------------------------------------------------------------------
# trace-summary command + CLI --trace smoke (tier-1)
# ---------------------------------------------------------------------------


def test_trace_summary_command(ring_dcop, tmp_path, capsys):
    from pydcop_tpu.api import solve
    from pydcop_tpu.cli import main

    path = tmp_path / "t.jsonl"
    solve(ring_dcop, "dsa", {}, rounds=20, chunk_size=13, trace=str(path))
    assert main(["trace-summary", str(path)]) == 0
    out = capsys.readouterr().out
    assert "cycle" in out and "total_s" in out
    # --json form parses
    assert main(["trace-summary", str(path), "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["phases"]["cycle"]["count"] >= 1
    # a bogus file exits cleanly
    bad = tmp_path / "bad.trace"
    bad.write_text("this is not a trace\n")
    with pytest.raises(SystemExit):
        main(["trace-summary", str(bad)])


def test_cli_solve_trace_smoke(ring_dcop, tmp_path, capsys):
    """Tier-1 smoke: `solve --trace` on a tiny problem produces a
    parseable trace and the result JSON carries telemetry."""
    from pydcop_tpu.cli import main

    yaml_path = tmp_path / "ring.yaml"
    yaml_path.write_text(_ring_yaml())
    trace_path = tmp_path / "smoke.jsonl"
    rc = main(
        [
            "solve", "--algo", "dsa", "--rounds", "20",
            "--trace", str(trace_path), str(yaml_path),
        ]
    )
    assert rc == 0
    result = json.loads(capsys.readouterr().out)
    assert "telemetry" in result and "phases" in result["telemetry"]
    records = [
        json.loads(line) for line in trace_path.read_text().splitlines()
    ]
    assert records[0]["kind"] == "meta"
    assert any(
        r.get("kind") == "span" and r.get("name") == "cycle"
        for r in records
    )


def test_tools_trace_summary_entry(ring_dcop, tmp_path, capsys):
    import tools.trace_summary as tts
    from pydcop_tpu.api import solve

    path = tmp_path / "t.jsonl"
    solve(ring_dcop, "dsa", {}, rounds=10, chunk_size=7, trace=str(path))
    assert tts.main([str(path)]) == 0
    assert "cycle" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# write_metrics CSV round-trip (satellite)
# ---------------------------------------------------------------------------


def _metrics_args(**kw):
    base = dict(
        run_metrics=None, end_metrics=None,
        collect_on="cycle_change", period=None,
    )
    base.update(kw)
    return SimpleNamespace(**base)


def _result(trace=(3.0, 2.0, 2.0, 1.0)):
    return {
        "status": "finished",
        "cost": trace[-1],
        "cycle": len(trace),
        "msg_count": 4 * len(trace),
        "time": 0.8,
        "cost_trace": list(trace),
    }


def test_write_metrics_run_csv_round_trip(tmp_path):
    from pydcop_tpu.commands._common import write_metrics

    run = tmp_path / "run.csv"
    write_metrics(_metrics_args(run_metrics=str(run)), _result())
    with open(run, newline="") as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["time", "cycle", "cost", "msg_count"]
    assert len(rows) == 5  # header + one row per trace entry
    assert [r[2] for r in rows[1:]] == ["3.0", "2.0", "2.0", "1.0"]
    assert [int(r[1]) for r in rows[1:]] == [1, 2, 3, 4]
    # documented asymmetry: a rerun TRUNCATES (one run per file)
    write_metrics(_metrics_args(run_metrics=str(run)), _result((5.0,)))
    with open(run, newline="") as f:
        rows = list(csv.reader(f))
    assert len(rows) == 2


def test_write_metrics_end_csv_append_and_header_guard(tmp_path):
    from pydcop_tpu.commands._common import write_metrics

    end = tmp_path / "end.csv"
    args = _metrics_args(end_metrics=str(end))
    write_metrics(args, _result())
    write_metrics(args, _result((9.0, 7.0)))
    with open(end, newline="") as f:
        rows = list(csv.reader(f))
    # appended across runs, with exactly ONE header row at creation
    assert rows[0] == ["status", "cost", "cycle", "msg_count", "time"]
    assert len(rows) == 3
    assert rows[1][0] == rows[2][0] == "finished"

    # an existing EMPTY file gets the header (it is being "created")
    empty = tmp_path / "empty.csv"
    empty.write_text("")
    write_metrics(_metrics_args(end_metrics=str(empty)), _result())
    with open(empty, newline="") as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["status", "cost", "cycle", "msg_count", "time"]

    # legacy header-less file: rows append, NO header mid-stream
    legacy = tmp_path / "legacy.csv"
    legacy.write_text("finished,1.0,10,40,0.5\r\n")
    write_metrics(_metrics_args(end_metrics=str(legacy)), _result())
    with open(legacy, newline="") as f:
        rows = list(csv.reader(f))
    assert len(rows) == 2
    assert rows[0][0] == "finished" and rows[0][1] == "1.0"
    assert "status" not in {r[0] for r in rows}


def test_end_metrics_csv_parses_with_dictreader(tmp_path):
    from pydcop_tpu.commands._common import write_metrics

    end = tmp_path / "end.csv"
    write_metrics(_metrics_args(end_metrics=str(end)), _result())
    with open(end, newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows[0]["status"] == "finished"
    assert float(rows[0]["cost"]) == 1.0
    assert int(rows[0]["cycle"]) == 4


# ---------------------------------------------------------------------------
# serving observability (ISSUE 14): histogram percentiles, the tracer
# record-cap counter, the flight recorder, the Prometheus exporter,
# and the trace-context purity contract
# ---------------------------------------------------------------------------


def test_histogram_aggregates_expose_shared_percentiles():
    """Satellite: result["telemetry"]-style histogram aggregates carry
    p50/p90/p99 computed by the ONE shared nearest-rank helper, so the
    serving report and the registry can never disagree on what a
    percentile means."""
    from pydcop_tpu.telemetry import MetricsRegistry
    from pydcop_tpu.telemetry.summary import (
        _percentile,
        percentiles_from_histogram,
    )

    m = MetricsRegistry()
    sample = [0.0008] * 50 + [0.3] * 45 + [30.0] * 5
    for v in sample:
        m.observe("lat", v)
    h = m.snapshot()["histograms"]["lat"]
    assert set(h) >= {"buckets", "counts", "sum", "count",
                      "p50", "p90", "p99"}
    assert h["p50"] == 0.5  # 0.3 at bucket resolution
    assert h["p90"] == 0.5
    assert h["p99"] == 60.0  # the 30s tail bucket
    # same nearest-rank convention as the raw-sample helper: the
    # bucket percentile is the upper bound of the bucket holding the
    # raw percentile
    for q, key in ((50, "p50"), (90, "p90"), (99, "p99")):
        raw = _percentile(sample, q)
        bounds = h["buckets"]
        expected = next(
            (b for b in bounds if raw <= b), bounds[-1]
        )
        assert h[key] == expected
    assert percentiles_from_histogram([], [], (50,)) == {"p50": 0.0}


def test_session_summary_histograms_carry_percentiles():
    from pydcop_tpu.telemetry import get_metrics, session

    with session() as tel:
        for v in (0.01, 0.02, 0.4):
            get_metrics().observe("x.y_s", v)
        out = tel.summary()
    assert out["histograms"]["x.y_s"]["p50"] == 0.05


def test_tracer_cap_emits_counter_and_flight_ring_overwrites():
    """Satellite: past the 1M-record cap the tracer (a) counts every
    dropped record on `telemetry.dropped_records` LIVE, not only in
    the meta line at close, and (b) the flight-recorder ring still
    sees every record — it overwrites its oldest, never drops."""
    from pydcop_tpu.telemetry import get_metrics, session

    with session() as tel:
        tel.tracer.max_records = 4
        for i in range(12):
            tel.tracer.event(f"e{i}", cat="test")
        assert tel.tracer.dropped == 8
        counters = get_metrics().snapshot()["counters"]
        assert counters["telemetry.dropped_records"] == 8
        ring_names = [
            r["name"]
            for r in tel.flight.snapshot()
            if r.get("kind") == "event" and r.get("cat") == "test"
        ]
        # the ring holds the NEWEST records, cap or no cap
        assert ring_names[-3:] == ["e9", "e10", "e11"]
        # the counter deltas the registry mirrored are on the ring too
        assert any(
            r.get("kind") == "counter"
            and r.get("name") == "telemetry.dropped_records"
            for r in tel.flight.snapshot()
        )
        out = tel.summary()
    assert out["dropped_records"] == 8


def test_flight_recorder_dump_roundtrip_and_render(tmp_path):
    from pydcop_tpu.telemetry import get_metrics, get_tracer, session
    from pydcop_tpu.telemetry.context import trace_scope
    from pydcop_tpu.telemetry.flightrec import format_dump, load_dump

    path = str(tmp_path / "flight.json")
    with session() as tel:
        get_metrics().inc("service.requests")
        with trace_scope(["tr-feed"]):
            get_tracer().event(
                "nan_inject", cat="fault", link="engine.chunk[1]"
            )
            with get_tracer().span("service.dispatch", cat="service"):
                pass
        get_tracer().event("service-shed", cat="service")
        doc = tel.flight.dump(path, "quarantine", trace_id="tr-feed")
        assert (
            get_metrics().snapshot()["counters"][
                "telemetry.flight_dumps"
            ]
            == 1
        )
    loaded = load_dump(path)
    assert loaded["trigger"] == "quarantine"
    assert loaded["trace_id"] == "tr-feed"
    assert len(loaded["records"]) == len(doc["records"])
    text = format_dump(loaded)
    assert "trigger='quarantine'" in text
    assert "trace=tr-feed" in text
    # the triggering request's records are flagged, others are not
    flagged = [
        line for line in text.splitlines() if line.startswith("*")
    ]
    assert any("nan_inject" in line for line in flagged)
    assert any("service.dispatch" in line for line in flagged)
    assert not any("service-shed" in line for line in flagged)
    # --tail bounds the rendering
    tail = format_dump(loaded, tail=1)
    assert "older records" in tail


def test_flight_dump_cli_renders(tmp_path, capsys):
    from pydcop_tpu.cli import main
    from pydcop_tpu.telemetry import session

    path = str(tmp_path / "fl.json")
    with session() as tel:
        tel.tracer.event("service-shed", cat="service")
        tel.flight.dump(path, "shed", trace_id="tr-x")
    assert main(["flight-dump", path]) == 0
    out = capsys.readouterr().out
    assert "trigger='shed'" in out and "service-shed" in out
    with pytest.raises(SystemExit):
        main(["flight-dump", str(tmp_path / "missing.json")])


def test_prometheus_text_round_trip():
    from pydcop_tpu.telemetry import MetricsRegistry
    from pydcop_tpu.telemetry.export import (
        parse_prometheus_text,
        prometheus_text,
    )

    m = MetricsRegistry()
    m.inc("service.requests", 7)
    m.gauge("service.queue_depth", 3)
    for v in (0.002, 0.02, 0.2, 2.0):
        m.observe("service.latency_s", v)
    text = prometheus_text(m.snapshot())
    parsed = parse_prometheus_text(text)
    assert parsed["pydcop_service_requests_total"] == 7
    assert parsed["pydcop_service_queue_depth"] == 3
    hist = parsed["pydcop_service_latency_s_bucket"]
    # cumulative buckets, +Inf == count
    assert hist['le="+Inf"'] == 4
    assert parsed["pydcop_service_latency_s_count"] == 4
    assert parsed["pydcop_service_latency_s_sum"] == pytest.approx(
        2.222
    )
    # the serving percentiles ride along as gauges (nearest-rank over
    # 4 samples puts p50 at the third value, 0.2 → the 0.5 bucket)
    assert parsed["pydcop_service_latency_s_p50"] == 0.5
    # cumulative monotonicity across the rendered bucket lines
    cum = [
        v
        for _k, v in sorted(
            hist.items(),
            key=lambda kv: float(
                kv[0].split("=")[1].strip('"').replace("+Inf", "inf")
            ),
        )
    ]
    assert cum == sorted(cum)
    # strictness: a garbage line is a parse error, not a zero
    with pytest.raises(ValueError):
        parse_prometheus_text("this is { not a sample\n")


def test_trace_context_ids_are_pure_and_scope_tags():
    from pydcop_tpu.telemetry import get_tracer, session
    from pydcop_tpu.telemetry.context import (
        attempt_span_id,
        mint_trace_id,
        parse_wire_trace,
        trace_scope,
        wire_trace,
    )

    # pure: same inputs, same ids — the determinism the stitched-
    # timeline soak contract rides on
    assert mint_trace_id("c7", 3) == mint_trace_id("c7", 3)
    assert mint_trace_id("c7", 3) != mint_trace_id("c7", 4)
    assert attempt_span_id("tr-x", 1) != attempt_span_id("tr-x", 2)
    wt = wire_trace("tr-x", 2)
    assert parse_wire_trace(wt) == ("tr-x", wt["span"], 2)
    assert parse_wire_trace({"span": "sp-only"}) is None
    assert parse_wire_trace("nonsense") is None
    with session() as tel:
        tr = get_tracer()
        with trace_scope(["tr-a", "tr-b"]):
            tr.event("grouped", cat="test")
            with trace_scope(["tr-c"]):  # nesting: innermost wins
                tr.event("inner", cat="test")
        tr.event("untagged", cat="test")
        recs = {
            r["name"]: (r.get("args") or {}).get("trace")
            for r in tel.tracer._records
            if r.get("kind") == "event"
        }
    assert recs["grouped"] == ["tr-a", "tr-b"]
    assert recs["inner"] == "tr-c"
    assert recs["untagged"] is None
