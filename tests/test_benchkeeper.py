"""tools/benchkeeper: the bench ledger, the deterministic comparator,
the interleave harness, and the bench-history / bench-compare CLIs.

Everything here is jax-free and fast: the comparator is pure seeded
stdlib, the ledger round-trips the repo's own BENCH_r*.json history,
and the CLI tests inject ``--now`` so staleness output is reproducible.
The acceptance properties from the issue are pinned directly: a seeded
10% regression gets verdict ``regression``, a 2x-variance null reads
``noise``, verdicts are bit-identical across runs, the history renders
every round (including the two failed ones), and a fingerprint
mismatch refuses the comparison instead of printing a number.
"""

import json
import os
import random
import sys
import types

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from benchkeeper import abtest, history, ledger, stats  # noqa: E402


# ---------------------------------------------------------------------------
# stats: the documented decision rule
# ---------------------------------------------------------------------------

def _seeded_pairs(n=20, shift=1.0, noise=0.02, seed=7):
    """Paired samples with multiplicative per-rep weather and a true
    multiplicative effect of ``shift`` on the candidate arm."""
    rng = random.Random(seed)
    baseline, candidate = [], []
    for _ in range(n):
        weather = rng.uniform(0.9, 1.1)
        baseline.append(1.0 * weather * rng.uniform(1 - noise, 1 + noise))
        candidate.append(shift * weather * rng.uniform(1 - noise, 1 + noise))
    return baseline, candidate


class TestCompareRule:
    def test_seeded_ten_percent_regression_is_detected(self):
        baseline, candidate = _seeded_pairs(shift=0.90)
        result = stats.compare(baseline, candidate, higher_is_better=True)
        assert result["verdict"] == "regression"
        assert result["median_ratio"] < 0.95
        assert result["ci_excludes_one"]
        assert result["p_sign"] <= result["alpha"]

    def test_ten_percent_gain_is_improvement(self):
        baseline, candidate = _seeded_pairs(shift=1.10)
        result = stats.compare(baseline, candidate, higher_is_better=True)
        assert result["verdict"] == "improvement"

    def test_direction_flips_with_higher_is_better(self):
        # same 10% drop, but the metric is latency: that's an improvement
        baseline, candidate = _seeded_pairs(shift=0.90)
        result = stats.compare(baseline, candidate, higher_is_better=False)
        assert result["verdict"] == "improvement"

    def test_high_variance_null_reads_noise(self):
        # independent arms with the box's ~2x swing and NO true effect:
        # the rule must not manufacture a verdict out of weather
        rng = random.Random(123)
        baseline = [rng.uniform(1.0, 2.0) for _ in range(20)]
        candidate = [rng.uniform(1.0, 2.0) for _ in range(20)]
        result = stats.compare(baseline, candidate)
        assert result["verdict"] == "noise"

    def test_real_but_tiny_shift_is_noise_by_floor(self):
        # a perfectly consistent 2% shift: statistically real (every
        # pair moves the same way) but under the 5% practical floor
        baseline = [1.0 + i * 0.01 for i in range(12)]
        candidate = [b * 0.98 for b in baseline]
        result = stats.compare(baseline, candidate)
        assert result["verdict"] == "noise"
        assert result["p_sign"] <= result["alpha"]  # floor did the work

    def test_verdicts_are_bit_identical(self):
        baseline, candidate = _seeded_pairs(shift=0.90)
        a = stats.compare(baseline, candidate)
        b = stats.compare(baseline, candidate)
        assert a == b

    def test_sign_test_exact_values(self):
        assert stats.sign_test_p(5, 5) == 1.0
        assert stats.sign_test_p(0, 0) == 1.0
        # all 10 pairs one way: 2 * 2^-10
        assert stats.sign_test_p(10, 0) == pytest.approx(2 * 0.5 ** 10)

    def test_median_and_ratio_validation(self):
        assert stats.median([3.0, 1.0, 2.0]) == 2.0
        assert stats.median([4.0, 1.0, 2.0, 3.0]) == 2.5
        with pytest.raises(ValueError):
            stats.median([])
        with pytest.raises(ValueError):
            stats.paired_ratios([1.0, 2.0], [1.0])
        with pytest.raises(ValueError):
            stats.paired_ratios([1.0, 0.0], [1.0, 1.0])
        with pytest.raises(ValueError):
            stats.paired_ratios([], [])

    def test_bootstrap_ci_is_deterministic_and_ordered(self):
        rng = random.Random(5)
        vals = [rng.uniform(0.8, 1.2) for _ in range(15)]
        a = stats.bootstrap_ci(vals, seed=11, n_boot=500)
        b = stats.bootstrap_ci(vals, seed=11, n_boot=500)
        assert a == b
        assert a[0] <= a[1]
        assert stats.bootstrap_ci(vals, seed=12, n_boot=500) != a


# ---------------------------------------------------------------------------
# abtest: the one interleave harness
# ---------------------------------------------------------------------------

class TestInterleave:
    def test_arms_run_interleaved_and_pair_by_rep(self):
        trace = []

        def arm(name, value):
            def thunk():
                trace.append(name)
                return value
            return thunk

        ab = abtest.interleave(
            [("a", arm("a", 1.0)), ("b", arm("b", 2.0))], 3
        )
        assert trace == ["a", "b", "a", "b", "a", "b"]
        assert ab.n_reps == 3
        assert ab.pairs("a", "b") == [(1.0, 2.0)] * 3
        assert ab.pair_ratios("b", "a") == [2.0] * 3
        assert ab.median_pair_ratio("b", "a") == 2.0
        assert ab.ratio("b", "a") == 2.0

    def test_alternate_flips_order_on_odd_reps(self):
        trace = []
        ab = abtest.interleave(
            [
                ("on", lambda: trace.append("on") or 1.0),
                ("off", lambda: trace.append("off") or 1.0),
            ],
            4,
            alternate=True,
        )
        assert trace == ["on", "off", "off", "on", "on", "off", "off", "on"]
        assert ab.n_reps == 4

    def test_warmup_results_are_discarded(self):
        calls = {"n": 0}

        def thunk():
            calls["n"] += 1
            return float(calls["n"])

        ab = abtest.interleave([("x", thunk)], 2, warmup=True)
        assert calls["n"] == 3
        assert ab.values("x") == [2.0, 3.0]  # the warmup 1.0 is dropped

    def test_record_carries_dispersion(self):
        ab = abtest.ABSamples(["x"])
        for v in (3.0, 1.0, 2.0):
            ab.add("x", v)
        rec = ab.record("x")
        assert rec == {
            "n": 3, "min": 1.0, "max": 3.0, "median": 2.0,
            "values": [3.0, 1.0, 2.0],
        }
        assert set(ab.records()) == {"x"}

    def test_compare_delegates_to_stats(self):
        baseline, candidate = _seeded_pairs(shift=0.90)
        ab = abtest.ABSamples(["base", "cand"])
        for b, c in zip(baseline, candidate):
            ab.add("base", b)
            ab.add("cand", c)
        assert ab.compare("base", "cand") == stats.compare(baseline, candidate)

    def test_validation(self):
        with pytest.raises(ValueError):
            abtest.ABSamples(["a", "a"])
        with pytest.raises(ValueError):
            abtest.interleave([("a", lambda: 1.0)], 0)
        with pytest.raises(ValueError):
            abtest.ABSamples(["a"]).record("a")


# ---------------------------------------------------------------------------
# ledger: fingerprints, refusal, round-trip from the real history
# ---------------------------------------------------------------------------

class TestFingerprint:
    def test_known_mismatch_refuses(self):
        a = ledger.null_fingerprint(backend="cpu", vcpus=2)
        b = ledger.null_fingerprint(backend="tpu", vcpus=2)
        ok, mismatched, unknown = ledger.comparability(a, b)
        assert not ok
        assert mismatched == ["backend"]
        reason = ledger.refusal_reason(a, b)
        assert reason is not None and "backend" in reason

    def test_unknown_fields_weaken_but_do_not_refuse(self):
        a = ledger.null_fingerprint(backend="cpu")
        b = ledger.null_fingerprint(backend="cpu", vcpus=2)
        ok, mismatched, unknown = ledger.comparability(a, b)
        assert ok and not mismatched
        assert "vcpus" in unknown and "jax" in unknown
        assert ledger.refusal_reason(a, b) is None

    def test_null_fingerprint_rejects_unknown_keys(self):
        with pytest.raises(KeyError):
            ledger.null_fingerprint(bogus=1)

    def test_environment_fingerprint_collects_locally(self):
        fp = ledger.environment_fingerprint(backend="cpu", sha="abc123")
        assert fp["backend"] == "cpu"
        assert fp["sha"] == "abc123"
        assert fp["vcpus"] == os.cpu_count()
        assert isinstance(fp["python"], str)
        assert set(fp) == set(ledger.FINGERPRINT_FIELDS)


class TestTimestamps:
    def test_parse_ts_both_formats(self):
        epoch = ledger.parse_ts("2026-08-05T12:00:00Z")
        assert ledger.format_ts(epoch) == "2026-08-05T12:00:00Z"
        # git %cI offset form: same instant, +02:00 local
        assert ledger.parse_ts("2026-08-05T14:00:00+02:00") == epoch
        assert ledger.parse_ts("2026-08-05T12:00:00+00:00") == epoch
        with pytest.raises(ValueError):
            ledger.parse_ts("yesterday-ish")

    def test_make_row_validates_ts(self):
        with pytest.raises(ValueError):
            ledger.make_row(
                ts="not-a-ts", source="s", stage="st", metric="m",
                value=1.0, unit="u", higher_is_better=True,
                fingerprint=ledger.null_fingerprint(),
            )


class TestLedgerRoundTrip:
    @pytest.fixture(scope="class")
    def seeded(self):
        return ledger.seed_rows(_REPO)

    def test_every_round_gets_a_status_row(self, seeded):
        status = [r for r in seeded if ledger.row_key(r) == ("bench_round", "rc")]
        assert sorted(r["round"] for r in status) == [
            f"r{i:02d}" for i in range(1, 13)
        ]
        by_round = {r["round"]: r for r in status}
        # r01 crashed (rc=1), r05 timed out (rc=0, nothing parsed) —
        # both must still be present, visibly unparsed
        assert by_round["r01"]["value"] == 1.0
        assert by_round["r01"]["extra"]["parsed"] is False
        assert by_round["r05"]["extra"]["parsed"] is False
        assert by_round["r12"]["extra"]["parsed"] is True

    def test_rows_round_trip_through_the_file(self, seeded, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        n = ledger.write_ledger(path, seeded)
        assert n == len(seeded)
        back = ledger.read_ledger(path)
        assert back == seeded
        m = ledger.append_rows(path, seeded[:3])
        assert m == 3
        assert ledger.read_ledger(path) == seeded + seeded[:3]

    def test_read_ledger_skips_garbage_lines(self, tmp_path):
        path = tmp_path / "ledger.jsonl"
        good = ledger.make_row(
            ts="2026-08-05T12:00:00Z", source="s", stage="st", metric="m",
            value=1.0, unit="u", higher_is_better=True,
            fingerprint=ledger.null_fingerprint(),
        )
        path.write_text(
            "not json\n\n[1,2]\n" + json.dumps(good) + "\n"
        )
        assert ledger.read_ledger(str(path)) == [good]
        assert ledger.read_ledger(str(tmp_path / "missing.jsonl")) == []

    def test_historic_rows_have_null_fingerprints_except_backend(self, seeded):
        metric_rows = [
            r for r in seeded
            if r["source"].startswith("bench_")
            and ledger.row_key(r) != ("bench_round", "rc")
        ]
        assert metric_rows
        for r in metric_rows:
            fp = r["fingerprint"]
            assert fp["vcpus"] is None and fp["jax"] is None
            assert set(fp) == set(ledger.FINGERPRINT_FIELDS)

    def test_tpu_log_rows_present_and_fingerprinted_tpu(self, seeded):
        tpu = [r for r in seeded if r["source"] == "tpu_log"]
        assert tpu
        for r in tpu:
            assert r["fingerprint"]["backend"] == "tpu"
            assert r["value"] > 0
            assert r["unit"] == "msgs/s"

    def test_rows_are_sorted_by_time(self, seeded):
        times = [ledger.parse_ts(r["ts"]) for r in seeded]
        assert times == sorted(times)


class TestTpuLogExtraction:
    def test_skips_bad_entries_and_keeps_embedded_fingerprint(self):
        fp = ledger.null_fingerprint(backend="tpu", device_kind="TPU v4")
        rows = ledger.extract_tpu_log_rows([
            {"ts": "2026-08-01T00:00:00Z", "workload": "w",
             "msgs_per_sec": 5.0, "fingerprint": fp, "rounds": 100},
            {"ts": "2026-08-01T00:00:00Z", "workload": "w",
             "msgs_per_sec": 0},                       # non-positive
            {"ts": "garbage", "workload": "w", "msgs_per_sec": 1.0},
            "not a dict",
            {"workload": "w", "msgs_per_sec": 1.0},    # no ts
        ])
        assert len(rows) == 1
        assert rows[0]["fingerprint"]["device_kind"] == "TPU v4"
        assert rows[0]["extra"] == {"rounds": 100}


# ---------------------------------------------------------------------------
# history: chaining, staleness, round comparison
# ---------------------------------------------------------------------------

def _row(ts, stage, metric, value, backend="cpu", rnd=None, **fp_known):
    return ledger.make_row(
        ts=ts, source="test", stage=stage, metric=metric, value=value,
        unit="u", higher_is_better=True, round_name=rnd,
        fingerprint=ledger.null_fingerprint(backend=backend, **fp_known),
    )


class TestHistory:
    def test_chain_normalize_anchors_new_segments(self):
        # env A measures 10 -> 20, env B (2x faster box) 60 -> 90:
        # the chained curve continues from 20, preserving B's 1.5x
        values = [10.0, 20.0, 60.0, 90.0]
        keys = [("a",), ("a",), ("b",), ("b",)]
        norm, n_seg = history.chain_normalize(values, keys)
        assert n_seg == 2
        assert norm == [10.0, 20.0, 20.0, 30.0]
        # single env: pass-through
        norm1, n1 = history.chain_normalize([1.0, 2.0], [("a",), ("a",)])
        assert (norm1, n1) == ([1.0, 2.0], 1)

    def test_sparkline_shape(self):
        s = history.sparkline([1.0, 2.0, 3.0])
        assert len(s) == 3
        assert s[0] == history.SPARK_BLOCKS[0]
        assert s[-1] == history.SPARK_BLOCKS[-1]
        assert history.sparkline([5.0, 5.0]) == history.SPARK_BLOCKS[3] * 2
        assert history.sparkline([]) == ""

    def test_stale_backends_flags_old_rows_only(self):
        now = ledger.parse_ts("2026-08-05T12:00:00Z")
        rows = [
            _row("2026-08-05T00:00:00Z", "s", "m", 1.0, backend="cpu"),
            _row("2026-08-01T00:00:00Z", "s", "m", 1.0, backend="tpu"),
            _row("2026-07-01T00:00:00Z", "s", "m", 1.0, backend="tpu"),
            _row("2026-08-05T00:00:00Z", "s", "m", 1.0, backend=None),
        ]
        report = history.stale_backends(rows, now_epoch=now, stale_hours=72.0)
        by_backend = {r["backend"]: r for r in report}
        assert set(by_backend) == {"cpu", "tpu"}  # unnamed backend skipped
        assert not by_backend["cpu"]["stale"]
        assert by_backend["tpu"]["stale"]
        # staleness is judged on the NEWEST tpu row (4.5 days), not the
        # month-old one
        assert by_backend["tpu"]["age_hours"] == pytest.approx(108.0)
        assert report[0]["backend"] == "tpu"  # stalest first

    def test_compare_rounds_refuses_on_fingerprint_mismatch(self):
        rows = [
            _row("2026-08-01T00:00:00Z", "s", "m", 10.0, rnd="r01",
                 backend="cpu", vcpus=2),
            _row("2026-08-02T00:00:00Z", "s", "m", 12.0, rnd="r02",
                 backend="cpu", vcpus=8),
            _row("2026-08-01T00:00:00Z", "s", "ok", 10.0, rnd="r01",
                 backend="cpu"),
            _row("2026-08-02T00:00:00Z", "s", "ok", 15.0, rnd="r02",
                 backend="cpu"),
        ]
        result = history.compare_rounds(rows, "r01", "r02")
        assert result["verdict"] is None  # never a statistical claim
        by_metric = {e["metric"]: e for e in result["entries"]}
        assert "refused" in by_metric["m"]
        assert "vcpus" in by_metric["m"]["refused"]
        assert "ratio" not in by_metric["m"]
        assert by_metric["ok"]["ratio"] == pytest.approx(1.5)
        text = history.format_compare_rounds(result)
        assert "REFUSED" in text and "x1.500" in text

    def test_compare_pairs_doc_round_trips_the_rule(self):
        baseline, candidate = _seeded_pairs(shift=0.90)
        doc = {"baseline": baseline, "candidate": candidate,
               "higher_is_better": True, "name": "t"}
        result = history.compare_pairs_doc(doc)
        assert result["verdict"] == "regression"
        assert result["name"] == "t"
        text = history.format_verdict(result)
        assert "REGRESSION" in text and "excludes 1.0" in text
        with pytest.raises(ValueError):
            history.compare_pairs_doc({"baseline": [1.0]})

    def test_history_report_renders_every_round(self):
        rows = ledger.seed_rows(_REPO)
        now = ledger.parse_ts("2026-08-05T12:00:00Z")
        report = history.history_report(rows, now_epoch=now)
        for i in range(1, 13):
            assert f"r{i:02d}" in report
        assert "r01 FAIL" in report
        assert "r05 empty" in report
        assert "r12 ok" in report
        # the TPU captures predate r09 by days: stale at the 72h bound
        assert "STALE" in report and "tpu:" in report


# ---------------------------------------------------------------------------
# CLI golden output (bench-history / bench-compare)
# ---------------------------------------------------------------------------

def _history_args(**over):
    base = dict(
        ledger=None, stage=None, stale_hours=72.0, now=None,
        rebuild=False, as_json=False, root=_REPO, output=None,
    )
    base.update(over)
    return types.SimpleNamespace(**base)


def _compare_args(**over):
    base = dict(
        pairs=None, baseline=None, candidate=None, stage=None,
        metric=None, ledger=None, seed=None, alpha=None,
        noise_floor=None, n_boot=None, as_json=False, root=_REPO,
        output=None,
    )
    base.update(over)
    return types.SimpleNamespace(**base)


class TestCLIs:
    @pytest.fixture(scope="class")
    def tmp_ledger(self, tmp_path_factory):
        """A rebuilt ledger in a scratch path — the committed one stays
        untouched, and the CLI's --rebuild path gets exercised."""
        from pydcop_tpu.commands import bench_history

        path = str(tmp_path_factory.mktemp("bk") / "ledger.jsonl")
        rc = bench_history.run_cmd(_history_args(
            ledger=path, rebuild=True, now="2026-08-05T12:00:00Z",
        ))
        assert rc == 0
        return path

    def test_bench_history_golden(self, tmp_ledger, capsys):
        from pydcop_tpu.commands import bench_history

        rc = bench_history.run_cmd(_history_args(
            ledger=tmp_ledger, now="2026-08-05T12:00:00Z",
        ))
        out = capsys.readouterr().out
        assert rc == 0
        assert "bench history — " in out
        assert "rounds:" in out
        for i in range(1, 12):
            assert f"r{i:02d}" in out
        assert "r01 FAIL" in out and "r05 empty" in out
        assert "north_star/msgs_per_sec" in out
        assert "STALE" in out  # the tpu rows are >72h old at --now
        # deterministic given --now: a second run is byte-identical
        bench_history.run_cmd(_history_args(
            ledger=tmp_ledger, now="2026-08-05T12:00:00Z",
        ))
        assert capsys.readouterr().out == out

    def test_bench_history_stage_detail_and_json(self, tmp_ledger, capsys):
        from pydcop_tpu.commands import bench_history

        rc = bench_history.run_cmd(_history_args(
            ledger=tmp_ledger, stage="bnb", now="2026-08-05T12:00:00Z",
        ))
        out = capsys.readouterr().out
        assert rc == 0
        assert "bnb/speedup_on_vs_off" in out
        assert "north_star" not in out
        rc = bench_history.run_cmd(_history_args(
            ledger=tmp_ledger, as_json=True, now="2026-08-05T12:00:00Z",
        ))
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert len(doc["rounds"]) == 12
        assert any(f["backend"] == "tpu" and f["stale"]
                   for f in doc["freshness"])

    def test_bench_history_empty_ledger_fails(self, tmp_path, capsys):
        from pydcop_tpu.commands import bench_history

        rc = bench_history.run_cmd(_history_args(
            ledger=str(tmp_path / "nope.jsonl"),
        ))
        capsys.readouterr()
        assert rc == 1

    def test_bench_compare_pairs_verdict_and_exit_code(
        self, tmp_path, capsys
    ):
        from pydcop_tpu.commands import bench_compare

        baseline, candidate = _seeded_pairs(shift=0.90)
        pairs = tmp_path / "pairs.json"
        pairs.write_text(json.dumps({
            "baseline": baseline, "candidate": candidate,
            "higher_is_better": True, "name": "synthetic 10% drop",
        }))
        rc = bench_compare.run_cmd(_compare_args(pairs=str(pairs)))
        out = capsys.readouterr().out
        assert rc == 1  # regression is a CI failure
        assert "verdict: REGRESSION" in out
        # bit-identical across runs (seeded bootstrap)
        bench_compare.run_cmd(_compare_args(pairs=str(pairs)))
        assert capsys.readouterr().out == out

    def test_bench_compare_pairs_noise_exits_zero(self, tmp_path, capsys):
        from pydcop_tpu.commands import bench_compare

        rng = random.Random(123)
        pairs = tmp_path / "null.json"
        pairs.write_text(json.dumps({
            "baseline": [rng.uniform(1.0, 2.0) for _ in range(20)],
            "candidate": [rng.uniform(1.0, 2.0) for _ in range(20)],
        }))
        rc = bench_compare.run_cmd(_compare_args(pairs=str(pairs)))
        out = capsys.readouterr().out
        assert rc == 0
        assert "verdict: NOISE" in out

    def test_bench_compare_rounds_golden(self, tmp_ledger, capsys):
        from pydcop_tpu.commands import bench_compare

        rc = bench_compare.run_cmd(_compare_args(
            baseline="r07", candidate="r09", ledger=tmp_ledger,
        ))
        out = capsys.readouterr().out
        assert rc == 0
        assert "r07 -> r09" in out
        assert "point ratios, no verdict" in out
        assert "north_star/msgs_per_sec" in out
        assert "not interleaved" in out

    def test_bench_compare_usage_errors(self, tmp_path, capsys):
        from pydcop_tpu.commands import bench_compare

        # neither mode selected
        assert bench_compare.run_cmd(_compare_args()) == 2
        # both modes selected
        assert bench_compare.run_cmd(_compare_args(
            pairs="x.json", baseline="r01", candidate="r02",
        )) == 2
        # unreadable pairs file
        assert bench_compare.run_cmd(_compare_args(
            pairs=str(tmp_path / "missing.json"),
        )) == 2
        # malformed pairs doc
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"baseline": [1.0]}))
        assert bench_compare.run_cmd(_compare_args(pairs=str(bad))) == 2
        capsys.readouterr()
