"""Compiler + cost-kernel tests: device results must match the host
(model-layer) evaluator exactly — the cost-parity acceptance gate of
SURVEY.md §7 item 2."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    Domain,
    ExternalVariable,
    Variable,
    VariableWithCostFunc,
)
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.ops import (
    BIG,
    compile_dcop,
    decode_assignment,
    encode_assignment,
    local_cost_sweep,
    neighbor_gather,
    total_cost,
)
from pydcop_tpu.utils.expressionfunction import ExpressionFunction


def random_dcop(seed, n_vars=8, n_bin=10, n_tern=2, mixed_domains=True):
    rnd = random.Random(seed)
    dcop = DCOP(f"rand{seed}")
    domains = [
        Domain("d2", "", [0, 1]),
        Domain("d3", "", ["a", "b", "c"]),
        Domain("d4", "", [10, 20, 30, 40]),
    ]
    vs = []
    for i in range(n_vars):
        d = rnd.choice(domains) if mixed_domains else domains[1]
        if rnd.random() < 0.3:
            v = VariableWithCostFunc(
                f"v{i}", d, ExpressionFunction(f"0.5 if v{i} == {d[0]!r} else 0.1")
            )
        else:
            v = Variable(f"v{i}", d)
        vs.append(v)
        dcop.add_variable(v)
    cid = 0
    for _ in range(n_bin):
        a, b = rnd.sample(range(n_vars), 2)
        m = np.round(
            np.random.RandomState(seed * 100 + cid)
            .uniform(0, 10, (len(vs[a].domain), len(vs[b].domain))),
            2,
        )
        dcop.add_constraint(
            NAryMatrixRelation([vs[a], vs[b]], m, name=f"c{cid}")
        )
        cid += 1
    for _ in range(n_tern):
        a, b, c = rnd.sample(range(n_vars), 3)
        m = np.round(
            np.random.RandomState(seed * 100 + cid).uniform(
                0, 10,
                (len(vs[a].domain), len(vs[b].domain), len(vs[c].domain)),
            ),
            2,
        )
        dcop.add_constraint(
            NAryMatrixRelation([vs[a], vs[b], vs[c]], m, name=f"c{cid}")
        )
        cid += 1
    # a unary constraint too (folds into the unary array)
    dcop.add_constraint(
        constraint_from_str("u0", "1.5 if v0 == v0 else 0", vs)
    )
    return dcop


def rand_assignment(dcop, rnd):
    return {
        name: rnd.choice(list(v.domain.values))
        for name, v in dcop.variables.items()
    }


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_total_cost_parity_fuzz(seed):
    dcop = random_dcop(seed)
    problem = compile_dcop(dcop)
    rnd = random.Random(seed + 1000)
    for _ in range(20):
        a = rand_assignment(dcop, rnd)
        host = dcop.solution_cost(a)
        dev = float(total_cost(problem, encode_assignment(problem, a)))
        assert dev == pytest.approx(host, rel=1e-5), a


def test_encode_decode_round_trip():
    dcop = random_dcop(7)
    problem = compile_dcop(dcop)
    rnd = random.Random(42)
    a = rand_assignment(dcop, rnd)
    assert decode_assignment(problem, encode_assignment(problem, a)) == a


def test_local_cost_sweep_matches_bruteforce():
    dcop = random_dcop(5)
    problem = compile_dcop(dcop)
    rnd = random.Random(5)
    a = rand_assignment(dcop, rnd)
    values = encode_assignment(problem, a)
    sweep = np.asarray(local_cost_sweep(problem, values))
    for i, name in enumerate(problem.var_names):
        v = dcop.variables[name]
        for k, val in enumerate(v.domain.values):
            mod = dict(a)
            mod[name] = val
            # host "local cost": all constraints involving name + v's own cost
            cost = v.cost_for_val(val) if v.has_cost else 0.0
            for c in dcop.constraints.values():
                if name in c.scope_names:
                    cost += c.get_value_for_assignment(
                        {n: mod[n] for n in c.scope_names}
                    )
            assert sweep[i, k] == pytest.approx(cost, rel=1e-5), (name, val)
        # padded cells are BIG-ish
        for k in range(len(v.domain), problem.d_max):
            assert sweep[i, k] >= BIG / 2


def test_max_objective_negates():
    d = Domain("d", "", [0, 1])
    dcop = DCOP("m", objective="max")
    x, y = Variable("x", d), Variable("y", d)
    dcop.add_variable(x)
    dcop.add_variable(y)
    dcop.add_constraint(
        NAryMatrixRelation([x, y], [[0, 5], [5, 0]], name="c")
    )
    problem = compile_dcop(dcop)
    assert problem.maximize
    # compiled cost is negated: best (max) assignment has lowest cost
    best = float(total_cost(problem, encode_assignment(problem, {"x": 0, "y": 1})))
    worst = float(total_cost(problem, encode_assignment(problem, {"x": 0, "y": 0})))
    assert best == -5 and worst == 0


def test_external_variable_sliced():
    d = Domain("d", "", [0, 1])
    dcop = DCOP("e")
    x = Variable("x", d)
    e = ExternalVariable("e", d, 1)
    dcop.add_variable(x)
    dcop.add_variable(e)
    dcop.add_constraint(
        constraint_from_str("c", "10 * x * e", [x, e])
    )
    problem = compile_dcop(dcop)
    assert problem.var_names == ("x",)
    # with e=1, cost(x=1) = 10 (folded as unary on x)
    assert float(
        total_cost(problem, encode_assignment(problem, {"x": 1}))
    ) == pytest.approx(10)


def test_neighbor_gather():
    dcop = DCOP("n")
    d = Domain("d", "", [0, 1])
    vs = [Variable(f"v{i}", d) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    dcop.add_constraint(constraint_from_str("c01", "v0 * v1", vs))
    dcop.add_constraint(constraint_from_str("c12", "v1 * v2", vs))
    problem = compile_dcop(dcop)
    # q keyed by the COMPILED variable order (the compiler relabels
    # variables degree-descending; names are the contract)
    q_host = np.zeros(3, dtype=np.float32)
    q_host[problem.var_index("v0")] = 10.0
    q_host[problem.var_index("v1")] = 20.0
    q_host[problem.var_index("v2")] = 30.0
    q = jnp.asarray(q_host)
    g = np.asarray(neighbor_gather(problem, q, fill=-1.0))
    i0 = problem.var_index("v0")
    i1 = problem.var_index("v1")
    row1 = sorted(g[i1].tolist())
    assert row1 == [10.0, 30.0]
    assert sorted(g[i0].tolist())[-1] == 20.0  # v0 sees only v1 (+fill)


def test_jit_and_pytree():
    """CompiledProblem must be a valid pytree usable as a jit arg."""
    dcop = random_dcop(9)
    problem = compile_dcop(dcop)
    f = jax.jit(total_cost)
    rnd = random.Random(0)
    a = rand_assignment(dcop, rnd)
    v = encode_assignment(problem, a)
    assert float(f(problem, v)) == pytest.approx(
        float(total_cost(problem, v)), rel=1e-6
    )
    leaves = jax.tree_util.tree_leaves(problem)
    assert all(hasattr(l, "shape") for l in leaves)


def test_arity_guard():
    d = Domain("d", "", [0, 1])
    dcop = DCOP("big")
    vs = [Variable(f"v{i}", d) for i in range(8)]
    for v in vs:
        dcop.add_variable(v)
    dcop.add_constraint(
        constraint_from_str("huge", " + ".join(f"v{i}" for i in range(8)), vs)
    )
    with pytest.raises(ValueError, match="MAX_ARITY"):
        compile_dcop(dcop)


def test_int32_offset_overflow_guard():
    """A problem whose flat table would exceed 2^31 cells must be
    refused up front — int32 offsets would otherwise silently wrap
    into corrupt table indices (advisor r3)."""
    from pydcop_tpu.ops.compile import _pack_runs

    # 1 arity-3 constraint at padded domain 1300: 1300^3 > 2^31 cells.
    # The guard fires before any table memory is touched, so a tiny
    # placeholder table array is enough.
    runs = [
        (
            3,
            np.array([[0, 1, 2]], dtype=np.int32),
            np.zeros((1, 1), dtype=np.float32),
        )
    ]
    with pytest.raises(ValueError, match="int32 table offsets"):
        _pack_runs(runs, n_vars=3, d_max=1300, dtype=np.float32)


# -- compile_from_arrays: the array-level fast path ---------------------


def _uniform_dcop_and_arrays(seed=7, n_vars=20, n_bin=28, d=3):
    """The same problem built both ways: model objects for
    ``compile_dcop`` and raw arrays for ``compile_from_arrays``."""
    rnd = random.Random(seed)
    dom = Domain("colors", "", list(range(d)))
    dcop = DCOP("parity")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    scopes = []
    seen = set()
    cid = 0
    table = np.round(
        np.random.RandomState(seed).uniform(0, 10, (d, d)), 2
    ).astype(np.float32)
    while len(scopes) < n_bin:
        a, b = rnd.sample(range(n_vars), 2)
        if (a, b) in seen:
            continue
        seen.add((a, b))
        dcop.add_constraint(
            NAryMatrixRelation([vs[a], vs[b]], table, name=f"c{cid}")
        )
        scopes.append((a, b))
        cid += 1
    unary = np.round(
        np.random.RandomState(seed + 1).uniform(0, 1, (n_vars, d)), 3
    ).astype(np.float32)
    for i, v in enumerate(vs):
        for k in range(d):
            dcop.add_constraint(
                constraint_from_str(
                    f"u{i}_{k}",
                    f"{float(unary[i, k])!r} if v{i} == {k} else 0",
                    [vs[i]],
                )
            )
    return dcop, np.asarray(scopes, dtype=np.int32), table, unary


def test_from_arrays_matches_compile_dcop():
    from pydcop_tpu.ops.compile import compile_from_arrays

    dcop, scopes, table, unary = _uniform_dcop_and_arrays()
    p_model = compile_dcop(dcop)
    # stacked (per-constraint) tables: byte-identical layout with the
    # model path; the deduplicated shared layout has its own parity
    # test (test_from_arrays_shared_vs_stacked_tables_equal)
    stacked = np.broadcast_to(
        table, (scopes.shape[0],) + table.shape
    ).copy()
    p_array = compile_from_arrays(scopes, stacked, 3, unary=unary)

    # identical slot ordering (same degree-sort invariant) ...
    assert tuple(p_array.var_names) == p_model.var_names
    assert p_array.var_slot_counts == p_model.var_slot_counts
    # ... and identical array fields
    for field in (
        "domain_sizes", "unary", "init_idx", "tables_flat",
        "con_offset", "con_scopes", "con_strides", "edge_var",
        "edge_con", "edge_offset", "edge_stride", "edge_covars",
        "edge_costrides", "neighbors", "neighbor_mask", "var_edges",
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(p_array, field)),
            np.asarray(getattr(p_model, field)),
            err_msg=field,
        )
    for k in p_model.buckets:
        np.testing.assert_array_equal(
            np.asarray(p_array.buckets[k].tables),
            np.asarray(p_model.buckets[k].tables),
        )
        np.testing.assert_array_equal(
            np.asarray(p_array.buckets[k].edge_slot),
            np.asarray(p_model.buckets[k].edge_slot),
        )

    # assignment round-trip agrees across the two name objects
    rnd = random.Random(0)
    assign = rand_assignment(dcop, rnd)
    np.testing.assert_array_equal(
        np.asarray(encode_assignment(p_array, assign)),
        np.asarray(encode_assignment(p_model, assign)),
    )
    c_a = float(total_cost(p_array, encode_assignment(p_array, assign)))
    c_m = float(total_cost(p_model, encode_assignment(p_model, assign)))
    assert c_a == pytest.approx(c_m, abs=1e-4)


def test_from_arrays_sharded_layout():
    from pydcop_tpu.ops.compile import compile_from_arrays

    dcop, scopes, table, unary = _uniform_dcop_and_arrays(n_bin=26)
    p1 = compile_from_arrays(scopes, table, 3, unary=unary)
    p4 = compile_from_arrays(scopes, table, 3, unary=unary, n_shards=4)
    # ghost-padded to equal per-shard buckets; real edge count unchanged
    assert p4.n_shards == 4
    assert p4.n_real_edges == p1.n_real_edges == 2 * len(scopes)
    assert p4.n_cons % 4 == 0
    # cost parity between layouts
    rnd = random.Random(1)
    assign = rand_assignment(dcop, rnd)
    c1 = float(total_cost(p1, encode_assignment(p1, assign)))
    c4 = float(total_cost(p4, encode_assignment(p4, assign)))
    assert c1 == pytest.approx(c4, abs=1e-4)


def test_from_arrays_maxsum_runs():
    from pydcop_tpu.api import solve_compiled
    from pydcop_tpu.ops.compile import compile_from_arrays

    _, scopes, table, unary = _uniform_dcop_and_arrays()
    p = compile_from_arrays(scopes, table, 3, unary=unary)
    res = solve_compiled(p, algo="maxsum", rounds=40, seed=0)
    assert set(res["assignment"]) == set(p.var_names)
    assert res["cost"] < BIG


def test_from_arrays_shared_vs_stacked_tables_equal():
    """A shared table is stored ONCE (flat + bucket) yet every cost
    and every algorithm result matches the per-constraint layout."""
    from pydcop_tpu.api import solve_compiled
    from pydcop_tpu.ops.compile import compile_from_arrays

    dcop, scopes, table, unary = _uniform_dcop_and_arrays()
    m = scopes.shape[0]
    stacked = np.broadcast_to(table, (m,) + table.shape).copy()
    p_shared = compile_from_arrays(scopes, table, 3, unary=unary)
    p_stacked = compile_from_arrays(scopes, stacked, 3, unary=unary)
    # deduplicated storage...
    assert p_shared.tables_flat.shape[0] == table.size
    assert p_shared.buckets[2].shared_table
    assert p_shared.buckets[2].tables.shape[0] == 1
    assert p_shared.buckets[2].n_cons == m
    assert not p_stacked.buckets[2].shared_table
    # ...identical semantics: costs and algorithm runs agree exactly
    rnd = random.Random(3)
    for _ in range(5):
        a = rand_assignment(dcop, rnd)
        c_sh = float(total_cost(p_shared, encode_assignment(p_shared, a)))
        c_st = float(total_cost(p_stacked, encode_assignment(p_stacked, a)))
        assert c_sh == pytest.approx(c_st, abs=1e-5)
    for algo, params in (
        ("maxsum", None),
        ("dsa", {"variant": "B"}),
        ("gdba", None),
        ("mgm", None),
    ):
        r_sh = solve_compiled(p_shared, algo, params, rounds=30, seed=0)
        r_st = solve_compiled(p_stacked, algo, params, rounds=30, seed=0)
        assert r_sh["cost"] == pytest.approx(r_st["cost"], abs=1e-4), algo
        assert r_sh["assignment"] == r_st["assignment"], algo


def test_from_arrays_merges_same_arity_groups():
    """Two same-arity scope groups must land in ONE (segment, arity)
    run: the Max-Sum factor phase reads each bucket position's q as a
    contiguous slice of the whole arity group (code-review r3)."""
    from pydcop_tpu.api import solve_compiled
    from pydcop_tpu.ops.compile import compile_from_arrays

    _, scopes, table, unary = _uniform_dcop_and_arrays()
    half = scopes.shape[0] // 2
    # identical problem, passed as two same-arity groups (one shared
    # table, one stacked)
    stacked_tail = np.broadcast_to(
        table, (scopes.shape[0] - half,) + table.shape
    ).copy()
    p_split = compile_from_arrays(
        [scopes[:half], scopes[half:]], [table, stacked_tail], 3,
        unary=unary,
    )
    p_whole = compile_from_arrays(scopes, table, 3, unary=unary)
    np.testing.assert_array_equal(
        np.asarray(p_split.edge_var), np.asarray(p_whole.edge_var)
    )
    r_split = solve_compiled(p_split, "maxsum", rounds=40, seed=0)
    r_whole = solve_compiled(p_whole, "maxsum", rounds=40, seed=0)
    assert r_split["cost"] == pytest.approx(r_whole["cost"], abs=1e-4)


def test_from_arrays_rejects_bad_input():
    from pydcop_tpu.ops.compile import compile_from_arrays

    table = np.eye(3, dtype=np.float32)
    with pytest.raises(ValueError, match="negative"):
        compile_from_arrays(
            np.array([[0, -1]], dtype=np.int32), table, 3
        )
    with pytest.raises(ValueError, match="domain_values"):
        compile_from_arrays(
            np.array([[0, 1]], dtype=np.int32), table, 3,
            domain_values=["a", "b"],
        )


def test_multi_restart_best_of():
    """n_restarts runs K independent instances in one vmapped program
    and reports the best across them.  Quality vs a single run is
    stochastic (the K streams are not a superset of the single-run
    stream), so the assertions here are the INVARIANTS: reported best
    = the minimum of the anytime trace, the returned assignment
    evaluates to the reported cost, and messages cover all K runs."""
    from pydcop_tpu.api import solve_compiled
    from pydcop_tpu.ops.compile import compile_from_arrays
    from pydcop_tpu.ops.generate import coloring_arrays

    sc, tb, un = coloring_arrays(120, seed=5)
    p = compile_from_arrays(sc, tb, 3, unary=un)
    r1 = solve_compiled(p, "dsa", {"variant": "B"}, rounds=60, seed=0)
    r8 = solve_compiled(
        p, "dsa", {"variant": "B"}, rounds=60, seed=0, n_restarts=8
    )
    assert r8["msg_count"] == 8 * r1["msg_count"]
    assert len(r8["cost_trace"]) == len(r1["cost_trace"])
    # best-seen can only be at or below every trace sample (the trace
    # is the per-sample minimum across restarts)
    assert r8["cost"] <= min(r8["cost_trace"]) + 1e-5
    # the returned assignments must actually have the returned costs
    from pydcop_tpu.ops import encode_assignment, total_cost

    c = float(total_cost(p, encode_assignment(p, r8["assignment"])))
    assert c == pytest.approx(r8["cost"], abs=1e-4)
    cf = float(
        total_cost(p, encode_assignment(p, r8["final_assignment"]))
    )
    assert cf == pytest.approx(r8["final_cost"], abs=1e-4)
    # the K-sample distribution is exposed; its min IS the reported best
    assert len(r8["restart_costs"]) == 8
    assert min(r8["restart_costs"]) == pytest.approx(r8["cost"], abs=1e-5)
    assert "restart_costs" not in r1


def test_multi_restart_remaining_rejections():
    """Restarts now compose with mesh + checkpointing (see
    test_parallel_sharded / test_checkpoint); what must still be
    rejected: n_restarts < 1 and the host-path solve modes."""
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.ops.compile import compile_from_arrays
    from pydcop_tpu.ops.generate import coloring_arrays

    sc, tb, un = coloring_arrays(30, seed=1)
    p = compile_from_arrays(sc, tb, 3, unary=un)
    module = load_algorithm_module("dsa")
    params = prepare_algo_params({"variant": "B"}, module.algo_params)
    with pytest.raises(ValueError, match="n_restarts"):
        run_batched(p, module, params, rounds=8, n_restarts=0)
    from pydcop_tpu.api import solve

    with pytest.raises(ValueError, match="n_restarts"):
        solve(random_dcop(1), "dsa", mode="sim", n_restarts=4)
    with pytest.raises(ValueError, match="host-path|exact"):
        solve(random_dcop(1), "dpop", n_restarts=4)
