"""External algorithm plugins via dotted module names (docs/extending.md).

The reference discovers algorithms inside its own package
(``pydcop/algorithms/__init__.py`` module path); the dotted-name escape
hatch lets third-party modules plug into the same registry seam without
being copied into the package.
"""

import sys
import textwrap

import pytest

from pydcop_tpu.algorithms import AlgorithmDefError, load_algorithm_module
from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str

PLUGIN = textwrap.dedent(
    """
    # Minimal external plugin: greedy best-response (DSA-C with p=1).
    import jax
    import jax.numpy as jnp
    from pydcop_tpu.ops.costs import local_cost_sweep

    GRAPH_TYPE = "constraints_hypergraph"
    algo_params = []

    def init_state(problem, key, params):
        return {"values": problem.init_idx}

    def step(problem, state, key, params, axis_name=None):
        local = local_cost_sweep(problem, state["values"], axis_name)
        # alternate parity classes so neighbors never move together
        parity = jnp.arange(problem.n_vars) % 2
        rnd = jax.random.randint(key, (), 0, 2)
        cand = jnp.argmin(local, axis=1).astype(state["values"].dtype)
        move = parity == rnd
        return {"values": jnp.where(move, cand, state["values"])}

    def values_from_state(state):
        return state["values"]

    def messages_per_round(problem, params=None):
        import numpy as np
        return int(np.asarray(problem.neighbor_mask).sum())

    def computation_memory(node):
        return len(node.neighbors)

    def communication_load(node, neighbor_name):
        return 1.0

    def build_computation(comp_def, seed=0):
        # host path: reuse the DSA skeleton (docs/extending.md)
        from pydcop_tpu.algorithms import _host_dsa
        return _host_dsa.build_computation(
            comp_def, seed=seed, variant="C", probability=1.0
        )
    """
)


def ring(n=8, colors=3):
    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP(f"ring{n}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"1 if v{i} == v{(i + 1) % n} else 0", vs
            )
        )
    return dcop


@pytest.fixture()
def plugin_on_path(tmp_path):
    pkg = tmp_path / "extlab"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "greedy.py").write_text(PLUGIN)
    sys.path.insert(0, str(tmp_path))
    try:
        yield "extlab.greedy"
    finally:
        sys.path.remove(str(tmp_path))
        for m in ("extlab", "extlab.greedy"):
            sys.modules.pop(m, None)


def test_dotted_name_loads_and_solves(plugin_on_path):
    mod = load_algorithm_module(plugin_on_path)
    assert mod.GRAPH_TYPE == "constraints_hypergraph"
    result = solve(ring(8, 3), plugin_on_path, rounds=60, seed=0)
    assert result["cost"] == 0.0
    assert result["msg_count"] > 0


def test_dotted_name_reaches_process_mode_children(plugin_on_path):
    # the forked agent processes must inherit the plugin's sys.path
    # entry (api._solve_process forwards it via PYTHONPATH) — without
    # it every child dies at deploy with an import error
    result = solve(
        ring(6, 3), plugin_on_path, mode="process", nb_agents=2,
        timeout=60,
    )
    # any clean terminal status proves the children imported the
    # plugin; a missing PYTHONPATH entry raises AgentFailureError
    assert result["status"] in ("finished", "stopped", "msg_budget")
    assert set(result["assignment"]) == {f"v{i}" for i in range(6)}


def test_dotted_name_must_be_a_plugin():
    with pytest.raises(AlgorithmDefError, match="not an algorithm plugin"):
        load_algorithm_module("os.path")  # importable, but no GRAPH_TYPE


def test_broken_external_plugin_reports_import_failure(tmp_path):
    pkg = tmp_path / "brokenlab"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "bad.py").write_text("import not_a_real_dependency\n")
    sys.path.insert(0, str(tmp_path))
    try:
        with pytest.raises(
            AlgorithmDefError, match="exists but failed to import"
        ):
            load_algorithm_module("brokenlab.bad")
        with pytest.raises(AlgorithmDefError) as ei:
            load_algorithm_module("brokenlab.nope")
        assert "available" not in str(ei.value)
    finally:
        sys.path.remove(str(tmp_path))
        for m in ("brokenlab", "brokenlab.bad"):
            sys.modules.pop(m, None)


def test_unknown_plain_name_lists_available():
    with pytest.raises(AlgorithmDefError, match="available"):
        load_algorithm_module("definitely_not_an_algo")


def test_relative_name_rejected_cleanly():
    with pytest.raises(AlgorithmDefError, match="relative"):
        load_algorithm_module(".foo")


def test_solve_host_only_external_plugin_loads(tmp_path):
    pkg = tmp_path / "exactlab"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "solver.py").write_text(
        "algo_params = []\n"
        "def solve_host(dcop, params, timeout=None):\n"
        "    return {}\n"
    )
    sys.path.insert(0, str(tmp_path))
    try:
        mod = load_algorithm_module("exactlab.solver")
        assert hasattr(mod, "solve_host")
    finally:
        sys.path.remove(str(tmp_path))
        for m in ("exactlab", "exactlab.solver"):
            sys.modules.pop(m, None)


def test_accel_agents_without_island_support_fails_prefork():
    # mgm2 has no island: its 5-phase offer/accept protocol has
    # per-neighbor payloads the lockstep skeleton does not model
    with pytest.raises(ValueError, match="no compiled-island support"):
        solve(
            ring(6, 3), "mgm2", mode="process", nb_agents=2,
            accel_agents=["agent_0"], timeout=30,
        )
