"""Semiring contraction core (``ops/semiring.py``,
``docs/semirings.md``): algebra axioms, logsumexp stability,
brute-force parity of marginals/log_z/MAP on small random graphs,
elimination-order equivalence, batched-vs-sequential identity, and
the device path's exactness/error contracts.
"""

import itertools
import random

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops import semiring as sr

pytestmark = pytest.mark.semiring


# -- helpers ------------------------------------------------------------


def _random_dcop(n, seed, d=3, extra_edges=2, objective="min"):
    """A random spanning tree plus a few loop edges: small enough to
    brute-force, loopy enough that pseudo_tree and min_fill orders
    genuinely differ."""
    rnd = random.Random(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP(f"g{seed}", objective=objective)
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    cid = 0
    for i in range(1, n):
        j = rnd.randrange(i)
        t = np.array(
            [[rnd.uniform(0, 3) for _ in range(d)] for _ in range(d)]
        )
        dcop.add_constraint(
            NAryMatrixRelation([vs[j], vs[i]], t, name=f"c{cid}")
        )
        cid += 1
    for _ in range(extra_edges):
        i, j = rnd.sample(range(n), 2)
        t = np.array(
            [[rnd.uniform(0, 3) for _ in range(d)] for _ in range(d)]
        )
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[min(i, j)], vs[max(i, j)]], t, name=f"c{cid}"
            )
        )
        cid += 1
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def _brute_force(dcop, beta=1.0):
    """Host-f64 enumeration: (log_z, marginals, min cost)."""
    sign = -1.0 if dcop.objective == "max" else 1.0
    vs = sorted(dcop.variables)
    doms = {v: list(dcop.variables[v].domain.values) for v in vs}
    logw, costs, assigns = [], [], []
    for combo in itertools.product(*(doms[v] for v in vs)):
        a = dict(zip(vs, combo))
        e = sign * dcop.solution_cost(a)
        logw.append(-beta * e)
        costs.append(e)
        assigns.append(a)
    logw = np.asarray(logw)
    m = logw.max()
    log_z = m + np.log(np.exp(logw - m).sum())
    p = np.exp(logw - log_z)
    marg = {}
    for v in vs:
        out = np.zeros(len(doms[v]))
        for pi, a in enumerate(assigns):
            out[doms[v].index(a[v])] += p[pi]
        marg[v] = out
    return float(log_z), marg, float(min(costs))


# -- semiring axioms ----------------------------------------------------


@pytest.mark.parametrize(
    "name", ["min_sum", "max_sum", "log_sum_exp", "marginals"]
)
def test_semiring_axioms(name):
    """⊕ is associative+commutative with its identity; ⊗ (+) is
    associative+commutative with identity 0; the ⊕-identity
    annihilates ⊗; ⊗ distributes over ⊕ — the properties the
    contraction sweep's reorderings rely on.  Idempotent ⊕ is exact
    (array equality); logsumexp up to f64 rounding."""
    s = sr.get_semiring(name)
    rnd = np.random.RandomState(7)
    a, b, c = (rnd.uniform(-5, 5, size=17) for _ in range(3))
    # min/max are EXACT on floats; logsumexp and chained f64 adds
    # (⊗-associativity, distributivity) carry rounding — approx there
    exact = (
        np.testing.assert_array_equal
        if s.idempotent
        else lambda x, y: np.testing.assert_allclose(
            x, y, rtol=0, atol=1e-12
        )
    )

    def approx(x, y):
        np.testing.assert_allclose(x, y, rtol=0, atol=1e-12)

    # ⊕: associative, commutative, identity
    exact(s.add(s.add(a, b), c), s.add(a, s.add(b, c)))
    exact(s.add(a, b), s.add(b, a))
    ident = np.full_like(a, s.plus_identity)
    exact(s.add(a, ident), a)
    # ⊗ (+ in log domain): associative, commutative, identity 0
    approx(
        s.combine(s.combine(a, b), c), s.combine(a, s.combine(b, c))
    )
    exact(s.combine(a, b), s.combine(b, a))
    exact(s.combine(a, np.full_like(a, s.times_identity)), a)
    # the ⊕-identity annihilates ⊗
    exact(s.combine(a, ident), ident)
    # distributivity: a ⊗ (b ⊕ c) == (a ⊗ b) ⊕ (a ⊗ c)
    approx(
        s.combine(a, s.add(b, c)),
        s.add(s.combine(a, b), s.combine(a, c)),
    )
    # idempotence where claimed
    if s.idempotent:
        exact(s.add(a, a), a)


def test_logsumexp_stability_vs_host_f64():
    """The stable logsumexp must survive magnitudes where the naive
    form overflows/underflows, and match a shifted f64 reference."""
    s = sr.get_semiring("log_sum_exp")
    for scale in (1.0, 500.0, 1000.0, -1000.0):
        rnd = np.random.RandomState(int(abs(scale)))
        a = rnd.uniform(-2, 2, size=64) + scale
        m = a.max()
        ref = m + np.log(np.exp(a - m).sum())
        got = float(s.reduce(a))
        assert np.isfinite(got)
        assert got == pytest.approx(ref, abs=1e-12)
    # all--inf reduces to -inf, not nan
    assert s.reduce(np.full(5, -np.inf)) == -np.inf
    # -inf entries are absorbed exactly
    a = np.array([-np.inf, 0.0, 1.0])
    assert float(s.reduce(a)) == pytest.approx(
        np.log(1 + np.e), abs=1e-12
    )


def test_registry_lookup_and_registration():
    assert sr.get_semiring("min_sum") is sr.MIN_SUM
    assert sr.get_semiring(sr.MAX_SUM) is sr.MAX_SUM
    with pytest.raises(ValueError, match="unknown semiring"):
        sr.get_semiring("tropical_typo")
    custom = sr.Semiring("test_custom_max", idempotent=True,
                         maximize=True)
    sr.register_semiring(custom)
    try:
        assert sr.get_semiring("test_custom_max") is custom
    finally:
        del sr.SEMIRINGS["test_custom_max"]


# -- brute-force parity -------------------------------------------------


@pytest.mark.parametrize("order", ["pseudo_tree", "min_fill"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_infer_matches_brute_force(order, seed):
    """log_z and marginals within 1e-6 of host-f64 enumeration, MAP
    cost exactly the brute-force optimum — on <=12-var random loopy
    graphs, under both elimination orders (the ISSUE 8 acceptance
    bar)."""
    from pydcop_tpu.api import infer

    n = 6 + seed  # 6, 7, 8 vars (brute force is 3^n enumerations)
    dcop = _random_dcop(n, seed)
    log_z, marg, best = _brute_force(dcop)
    rz = infer(dcop, "log_z", order=order)
    assert rz["status"] == "finished"
    assert rz["log_z"] == pytest.approx(log_z, abs=1e-6)
    assert rz["error_bound"] < 1e-6
    rm = infer(dcop, "marginals", order=order)
    assert rm["log_z"] == pytest.approx(log_z, abs=1e-6)
    for v, probs in marg.items():
        np.testing.assert_allclose(
            rm["marginals"][v], probs, atol=1e-6
        )
        assert sum(rm["marginals"][v]) == pytest.approx(1.0)
    rmap = infer(dcop, "map", order=order)
    assert rmap["cost"] == pytest.approx(best, abs=1e-9)
    assert dcop.solution_cost(rmap["assignment"]) == rmap["cost"]
    # the MAP log-weight is -beta * cost (up to fp noise)
    assert rmap["log_weight"] == pytest.approx(-best, abs=1e-6)


def test_infer_beta_scales_distribution():
    """beta reweights the Gibbs distribution: large beta concentrates
    mass on the optimum (log_z -> -beta * min cost + log #optima)."""
    from pydcop_tpu.api import infer

    dcop = _random_dcop(6, 3)
    _, _, best = _brute_force(dcop)
    r = infer(dcop, "log_z", beta=50.0)
    assert r["log_z"] == pytest.approx(-50.0 * best, abs=1e-3)
    bb = _brute_force(dcop, beta=0.25)
    r2 = infer(dcop, "log_z", beta=0.25)
    assert r2["log_z"] == pytest.approx(bb[0], abs=1e-6)


def test_infer_max_objective_and_map_equals_dpop():
    """`objective: max` problems fold signs the same way solve() does:
    MAP equals the DPOP optimum."""
    from pydcop_tpu.api import infer, solve

    dcop = _random_dcop(7, 5, objective="max")
    rmap = infer(dcop, "map")
    rdpop = solve(dcop, "dpop", {"util_device": "never"})
    assert rmap["cost"] == pytest.approx(rdpop["cost"], abs=1e-9)


def test_infer_handles_isolated_variable_and_unary_costs():
    """A constraint-free variable contributes log(d) to log_z and a
    uniform marginal; unary value costs are folded in."""
    from pydcop_tpu.api import infer

    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("iso")
    a = Variable("a", dom)
    b = Variable("b", dom)  # isolated
    dcop.add_variable(a)
    dcop.add_variable(b)
    dcop.add_constraint(
        NAryMatrixRelation([a], np.array([0.0, 1.0, 2.0]), name="u")
    )
    dcop.add_agents([AgentDef("ag0"), AgentDef("ag1")])
    r = infer(dcop, "marginals")
    w = np.exp(-np.array([0.0, 1.0, 2.0]))
    np.testing.assert_allclose(
        r["marginals"]["a"], w / w.sum(), atol=1e-9
    )
    np.testing.assert_allclose(
        r["marginals"]["b"], np.full(3, 1 / 3), atol=1e-9
    )
    assert r["log_z"] == pytest.approx(
        float(np.log(w.sum()) + np.log(3)), abs=1e-9
    )


def test_min_fill_is_narrower_on_a_loopy_grid():
    """On a grid the DFS pseudo-tree order's induced width is known
    to exceed min-fill's (which achieves the grid's treewidth-ish
    bound) — the reason the heuristic is pluggable at all.  Both must
    agree on the answer, and match brute force."""
    from pydcop_tpu.api import infer

    rows, cols = 3, 4
    dom = Domain("d", "", [0, 1])
    dcop = DCOP("grid")
    vs = {}
    for i in range(rows):
        for j in range(cols):
            v = Variable(f"v{i}{j}", dom)
            vs[i, j] = v
            dcop.add_variable(v)
    rnd = np.random.RandomState(0)
    cid = 0
    for i in range(rows):
        for j in range(cols):
            for di, dj in ((0, 1), (1, 0)):
                if i + di < rows and j + dj < cols:
                    dcop.add_constraint(
                        NAryMatrixRelation(
                            [vs[i, j], vs[i + di, j + dj]],
                            rnd.uniform(0, 2, (2, 2)),
                            name=f"c{cid}",
                        )
                    )
                    cid += 1
    dcop.add_agents([AgentDef(f"ag{i}") for i in range(rows * cols)])
    rp = infer(dcop, "log_z", order="pseudo_tree")
    rf = infer(dcop, "log_z", order="min_fill")
    assert rf["log_z"] == pytest.approx(rp["log_z"], abs=1e-6)
    assert rf["width"] <= rp["width"]
    log_z, _, _ = _brute_force(dcop)
    assert rf["log_z"] == pytest.approx(log_z, abs=1e-6)


# -- batching -----------------------------------------------------------


def test_infer_many_batched_identical_to_sequential():
    """K>1 merged sweeps return byte-identical payloads to sequential
    infer() calls — the solve_many batching contract (ISSUE 8
    acceptance)."""
    from pydcop_tpu.api import infer, infer_many

    dcops = [_random_dcop(6 + s, s) for s in range(4)]
    for query in ("log_z", "marginals", "map"):
        many = infer_many(dcops, query, pad_policy="pow2")
        for i, d in enumerate(dcops):
            one = infer(d, query, pad_policy="pow2")
            assert many[i]["instances_batched"] == len(dcops)
            if query == "map":
                assert many[i]["assignment"] == one["assignment"]
                assert many[i]["cost"] == one["cost"]
            elif query == "log_z":
                assert many[i]["log_z"] == one["log_z"]
            else:
                assert many[i]["marginals"] == one["marginals"]
                assert many[i]["log_z"] == one["log_z"]


def test_infer_many_empty_and_validation():
    from pydcop_tpu.api import infer_many

    assert infer_many([], "log_z") == []
    dcop = _random_dcop(5, 0)
    with pytest.raises(ValueError, match="unknown query"):
        infer_many([dcop], "entropy")
    with pytest.raises(ValueError, match="unknown elimination order"):
        infer_many([dcop], "log_z", order="min_width")
    with pytest.raises(ValueError, match="device"):
        infer_many([dcop], "log_z", device="gpu")
    with pytest.raises(ValueError, match="beta"):
        infer_many([dcop], "log_z", beta=0.0)


def test_infer_width_guard_suggests_min_fill():
    """An over-width contraction fails with an actionable error
    instead of a MemoryError."""
    from pydcop_tpu.api import infer

    dcop = _random_dcop(10, 2, extra_edges=12)
    with pytest.raises(ValueError, match="min_fill"):
        infer(dcop, "log_z", max_table_size=8)


# -- device path --------------------------------------------------------


def test_device_map_is_exact_and_log_z_within_bound():
    """device='always': MAP stays EXACT (f32 argmax certificate +
    host-f64 values), and the device log_z lands within its reported
    error_bound of the host-f64 answer."""
    from pydcop_tpu.api import infer

    dcop = _random_dcop(8, 4)
    host_map = infer(dcop, "map", device="never")
    dev_map = infer(dcop, "map", device="always", pad_policy="pow2")
    assert dev_map["device_nodes"] > 0
    assert dev_map["assignment"] == host_map["assignment"]
    assert dev_map["cost"] == host_map["cost"]

    host_z = infer(dcop, "log_z", device="never")
    dev_z = infer(
        dcop, "log_z", device="always", tol=float("inf"),
        pad_policy="pow2",
    )
    assert dev_z["device_nodes"] > 0
    assert dev_z["error_bound"] > 0
    assert (
        abs(dev_z["log_z"] - host_z["log_z"])
        <= dev_z["error_bound"] + 1e-9
    )


def test_logsumexp_tol_gate_forces_host_and_counts_repairs():
    """With the default tight tol, device-eligible logsumexp
    contractions are repaired onto host f64 (counted), and the
    result matches the pure-host run bit-for-bit."""
    from pydcop_tpu.api import infer
    from pydcop_tpu.telemetry import session

    dcop = _random_dcop(8, 4)
    with session() as tel:
        r = infer(dcop, "log_z", device="always", tol=1e-9)
    counters = tel.summary()["counters"]
    assert r["device_nodes"] == 0  # every contraction gated to host
    assert int(counters.get("semiring.logsumexp_repairs", 0)) > 0
    host = infer(dcop, "log_z", device="never")
    assert r["log_z"] == host["log_z"]
    assert r["error_bound"] < 1e-9


def test_contraction_kernel_cache_is_per_semiring():
    """The kernel cache keys on the semiring name: the same shape
    bucket resolves to distinct executables per ⊕, and repeat lookups
    hit the cache."""
    shape = (4, 4)
    parts = ((4, 4), (1, 4))
    k_min = sr.contraction_kernel("min_sum", shape, parts)
    k_max = sr.contraction_kernel("max_sum", shape, parts)
    k_lse = sr.contraction_kernel("log_sum_exp", shape, parts)
    assert k_min is not k_max and k_max is not k_lse
    assert sr.contraction_kernel("min_sum", shape, parts) is k_min
    # marginals and log_sum_exp share ⊕ but cache separately (their
    # sweeps differ in normalization, not in the kernel math)
    assert (
        sr.contraction_kernel("marginals", shape, parts) is not k_lse
    )


def test_dpop_join_kernel_is_the_min_sum_instantiation():
    """algorithms/dpop.py's UTIL join resolves to the shared semiring
    kernel cache (the rebuilt-on-top property, not a parallel code
    path)."""
    from pydcop_tpu.algorithms import dpop

    assert dpop._JOIN_KERNELS is sr._KERNELS
    shape, parts = (3, 5), ((3, 5), (1, 5))
    fn = dpop._join_kernel(shape, parts)
    assert (
        sr.contraction_kernel("min_sum", shape, parts) is fn
    )


# -- BP factor messages (the Max-Sum instantiation) ---------------------


def test_bp_factor_messages_min_sum_matches_inline_loop():
    """bp_factor_messages(min_sum) reproduces Max-Sum's historical
    factor phase bit-for-bit (the refactor's parity contract)."""
    import jax.numpy as jnp

    rnd = np.random.RandomState(3)
    d, m, k = 3, 5, 2
    tab = jnp.asarray(
        rnd.uniform(0, 4, size=(d, d, m)).astype(np.float32)
    )
    q_pos = [
        jnp.asarray(rnd.uniform(0, 2, size=(d, m)).astype(np.float32))
        for _ in range(k)
    ]
    # the historical inline loop
    s = tab
    for p in range(k):
        shape = (1,) * p + (d,) + (1,) * (k - 1 - p) + (m,)
        s = s + q_pos[p].astype(tab.dtype).reshape(shape)
    expect = []
    for p in range(k):
        axes = tuple(a for a in range(k) if a != p)
        mp = jnp.min(s, axis=axes)
        rp = mp - q_pos[p].astype(tab.dtype)
        rp = rp - jnp.min(rp, axis=0, keepdims=True)
        expect.append(rp)
    got = sr.bp_factor_messages(sr.MIN_SUM, tab, q_pos, tab.dtype)
    for g, e in zip(got, expect):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e))


def test_bp_factor_messages_sum_product_is_normalized_marginal_bp():
    """The same wiring at +/x computes sum-product messages: for a
    single binary factor, exp(r_p) must be proportional to the true
    conditional-marginal message."""
    rnd = np.random.RandomState(1)
    d, m = 3, 1
    tab = (-rnd.uniform(0, 2, size=(d, d, m))).astype(np.float32)
    q0 = np.zeros((d, m), dtype=np.float32)
    q1 = np.log(
        rnd.dirichlet(np.ones(d)).reshape(d, m)
    ).astype(np.float32)
    import jax.numpy as jnp

    got = sr.bp_factor_messages(
        sr.LOG_SUM_EXP, jnp.asarray(tab), [jnp.asarray(q0),
                                           jnp.asarray(q1)],
        jnp.float32,
    )
    # reference: r_0(x0) ~ log sum_x1 exp(tab + q1)
    ref = np.log(
        np.sum(np.exp(tab[..., 0] + q1[:, 0][None, :]), axis=1)
    )
    r0 = np.asarray(got[0])[:, 0]
    np.testing.assert_allclose(
        r0 - r0.max(), ref - ref.max(), atol=1e-5
    )


def test_error_bound_accumulates_linearly_with_depth():
    """The reported error_bound is the sum of ROOT accumulations (each
    root entry already chains its subtree) — doubling a chain's depth
    must roughly double the bound, not quadruple it (the
    every-node-summed regression counted each local error once per
    ancestor)."""
    from pydcop_tpu.api import infer

    def chain(n):
        rnd = random.Random(0)
        dom = Domain("d", "", [0, 1, 2])
        dcop = DCOP(f"chain{n}")
        vs = [Variable(f"v{i:03d}", dom) for i in range(n)]
        for v in vs:
            dcop.add_variable(v)
        for i in range(1, n):
            t = np.array(
                [[rnd.uniform(0, 3) for _ in range(3)] for _ in range(3)]
            )
            dcop.add_constraint(
                NAryMatrixRelation([vs[i - 1], vs[i]], t, name=f"c{i}")
            )
        dcop.add_agents([AgentDef("a")])
        return dcop

    kw = dict(device="always", tol=float("inf"), pad_policy="pow2")
    b8 = infer(chain(8), "log_z", **kw)["error_bound"]
    b16 = infer(chain(16), "log_z", **kw)["error_bound"]
    b32 = infer(chain(32), "log_z", **kw)["error_bound"]
    assert 0 < b8 < b16 < b32
    assert b16 / b8 < 3.0 and b32 / b16 < 3.0


def test_min_fill_incremental_matches_recompute_reference():
    """The incrementally-cached min-fill must pick the exact same
    order as the naive recompute-every-count definition (same fill
    counts, same (fill, degree, name) tie-break), and its deadline
    turns an over-budget search into a timeout instead of a hang."""

    def min_fill_ref(domains, scopes):
        adj = {v: set() for v in domains}
        for scope in scopes:
            sc = [v for v in scope if v in adj]
            for a in sc:
                for b in sc:
                    if a != b:
                        adj[a].add(b)
        remaining = {v: set(ns) for v, ns in adj.items()}
        order = []

        def fc(v):
            ns = list(remaining[v])
            c = 0
            for i in range(len(ns)):
                for j in range(i + 1, len(ns)):
                    if ns[j] not in remaining[ns[i]]:
                        c += 1
            return c

        while remaining:
            v = min(
                remaining,
                key=lambda x: (fc(x), len(remaining[x]), x),
            )
            order.append(v)
            ns = list(remaining[v])
            for i in range(len(ns)):
                for j in range(i + 1, len(ns)):
                    remaining[ns[i]].add(ns[j])
                    remaining[ns[j]].add(ns[i])
            for nb in ns:
                remaining[nb].discard(v)
            del remaining[v]
        return order

    for seed in range(4):
        rnd = random.Random(seed)
        n = 30
        doms = {f"v{i}": [0, 1] for i in range(n)}
        scopes = [
            [f"v{rnd.randrange(n)}", f"v{rnd.randrange(n)}"]
            for _ in range(70)
        ]
        assert sr.min_fill_order(doms, scopes) == min_fill_ref(
            doms, scopes
        ), seed
    with pytest.raises(TimeoutError, match="min_fill"):
        sr.min_fill_order(doms, scopes, deadline=0.0)
    # and through the API: a spent budget surfaces as a timeout
    # result (large enough that the min_fill search cannot finish
    # inside the 10ms floor the API clamps a spent deadline to)
    from pydcop_tpu.api import infer

    r = infer(_random_dcop(400, 0, extra_edges=400), "log_z",
              order="min_fill", timeout=1e-9)
    assert r["status"] == "timeout"


# -- observability ------------------------------------------------------


def test_trace_summary_folds_semiring_report(tmp_path):
    """A traced infer run lands contraction spans + counters, and
    trace-summary folds them into a per-semiring report (cells/sec),
    in both the JSON and text renderings."""
    from pydcop_tpu.api import infer
    from pydcop_tpu.telemetry.summary import (
        format_summary,
        load_trace,
        summarize,
    )

    trace = str(tmp_path / "t.jsonl")
    infer(_random_dcop(6, 0), "marginals", trace=trace)
    s = summarize(load_trace(trace))
    assert "marginals" in s["semiring"]["by_semiring"]
    rec = s["semiring"]["by_semiring"]["marginals"]
    assert rec["sweeps"] >= 2  # upward contract + downward pass
    assert rec["cells"] > 0 and "cells_per_sec" in rec
    assert (
        s["semiring"]["counters"]["semiring.contractions"] == 6
    )
    text = format_summary(s)
    assert "semiring contractions" in text
    assert "cells/s" in text


# -- branch-and-bound pruned kernels (bnb) ------------------------------


def _hard_band_dcop(
    n, seed, d=4, arity=4, stride=2, cap=1.15, ties=False,
):
    """Chained overlap band with HARD over-sum caps (``+inf`` past
    ``cap × target``) — the structure the two-pass ⊕-bounded kernels
    prune.  ``ties=True`` quantizes costs to a coarse grid so tables
    are tie-heavy (exercising pruning × certificate-repair at once).
    Small enough to brute-force / run in-suite."""
    rnd = random.Random(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP(f"hb{seed}")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for i, v in enumerate(vs):
        dcop.add_variable(v)
        dcop.add_constraint(
            NAryMatrixRelation(
                [v],
                np.arange(d, dtype=np.float64)
                * rnd.uniform(0.05, 0.3),
                name=f"u{i}",
            )
        )
    for m in range((n - arity) // stride + 1):
        scope = vs[m * stride:m * stride + arity]
        t = rnd.uniform(0.3, 0.8) * arity * (d - 1)
        mat = np.zeros((d,) * arity)
        for idx in itertools.product(range(d), repeat=arity):
            s = sum(idx)
            if s > cap * t:
                mat[idx] = np.inf
            else:
                c = abs(s - t)
                mat[idx] = round(c * 2) / 2.0 if ties else c
        dcop.add_constraint(
            NAryMatrixRelation(scope, mat, name=f"m{m}")
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def _bnb_counters(result):
    c = result["telemetry"]["counters"]
    return (
        int(c.get("semiring.bnb_passes", 0)),
        int(c.get("semiring.bnb_pruned_cells", 0)),
    )


@pytest.mark.semiring
@pytest.mark.parametrize(
    "seed,ties", [(1, False), (2, True), (5, True)]
)
def test_bnb_idempotent_bitwise_parity(seed, ties):
    """bnb=on is BIT-IDENTICAL to bnb=off for the idempotent ⊕s on
    hard-capped, tie-heavy bands: same dpop cost+assignment, same
    infer map assignment — pruned rows provably cannot enter the
    optimum, and the f32 slack keeps the comparison conservative."""
    from pydcop_tpu.api import infer, solve

    dcop = _hard_band_dcop(10, seed, ties=ties)
    kw = dict(pad_policy="pow2")
    r_off = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "off"}, **kw
    )
    r_on = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "on"}, **kw
    )
    assert r_on["cost"] == r_off["cost"]
    assert r_on["assignment"] == r_off["assignment"]
    passes, pruned = _bnb_counters(r_on)
    assert passes >= 1  # the pruned kernels actually ran
    m_off = infer(dcop, "map", device="always", bnb="off")
    m_on = infer(dcop, "map", device="always", bnb="on")
    assert m_on["cost"] == m_off["cost"]
    assert m_on["assignment"] == m_off["assignment"]


@pytest.mark.semiring
def test_bnb_prunes_hard_capped_rows():
    """On a hard-capped band the pruned-cell counter is non-zero
    (jointly-over-budget rows die in pass 1) and the result is still
    exact vs the pure host-f64 solve."""
    from pydcop_tpu.api import solve

    dcop = _hard_band_dcop(12, 3, d=5, arity=5, stride=2, cap=1.1)
    base = solve(dcop, "dpop", {"util_device": "never"})
    r_on = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "on"},
        pad_policy="pow2",
    )
    assert r_on["cost"] == base["cost"]
    assert r_on["assignment"] == base["assignment"]
    passes, pruned = _bnb_counters(r_on)
    assert pruned >= 1, r_on["telemetry"]["counters"]


@pytest.mark.semiring
def test_bnb_mass_queries_within_error_bound():
    """logsumexp/marginals/expectation under bnb=on: discarded mass
    is accounted — results stay within the REPORTED error_bound of
    the unpruned run (tol loosened so the device + pruning actually
    engage on these small tables)."""
    from pydcop_tpu.api import infer

    dcop = _hard_band_dcop(9, 4, d=4, arity=4)
    kw = dict(device="always", tol=1e-3, pad_policy="pow2")
    for query in ("log_z", "expectation"):
        off = infer(dcop, query, bnb="off", **kw)
        on = infer(dcop, query, bnb="on", **kw)
        bound = max(on["error_bound"], off["error_bound"]) + 1e-9
        key = "log_z" if query == "log_z" else "e_cost"
        tol_key = (
            bound if query == "log_z"
            # e_cost error scales the weight-plane bound by the cost
            # magnitude (docs/semirings.md) — allow that factor
            else bound * max(abs(on["e_cost"]), 1.0) * 10
        )
        assert abs(on[key] - off[key]) <= tol_key, (
            query, on[key], off[key], bound,
        )
    off = infer(dcop, "marginals", bnb="off", **kw)
    on = infer(dcop, "marginals", bnb="on", **kw)
    for v, p in off["marginals"].items():
        assert np.allclose(
            p, on["marginals"][v],
            atol=max(on["error_bound"], 1e-6) * 10 + 1e-9,
        )


@pytest.mark.semiring
def test_bnb_kbest_prunes_without_losing_slot_k():
    """kbest:5 under bnb=on: per-slot bounds against the k-th
    incumbent prune rows WITHOUT losing any of the 5 best — the
    solution list (assignments, costs, order) is bit-identical to
    the unpruned kernel, 5 distinct ascending entries."""
    from pydcop_tpu.api import infer

    dcop = _hard_band_dcop(11, 7, d=4, arity=4, cap=1.2)
    kw = dict(device="always", pad_policy="pow2")
    off = infer(dcop, "kbest:5", bnb="off", **kw)
    on = infer(dcop, "kbest:5", bnb="on", **kw)
    assert on["costs"] == off["costs"]
    assert [s["assignment"] for s in on["solutions"]] == [
        s["assignment"] for s in off["solutions"]
    ]
    assert len(on["solutions"]) == 5
    es = [s["energy"] for s in on["solutions"]]
    assert es == sorted(es)
    assert len({tuple(sorted(s["assignment"].items()))
                for s in on["solutions"]}) == 5
    passes, pruned = _bnb_counters(on)
    assert pruned >= 1, on["telemetry"]["counters"]


@pytest.mark.semiring
def test_bnb_auto_skips_small_dispatches():
    """bnb='auto' keeps the single-pass kernel for dispatches below
    the size threshold (semiring.bnb_skipped_small counts them) —
    small factors must not pay the two-pass overhead."""
    from pydcop_tpu.api import solve

    dcop = _hard_band_dcop(8, 9, d=3, arity=3)
    r = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "auto"},
        pad_policy="pow2",
    )
    c = r["telemetry"]["counters"]
    assert int(c.get("semiring.bnb_skipped_small", 0)) >= 1, c
    assert int(c.get("semiring.bnb_passes", 0)) == 0, c


@pytest.mark.semiring
def test_bnb_bp_factor_messages_bitwise():
    """The BP factor phase's two-pass variant is bit-identical to
    the single-pass kernel — tie-heavy and ±inf hard-constraint
    tables included (pruned configs are strictly worse than every
    output's f32 optimum)."""
    import jax.numpy as jnp

    rnd = np.random.default_rng(11)
    k, d, m = 3, 4, 6
    tab = np.round(rnd.uniform(0, 4, size=(d,) * k + (m,)), 1)
    tab[tab > 3.5] = np.inf  # hard cells + plenty of exact ties
    q = [
        np.round(rnd.uniform(0, 2, size=(d, m)), 1).astype(
            np.float32
        )
        for _ in range(k)
    ]
    tab32 = jnp.asarray(tab, dtype=jnp.float32)
    qj = [jnp.asarray(x) for x in q]
    base = sr.bp_factor_messages(sr.MIN_SUM, tab32, qj, jnp.float32)
    bnb = sr.bp_factor_messages(
        sr.MIN_SUM, tab32, qj, jnp.float32, bnb=True
    )
    for a, b in zip(base, bnb):
        assert np.array_equal(
            np.asarray(a), np.asarray(b), equal_nan=True
        )


@pytest.mark.semiring
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_bnb_parity_matrix_slow(seed):
    """The broad property matrix (kept out of tier-1 for the time
    budget): random hard/tie bands × every query family, bnb=on vs
    off — idempotent ⊕ bitwise, mass ⊕ within bounds."""
    from pydcop_tpu.api import infer, solve

    ties = seed % 2 == 1
    dcop = _hard_band_dcop(
        12, 20 + seed, d=4, arity=4 + seed % 2, ties=ties,
        cap=1.1 + 0.1 * (seed % 3),
    )
    kw = dict(pad_policy="pow2")
    r_off = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "off"}, **kw
    )
    r_on = solve(
        dcop, "dpop", {"util_device": "always", "bnb": "on"}, **kw
    )
    assert r_on["cost"] == r_off["cost"]
    assert r_on["assignment"] == r_off["assignment"]
    off = infer(dcop, "kbest:5", device="always", bnb="off", **kw)
    on = infer(dcop, "kbest:5", device="always", bnb="on", **kw)
    assert on["costs"] == off["costs"]
    z_off = infer(
        dcop, "log_z", device="always", tol=1e-3, bnb="off", **kw
    )
    z_on = infer(
        dcop, "log_z", device="always", tol=1e-3, bnb="on", **kw
    )
    assert abs(z_on["log_z"] - z_off["log_z"]) <= (
        max(z_on["error_bound"], z_off["error_bound"]) + 1e-9
    )


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
