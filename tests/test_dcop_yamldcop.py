import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.yamldcop import (
    DcopInvalidFormatError,
    dcop_yaml,
    load_dcop,
    load_scenario,
)

GRAPH_COLORING_YAML = """
name: graph coloring
objective: min
description: a small graph coloring problem

domains:
  colors:
    values: [R, G, B]
    type: color

variables:
  v1:
    domain: colors
    initial_value: R
  v2:
    domain: colors
  v3:
    domain: colors
    cost_function: 0.1 if v3 == 'R' else 0

constraints:
  diff_12:
    type: intention
    function: 10 if v1 == v2 else 0
  diff_23:
    type: extensional
    variables: [v2, v3]
    default: 0
    values:
      10: R R | G G | B B

agents:
  a1:
    capacity: 100
  a2:
    capacity: 100
    hosting:
      default: 1
      computations: {v1: 3}
    routes:
      default: 2
      a1: 0.5
"""


def test_load_graph_coloring():
    dcop = load_dcop(GRAPH_COLORING_YAML)
    assert dcop.name == "graph coloring"
    assert dcop.objective == "min"
    assert set(dcop.variables) == {"v1", "v2", "v3"}
    assert dcop.variables["v1"].initial_value == "R"
    assert set(dcop.constraints) == {"diff_12", "diff_23"}
    assert set(dcop.agents) == {"a1", "a2"}
    assert dcop.agents["a2"].hosting_cost("v1") == 3
    assert dcop.agents["a2"].hosting_cost("zz") == 1
    assert dcop.agents["a2"].route("a1") == 0.5


def test_constraint_semantics():
    dcop = load_dcop(GRAPH_COLORING_YAML)
    c12 = dcop.constraints["diff_12"]
    assert c12(v1="R", v2="R") == 10
    assert c12(v1="R", v2="G") == 0
    c23 = dcop.constraints["diff_23"]
    assert c23(v2="G", v3="G") == 10
    assert c23(v2="G", v3="B") == 0


def test_variable_cost_function():
    dcop = load_dcop(GRAPH_COLORING_YAML)
    v3 = dcop.variables["v3"]
    assert v3.has_cost
    assert v3.cost_for_val("R") == pytest.approx(0.1)
    assert v3.cost_for_val("G") == 0


def test_solution_cost():
    dcop = load_dcop(GRAPH_COLORING_YAML)
    cost = dcop.solution_cost({"v1": "R", "v2": "R", "v3": "R"})
    assert cost == pytest.approx(10 + 10 + 0.1)
    cost2 = dcop.solution_cost({"v1": "R", "v2": "G", "v3": "B"})
    assert cost2 == pytest.approx(0)


def test_range_domain():
    y = """
name: t
objective: min
domains:
  ten:
    values: [1 .. 5]
variables:
  x: {domain: ten}
constraints:
  u:
    type: intention
    function: x * 2
agents: [a1]
"""
    dcop = load_dcop(y)
    assert list(dcop.domains["ten"].values) == [1, 2, 3, 4, 5]
    assert dcop.constraints["u"](x=4) == 8


def test_agents_as_list():
    y = """
name: t
objective: min
domains:
  d: {values: [0, 1]}
variables:
  x: {domain: d}
constraints:
  u: {type: intention, function: x}
agents: [a1, a2, a3]
"""
    dcop = load_dcop(y)
    assert set(dcop.agents) == {"a1", "a2", "a3"}
    assert dcop.agents["a1"].capacity == 100.0


def test_external_variables():
    y = """
name: t
objective: min
domains:
  d: {values: [0, 1]}
variables:
  x: {domain: d}
external_variables:
  e: {domain: d, initial_value: 1}
constraints:
  c: {type: intention, function: x * e}
agents: [a1]
"""
    dcop = load_dcop(y)
    assert "e" in dcop.external_variables
    assert dcop.external_variables["e"].value == 1
    assert dcop.constraints["c"].arity == 2


def test_distribution_hints():
    y = """
name: t
objective: min
domains:
  d: {values: [0, 1]}
variables:
  x: {domain: d}
constraints:
  u: {type: intention, function: x}
agents: [a1, a2]
distribution_hints:
  must_host:
    a1: [x]
"""
    dcop = load_dcop(y)
    assert dcop.dist_hints is not None
    assert dcop.dist_hints.must_host("a1") == ["x"]


def test_invalid_yaml_raises():
    with pytest.raises(DcopInvalidFormatError):
        load_dcop("name: t\ndomains:\n  d: {novalues: 1}\nvariables: {}\n")
    with pytest.raises(DcopInvalidFormatError):
        load_dcop(
            "name: t\ndomains:\n  d: {values: [0]}\n"
            "variables:\n  x: {domain: nope}\n"
        )


def test_yaml_round_trip():
    dcop = load_dcop(GRAPH_COLORING_YAML)
    dumped = dcop_yaml(dcop)
    dcop2 = load_dcop(dumped)
    assert set(dcop2.variables) == set(dcop.variables)
    assert set(dcop2.constraints) == set(dcop.constraints)
    assert set(dcop2.agents) == set(dcop.agents)
    # semantics preserved
    for a in (
        {"v1": "R", "v2": "R", "v3": "R"},
        {"v1": "R", "v2": "G", "v3": "B"},
        {"v1": "B", "v2": "G", "v3": "G"},
    ):
        assert dcop2.solution_cost(a) == pytest.approx(dcop.solution_cost(a))


def test_load_scenario():
    y = """
events:
  - delay: 10
  - id: e1
    actions:
      - type: remove_agent
        agent: a2
  - id: e2
    actions:
      - type: set_value
        variable: e
        value: 1
"""
    s = load_scenario(y)
    assert len(s) == 3
    assert s.events[0].is_delay and s.events[0].delay == 10
    assert s.events[1].actions[0].type == "remove_agent"
    assert s.events[1].actions[0].args["agent"] == "a2"


def test_external_variable_and_hints_simple_repr_round_trip():
    from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

    dcop = load_dcop("""
name: t
objective: min
domains:
  d: {values: [0, 1]}
variables:
  x: {domain: d}
external_variables:
  e: {domain: d, initial_value: 1}
constraints:
  u: {type: intention, function: x}
agents: [a1]
distribution_hints:
  must_host:
    a1: [x]
""")
    dcop2 = from_repr(simple_repr(dcop))
    assert "e" in dcop2.external_variables
    assert dcop2.dist_hints.must_host("a1") == ["x"]


def test_agent_extra_attrs_yaml_round_trip():
    y = """
name: t
objective: min
domains:
  d: {values: [0, 1]}
variables:
  x: {domain: d}
constraints:
  u: {type: intention, function: x}
agents:
  a1: {capacity: 10, color_pref: blue}
"""
    dcop = load_dcop(y)
    assert dcop.agents["a1"].color_pref == "blue"
    dcop2 = load_dcop(dcop_yaml(dcop))
    assert dcop2.agents["a1"].color_pref == "blue"


def test_empty_actions_event_rejected():
    import pytest as _pytest

    with _pytest.raises(ValueError):
        load_scenario("events:\n  - id: e1\n    actions: []\n")


def test_solution_cost_with_external_variables():
    dcop = load_dcop("""
name: t
objective: min
domains:
  d: {values: [0, 1]}
variables:
  x: {domain: d}
external_variables:
  e: {domain: d, initial_value: 1}
constraints:
  c: {type: intention, function: 10 * x * e}
agents: [a1]
""")
    assert dcop.solution_cost({"x": 1}) == 10
    dcop.external_variables["e"].value = 0
    assert dcop.solution_cost({"x": 1}) == 0
