"""dsatuto / A-DSA / A-Max-Sum: the async family as batched schedules.

Parity testing follows SURVEY.md §7: asynchronous algorithms are
schedule variants of their synchronous counterparts, so we assert
distributional equivalence of solution quality (costs on known-optimum
problems), not message-trace equality.
"""

import numpy as np
import pytest

from pydcop_tpu.algorithms import (
    list_available_algorithms,
    load_algorithm_module,
    prepare_algo_params,
)
from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.batched import run_batched
from pydcop_tpu.ops.compile import compile_dcop


def coloring_ring(n=10, colors=3):
    d = Domain("colors", "", list(range(colors)))
    dcop = DCOP(f"ring{n}")
    vs = [Variable(f"v{i}", d) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def test_registry_lists_async_family():
    algos = list_available_algorithms()
    for name in ("dsatuto", "adsa", "amaxsum"):
        assert name in algos


def test_dsatuto_solves_ring():
    result = solve(coloring_ring(10, 3), "dsatuto", rounds=200, seed=1)
    assert result["cost"] == 0.0
    a = result["assignment"]
    for i in range(10):
        assert a[f"v{i}"] != a[f"v{(i + 1) % 10}"]


def test_dsatuto_has_no_algorithm_params():
    """The tutorial algorithm's SEMANTICS are parameter-free (fixed
    variant A, p=0.5); the only declared params are the compiled-
    island deployment knobs."""
    mod = load_algorithm_module("dsatuto")
    params = prepare_algo_params({}, mod.algo_params)
    assert set(params) == {"island_rounds", "island_start_rounds"}
    with pytest.raises(Exception):
        prepare_algo_params({"variant": "B"}, mod.algo_params)


def test_adsa_solves_ring():
    result = solve(
        coloring_ring(12, 3),
        "adsa",
        {"activation": 0.6, "probability": 0.8},
        rounds=300,
        seed=2,
    )
    assert result["cost"] == 0.0


def test_adsa_full_activation_matches_dsa():
    """activation=1.0 reduces A-DSA to synchronous DSA: on a problem
    with unique per-variable argmins (no ties) and probability=1, both
    produce the SAME value trajectory from the same start state."""
    import itertools
    import jax

    rng = np.random.default_rng(0)
    d = Domain("d", "", [0, 1, 2])
    dcop = DCOP("uniq")
    vs = [Variable(f"x{i}", d) for i in range(6)]
    for v in vs:
        dcop.add_variable(v)
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    for k, (i, j) in enumerate(itertools.combinations(range(6), 2)):
        if k % 2:
            continue
        # continuous random costs: exact per-row ties (which the two
        # modules break with DIFFERENT key splits) are measure-zero —
        # integer tables hit one after the compiler's degree-sorted
        # relabeling changed the trajectory
        dcop.add_constraint(
            NAryMatrixRelation(
                [vs[i], vs[j]], rng.uniform(0.0, 10.0, (3, 3)),
                name=f"c{k}",
            )
        )
    problem = compile_dcop(dcop)
    dsa = load_algorithm_module("dsa")
    adsa = load_algorithm_module("adsa")
    p_dsa = prepare_algo_params(
        {"variant": "C", "probability": 1.0}, dsa.algo_params
    )
    p_adsa = prepare_algo_params(
        {"variant": "C", "probability": 1.0, "activation": 1.0},
        adsa.algo_params,
    )
    key = jax.random.PRNGKey(9)
    s1 = dsa.init_state(problem, key, p_dsa)
    s2 = adsa.init_state(problem, key, p_adsa)
    np.testing.assert_array_equal(s1["values"], s2["values"])
    for i in range(12):
        k = jax.random.fold_in(key, i)
        s1 = dsa.step(problem, s1, k, p_dsa)
        s2 = adsa.step(problem, s2, k, p_adsa)
        np.testing.assert_array_equal(s1["values"], s2["values"])


def test_adsa_message_accounting_scales_with_activation():
    problem = compile_dcop(coloring_ring(10, 3))
    mod = load_algorithm_module("adsa")
    full = mod.messages_per_round(problem, {"activation": 1.0})
    half = mod.messages_per_round(problem, {"activation": 0.5})
    assert full == 2 * 10  # ring: each var has 2 neighbors
    assert half == 10


def test_amaxsum_solves_ring():
    result = solve(
        coloring_ring(10, 3),
        "amaxsum",
        {"activation": 0.7},
        rounds=150,
        seed=3,
    )
    assert result["cost"] == 0.0


def test_amaxsum_full_activation_equals_sync_maxsum():
    """With activation=1.0 every edge fires: the q/r message arrays must
    EQUAL synchronous Max-Sum's after every step (maxsum.step never
    consumes its key, so the trajectories are comparable directly)."""
    import jax

    dcop = coloring_ring(8, 3)
    problem = compile_dcop(dcop)
    ms = load_algorithm_module("maxsum")
    ams = load_algorithm_module("amaxsum")
    p_ms = prepare_algo_params({"damping": 0.5}, ms.algo_params)
    p_ams = prepare_algo_params(
        {"damping": 0.5, "activation": 1.0}, ams.algo_params
    )
    key = jax.random.PRNGKey(7)
    s_sync = ms.init_state(problem, key, p_ms)
    s_async = ams.init_state(problem, key, p_ams)
    for i in range(15):
        k = jax.random.fold_in(key, i)
        s_sync = ms.step(problem, s_sync, k, p_ms)
        s_async = ams.step(problem, s_async, k, p_ams)
        np.testing.assert_array_equal(s_sync["q"], s_async["q"])
        np.testing.assert_array_equal(s_sync["r"], s_async["r"])
        np.testing.assert_array_equal(s_sync["values"], s_async["values"])


def test_amaxsum_message_accounting():
    problem = compile_dcop(coloring_ring(10, 3))
    mod = load_algorithm_module("amaxsum")
    full = mod.messages_per_round(problem, {"activation": 1.0})
    assert full == 2 * problem.n_real_edges
    half = mod.messages_per_round(problem, {"activation": 0.5})
    assert half == problem.n_real_edges


def test_engine_reports_activation_scaled_msg_count():
    dcop = coloring_ring(10, 3)
    r = solve(dcop, "adsa", {"activation": 0.5}, rounds=100, seed=1)
    assert r["msg_count"] == 100 * 10
