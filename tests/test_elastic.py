"""Elastic cross-process runtime: reform-on-death + discovery events.

VERDICT r2 items 3/4/5 beyond scenarios: a SIGKILLed agent process
must not fail the run — the orchestrator re-forms the cluster on the
survivors, the dead agent's computations freeze (or migrate with
k_target), and the solve completes with a full assignment.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ring_yaml(n=12):
    lines = [
        "name: ring",
        "objective: min",
        "domains:",
        "  colors: {values: [0, 1, 2]}",
        "variables:",
    ]
    for i in range(n):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(n):
        j = (i + 1) % n
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append(f"agents: [{', '.join(f'a{i}' for i in range(n))}]")
    return "\n".join(lines) + "\n"


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return env


def _parse_json_tail(text):
    start = text.index("{")
    return json.loads(text[start:])


# the three subprocess gauntlets below need multi-process
# jax.distributed elastic reform, which this CPU-only image cannot run
# (failing since seed — ROADMAP open item 5); at 20-75s apiece they
# are `slow` on their own merits, and in tier-1 they only burned ~2.5
# minutes of the budget re-reporting a known image limitation.  Run
# them explicitly (no `-m 'not slow'`) on an image with working
# multi-process jax.distributed.


@pytest.mark.slow
def test_elastic_survives_agent_sigkill(tmp_path):
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())
    env = _env()
    port = 9700 + (os.getpid() % 90)

    ui_port = port + 91
    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--port", str(port),
            "--nb_agents", "2", "--rounds", "20000",
            "--chunk_size", "8", "--seed", "5", "--elastic",
            "--heartbeat_timeout", "60", "--uiport", str(ui_port),
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--orchestrator", f"localhost:{port}",
            ],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for name in ("a1", "a2")
    ]
    try:
        # wait for epoch 1 to be LIVE (first chunk barrier published),
        # then SIGKILL one agent's whole supervision (worker orphaned).
        # /state polling instead of a fixed sleep: a loaded box can
        # stretch registration + jax init arbitrarily (VERDICT r3
        # weak #4)
        _wait_state(
            ui_port, lambda s: s.get("epoch") == 1, 240, "epoch 1",
            proc=orch,
        )
        agents[1].send_signal(signal.SIGKILL)

        orc_out, orc_err = orch.communicate(timeout=240)
        assert orch.returncode == 0, orc_err[-3000:]
        r = _parse_json_tail(orc_out)

        # the run FINISHED despite the death
        assert r["status"] == "finished"
        assert r["epochs"] >= 2  # at least one reform happened
        lost_events = [
            e for e in r["events"] if e["type"] == "participant_lost"
        ]
        assert len(lost_events) == 1
        # the dead participant's variables froze (k_target=0)
        assert lost_events[0]["frozen"] == r["lost_computations"]
        assert 0 < len(r["lost_computations"]) <= 4  # 12 vars / 3 parts
        # full assignment including the frozen variables, real cost
        assert len(r["assignment"]) == 12
        assert r["cost"] is not None
        # one agent survived to the end
        assert len(r["agents_final"]) == 1
    finally:
        for p in [orch] + agents:
            if p.poll() is None:
                p.kill()
                p.wait()


def _wait_state(ui_port, pred, deadline_s, what, proc=None):
    """Poll the orchestrator's /state endpoint until ``pred`` holds —
    the load-robust alternative to fixed sleeps (VERDICT r3 weak #4:
    kill-timing tests must not race wall-clock margins on a loaded
    box).  With ``proc`` given, an orchestrator that exits while we
    wait fails immediately with its own output (diagnosis beats a
    silent deadline burn)."""
    import urllib.request

    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            out, err = proc.communicate(timeout=10)
            raise AssertionError(
                f"orchestrator exited (rc={proc.returncode}) while "
                f"waiting for {what}; last={last}\n"
                f"stdout tail: {out[-1500:]}\nstderr tail: {err[-1500:]}"
            )
        try:
            with urllib.request.urlopen(
                f"http://localhost:{ui_port}/state", timeout=5
            ) as resp:
                last = json.loads(resp.read().decode())
            if pred(last):
                return last
        except OSError:
            pass
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}; last={last}")


@pytest.mark.slow
def test_elastic_two_kills_and_orchestrator_worker_death(tmp_path):
    """The full resilience gauntlet (VERDICT r3 next #6): 3 agents;
    two agent supervisions SIGKILLed in sequence (two reforms, two
    partitions frozen), then the ORCHESTRATOR-SIDE worker process
    killed (a worker_crash reform: same participant set, respawn);
    the run must still finish with a complete assignment.  Every kill
    waits on the /state endpoint for the previous epoch to be live —
    no wall-clock margins to race on a loaded box."""
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())
    env = _env()
    port = 9880 + (os.getpid() % 60)
    ui_port = port + 61

    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--port", str(port),
            # budget balance: enough barriers that the kill sequence
            # (~60-90s of epoch-driven waits) cannot outrun the solve,
            # but few enough that the post-reform run cannot overrun
            # the final communicate timeout on a loaded box
            "--nb_agents", "3", "--rounds", "12000",
            "--chunk_size", "4", "--seed", "5", "--elastic",
            "--heartbeat_timeout", "60", "--uiport", str(ui_port),
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--orchestrator", f"localhost:{port}",
            ],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for name in ("a1", "a2", "a3")
    ]
    try:
        _wait_state(
            ui_port, lambda s: s.get("epoch") == 1, 240, "epoch 1",
            proc=orch,
        )
        agents[2].send_signal(signal.SIGKILL)
        _wait_state(
            ui_port, lambda s: (s.get("epoch") or 0) >= 2, 240,
            "epoch 2", proc=orch,
        )
        agents[1].send_signal(signal.SIGKILL)
        _wait_state(
            ui_port, lambda s: (s.get("epoch") or 0) >= 3, 240,
            "epoch 3", proc=orch,
        )
        # the orchestrator's LOCAL worker is its own child process
        kids = subprocess.run(
            ["pgrep", "-P", str(orch.pid)],
            capture_output=True, text=True,
        ).stdout.split()
        assert kids, "no orchestrator-side worker process found"
        os.kill(int(kids[0]), signal.SIGKILL)
        _wait_state(
            ui_port, lambda s: (s.get("epoch") or 0) >= 4, 240,
            "epoch 4", proc=orch,
        )

        orc_out, orc_err = orch.communicate(timeout=600)
        assert orch.returncode == 0, orc_err[-3000:]
        r = _parse_json_tail(orc_out)
        assert r["status"] == "finished"
        assert r["epochs"] >= 4
        lost = [
            e for e in r["events"] if e["type"] == "participant_lost"
        ]
        crashes = [
            e for e in r["events"] if e["type"] == "worker_crash"
        ]
        assert len(lost) == 2, r["events"]
        assert len(crashes) >= 1, r["events"]
        assert len(r["assignment"]) == 12  # complete, frozen included
        assert r["cost"] is not None
        assert len(r["agents_final"]) == 1  # only a1 survived
    finally:
        for p in [orch] + agents:
            if p.poll() is None:
                p.kill()
                p.wait()


@pytest.mark.slow
def test_elastic_happy_path_no_deaths(tmp_path):
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())
    env = _env()
    port = 9790 + (os.getpid() % 90)

    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--port", str(port),
            "--nb_agents", "1", "--rounds", "64", "--chunk_size", "16",
            "--seed", "5", "--elastic",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agent = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "agent",
            "--names", "a1", "--orchestrator", f"localhost:{port}",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        orc_out, orc_err = orch.communicate(timeout=180)
        ag_out, _ = agent.communicate(timeout=30)
        assert orch.returncode == 0, orc_err[-3000:]
        r = _parse_json_tail(orc_out)
        assert r["status"] == "finished"
        assert r["epochs"] == 1
        assert r["cost"] == 0.0  # ring 3-coloring optimum
        assert r["events"] == []
        assert len(r["assignment"]) == 12
    finally:
        for p in (orch, agent):
            if p.poll() is None:
                p.kill()
                p.wait()


def test_discovery_events():
    from pydcop_tpu.infrastructure.discovery import (
        ADDED,
        AGENT,
        COMPUTATION,
        REMOVED,
        Discovery,
    )

    d = Discovery()
    events = []
    unsub = d.subscribe(
        lambda kind, ev, name, detail: events.append(
            (kind, ev, name, detail)
        )
    )
    d.register_agent("a1", capacity=10)
    d.register_computation("v1", "a1")
    d.register_computation("v2", "a1")
    assert d.agents() == ["a1"]
    assert d.computations("a1") == ["v1", "v2"]
    assert d.computation_agent("v1") == "a1"
    assert d.agent_info("a1") == {"capacity": 10}

    orphans = d.unregister_agent("a1")
    assert sorted(orphans) == ["v1", "v2"]
    assert d.agents() == []
    assert d.computations() == []

    kinds = [(k, e, n) for k, e, n, _ in events]
    assert (AGENT, ADDED, "a1") in kinds
    assert (COMPUTATION, ADDED, "v1") in kinds
    assert (COMPUTATION, REMOVED, "v1") in kinds
    assert (AGENT, REMOVED, "a1") in kinds
    # computation removals fire BEFORE the agent removal (reference
    # ordering: subscribers see orphans while the agent is still known)
    assert kinds.index((COMPUTATION, REMOVED, "v2")) < kinds.index(
        (AGENT, REMOVED, "a1")
    )

    unsub()
    d.register_agent("a2")
    assert all(n != "a2" for _, _, n, _ in events)

    with pytest.raises(ValueError):
        d.register_computation("vx", "missing_agent")
