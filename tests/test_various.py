"""utils/various.py: duration formatting edge cases (telemetry
satellite: sub-second spans used to print as `0h 00m 00s`-style
noise; negatives indicated a clock bug and were silently clamped)."""

import pytest

from pydcop_tpu.utils.various import elapsed_str, number_format


def test_elapsed_str_sub_second_is_milliseconds():
    assert elapsed_str(0.123) == "123ms"
    assert elapsed_str(0.9994) == "999ms"
    assert elapsed_str(0.0005) == "0ms"
    assert elapsed_str(0) == "0ms"
    # the rounding boundary never prints "1000ms"
    assert elapsed_str(0.9996) == "1s"


def test_elapsed_str_seconds_and_up_unchanged():
    assert elapsed_str(1.5) == "1.5s"
    assert elapsed_str(59) == "59s"
    assert elapsed_str(65) == "1m 05s"
    assert elapsed_str(3723) == "1h 02m 03s"


def test_elapsed_str_negative_raises():
    with pytest.raises(ValueError):
        elapsed_str(-0.001)
    with pytest.raises(ValueError):
        elapsed_str(-60)


def test_number_format_still_compact():
    # neighbor helper sanity (unchanged behavior)
    assert number_format(1500) == "1.5k"
    assert number_format(True) == "True"
