"""End-to-end test of the cross-process orchestrator/agent commands.

VERDICT r1 item 4's done-criterion: spawn real orchestrator + agent OS
processes, and the assembled result must match the same sharded solve
run in-process.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ring_yaml(n=12):
    lines = [
        "name: ring",
        "objective: min",
        "domains:",
        "  colors: {values: [0, 1, 2]}",
        "variables:",
    ]
    for i in range(n):
        lines.append(f"  v{i}: {{domain: colors}}")
    lines.append("constraints:")
    for i in range(n):
        j = (i + 1) % n
        lines.append(f"  c{i}:")
        lines.append("    type: intention")
        lines.append(f"    function: 1 if v{i} == v{j} else 0")
    lines.append(f"agents: [{', '.join(f'a{i}' for i in range(n))}]")
    return "\n".join(lines) + "\n"


def _parse_json_tail(text):
    """Parse the JSON object from output that may carry Gloo banners."""
    start = text.index("{")
    return json.loads(text[start:])


# the multi-process orchestrator gauntlets below die on
# "Multiprocess computations aren't implemented on the CPU backend"
# (no multi-process jax.distributed on this image — failing since
# seed, ROADMAP open item 5, the same limitation that already moved
# the test_elastic gauntlets to `slow` in PR 6); at several seconds
# apiece they only burned tier-1 budget re-reporting it.  Run them
# explicitly (no `-m 'not slow'`) on an image with working
# multi-process jax.distributed.


@pytest.mark.slow
def test_orchestrator_agent_matches_inprocess(tmp_path):
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    # one device per process → a 2-device global mesh over 2 processes
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    port = 9600 + (os.getpid() % 200)
    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--port", str(port),
            "--nb_agents", "1", "--rounds", "32", "--chunk_size", "16",
            "--seed", "5",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agent = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "agent",
            "--names", "a1", "--orchestrator", f"localhost:{port}",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    orc_out, orc_err = orch.communicate(timeout=150)
    ag_out, ag_err = agent.communicate(timeout=30)
    assert orch.returncode == 0, orc_err[-3000:]
    assert agent.returncode == 0, ag_err[-3000:]

    result = _parse_json_tail(orc_out)
    agent_result = _parse_json_tail(ag_out)
    assert result["n_shards"] == 2
    assert result["num_processes"] == 2
    assert result["agents"] == ["a1"]
    assert result["cycle"] == 32
    # SPMD replication: the agent saw the identical cost
    assert agent_result["cost"] == result["cost"]

    # and the whole thing matches the same sharded solve in-process
    # (2-shard mesh on the virtual-device conftest backend, same seeds)
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.engine.batched import run_batched
    from pydcop_tpu.ops import compile_dcop
    from pydcop_tpu.parallel import make_mesh

    dcop = load_dcop_from_file(str(yaml_file))
    problem = compile_dcop(dcop, n_shards=2)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params({}, module.algo_params)
    local = run_batched(
        problem, module, params, rounds=32, seed=5, chunk_size=16,
        mesh=make_mesh(2),
    )
    np.testing.assert_allclose(local.best_cost, result["cost"], atol=1e-5)


@pytest.mark.slow  # multi-process jax.distributed — see note above
@pytest.mark.parametrize("nb_agents", [2, 4])
def test_orchestrator_multi_process(tmp_path, nb_agents):
    """Control-plane scaling past toy counts (VERDICT r3 #56): 1
    orchestrator + N agent processes form an (N+1)-way SPMD mesh over
    jax.distributed — the multi-host-over-DCN shape, each process one
    device — and every process reports the identical cost.  N=4 gives
    the 5-process harness the round-3 review found missing."""
    yaml_file = tmp_path / "ring.yaml"
    yaml_file.write_text(_ring_yaml())

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYDCOP_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    port = 9420 + (os.getpid() % 180)
    orch = subprocess.Popen(
        [
            sys.executable, "-m", "pydcop_tpu", "orchestrator",
            str(yaml_file), "-a", "maxsum", "--port", str(port),
            "--nb_agents", str(nb_agents), "--rounds", "24",
            "--chunk_size", "8", "--seed", "7",
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    time.sleep(0.5)
    agents = [
        subprocess.Popen(
            [
                sys.executable, "-m", "pydcop_tpu", "agent",
                "--names", name, "--orchestrator", f"localhost:{port}",
            ],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for name in [f"a{i}" for i in range(1, nb_agents + 1)]
    ]
    try:
        orc_out, orc_err = orch.communicate(timeout=300)
        assert orch.returncode == 0, orc_err[-3000:]
        result = _parse_json_tail(orc_out)
        assert result["n_shards"] == nb_agents + 1
        assert result["num_processes"] == nb_agents + 1
        assert sorted(result["agents"]) == [
            f"a{i}" for i in range(1, nb_agents + 1)
        ]
        for a in agents:
            a_out, a_err = a.communicate(timeout=30)
            assert a.returncode == 0, a_err[-3000:]
            assert _parse_json_tail(a_out)["cost"] == result["cost"]
    finally:  # never orphan the subprocesses on a timeout/assert
        for proc in [orch, *agents]:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
