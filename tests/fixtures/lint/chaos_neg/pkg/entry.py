"""Clean entry point: both live categories accepted-or-rejected."""


def run(plan):
    if plan.message_faults_configured:
        raise ValueError("message kinds not supported here")
    if plan.device_faults_configured:
        raise ValueError("device kinds not supported here")
    return "ok"
