"""Fixture trace-summary module: every folded span family has a doc
row (the span-undocumented negative case)."""

ATTEMPT_SPAN = "cli.attempt"


def summarize(records):
    out = {"queue": 0, "attempts": 0, "semiring": 0}
    for r in records:
        name = r.get("name")
        if name == "svc.queue-wait":
            out["queue"] += 1
        elif name == ATTEMPT_SPAN:
            out["attempts"] += 1
        elif name.startswith("ring."):
            out["semiring"] += 1
    return out
