"""Clean: every emission documented, no stale rows."""


def record(met, kind):
    if met.enabled:
        met.inc("foo.hits")
        met.inc("foo.requests")
        met.inc("foo.runner_cache_hits")
        met.inc("foo.runner_cache_misses")
        met.inc(f"bar.{kind}")
