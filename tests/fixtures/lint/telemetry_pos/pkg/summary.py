"""Fixture trace-summary module: folds span families, some of them
undocumented (the span-undocumented positive case)."""

ATTEMPT_SPAN = "cli.attempt"  # *_SPAN constant, undocumented


def summarize(records):
    out = {"queue": 0, "attempts": 0, "semiring": 0, "drains": 0}
    for r in records:
        name = r.get("name")
        if name == "svc.queue-wait":  # documented: stays quiet
            out["queue"] += 1
        elif name == "svc.request":  # undocumented compare
            pass
        elif name == ATTEMPT_SPAN:
            out["attempts"] += 1
        elif name.startswith("ring."):  # undocumented family
            out["semiring"] += 1
    # undocumented dotted .get key on the span table
    out["drains"] = out.get("svc.drain", 0)
    return out
