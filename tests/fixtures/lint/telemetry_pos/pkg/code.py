"""VIOLATES metric-undocumented: emits `foo.hits` which the fixture
doc never mentions (the doc's stale `foo.gone` row violates
metric-stale-doc, and the plan/doc clause mismatch violates
chaos-clause-doc)."""


def record(met, kind):
    if met.enabled:
        met.inc("foo.hits")
        met.inc("foo.requests")
        met.inc(f"bar.{kind}")
