"""Registers one chaos clause: `zap=` (the fixture doc documents a
different, stale one)."""


class FaultPlan:
    @classmethod
    def from_spec(cls, spec, seed=0):
        for clause in spec.split(","):
            if clause.startswith("zap="):
                continue
            raise ValueError(clause)
        return cls()
