"""Clean: everything routes through profiled_jit; the sanctioned
helper module (pkg/helper.py in the fixture config) may call jax.jit
directly."""

from pkg.telemetry import profiled_jit


def build(fn):
    return profiled_jit(fn, label="mod.build")
