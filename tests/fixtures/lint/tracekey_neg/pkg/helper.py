"""The sanctioned cache helper: direct jax.jit allowed by config."""

import jax


def cached_jit(fn, **kw):
    return jax.jit(fn, **kw)
