"""Clean runner builder: captured state is an immutable tuple, and
mutable containers stay OUT of the jitted closure (threaded through
the traced arguments instead)."""

from pkg.telemetry import profiled_jit


def build_runner(tables):
    shapes = tuple(t.shape for t in tables)  # immutable capture: fine
    scratch = []  # mutable, but never captured by the jitted fn

    def step(state, tables_in):
        return state + len(shapes), tables_in

    scratch.append(shapes)
    return profiled_jit(step, label="runner")
