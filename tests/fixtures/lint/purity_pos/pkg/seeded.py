"""VIOLATES impure-call and set-iteration inside a seeded scope."""

import random
import time


def decide(seed, link, seq):
    jitter = time.time()  # wall clock in a replay path
    pick = random.choice([0, 1])  # bare module stream
    return (jitter, pick)


def fan_out(agents):
    order = []
    for a in {"a1", "a2", "a3"}:  # hash order escapes into order
        order.append(a)
    first = list(set(agents))  # same escape, list() spelling
    return order, first
