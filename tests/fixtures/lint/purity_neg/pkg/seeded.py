"""Clean seeded scope: seeded private streams, keyed hashes,
sorted set iteration, injectable clock references, and ONE audited
allow-marked exception."""

import hashlib
import random
import time


def _hashed_unit(seed, key, attempt):
    h = hashlib.blake2b(
        f"{seed}|{key}|{attempt}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2.0**64


def decide(seed, link, seq):
    rnd = random.Random(seed)  # seeded PRIVATE stream: approved
    return rnd.random() + _hashed_unit(seed, link, seq)


def fan_out(agents):
    return [a for a in sorted(set(agents))]  # sorted: approved


def wait(sleep=time.sleep, clock=time.monotonic):
    # references as injectable defaults are fine — only calls count
    return sleep, clock


def nonce():
    # graftlint: allow[impure-call] — audited: uniqueness is the point
    return time.time_ns()
