"""VIOLATES lazy-init-eager-import: the PEP-562 table lazily exposes
``pkg.lazy.impl`` — and then eagerly imports it anyway, so the
laziness is decorative."""

from pkg.lazy.impl import thing  # defeats the table below

_LAZY = {"thing"}


def __getattr__(name):
    if name in _LAZY:
        import pkg.lazy.impl as _impl

        return getattr(_impl, name)
    raise AttributeError(name)
