thing = object()
