"""VIOLATES jax-import-surface TRANSITIVELY: no jax import in sight,
but the module-level import of pkg.heavy drags jax onto the cold
path — the regression class reviewers miss."""

from pkg.heavy import kernel


def run(x):
    return kernel(x)
