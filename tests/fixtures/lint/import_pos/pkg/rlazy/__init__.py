"""VIOLATES lazy-init-eager-import in the RELATIVE-import style:
the table lazily exposes ``.impl`` via ``from . import impl`` while
the body eagerly does ``from .impl import thing`` — same defeat, no
absolute names anywhere."""

from .impl import thing  # defeats the table below

_LAZY = {"thing"}


def __getattr__(name):
    if name in _LAZY:
        from . import impl as _impl

        return getattr(_impl, name)
    raise AttributeError(name)
