thing = object()
