"""VIOLATES jax-import-surface: direct module-level jax import on a
module declared jax-free."""

import jax  # the stray eager import the rule exists to catch


def solve():
    return jax.numpy.zeros(1)
