"""A device-side module: module-level jax is fine HERE (not on the
declared surface) — it exists to poison the transitive chain."""

import jax


def kernel(x):
    return jax.numpy.asarray(x)
