"""VIOLATES chaos-symmetry: this entry point validates message kinds
but never consults the device predicate — a `zap=` clause would be
silently ignored."""


def run(plan):
    if plan.message_faults_configured:
        raise ValueError("message kinds not supported here")
    return "ok"
