"""VIOLATES chaos-symmetry (the `boom` kind is unclassified in the
fixture config) and chaos-inert-field (`fizzle` never flips
``configured``)."""

import re
from dataclasses import dataclass

_CLAUSE = re.compile(r"^(?P<key>drop|delay)=(?P<val>[^=]+)$")


@dataclass(frozen=True)
class DeviceFaults:
    zap: float = 0.0
    zap_after: int = 0  # modifier: exempt from the inert check
    fizzle: float = 0.0  # parses but never read below: INERT

    @property
    def configured(self) -> bool:
        return self.zap > 0.0


class FaultPlan:
    @classmethod
    def from_spec(cls, spec, seed=0):
        for clause in spec.split(","):
            if clause.startswith(("zap=", "boom=")):
                continue
            if not _CLAUSE.match(clause):
                raise ValueError(clause)
        return cls()
