"""Clean relative-style PEP-562 table: ``.impl`` imported only
inside ``__getattr__``."""

_LAZY = {"thing"}


def __getattr__(name):
    if name in _LAZY:
        from . import impl as _impl

        return getattr(_impl, name)
    raise AttributeError(name)
