thing = object()
