"""Device-side module off the declared surface — module-level jax is
allowed here."""

import jax


def kernel(x):
    return jax.numpy.asarray(x)
