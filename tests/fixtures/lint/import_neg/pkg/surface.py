"""Clean: the heavy module is imported inside the function, so the
module-level chain stays jax-free; TYPE_CHECKING imports never
execute and are exempt."""

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from pkg.heavy import kernel  # noqa: F401 — typing only


def run(x):
    from pkg.heavy import kernel

    return kernel(x)
