"""Clean PEP-562 table: the lazily exposed module is imported only
inside ``__getattr__``."""

_LAZY = {"thing"}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import pkg.lazy.impl as _impl

        return getattr(_impl, name)
    raise AttributeError(name)
