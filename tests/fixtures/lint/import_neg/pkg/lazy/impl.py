thing = object()
