"""Clean: jax deferred into the function that needs it, the approved
pattern for the jax-free surface."""


def solve():
    import jax

    return jax.numpy.zeros(1)
