"""VIOLATES unhashable-closure: the cached runner builder jits a
function closing over a dict local the cache key cannot see."""

from pkg.telemetry import profiled_jit


def build_runner(tables):
    opts = {"damping": 0.5}  # mutable: invisible to the cache key

    def step(state):
        return state * opts["damping"]

    return profiled_jit(step, label="runner")
