"""VIOLATES bare-jit: direct jax.jit outside the sanctioned cache
helpers (and a partial-wrapped one)."""

import functools

import jax


def build(fn):
    return jax.jit(fn)


def build_partial(fn):
    wrap = functools.partial(jax.jit, static_argnums=(1,))
    return wrap(fn)


@jax.jit
def decorated(x):
    return x
