def profiled_jit(fn, **kw):
    return fn
