"""Tier-1 hook for ``tools/recompile_guard.py``: compile-count
regressions on the dynamic-run path fail CI like any other test.

The guard runs a canned two-segment dynamic solve (one ``set_value``
event) and checks the telemetry ``jit.compiles`` counter against the
recorded budget — see the tool's docstring for what a failure means.
"""

import importlib.util
import os

import pytest

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "recompile_guard.py",
)


def _load_guard():
    spec = importlib.util.spec_from_file_location(
        "recompile_guard", _TOOL
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_recompile_guard_within_budget():
    guard = _load_guard()
    report = guard.run_guard()
    assert report["ok"], report
    assert report["jit_compiles"] <= guard.BUDGET, report
    # the guard must exercise BOTH reuse mechanisms it protects
    assert report["compile_incremental"] >= 1, report
    assert report["jit_cache_hits"] >= 1, report


def test_solve_many_guard_within_budget():
    """Overrun detection is NOT re-tested here (the scenario is
    expensive and the verdict machinery is the same ``ok``-vs-budget
    pattern the dynamic overrun test below exercises)."""
    guard = _load_guard()
    report = guard.run_many_guard()
    assert report["ok"], report
    assert report["jit_compiles"] <= guard.MANY_BUDGET, report
    assert report["jit_compiles"] >= 1, report  # guard actually ran
    # one vmapped group covering every instance — K compiles (or K
    # groups) is the silent-de-batching regression this exists for
    assert report["batch_groups"] == 1, report
    assert report["instances_batched"] == guard.MANY_K, report


def test_recompile_guard_detects_overrun(monkeypatch):
    """The guard actually fails when the budget is exceeded (guards
    that cannot fail are decoration)."""
    guard = _load_guard()
    monkeypatch.setattr(guard, "BUDGET", -1)
    report = guard.run_guard()
    assert not report["ok"]


@pytest.mark.dpop
def test_dpop_guard_within_budget():
    """Level-batched DPOP through solve_many: one merged group, each
    level-bucket join executable compiled exactly once (zero compiles
    on an identical second call), results bit-identical to sequential
    solves — see tools/recompile_guard.py:run_dpop_guard."""
    guard = _load_guard()
    report = guard.run_dpop_guard()
    assert report["ok"], report
    assert report["jit_compiles"] <= guard.DPOP_BUDGET, report
    assert report["jit_compiles"] >= 1, report  # guard actually ran
    assert report["second_call_compiles"] == 0, report
    assert report["batch_groups"] == 1, report
    assert report["instances_batched"] == guard.DPOP_K, report
    # the merged sweep must actually batch: far fewer dispatches than
    # the K * n_nodes a per-node walk would pay
    assert report["level_dispatches"] < guard.DPOP_K * 10, report


@pytest.mark.supervisor
def test_supervisor_guard_within_budget():
    """Supervised recovery must not hide a compile storm: transient
    retries re-dispatch the already-compiled runner (ZERO new
    compiles), an OOM group-split adds at most the one runner compile
    its equal halves share, and both recovered runs stay bit-identical
    to the fault-free baseline — see
    tools/recompile_guard.py:run_supervisor_guard."""
    guard = _load_guard()
    report = guard.run_supervisor_guard()
    assert report["ok"], report
    assert report["base_compiles"] >= 1, report  # guard actually ran
    assert report["retry_compiles"] == 0, report
    assert report["retries"] >= 1, report
    assert report["split_compiles"] <= guard.SUP_SPLIT_BUDGET, report
    assert report["oom_splits"] == 1, report


@pytest.mark.service
def test_service_guard_steady_state_zero_compiles():
    """The serving-path acceptance criterion: waves of concurrent
    requests in two shape buckets through a live SolverService compile
    exactly one vmapped runner per bucket on the COLD tick and ZERO on
    every steady-state tick, each wave coalesces into one tick of two
    groups, and coalesced results are bit-identical to sequential
    api.solve calls — see tools/recompile_guard.py:run_service_guard."""
    guard = _load_guard()
    report = guard.run_service_guard()
    assert report["ok"], report
    assert report["wave_compiles"][0] == guard.SERVICE_BUDGET, report
    assert all(c == 0 for c in report["wave_compiles"][1:]), report
    assert report["ticks"] == guard.SERVICE_WAVES, report
    assert report["dispatches"] == 2 * guard.SERVICE_WAVES, report
    # every request shared its group with >= 1 other
    assert (
        report["coalesced_requests"]
        == guard.SERVICE_WAVES * guard.SERVICE_WAVE_K
    ), report


@pytest.mark.service
def test_restore_guard_zero_recompiles_after_resume():
    """The drain/restore acceptance criterion: a drained service's
    session checkpoint, resumed by a fresh service, replays the
    set_values deltas at startup (exactly ONE compile.full) and the
    session's next follow-up is compile.incremental-only — zero full
    recompiles, zero XLA compiles — bit-identical to the same
    follow-up on an undisturbed service.  See
    tools/recompile_guard.py:run_restore_guard."""
    guard = _load_guard()
    report = guard.run_restore_guard()
    assert report["ok"], report
    assert report["sessions_restored"] == 1, report
    assert report["restore_fulls"] == 1, report
    assert report["followup_fulls"] == 0, report
    assert report["followup_incrementals"] >= 1, report
    assert report["followup_jit_compiles"] == 0, report


@pytest.mark.service
def test_fleet_guard_failover_zero_xla_compiles():
    """The fleet failover acceptance criterion: a standby taking over
    a replicated session replays it at the cost of exactly ONE
    compile.full (segment 1 of the replay) plus the delta tail, with
    ZERO XLA compiles on the warm runner cache, and the failed-over
    follow-up is compile.incremental-only — zero fulls, zero XLA
    compiles — bit-identical to an undisturbed service that never
    failed over.  See tools/recompile_guard.py:run_fleet_guard."""
    guard = _load_guard()
    report = guard.run_fleet_guard()
    assert report["ok"], report
    assert report["primary_jit_compiles"] >= 1, report  # non-vacuous
    assert report["takeover_fulls"] == 1, report
    assert report["takeover_jit_compiles"] == 0, report
    assert report["followup_fulls"] == 0, report
    assert report["followup_incrementals"] >= 1, report
    assert report["followup_jit_compiles"] == 0, report
    assert report["sessions_promoted"] == 1, report


@pytest.mark.semiring
def test_semiring_guard_swap_reuses_buckets():
    """Swapping the semiring on the same problem bucket reuses the
    level-pack bucketing and compiles at most one new executable per
    semiring — zero on repeat — with device results matching host f64
    (map exactly, log_z within the reported bound).  See
    tools/recompile_guard.py:run_semiring_guard."""
    guard = _load_guard()
    report = guard.run_semiring_guard()
    assert report["ok"], report
    assert report["map_compiles"] >= 1, report  # guard actually ran
    assert report["log_z_compiles"] <= report["map_compiles"], report
    assert report["repeat_compiles"] == 0, report


@pytest.mark.semiring
def test_query_guard_structured_queries_reuse_buckets():
    """The structured-cell query pack (kbest / marginal_map /
    expectation): swapping the query on the same K instances compiles
    at most one new executable per (semiring, level-pack bucket) —
    within the recorded per-query budget — ZERO on repeat, with
    device results matching host f64 (kbest exactly, marginal_map
    assignment exactly + value in bound, expectation in bound).  See
    tools/recompile_guard.py:run_query_guard."""
    guard = _load_guard()
    report = guard.run_query_guard()
    assert report["ok"], report
    assert report["kbest_compiles"] >= 1, report  # guard actually ran
    assert report["kbest_compiles"] <= guard.QUERY_BUDGET, report
    assert (
        report["marginal_map_compiles"] <= guard.QUERY_BUDGET
    ), report
    assert (
        report["expectation_compiles"] <= guard.QUERY_BUDGET
    ), report
    assert report["repeat_compiles"] == 0, report


@pytest.mark.semiring
def test_bnb_guard_pruned_kernels_share_buckets():
    """Branch-and-bound pruned contraction kernels (ops/semiring.py
    ``bnb``): on a K=4 hard-capped overlap-SECP stack, bnb=on
    compiles at most ONE extra executable per (semiring, bucket)
    versus bnb=off (here: no more compiles than the off pass, whose
    plain kernels are already cached), an identical bnb=on repeat
    compiles ZERO, at least one join cell actually pruned, and
    results stay BIT-IDENTICAL to the unpruned kernels.  See
    tools/recompile_guard.py:run_bnb_guard."""
    guard = _load_guard()
    report = guard.run_bnb_guard()
    assert report["ok"], report
    assert report["off_compiles"] >= 1, report  # guard actually ran
    assert report["on_compiles"] <= report["off_compiles"], report
    assert report["repeat_compiles"] == 0, report
    assert report["pruned_cells"] >= 1, report


@pytest.mark.dpop
def test_delta_guard_warm_followup_is_o_delta():
    """The O(delta) incremental-contraction acceptance criterion
    (ISSUE 18): a 1-delta ``set_values`` follow-up on a ~10k-node
    broad tree through a live exact session performs ZERO XLA
    compiles, re-contracts < 5% of the nodes (memo-hitting the
    rest), and is bit-identical (cost AND assignment) to a fresh
    cold solve at the post-delta externals.  See
    tools/recompile_guard.py:run_delta_guard."""
    guard = _load_guard()
    report = guard.run_delta_guard()
    assert report["ok"], report
    assert report["nodes"] >= 10_000, report
    assert report["cold_compiles"] >= 1, report  # guard actually ran
    assert report["warm_compiles"] == 0, report
    assert (
        report["recontracted_fraction"] <= guard.DELTA_MAX_FRACTION
    ), report
    assert (
        report["warm_memo"]["hits"]
        + report["warm_memo"]["recontracted"]
        == report["nodes"]
    ), report


@pytest.mark.semiring
def test_precision_guard_bf16_reuses_buckets():
    """Mixed-precision table packs (ISSUE 19): running the same K
    instances at table_dtype='bf16' after a warm f32 pass — map via
    infer_many AND dpop via solve_many — compiles at most one new
    executable per (semiring, bucket) (bf16 count <= the f32 pass's),
    ZERO on repeat of either precision, and both queries stay
    bit-identical across precisions (the certificate ladder's repair
    contract).  See tools/recompile_guard.py:run_precision_guard."""
    guard = _load_guard()
    report = guard.run_precision_guard()
    assert report["ok"], report
    assert report["f32_compiles"] >= 1, report  # guard actually ran
    assert report["bf16_compiles"] <= report["f32_compiles"], report
    assert report["repeat_compiles"] == 0, report
    assert report["device_nodes"] >= 1, report


@pytest.mark.semiring
def test_sparse_guard_format_keys_stable():
    """Sparse constraint tables (ISSUE 20): a dense -> sparse format
    swap on the same K hard-capped overlap-SECP instances — map via
    infer_many AND dpop via solve_many — actually packs (the counters
    are non-vacuous), repeats with ZERO new compiles and zero new
    sparse kernel-cache entries, and stays bit-identical across
    formats.  See tools/recompile_guard.py:run_sparse_guard."""
    guard = _load_guard()
    report = guard.run_sparse_guard()
    assert report["ok"], report
    assert report["dense_compiles"] >= 1, report  # guard actually ran
    assert report["sparse_packs"] >= 1, report
    assert report["sparse_nodes"] >= 1, report
    assert report["sparse_kernel_entries"] >= 1, report
    assert report["repeat_compiles"] == 0, report
    assert report["new_entries_on_repeat"] == 0, report
    assert report["device_nodes"] >= 1, report


@pytest.mark.membound
def test_membound_guard_budgeted_solve_reuses_buckets():
    """Memory-bounded solves (ops/membound.py): the first budgeted
    solve compiles within its recorded budget (cut lanes share the
    level-pack stack), an identical repeat compiles ZERO, a second
    budget reuses the buckets, and every budgeted result is
    bit-identical to the unbounded solve.  See
    tools/recompile_guard.py:run_membound_guard."""
    guard = _load_guard()
    report = guard.run_membound_guard()
    assert report["ok"], report
    assert report["b1_compiles"] >= 1, report  # guard actually ran
    assert report["b1_compiles"] <= guard.MEMBOUND_BUDGET, report
    assert report["repeat_compiles"] == 0, report
    assert report["b2_compiles"] <= report["b1_compiles"], report
    assert report["cut_width"] >= 1, report


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
