"""The continuous-batching solver service (``engine/service.py``,
``docs/serving.md``): admission/tick policy, coalesced dispatch parity
with sequential ``api.solve``, session-affine incremental solves (the
zero-recompile acceptance criterion), device-chaos quarantine on the
serving path, and the newline-JSON wire protocol
(:class:`ServiceServer` / :class:`ServiceClient`).

Timing discipline: tests that need a deterministic tick use
``max_batch == number of submitted requests`` with a long ``max_wait``
— the tick fires exactly when the last submit lands, never on a clock.
"""

import threading

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.service import (
    ServiceClient,
    ServiceError,
    ServiceServer,
    SolverService,
    TickPolicy,
)
from pydcop_tpu.telemetry import session

pytestmark = pytest.mark.service

D = Domain("d", "", [0, 1, 2])


def ring_dcop(n=6, name="ring"):
    dcop = DCOP(name)
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs
            )
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def sensor_dcop():
    """One chain + an external 'sensor' variable driving v0 (the
    session-affinity workload: ``set_values`` deltas re-tabulate only
    the 'track' constraint)."""
    dcop = DCOP("ext")
    vs = [Variable(f"v{i}", D) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    sensor = ExternalVariable("sensor", D, value=0)
    dcop.add_variable(sensor)
    for i in range(2):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"1 if v{i} == v{i + 1} else 0", vs
            )
        )
    dcop.add_constraint(
        constraint_from_str(
            "track", "0 if v0 == sensor else 1", [vs[0], sensor]
        )
    )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
    return dcop


RING_YAML = (
    "name: ring\n"
    "objective: min\n"
    "domains:\n"
    "  colors: {values: [0, 1, 2]}\n"
    "variables:\n"
    + "".join(f"  v{i}: {{domain: colors}}\n" for i in range(6))
    + "constraints:\n"
    + "".join(
        f"  c{i}: {{type: intention, "
        f"function: '1 if v{i} == v{(i + 1) % 6} else 0'}}\n"
        for i in range(6)
    )
    + "agents: [a1]\n"
)


# -- admission / validation (no device work) ---------------------------


def test_tick_policy_and_constructor_validation():
    with pytest.raises(ValueError, match="max_batch"):
        TickPolicy(max_batch=0)
    with pytest.raises(ValueError, match="max_wait"):
        TickPolicy(max_wait=-1)
    with pytest.raises(ValueError, match="instance_bucket"):
        SolverService(instance_bucket="pow3", autostart=False)
    with pytest.raises(ValueError):  # malformed pad policy fails fast
        SolverService(pad_policy="pow3", autostart=False)
    # message-plane chaos kinds are rejected: the service dispatches
    # on the batched engine, which has no message plane
    with pytest.raises(ValueError, match="DEVICE-layer"):
        SolverService(chaos="drop=0.5", autostart=False)
    with pytest.raises(ValueError, match="DEVICE-layer"):
        SolverService(chaos="crash=a1@1", autostart=False)
    svc = SolverService(
        max_batch=4, max_wait=0.25, autostart=False
    )
    assert svc.tick.max_batch == 4 and svc.tick.max_wait == 0.25


def test_submit_validation_errors_raise_before_admission():
    svc = SolverService(autostart=False)
    with pytest.raises(ValueError, match="dcop is required"):
        svc.submit(None, "dsa")
    with pytest.raises(ValueError, match="algo is required"):
        svc.submit(ring_dcop())
    with pytest.raises(ValueError, match="n_restarts"):
        svc.submit(ring_dcop(), "dsa", n_restarts=0)
    with pytest.raises(ValueError, match="session"):
        svc.submit(ring_dcop(), "dsa", set_values={"sensor": 1})
    with pytest.raises(ValueError, match="DCOP object"):
        svc.submit(123, "dsa")
    assert svc.stats()["requests"] == 0  # nothing was admitted
    svc.close()
    with pytest.raises(ServiceError, match="closed"):
        svc.submit(ring_dcop(), "dsa")


# -- coalesced dispatch: parity with sequential api.solve --------------


def test_coalesced_results_bit_identical_to_sequential():
    """Acceptance: requests coalesced into one tick return results
    bit-identical to per-request sequential ``api.solve`` calls with
    the same pad_policy (including an odd group that exercises the
    pow-2 occupancy padding: 3 requests ride a 4-lane dispatch)."""
    from pydcop_tpu.api import solve

    dcops = [ring_dcop(5 + i, name=f"r{i}") for i in range(3)]
    kw = dict(rounds=24, chunk_size=24)
    with SolverService(
        pad_policy="pow2:16", max_batch=3, max_wait=30.0,
        autostart=False,
    ) as svc:
        pendings = [
            svc.submit(d, "mgm", {}, seed=i, **kw)
            for i, d in enumerate(dcops)
        ]
        got = [p.result(timeout=300) for p in pendings]
        stats = svc.stats()
    assert stats["ticks"] == 1 and stats["dispatches"] == 1
    assert stats["coalesced_requests"] == 3
    assert stats["pad_instances"] == 1  # 3 -> 4-lane pow2 dispatch
    for i, (d, r) in enumerate(zip(dcops, got)):
        seq = solve(
            d, "mgm", {}, pad_policy="pow2:16", seed=i, **kw
        )
        assert r["cost"] == seq["cost"]
        assert r["assignment"] == seq["assignment"]
        assert r["cost_trace"] == seq["cost_trace"]
        assert r["instances_batched"] == 3
        assert r["queue_wait"] >= 0.0


def test_mixed_param_partitions_in_one_tick():
    """Requests whose STATIC params differ land in separate dispatch
    groups within the same tick — and each still matches its own
    sequential solve."""
    from pydcop_tpu.api import solve

    kw = dict(rounds=24, chunk_size=24, seed=5)
    with SolverService(
        pad_policy="pow2:16", max_batch=2, max_wait=30.0,
        autostart=False,
    ) as svc:
        p1 = svc.submit(ring_dcop(6), "dsa", {"variant": "A"}, **kw)
        p2 = svc.submit(ring_dcop(6), "dsa", {"variant": "B"}, **kw)
        r1, r2 = p1.result(timeout=300), p2.result(timeout=300)
        stats = svc.stats()
    assert stats["ticks"] == 1 and stats["dispatches"] == 2
    for variant, r in (("A", r1), ("B", r2)):
        seq = solve(
            ring_dcop(6), "dsa", {"variant": variant},
            pad_policy="pow2:16", **kw,
        )
        assert r["cost"] == seq["cost"]
        assert r["assignment"] == seq["assignment"]


def test_dispatch_error_fails_only_its_partition():
    """A request the engine cannot solve surfaces as ServiceError from
    ITS pending result; batchmates in other partitions still finish,
    and the service keeps serving.  (Bad algo PARAMS never get this
    far — they raise at submit, before admission.)"""
    with pytest.raises(Exception, match="not in allowed values"):
        SolverService(autostart=False).submit(
            ring_dcop(6), "dsa", {"variant": "nope"}
        )
    with SolverService(
        max_batch=2, max_wait=30.0, autostart=False
    ) as svc:
        good = svc.submit(
            ring_dcop(6), "dsa", {}, rounds=24, chunk_size=24
        )
        # an empty DCOP passes admission but fails compile at dispatch
        bad = svc.submit(DCOP("empty"), "dsa", {}, rounds=24)
        with pytest.raises(ServiceError, match="dispatch failed"):
            bad.result(timeout=300)
        assert good.result(timeout=300)["status"] == "finished"
        assert svc.stats()["errors"] == 1


def test_host_path_algorithms_dispatch_through_run_many_host():
    """Exact host-path algos (DPOP) serve through the service too —
    same cost as the direct api.solve call."""
    from pydcop_tpu.api import solve

    with SolverService(
        max_batch=2, max_wait=30.0, autostart=False
    ) as svc:
        pendings = [
            svc.submit(ring_dcop(5), "dpop", {}) for _ in range(2)
        ]
        got = [p.result(timeout=300) for p in pendings]
    seq = solve(ring_dcop(5), "dpop", {})
    for r in got:
        assert r["cost"] == seq["cost"]


def test_timeout_in_group_key_never_truncates_batchmates():
    """A request carrying a deadline may only coalesce with requests
    carrying the SAME deadline (the run_many_batched timeout acts
    group-wide at chunk boundaries) — so a tight timeout splits off
    into its own dispatch instead of truncating a batchmate's solve."""
    kw = dict(rounds=24, chunk_size=24, seed=3)
    with SolverService(
        pad_policy="pow2:16", max_batch=2, max_wait=30.0,
        autostart=False,
    ) as svc:
        p1 = svc.submit(ring_dcop(6, name="a"), "mgm", {}, **kw)
        p2 = svc.submit(
            ring_dcop(6, name="b"), "mgm", {}, timeout=120.0, **kw
        )
        r1, r2 = p1.result(timeout=300), p2.result(timeout=300)
        stats = svc.stats()
    # one tick, but two dispatches: the deadline split the group
    assert stats["ticks"] == 1 and stats["dispatches"] == 2
    assert r1["instances_batched"] == 1
    assert r2["instances_batched"] == 1
    assert r1["status"] == "finished" and r2["status"] == "finished"


def test_group_failure_keeps_earlier_groups_results(monkeypatch):
    """A partition can span several shape-bucket groups; when a LATER
    group's dispatch raises, requests of an already-delivered earlier
    group keep their results (only the failed group's clients see the
    ServiceError)."""
    from pydcop_tpu.engine import batched

    real = batched.run_many_batched

    def poisoned(stacked, *args, **kwargs):
        # under pow2:16 the small rings stack at 16 padded vars, the
        # big ones at 32 — poison only the big bucket
        if stacked.template.n_real_vars > 16:
            raise RuntimeError("big-bucket dispatch exploded")
        return real(stacked, *args, **kwargs)

    monkeypatch.setattr(batched, "run_many_batched", poisoned)
    kw = dict(rounds=16, chunk_size=16)
    with SolverService(
        pad_policy="pow2:16", max_batch=4, max_wait=30.0,
        autostart=False,
    ) as svc:
        # same partition (identical params), two shape buckets: the
        # small group dispatches (and delivers) first, then the big
        # group raises
        smalls = [
            svc.submit(ring_dcop(5 + i, name=f"s{i}"), "mgm", {}, **kw)
            for i in range(2)
        ]
        bigs = [
            svc.submit(
                ring_dcop(17 + i, name=f"b{i}"), "mgm", {}, **kw
            )
            for i in range(2)
        ]
        for p in smalls:
            assert p.result(timeout=300)["status"] == "finished"
        for p in bigs:
            with pytest.raises(ServiceError, match="big-bucket"):
                p.result(timeout=300)
        assert svc.stats()["errors"] == 2  # only the failed group


def test_worker_survives_a_poisoned_tick(monkeypatch):
    """The tick worker outlives an exception that escapes dispatch
    entirely (e.g. a broken telemetry sink): the batch's clients get a
    ServiceError instead of blocking forever, and the NEXT request is
    served normally."""
    calls = {"n": 0}
    orig = SolverService._dispatch_tick

    def flaky(self, batch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("telemetry sink exploded")
        return orig(self, batch)

    monkeypatch.setattr(SolverService, "_dispatch_tick", flaky)
    with SolverService(
        max_batch=1, max_wait=30.0, autostart=False
    ) as svc:
        p1 = svc.submit(ring_dcop(6), "dsa", {}, rounds=8, chunk_size=8)
        with pytest.raises(ServiceError, match="telemetry sink"):
            p1.result(timeout=300)
        p2 = svc.submit(ring_dcop(6), "dsa", {}, rounds=8, chunk_size=8)
        assert p2.result(timeout=300)["status"] == "finished"


# -- session affinity: the zero-recompile satellite --------------------


def test_session_set_values_zero_full_recompiles_after_segment_1():
    """Satellite acceptance: a client streaming ``set_values`` deltas
    through its pinned session hits ``compile.reused`` /
    ``compile.incremental`` ONLY after segment 1 — zero full
    recompiles, zero XLA compiles (counter-asserted)."""
    from pydcop_tpu.engine import batched

    batched._RUNNER_CACHE.clear()
    kw = dict(rounds=48, chunk_size=48, seed=7)
    with session() as tel:
        with SolverService(
            max_batch=1, max_wait=0.0, autostart=False
        ) as svc:
            r1 = svc.solve(
                sensor_dcop(), "dsa", {"variant": "B"},
                session="client-1", **kw,
            )
            assert r1["segment"] == 1
            c1 = dict(tel.summary()["counters"])
            # segment 2: delta on the external -> incremental update
            r2 = svc.solve(
                None, "dsa", {"variant": "B"},
                session="client-1", set_values={"sensor": 2}, **kw,
            )
            assert r2["segment"] == 2
            assert r2["assignment"]["v0"] == 2  # the delta took
            # segment 3: same externals -> pure reuse
            r3 = svc.solve(
                None, "dsa", {"variant": "B"}, session="client-1",
                **kw,
            )
            assert r3["segment"] == 3
            c3 = dict(tel.summary()["counters"])
            assert svc.close_session("client-1")
            assert not svc.close_session("client-1")
    assert c1.get("compile.full", 0) == 1
    assert c3.get("compile.full", 0) == 1  # never recompiled
    assert c3.get("compile.incremental", 0) >= 1
    assert c3.get("compile.reused", 0) >= 1
    # zero NEW XLA compiles after segment 1
    assert c3["jit.compiles"] == c1["jit.compiles"], (c1, c3)


def test_session_rejects_unknown_externals_and_keeps_serving():
    with SolverService(
        max_batch=1, max_wait=0.0, autostart=False
    ) as svc:
        r = svc.solve(
            sensor_dcop(), "dsa", {}, rounds=24, chunk_size=24,
            session="s",
        )
        assert r["session"] == "s"
        with pytest.raises(ServiceError, match="external"):
            svc.solve(
                None, "dsa", {}, rounds=24, chunk_size=24,
                session="s", set_values={"nope": 1},
            )
        # the session survives the bad delta
        assert svc.solve(
            None, "dsa", {}, rounds=24, chunk_size=24, session="s"
        )["segment"] == 2


def test_session_follow_up_with_different_dcop_is_rejected():
    """A follow-up naming an open session may resend the SAME dcop (a
    reconnecting wire client re-ships its yaml) but a DIFFERENT one is
    rejected at admission — silently solving the pinned problem under
    the new problem's name would be a wrong answer."""
    kw = dict(rounds=16, chunk_size=16)
    with SolverService(
        max_batch=1, max_wait=0.0, autostart=False
    ) as svc:
        d = sensor_dcop()
        assert svc.solve(d, "dsa", {}, session="s", **kw)["segment"] == 1
        # resending the SAME object is a normal follow-up
        assert svc.solve(d, "dsa", {}, session="s", **kw)["segment"] == 2
        with pytest.raises(ServiceError, match="pinned to a different"):
            svc.submit(ring_dcop(6), "dsa", {}, session="s", **kw)
        # the session survives the rejected mismatch
        assert svc.solve(
            None, "dsa", {}, session="s", **kw
        )["segment"] == 3
        # ... and the same yaml TEXT re-keys identically over the wire
        assert svc.solve(
            RING_YAML, "dsa", {}, session="wire", **kw
        )["segment"] == 1
        assert svc.solve(
            RING_YAML, "dsa", {}, session="wire", **kw
        )["segment"] == 2


# -- exact sessions: the O(delta) memoized serving path ----------------


def _host_ref(sensor_val):
    """Fresh cold solve of the mutated problem — the parity oracle
    for the memoized exact-session path."""
    from pydcop_tpu.algorithms.dpop import solve_host

    d = sensor_dcop()
    d.external_variables["sensor"].value = sensor_val
    r = solve_host(d, {})
    return r["cost"], r["assignment"]


def test_exact_session_segments_memo_hit_and_match_reference():
    """ISSUE 18: a session whose algo has ``solve_host`` (dpop) is
    served by a live memoized :class:`ExactSession` — segment 1 is
    the cold sweep, a 1-delta follow-up re-contracts only the dirty
    path (memo hits on the rest), a no-delta follow-up hits EVERY
    node, and every segment is bit-identical to a fresh cold solve of
    the mutated problem.  Non-memoized exact algos (syncbb) ride the
    same session dispatch through a plain pinned clone."""
    with session() as tel:
        with SolverService(
            max_batch=1, max_wait=0.0, autostart=False
        ) as svc:
            r1 = svc.solve(sensor_dcop(), "dpop", {}, session="c1")
            assert r1["segment"] == 1
            assert r1["memo"]["hits"] == 0
            cost0, asg0 = _host_ref(0)
            assert (r1["cost"], r1["assignment"]) == (cost0, asg0)

            r2 = svc.solve(
                None, "dpop", {}, session="c1",
                set_values={"sensor": 2},
            )
            assert r2["segment"] == 2
            cost2, asg2 = _host_ref(2)
            assert (r2["cost"], r2["assignment"]) == (cost2, asg2)
            m = r2["memo"]
            assert m["hits"] >= 1, m
            assert m["hits"] + m["recontracted"] == m["nodes"], m

            r3 = svc.solve(None, "dpop", {}, session="c1")
            assert r3["memo"]["hits"] == r3["memo"]["nodes"]
            assert r3["cost"] == cost2

            # plain exact algo: same session surface, no memo block
            rs = svc.solve(sensor_dcop(), "syncbb", {}, session="c2")
            assert rs["cost"] == cost0
            rs2 = svc.solve(
                None, "syncbb", {}, session="c2",
                set_values={"sensor": 2},
            )
            assert rs2["cost"] == cost2
            assert "memo" not in rs2
    counters = tel.summary()["counters"]
    assert counters.get("engine.memo_hits", 0) >= r2["memo"][
        "hits"
    ] + r3["memo"]["hits"]


def test_exact_session_checkpoint_resume_replays_memoized(tmp_path):
    """Satellite acceptance (serve --resume): the drained checkpoint
    records the memoized sessions' algo params, a resuming service
    warm-replays them (ONE solve at the final accumulated state), and
    the restored session's FIRST live follow-up is already O(delta):
    memo hits on the replayed segments, ZERO XLA compiles, zero full
    rebuilds (the exact path never touches ``compile.full``) —
    bit-identical to a fresh cold solve of the mutated problem."""
    ck = str(tmp_path / "sessions.json")
    with SolverService(
        max_batch=1, max_wait=0.0, autostart=False,
        session_checkpoint=ck,
    ) as svc:
        svc.solve(sensor_dcop(), "dpop", {}, session="c1")
        svc.solve(
            None, "dpop", {}, session="c1",
            set_values={"sensor": 2},
        )
        svc.solve(sensor_dcop(), "syncbb", {}, session="c2")
    # drain wrote the exact record: dpop (memoized) yes, syncbb no
    import json as _json

    with open(ck) as f:
        doc = _json.load(f)
    ent = {e["name"]: e for e in doc["sessions"]}
    assert "dpop" in ent["c1"]["exact"], ent["c1"]
    assert ent["c2"].get("exact") == {}, ent["c2"]

    with SolverService(
        max_batch=1, max_wait=0.0, autostart=False,
        session_checkpoint=ck, resume=True,
    ) as svc:
        with session() as tel:
            r = svc.solve(
                None, "dpop", {}, session="c1",
                set_values={"sensor": 1},
            )
        cost1, asg1 = _host_ref(1)
        assert (r["cost"], r["assignment"]) == (cost1, asg1)
        assert r["memo"]["hits"] >= 1, r["memo"]
        counters = tel.summary()["counters"]
        assert counters.get("jit.compiles", 0) == 0, counters
        assert counters.get("compile.full", 0) == 0, counters


def test_standby_promotion_followup_is_o_delta():
    """Satellite acceptance (fleet standby tail replay): a standby
    applies a replicated exact session via ONE rebuild solve, follows
    the owner's delta stream with cheap ``set_values``-only
    incremental entries (no per-segment re-solves), and the
    promotion follow-up memo-hits the clean subtrees with ZERO XLA
    compiles — bit-identical to the owner's own follow-up."""
    with SolverService(
        max_batch=1, max_wait=0.0, autostart=False
    ) as owner:
        owner.solve(sensor_dcop(), "dpop", {}, session="c1")
        with SolverService(
            max_batch=1, max_wait=0.0, autostart=False
        ) as standby:
            rep = standby.apply_replica_entry(owner.session_entry("c1"))
            assert rep["mode"] == "rebuild", rep
            # owner streams a delta; the standby applies it as an
            # incremental entry (set_values only, no solve)
            r_owner = owner.solve(
                None, "dpop", {}, session="c1",
                set_values={"sensor": 2},
            )
            rep2 = standby.apply_replica_entry(
                owner.session_entry("c1")
            )
            assert rep2["mode"] == "incremental", rep2
            # promote: the standby serves the session's next segment
            with session() as tel:
                r6 = standby.solve(None, "dpop", {}, session="c1")
            assert r6["cost"] == r_owner["cost"]
            assert r6["assignment"] == r_owner["assignment"]
            assert r6["memo"]["hits"] >= 1, r6["memo"]
            counters = tel.summary()["counters"]
            assert counters.get("jit.compiles", 0) == 0, counters


# -- device chaos on the serving path ----------------------------------


def test_service_nan_inject_degrades_only_the_poisoned_request():
    """Acceptance: a ``nan_inject`` chaos spec against the service
    degrades only the affected request while its batchmates return
    results bit-identical to a fault-free service."""
    dcops = [ring_dcop(5 + i % 3, name=f"q{i}") for i in range(8)]
    kw = dict(rounds=24, chunk_size=12)

    def serve_all(**svc_kw):
        with SolverService(
            pad_policy="pow2:16", max_batch=8, max_wait=30.0,
            autostart=False, **svc_kw,
        ) as svc:
            pendings = [
                svc.submit(d, "mgm", {}, seed=7, **kw) for d in dcops
            ]
            return [p.result(timeout=300) for p in pendings]

    base = serve_all()
    nan = serve_all(chaos="nan_inject=1:2", chaos_seed=3)
    statuses = [r["status"] for r in nan]
    assert statuses.count("degraded") == 1
    poisoned = statuses.index("degraded")
    for i, (b, o) in enumerate(zip(base, nan)):
        if i != poisoned:
            assert b["cost"] == o["cost"]
            assert b["assignment"] == o["assignment"]
            assert b["cost_trace"] == o["cost_trace"]


def test_service_device_oom_splits_and_stays_bit_identical():
    """Acceptance: ``device_oom`` against the service completes via
    supervised group-split with every request bit-identical to the
    fault-free service run (no request fails, none degrade)."""
    dcops = [ring_dcop(5 + i % 3, name=f"q{i}") for i in range(8)]
    kw = dict(rounds=24, chunk_size=12)

    def serve_all(**svc_kw):
        with SolverService(
            pad_policy="pow2:16", max_batch=8, max_wait=30.0,
            autostart=False, **svc_kw,
        ) as svc:
            pendings = [
                svc.submit(d, "mgm", {}, seed=7, **kw) for d in dcops
            ]
            return [p.result(timeout=300) for p in pendings]

    base = serve_all()
    oom = serve_all(chaos="device_oom=4", chaos_seed=3)
    for b, o in zip(base, oom):
        assert o["status"] == "finished"
        assert b["cost"] == o["cost"]
        assert b["assignment"] == o["assignment"]
        assert b["cost_trace"] == o["cost_trace"]


# -- the wire protocol -------------------------------------------------


def test_wire_protocol_round_trip_and_concurrent_clients():
    """ServiceServer/ServiceClient over a real socket: ping, yaml-text
    solve (cost_trace trimmed for the wire), per-request errors that
    don't kill the connection, stats, and N concurrent clients
    coalescing into shared ticks."""
    with SolverService(
        pad_policy="pow2:16", max_batch=4, max_wait=0.25,
        autostart=False,
    ) as svc:
        with ServiceServer(svc, port=0) as server:
            with ServiceClient(server.address) as cli:
                assert cli.ping()
                r = cli.solve(RING_YAML, "dsa", rounds=24, seed=1)
                assert r["status"] == "finished"
                assert "cost_trace" not in r  # trimmed for the wire
                # a bad request errors THIS call, not the connection
                with pytest.raises(ServiceError, match="algo"):
                    cli.solve(RING_YAML, None)
                with pytest.raises(ValueError, match="unknown solve"):
                    cli.solve(RING_YAML, "dsa", bogus=1)
                assert cli.ping()  # connection still live

            # 4 concurrent clients coalesce into shared ticks
            results = [None] * 4

            def one(i):
                with ServiceClient(server.address) as c:
                    results[i] = c.solve(
                        RING_YAML, "dsa", rounds=24, seed=9
                    )

            threads = [
                threading.Thread(target=one, args=(i,))
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            assert all(r is not None for r in results)
            assert len({r["cost"] for r in results}) == 1

            with ServiceClient(server.address) as cli:
                stats = cli.stats()
                # 5 admitted solves (the algo-less one was rejected
                # at validation, before admission)
                assert stats["requests"] == 5
                # the burst actually shared ticks
                assert stats["coalesced_requests"] >= 2
                assert stats["latency_s"]["p99"] > 0


def test_trace_summary_reports_service_percentiles(tmp_path, capsys):
    """A trace written while serving folds into a serving report:
    ``summarize`` gains a ``service`` block (queue-wait / latency /
    batch-occupancy percentiles + coalesce ratio) and the
    ``trace-summary`` command renders it — a trace from ``serve`` is
    readable without custom scripts."""
    import json

    from pydcop_tpu.cli import main
    from pydcop_tpu.telemetry.summary import load_trace, summarize

    path = tmp_path / "serve.jsonl"
    with session(str(path)):
        with SolverService(
            pad_policy="pow2:16", max_batch=4, max_wait=10.0,
            autostart=False,
        ) as svc:
            pendings = [
                svc.submit(
                    ring_dcop(name=f"r{i}"), "dsa", {},
                    rounds=16, chunk_size=16, seed=i,
                )
                for i in range(4)
            ]
            for p in pendings:
                p.result(timeout=300)
    s = summarize(load_trace(str(path)))
    svc_s = s["service"]
    assert svc_s["requests"] == 4
    assert svc_s["dispatches"] == 1  # one tick, one coalesced group
    assert svc_s["coalesce_ratio"] == 4.0
    assert svc_s["batch_occupancy"]["max"] == 4.0
    for block in ("queue_wait_s", "latency_s"):
        v = svc_s[block]
        assert 0 <= v["p50"] <= v["p90"] <= v["p99"] <= v["max"]
    # request latency covers the queue wait plus the dispatch
    assert svc_s["latency_s"]["max"] >= svc_s["queue_wait_s"]["p50"]
    # the command renders the serving block (text and --json forms)
    assert main(["trace-summary", str(path)]) == 0
    out = capsys.readouterr().out
    assert "service: 4 requests / 1 dispatches" in out
    assert "batch_occupancy" in out
    assert main(["trace-summary", str(path), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["service"]["requests"] == 4


def test_server_prunes_closed_connections():
    """'Concurrency is connections' means a resident server sees an
    unbounded stream of short-lived ones — handler bookkeeping must
    drain as they close, not accumulate forever."""
    import time

    with SolverService(max_batch=1, autostart=False) as svc:
        with ServiceServer(svc, port=0) as server:
            for _ in range(3):
                with ServiceClient(server.address) as cli:
                    assert cli.ping()
            deadline = time.time() + 10
            while (
                server._threads or server._conns
            ) and time.time() < deadline:
                time.sleep(0.05)
            assert not server._threads and not server._conns


def test_wire_shutdown_op_stops_the_server():
    with SolverService(max_batch=1, autostart=False) as svc:
        server = ServiceServer(svc, port=0)
        with ServiceClient(server.address) as cli:
            cli.shutdown()
        assert server.wait(timeout=10)
        server.close()


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
