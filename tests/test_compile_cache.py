"""Tests for the compile-reuse layer: shape-bucketed padding
(``ops/padding.py`` + ``compile_dcop(pad_policy=...)``), incremental
problem recompilation (``engine/incremental.py``), execution-problem
canonicalization, and the runner-cache LRU cap.

Covers the PR-3 acceptance criteria: a two-segment dynamic run with a
``set_value`` event performs zero new XLA compiles after segment 1,
``n_vars`` changes within one bucket carry the compiled executables
across segments, and padded runs match unpadded ``best_cost`` exactly.
"""

import dataclasses

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
)
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.dcop.scenario import EventAction, Scenario, ScenarioEvent
from pydcop_tpu.engine import batched
from pydcop_tpu.engine.dynamic import run_dynamic
from pydcop_tpu.engine.incremental import IncrementalCompiler
from pydcop_tpu.ops.compile import (
    canonical_execution_problem,
    compile_dcop,
    decode_assignment,
    encode_assignment,
    problem_fingerprint,
)
from pydcop_tpu.ops.costs import total_cost
from pydcop_tpu.ops.padding import as_pad_policy
from pydcop_tpu.telemetry import session

D = Domain("d", "", [0, 1, 2])


def ring_dcop(n=6):
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs)
        )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def sensor_dcop():
    """One chain + an external 'sensor' variable driving v0."""
    dcop = DCOP("ext")
    vs = [Variable(f"v{i}", D) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    sensor = ExternalVariable("sensor", D, value=0)
    dcop.add_variable(sensor)
    for i in range(2):
        dcop.add_constraint(
            constraint_from_str(
                f"c{i}", f"1 if v{i} == v{i + 1} else 0", vs
            )
        )
    dcop.add_constraint(
        constraint_from_str(
            "track", "0 if v0 == sensor else 1", [vs[0], sensor]
        )
    )
    dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
    return dcop


# -- pad policy parsing ------------------------------------------------


def test_pad_policy_parse():
    assert not as_pad_policy("none").enabled
    assert not as_pad_policy(None).enabled
    pol = as_pad_policy("pow2")
    assert pol.enabled and pol.floor == 16
    assert as_pad_policy("pow2:64").floor == 64
    assert pol.bucket(5) == 16
    assert pol.bucket(17) == 32
    assert pol.bucket(0) == 0
    with pytest.raises(ValueError):
        as_pad_policy("pow3")
    with pytest.raises(ValueError):
        as_pad_policy("pow2:0")


# -- padded compiles ---------------------------------------------------


def test_padded_shapes_are_bucketed_and_costs_match():
    dcop = ring_dcop(6)
    p0 = compile_dcop(dcop)
    p1 = compile_dcop(dcop, pad_policy="pow2:16")
    assert p0.n_vars == 6 and p1.n_vars == 16
    assert p1.n_real_vars == 6 and p1.n_pad_vars == 10
    # ghost constraints pad the arity-2 group to the bucket
    assert p1.n_cons == 16 and p1.n_edges == 32
    # identical cost for the same (real) assignment
    vals0 = p0.init_idx
    vals1 = p1.init_idx
    assert float(total_cost(p0, vals0)) == float(total_cost(p1, vals1))
    # assignments in/out ignore ghost variables
    a = decode_assignment(p1, p1.init_idx)
    assert sorted(a) == [f"v{i}" for i in range(6)]
    enc = np.asarray(encode_assignment(p1, a))
    assert enc.shape == (16,) and (enc[6:] == 0).all()


def test_same_bucket_same_shapes_after_structure_change():
    """A ring losing one variable must land in the SAME shape bucket:
    every array shape and every traced static must match, so the jit
    trace cache can reuse the compiled executables."""
    full = compile_dcop(ring_dcop(6), pad_policy="pow2:16")
    # v0 frozen: its two constraints slice to unary
    dcop = ring_dcop(6)
    inc = IncrementalCompiler(dcop, pad_policy="pow2:16")
    shrunk, _ = inc.compile({"v0": 0}, {})
    a = canonical_execution_problem(full)
    b = canonical_execution_problem(shrunk)
    import jax

    ta = jax.tree_util.tree_structure(a)
    tb = jax.tree_util.tree_structure(b)
    assert ta == tb, f"{ta}\n!=\n{tb}"
    for la, lb in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        assert la.shape == lb.shape and la.dtype == lb.dtype


def test_padded_best_cost_matches_unpadded_exactly():
    """Acceptance: padded runs match unpadded best_cost exactly on the
    coloring fixture (maxsum with noise=0 is deterministic)."""
    import __graft_entry__ as g
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import run_batched

    dcop = g._make_coloring_dcop(40, seed=2)
    module = load_algorithm_module("maxsum")
    params = prepare_algo_params(
        {"damping": 0.5, "noise": 0.0}, module.algo_params
    )
    r0 = run_batched(
        compile_dcop(dcop), module, params,
        rounds=64, seed=0, chunk_size=32,
    )
    r1 = run_batched(
        compile_dcop(dcop, pad_policy="pow2:16"), module, params,
        rounds=64, seed=0, chunk_size=32,
    )
    assert r1.best_cost == r0.best_cost
    assert r1.best_assignment == r0.best_assignment
    assert r1.cost == r0.cost


# -- incremental recompilation -----------------------------------------


def test_incremental_update_matches_full_recompile():
    """A set_value delta-update must produce byte-identical arrays to
    a from-scratch compile of the perturbed problem."""
    dcop = sensor_dcop()
    inc = IncrementalCompiler(dcop)
    p0, fp0 = inc.compile({}, {})
    p1, fp1 = inc.compile({}, {"sensor": 2})
    assert fp1 != fp0
    fresh = compile_dcop(inc._active_dcop({}, {"sensor": 2}))
    np.testing.assert_array_equal(
        np.asarray(p1.tables_flat), np.asarray(fresh.tables_flat)
    )
    np.testing.assert_array_equal(
        np.asarray(p1.unary), np.asarray(fresh.unary)
    )
    for k in fresh.buckets:
        np.testing.assert_array_equal(
            np.asarray(p1.buckets[k].tables),
            np.asarray(fresh.buckets[k].tables),
        )
        np.testing.assert_array_equal(
            np.asarray(p1.buckets[k].tables_t),
            np.asarray(fresh.buckets[k].tables_t),
        )
    # reverting the external restores the original content AND fp
    p2, fp2 = inc.compile({}, {"sensor": 0})
    assert fp2 == fp0
    np.testing.assert_array_equal(
        np.asarray(p2.unary), np.asarray(p0.unary)
    )
    # static metadata objects are shared — the jit trace cache key
    # cannot drift across incremental updates
    assert p1.var_names is p0.var_names
    assert p1.con_names is p0.con_names


def test_incremental_delay_reuses_problem_object():
    dcop = sensor_dcop()
    inc = IncrementalCompiler(dcop)
    p0, fp0 = inc.compile({}, {})
    p1, fp1 = inc.compile({}, {})
    assert p1 is p0 and fp1 == fp0


def test_const_external_change_keeps_fingerprint():
    """A set_value on an external read ONLY by fully-external
    constraints (compiler drops them) must not change the fingerprint
    — the compiled arrays are byte-identical and full-state carry
    must survive."""
    dcop = sensor_dcop()
    inc = IncrementalCompiler(dcop)
    # freeze v0: 'track' (v0, sensor) becomes fully external
    p0, fp0 = inc.compile({"v0": 0}, {})
    p1, fp1 = inc.compile({"v0": 0}, {"sensor": 2})
    assert fp1 == fp0
    np.testing.assert_array_equal(
        np.asarray(p1.unary), np.asarray(p0.unary)
    )


def test_persistent_cache_unwritable_dir_returns_false():
    from pydcop_tpu.ops.compile import (
        enable_persistent_compilation_cache,
    )

    assert not enable_persistent_compilation_cache(
        "/proc/definitely/not/writable"
    )


def test_incremental_structure_change_full_recompile():
    dcop = ring_dcop(4)
    inc = IncrementalCompiler(dcop)
    p0, _ = inc.compile({}, {})
    p1, _ = inc.compile({"v0": 1}, {})
    assert p1.n_real_vars == 3
    # frozen value baked in: the fingerprint distinguishes freezes
    p2, fp2 = inc.compile({"v0": 2}, {})
    _, fp1 = inc.compile({"v0": 1}, {})
    assert fp2 != fp1


# -- dynamic runs: zero recompiles after segment 1 ---------------------


def _jit_counters(tel):
    return tel.summary()["counters"]


def test_dynamic_set_value_zero_new_compiles():
    """Acceptance: a two-segment dynamic run with a set_value event
    performs 0 new XLA compiles after segment 1."""
    scenario = Scenario(
        [
            ScenarioEvent(
                "e1",
                actions=[
                    EventAction("set_value", variable="sensor", value=2)
                ],
            ),
        ]
    )
    batched._RUNNER_CACHE.clear()
    with session() as tel:
        r = run_dynamic(
            sensor_dcop(), "dsa", {"variant": "B"},
            scenario=scenario, final_rounds=48, chunk_size=48, seed=7,
        )
    c = _jit_counters(tel)
    assert r["assignment"]["v0"] == 2
    assert c["jit.compiles"] == 1, c
    assert c.get("compile.incremental", 0) >= 1, c
    assert c.get("jit.cache_hits", 0) >= 1, c


def test_dynamic_bucketed_nvars_change_zero_new_compiles():
    """Satellite: n_vars changes within one bucket (a variable freezes
    after remove_agent) → zero new jit_compiles after segment 1."""
    scenario = Scenario(
        [
            ScenarioEvent(
                "e1", actions=[EventAction("remove_agent", agent="a0")]
            ),
            ScenarioEvent(delay=2.4),  # 48 rounds at 20 rps
        ]
    )
    batched._RUNNER_CACHE.clear()
    with session() as tel:
        r = run_dynamic(
            ring_dcop(6), "maxsum", {"noise": 0.0},
            scenario=scenario, distribution="adhoc", k_target=0,
            final_rounds=48, chunk_size=48, seed=3,
            pad_policy="pow2:16",
        )
    c = _jit_counters(tel)
    assert r["lost_computations"], r  # a variable actually froze
    assert len(r["assignment"]) == 6
    assert c["jit.compiles"] == 1, c
    assert c.get("jit.cache_hits", 0) >= 2, c
    # sanity: without padding the same scenario recompiles on the
    # freeze — the bucket is what carries the executable across
    batched._RUNNER_CACHE.clear()
    with session() as tel2:
        run_dynamic(
            ring_dcop(6), "maxsum", {"noise": 0.0},
            scenario=scenario, distribution="adhoc", k_target=0,
            final_rounds=48, chunk_size=48, seed=3,
        )
    assert _jit_counters(tel2)["jit.compiles"] == 2


def test_dynamic_padded_state_carry_across_delays():
    """Full-state carry still works across bucketed segments (delays
    keep the fingerprint stable under padding)."""
    scenario = Scenario(
        [ScenarioEvent(delay=2.4), ScenarioEvent(delay=2.4)]
    )
    r = run_dynamic(
        ring_dcop(6), "maxsum", {"noise": 0.0},
        scenario=scenario, distribution="adhoc", k_target=0,
        final_rounds=48, chunk_size=48, seed=5, pad_policy="pow2:16",
    )
    delays = [e for e in r["events"] if e["type"] == "delay"]
    assert [e["state_carried"] for e in delays] == [True, True]
    assert r["state_transfers"] == 3  # 2 delays + final settle


# -- canonical execution problem ---------------------------------------


def test_canonical_execution_problem_shares_arrays():
    p = compile_dcop(ring_dcop(4))
    c = canonical_execution_problem(p)
    assert c.unary is p.unary and c.tables_flat is p.tables_flat
    assert c.var_names != p.var_names
    # fingerprint of the ORIGINAL is unaffected
    assert problem_fingerprint(p) == problem_fingerprint(p)
    # two differently-named but same-shaped problems canonicalize to
    # equal treedefs
    import jax

    q = compile_dcop(ring_dcop(4))
    q = dataclasses.replace(q, var_names=tuple(f"w{i}" for i in range(4)))
    assert jax.tree_util.tree_structure(
        canonical_execution_problem(q)
    ) == jax.tree_util.tree_structure(c)


# -- runner cache LRU --------------------------------------------------


def test_runner_cache_lru_eviction():
    import __graft_entry__ as g
    from pydcop_tpu.algorithms import (
        load_algorithm_module,
        prepare_algo_params,
    )
    from pydcop_tpu.engine.batched import (
        run_batched,
        set_runner_cache_limit,
    )

    problem = compile_dcop(g._make_coloring_dcop(12, seed=4))
    module = load_algorithm_module("dsa")
    params = prepare_algo_params({"variant": "A"}, module.algo_params)
    batched._RUNNER_CACHE.clear()
    try:
        set_runner_cache_limit(2)
        with session() as tel:
            for chunk in (7, 9, 11):  # three distinct runner keys
                run_batched(
                    problem, module, params,
                    rounds=chunk, seed=0, chunk_size=chunk,
                )
        assert len(batched._RUNNER_CACHE) <= 2
        counters = tel.summary()["counters"]
        assert counters.get("engine.runner_cache_evictions", 0) >= 1
        with pytest.raises(ValueError):
            set_runner_cache_limit(0)
    finally:
        set_runner_cache_limit(None)
        batched._RUNNER_CACHE.clear()


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
