"""Tests for the distribution (placement) strategies (L4)."""

import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.distribution import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
    list_available_distributions,
    load_distribution_module,
)
from pydcop_tpu.graphs import constraints_hypergraph, factor_graph

D = Domain("d", "", [0, 1, 2])


def ring_dcop(n=4):
    dcop = DCOP("ring")
    vs = [Variable(f"v{i}", D) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n):
        j = (i + 1) % n
        dcop.add_constraint(
            constraint_from_str(f"c{i}_{j}", f"1 if v{i} == v{j} else 0", vs)
        )
    return dcop


def agents(n, **kwargs):
    return [AgentDef(f"a{i}", **kwargs) for i in range(n)]


def mem_one(node):
    return 1.0


def load_one(node, neighbor):
    return 1.0


def test_registry():
    avail = list_available_distributions()
    for name in ("oneagent", "adhoc", "heur_comhost", "ilp_fgdp", "ilp_compref"):
        assert name in avail
    with pytest.raises(ValueError):
        load_distribution_module("objects")
    with pytest.raises(ValueError):
        load_distribution_module("nope")


def test_oneagent_basic():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    mod = load_distribution_module("oneagent")
    dist = mod.distribute(g, agents(4))
    assert sorted(dist.computations) == ["v0", "v1", "v2", "v3"]
    # one computation per agent
    for a in dist.agents:
        assert len(dist.computations_hosted(a)) <= 1


def test_oneagent_not_enough_agents():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    mod = load_distribution_module("oneagent")
    with pytest.raises(ImpossibleDistributionException):
        mod.distribute(g, agents(3))


def test_adhoc_respects_capacity():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    mod = load_distribution_module("adhoc")
    dist = mod.distribute(
        g, agents(2, capacity=2.0), computation_memory=mem_one
    )
    assert sorted(dist.computations) == ["v0", "v1", "v2", "v3"]
    for a in dist.agents:
        assert len(dist.computations_hosted(a)) == 2
    with pytest.raises(ImpossibleDistributionException):
        mod.distribute(g, agents(1, capacity=2.0), computation_memory=mem_one)


def test_adhoc_hints():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    mod = load_distribution_module("adhoc")
    hints = DistributionHints(
        must_host={"a0": ["v2"]}, host_with={"v2": ["v3"]}
    )
    dist = mod.distribute(g, agents(2), hints=hints)
    assert dist.agent_for("v2") == "a0"
    assert dist.agent_for("v3") == "a0"


def test_heur_comhost_prefers_cheap_hosting():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(3))
    mod = load_distribution_module("heur_comhost")
    ags = [
        AgentDef("cheap", default_hosting_cost=0.0, default_route=0.0),
        AgentDef("dear", default_hosting_cost=10.0, default_route=0.0),
    ]
    dist = mod.distribute(
        g, ags, computation_memory=mem_one, communication_load=load_one
    )
    # with free routes, everything lands on the cheap-host agent
    assert dist.computations_hosted("cheap") and not dist.computations_hosted(
        "dear"
    )


def test_heur_comhost_groups_neighbors():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    mod = load_distribution_module("heur_comhost")
    # routes are expensive, hosting free: placement should co-locate
    dist = mod.distribute(
        g,
        agents(4, default_route=100.0),
        computation_memory=mem_one,
        communication_load=load_one,
    )
    # all computations on a single agent minimizes the greedy objective
    hosting = [a for a in dist.agents if dist.computations_hosted(a)]
    assert len(hosting) == 1


@pytest.mark.parametrize("name", ["ilp_fgdp", "ilp_compref"])
def test_ilp_colocates_under_expensive_routes(name):
    g = factor_graph.build_computation_graph(ring_dcop(3))
    mod = load_distribution_module(name)
    dist = mod.distribute(
        g,
        agents(2, capacity=100.0, default_route=10.0),
        computation_memory=mem_one,
        communication_load=load_one,
    )
    hosting = [a for a in dist.agents if dist.computations_hosted(a)]
    assert len(hosting) == 1  # optimal: zero cut edges
    assert len(dist.computations) == 6  # 3 variables + 3 factors


def test_ilp_capacity_forces_split():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    mod = load_distribution_module("ilp_compref")
    dist = mod.distribute(
        g,
        agents(2, capacity=2.0),
        computation_memory=mem_one,
        communication_load=load_one,
    )
    for a in dist.agents:
        assert len(dist.computations_hosted(a)) == 2
    # optimal split of a 4-ring in halves cuts exactly 2 edges
    total, comm, hosting = mod.distribution_cost(
        dist, g, agents(2, capacity=2.0), mem_one, load_one
    )
    assert comm == pytest.approx(2.0)


def test_ilp_must_host_pin():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(3))
    mod = load_distribution_module("ilp_compref")
    hints = DistributionHints(must_host={"a1": ["v0"]})
    dist = mod.distribute(
        g,
        agents(2, default_route=10.0),
        hints=hints,
        communication_load=load_one,
    )
    assert dist.agent_for("v0") == "a1"
    # colocated with pin: everything follows v0 to a1
    assert dist.agent_for("v1") == "a1"


def test_ilp_infeasible():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    mod = load_distribution_module("ilp_fgdp")
    with pytest.raises(ImpossibleDistributionException):
        mod.distribute(
            g, agents(1, capacity=3.0), computation_memory=mem_one
        )


def test_distribution_cost_breakdown():
    g = constraints_hypergraph.build_computation_graph(ring_dcop(4))
    from pydcop_tpu.distribution._cost import RATIO_HOST_COMM, distribution_cost

    dist = Distribution({"a0": ["v0", "v1"], "a1": ["v2", "v3"]})
    ags = agents(2, default_hosting_cost=1.0, default_route=2.0)
    total, comm, hosting = distribution_cost(
        dist, g, ags, mem_one, load_one
    )
    # ring v0-v1-v2-v3-v0 split in halves cuts c1_2 and c3_0: 2 links × route 2
    assert comm == pytest.approx(4.0)
    assert hosting == pytest.approx(4.0)
    assert total == pytest.approx(comm + RATIO_HOST_COMM * hosting)


# -- SECP variants (VERDICT r1 item 9) ----------------------------------


def _secp_instance():
    """3 lights owned by 3 device agents (own light hosts at cost 0),
    one 2-light model factor."""
    import types

    from pydcop_tpu.commands.generators.secp import generate

    args = types.SimpleNamespace(
        nb_lights=3, nb_models=2, nb_rules=1, light_levels=3,
        model_arity=2, efficiency_weight=0.1, capacity=100.0, seed=4,
    )
    dcop = generate(args)
    from pydcop_tpu.algorithms import load_algorithm_module

    module = load_algorithm_module("maxsum")
    graph = factor_graph.build_computation_graph(dcop)
    return dcop, graph, module


@pytest.mark.parametrize("name", ["gh_secp", "oilp_secp"])
def test_secp_variants_pin_lights_to_owners(name):
    dcop, graph, module = _secp_instance()
    mod = load_distribution_module(name)
    dist = mod.distribute(
        graph,
        dcop.agents.values(),
        computation_memory=module.computation_memory,
        communication_load=module.communication_load,
    )
    # every light variable computation sits on its owning agent
    for i in range(3):
        assert dist.agent_for(f"l{i:04d}") == f"a{i:04d}"
    # every computation is placed somewhere
    assert set(dist.computations) == {n.name for n in graph.nodes}


def test_oilp_secp_beats_or_matches_greedy():
    dcop, graph, module = _secp_instance()
    costs = {}
    for name in ("gh_secp", "oilp_secp"):
        mod = load_distribution_module(name)
        dist = mod.distribute(
            graph,
            dcop.agents.values(),
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        costs[name], _, _ = mod.distribution_cost(
            dist,
            graph,
            dcop.agents.values(),
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
    assert costs["oilp_secp"] <= costs["gh_secp"] + 1e-9


def test_secp_pins_require_an_owner():
    """A variable with no zero-cost agent and no hint is an error."""
    from pydcop_tpu.distribution._secp import secp_pins

    d = Domain("d", "", [0, 1])
    v = Variable("v1", d)
    dcop = DCOP("t")
    dcop.add_variable(v)
    dcop.add_constraint(constraint_from_str("c1", "v1", [v]))
    graph = constraints_hypergraph.build_computation_graph(dcop)
    agents = [AgentDef("a1", default_hosting_cost=5.0)]
    with pytest.raises(ImpossibleDistributionException, match="owning"):
        secp_pins(graph, agents, None)
